"""North-star head-to-head: reference asyncio backend vs ConsensusEngine.

BASELINE.md's north star asks >= 5x wall-clock over the reference on the
same decentralized task.  The reference's TCP path has never run (its
master crashes on the first round request — ``master.py:140``), but its
*asyncio* backend works and runs right here on CPU, so this benchmark
turns the argument into a number: the Titanic consensus-GD recipe
(``notebooks/Titanic Consensus GD test.ipynb`` cell 14 — local
subgradient step with the ``alpha*(it+1)^-0.5`` schedule, then gossip to
convergence after every step) on the SAME topology, shards, step
schedule, and convergence eps, driven through

* the reference: ``/root/reference/utils/consensus_asyncio.py`` —
  ConsensusNetwork/ConsensusAgent over asyncio queues, one coroutine per
  agent (imported and RUN as the baseline, not copied); the driver loop
  below is a fresh implementation of the notebook's ``learning_instance``
  (cell 14) against that API;
* this framework: one jitted program — vmapped local steps +
  ``ConsensusEngine.mix_until`` (eps-stopped Perron gossip) inside a
  ``lax.fori_loop``, on the 8-virtual-device CPU mesh settings the tests
  use (no TPU needed: the point is same-hardware wall-clock).

Both sides use the uniform-eps Perron mixing the reference's master
distributes (eps = 0.95/max_deg, ``consensus_asyncio.py:78-86``) and the
notebook's convergence_eps=1e-4 default.  Prints one JSON line and (with
--publish) records absolute times for both sides in BASELINE.json.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

import numpy as np

ALPHA, TAU = 0.1, 1e-4
CONVERGENCE_EPS = 1e-4  # reference ConsensusAgent default
TOPOLOGY = [(0, 1), (1, 2), (2, 3), (3, 4)]  # 5-node path ("grid") graph
N_AGENTS = 5


def _shards():
    from distributed_learning_tpu.data import load_titanic, split_data

    X_tr, y_tr, X_te, y_te = load_titanic()
    shards = split_data(X_tr, y_tr, N_AGENTS)
    m = min(len(s[0]) for s in shards.values())
    Xs = np.stack([np.asarray(shards[i][0][:m]) for i in range(N_AGENTS)])
    ys = np.stack(
        [np.asarray(shards[i][1][:m], np.float32) for i in range(N_AGENTS)]
    )
    return Xs, ys, np.asarray(X_te), np.asarray(y_te, np.float32)


def _np_grad(w, X, y):
    """Numpy gradient of the ridge logistic loss (labels {-1,+1}) — the
    notebook's inline manual gradient, matching models/logreg.loss_fn."""
    margins = y * (X @ w)
    s = 1.0 / (1.0 + np.exp(margins))  # sigmoid(-margins)
    return TAU * w - (X.T @ (y * s)) / len(y)


def run_reference(Xs, ys, iters):
    """Drive the reference asyncio backend through the notebook recipe,
    in a SUBPROCESS: the reference tree is untrusted public content, so
    its module-level code never runs in the measuring process — and its
    asyncio event loop cannot leak state into ours.  Wall-clock is
    timed inside the child around the run itself (not the interpreter
    spawn), keeping the comparison fair."""
    import os
    import subprocess
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        inp, out = os.path.join(td, "in.npz"), os.path.join(td, "out.npz")
        np.savez(inp, Xs=Xs, ys=ys, iters=iters)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "from benchmarks.bench_northstar import _reference_child; "
                 f"_reference_child({inp!r}, {out!r})"],
                env=env, capture_output=True, text=True,
                timeout=900,  # the reference's asyncio loop can stall;
                              # a hang must surface as an error record
            )
        except subprocess.TimeoutExpired as e:
            raise RuntimeError(
                f"reference subprocess hung past 900s: "
                f"{(e.stderr or '')[-500:]}"
            ) from e
        if proc.returncode:
            raise RuntimeError(
                f"reference subprocess failed: {proc.stderr[-2000:]}"
            )
        rec = np.load(out)
        return rec["ws"], float(rec["elapsed"])


def _reference_child(in_path: str, out_path: str) -> None:
    """Subprocess body for :func:`run_reference` (child-only import of
    the reference package)."""
    rec = np.load(in_path)
    Xs, ys, iters = rec["Xs"], rec["ys"], int(rec["iters"])
    ws, elapsed = _run_reference_inproc(Xs, ys, iters)
    np.savez(out_path, ws=ws, elapsed=elapsed)


def _run_reference_inproc(Xs, ys, iters):
    sys.path.insert(0, "/root/reference")
    from utils.consensus_asyncio import ConsensusAgent, ConsensusNetwork

    dim = Xs.shape[-1]

    async def learning_instance(agent, X, y):
        w = np.zeros(dim)
        for it in range(iters):
            w = w - ALPHA * (it + 1.0) ** -0.5 * _np_grad(w, X, y)
            w = await agent.run_round(w, len(y))
        return w

    async def main():
        shutdown_q = asyncio.Queue()
        net = ConsensusNetwork(TOPOLOGY, shutdown_q)
        agents = [
            ConsensusAgent(t, convergence_eps=CONVERGENCE_EPS)
            for t in range(N_AGENTS)
        ]
        for a in agents:
            net.register_agent(a)
        serve = asyncio.create_task(net.serve())
        ws = await asyncio.gather(
            *[
                learning_instance(a, Xs[i], ys[i])
                for i, a in enumerate(agents)
            ]
        )
        await shutdown_q.put(True)
        await serve
        return np.stack(ws)

    t0 = time.perf_counter()
    ws = asyncio.run(main())
    return ws, time.perf_counter() - t0


def run_engine(Xs, ys, iters):
    """The same recipe as one jitted SPMD program."""
    import jax
    import jax.numpy as jnp

    from distributed_learning_tpu.models.logreg import loss_fn
    from distributed_learning_tpu.parallel import Topology
    from distributed_learning_tpu.parallel.consensus import ConsensusEngine

    engine = ConsensusEngine(Topology.from_edges(TOPOLOGY).perron())
    Xs_d, ys_d = jnp.asarray(Xs), jnp.asarray(ys)

    def local_step(w, X, y, lr):
        return w - lr * jax.grad(loss_fn)(w, X, y, TAU)

    vstep = jax.vmap(local_step, in_axes=(0, 0, 0, None))

    @jax.jit
    def run(w0):
        def body(it, w):
            lr = ALPHA * (it + 1.0) ** -0.5
            w = vstep(w, Xs_d, ys_d, lr)
            w, _, _ = engine.mix_until(
                w, eps=CONVERGENCE_EPS, max_rounds=300
            )
            return w

        return jax.lax.fori_loop(0, iters, body, w0)

    w0 = jnp.zeros(Xs.shape[:1] + Xs.shape[2:])
    t0 = time.perf_counter()
    w_warm = run(w0).block_until_ready()  # includes compile
    compile_and_run = time.perf_counter() - t0
    t0 = time.perf_counter()
    w = run(w0).block_until_ready()
    steady = time.perf_counter() - t0
    return np.asarray(w), steady, compile_and_run


def _accuracy(w, X, y):
    pred = np.where(1.0 / (1.0 + np.exp(-(X @ w))) >= 0.5, 1.0, -1.0)
    return float((pred == y).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=500)
    ap.add_argument("--publish", action="store_true",
                    help="record the result in BASELINE.json")
    args = ap.parse_args()

    Xs, ys, X_te, y_te = _shards()

    w_eng, t_eng, t_eng_cold = run_engine(Xs, ys, args.iters)
    w_ref, t_ref = run_reference(Xs, ys, args.iters)

    acc_ref = _accuracy(w_ref.mean(0), X_te, y_te)
    acc_eng = _accuracy(w_eng.mean(0), X_te, y_te)
    spread_ref = float(np.abs(w_ref - w_ref.mean(0)).max())
    spread_eng = float(np.abs(w_eng - w_eng.mean(0)).max())

    rec = {
        "metric": "northstar_titanic_gd_wallclock_ratio",
        "value": round(t_ref / t_eng, 2),
        "unit": "x (reference asyncio / engine steady-state)",
        "vs_baseline": round(t_ref / t_eng, 2),
        "iters": args.iters,
        "topology": "path-5",
        "convergence_eps": CONVERGENCE_EPS,
        "reference_s": round(t_ref, 3),
        "engine_steady_s": round(t_eng, 3),
        "engine_with_compile_s": round(t_eng_cold, 3),
        "test_acc_reference": round(acc_ref, 4),
        "test_acc_engine": round(acc_eng, 4),
        "agent_spread_reference": spread_ref,
        "agent_spread_engine": spread_eng,
        "platform": "cpu-8dev",
    }
    print(json.dumps(rec))

    if args.publish:
        import collections

        with open("BASELINE.json") as f:
            d = json.load(f, object_pairs_hook=collections.OrderedDict)
        d["published"]["northstar_titanic_asyncio_headtohead"] = rec
        with open("BASELINE.json", "w") as f:
            json.dump(d, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
