"""WRN training-to-accuracy: the reference's headline experiment, end to end.

The reference's anchor is the single-node torch run recorded in
``CIFAR_10_Baseline.ipynb`` cell 9: WRN-28-10, dropout 0.3, lr 0.1 with the
WRN step schedule, 100 CIFAR-10 epochs -> **93.77%** test Acc@1 (8h18m on a
T4).  This script runs the same recipe through this framework's gossip
trainer (8-agent ring, mixing every epoch) and records the full per-agent
accuracy curve plus the final number.

Data reality: this environment is zero-egress, so if no real CIFAR is
present (``DLT_CIFAR_DIR``), the learnable synthetic stand-in from
``data/cifar.py`` is used and the emitted records say so — the run then
demonstrates the complete training dynamics (optimizer, BN, augmentation,
lr schedule, gossip consensus, eval) rather than the CIFAR number itself.
The emitted JSON marks which source was used; ``vs_baseline`` is only
reported for real CIFAR.

Usage:
    python -m benchmarks.train_wrn_accuracy             # full (TPU) scale
    python -m benchmarks.train_wrn_accuracy --proxy     # reduced CPU scale
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.data import load_cifar, normalize, shard_dataset
from distributed_learning_tpu.data.cifar import (
    normalized_pad_value,
    real_cifar_present,
)
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.training import MasterNode
from distributed_learning_tpu.training.config import wrn_lr_schedule

# Reference anchors: CIFAR_10_Baseline.ipynb cell 9 (WRN-28-10, T4) and
# CIFAR_100_Baseline.ipynb cell 9 (WRN-28-10, P100).
REFERENCE_ACC = {"cifar10": 0.9377, "cifar100": 0.7571}


def run(
    *,
    proxy: bool = False,
    epochs: int | None = None,
    n_agents: int = 8,
    out_path: str | None = None,
    dataset: str = "cifar10",
    n_train: int | None = None,
    n_test: int | None = None,
):
    if dataset not in REFERENCE_ACC:
        raise ValueError(f"dataset {dataset!r} (want cifar10|cifar100)")
    full = common.full_scale() and not proxy
    real = real_cifar_present(dataset)
    ref_acc = REFERENCE_ACC[dataset]
    n_classes = 10 if dataset == "cifar10" else 100

    # Proxy scale is sized for a single CPU core (this environment gives
    # exactly one; measured ~8 train samples/s on WRN-10-1 there); the
    # full recipe needs the chip.
    depth, widen = (28, 10) if full else (10, 1)
    batch = 128 if full else 64
    epochs = epochs or (100 if full else 8)
    if n_train is None:
        n_train = 50_000 if (full or real) else 2048
    if n_test is None:
        n_test = None if (full or real) else 256

    (X, y), (Xt, yt) = load_cifar(dataset)
    X, y = X[:n_train], y[:n_train]
    if n_test:
        Xt, yt = Xt[:n_test], yt[:n_test]
    Xn = np.asarray(normalize(jnp.asarray(X), dataset=dataset))
    Xtn = np.asarray(normalize(jnp.asarray(Xt), dataset=dataset))
    names = list(range(n_agents))
    shards = shard_dataset(Xn, y, names, batch_size=batch, seed=0)

    epoch_len = len(shards[0][0]) // batch
    master = MasterNode(
        node_names=names,
        model="wide-resnet",
        model_args=[n_classes],
        model_kwargs={
            "depth": depth,
            "widen_factor": widen,
            "dropout_rate": 0.3,
            # bf16 hits the MXU on TPU; on CPU it is emulated, so the
            # proxy keeps f32.
            "dtype": jnp.bfloat16 if full else jnp.float32,
        },
        optimizer="sgd",
        optimizer_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
        learning_rate=wrn_lr_schedule(0.1, epochs, epoch_len),
        error="cross_entropy",
        weights=Topology.ring(n_agents),
        train_loaders=shards,
        test_loader=(Xtn, yt),
        stat_step=100,
        epoch=epochs,
        epoch_cons_num=1,
        batch_size=batch,
        mix_times=1,
        augment=True,
        augment_pad_value=normalized_pad_value(dataset),
        mesh=common.agent_mesh_or_none(n_agents),
    )
    master.initialize_nodes()

    curve = []
    t0 = time.perf_counter()
    for e in range(epochs):
        out = master.train_epoch()
        accs = np.asarray(out["test_acc"], dtype=np.float64)
        rec = {
            "epoch": e + 1,
            "train_loss": float(np.mean(out["train_loss"])),
            "test_acc_mean": float(accs.mean()),
            "test_acc_min": float(accs.min()),
            "test_acc_max": float(accs.max()),
            "deviation": float(out["deviation"]),
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }
        curve.append(rec)
        print(json.dumps({"progress": rec}), flush=True)

    final = curve[-1]
    record = common.emit(
        {
            "metric": f"wrn{depth}x{widen}_{dataset}_gossip_final_test_acc",
            "value": round(final["test_acc_mean"], 4),
            "unit": "accuracy",
            "vs_baseline": round(final["test_acc_mean"] / ref_acc, 4)
            if (real and (depth, widen) == (28, 10))
            else None,
            "config": (
                f"{n_agents}-agent ring, batch {batch}/agent, {epochs} epochs, "
                "wrn_step lr, dropout 0.3, RandomCrop+Flip, mix 1/epoch"
            ),
            "data_source": "real-cifar" if real else "synthetic-stand-in",
            "reference_anchor": ref_acc if real else None,
            "per_agent_spread": round(
                final["test_acc_max"] - final["test_acc_min"], 5
            ),
            "wall_clock_s": final["elapsed_s"],
        }
    )
    out_path = out_path or os.path.join(
        os.path.dirname(__file__), "results",
        f"wrn_accuracy_{'real' if real else 'synthetic'}_"
        f"{dataset}_{depth}x{widen}.json",
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"summary": record, "curve": curve}, f, indent=2)
    print(f"# curve written to {out_path}", flush=True)
    return record


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--proxy", action="store_true",
                    help="reduced scale for CPU / quick runs")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--agents", type=int, default=8)
    ap.add_argument("--dataset", choices=("cifar10", "cifar100"),
                    default="cifar10",
                    help="cifar100 covers the reference's second anchor "
                         "(75.71%% — CIFAR_100_Baseline.ipynb cell 9)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    run(proxy=args.proxy, epochs=args.epochs, n_agents=args.agents,
        out_path=args.out, dataset=args.dataset)
