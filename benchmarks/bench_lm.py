"""Language-model training throughput: tokens/sec, full vs flash attention.

Beyond-parity evidence for the long-context path (the reference has no
sequence models anywhere — SURVEY.md §5): steady-state causal-LM training
throughput of :class:`TransformerLM` on one chip, with the O(T^2)
materialized reference attention versus the Pallas flash kernels
(``ops/flash_attention.py``, fwd + custom-vjp backward).  Same model, same
data, same optimizer — the only variable is ``attn_impl``, so the delta is
the kernel.

Model at full scale: 8 layers, 8 heads x 128 head-dim (d_model=1024),
vocab 8192, bf16 compute — ~117M params, the MXU-friendly shape class.
Sequence lengths 4096 and 8192 (flash only at 8192; full attention's
(B, H, T, T) f32 score tensor is already multi-GB there).

Prints one JSON line per (impl, T); ``vs_baseline`` is null (no reference
anchor exists for any sequence workload).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from benchmarks.common import emit, full_scale, platform, smoke, sync


def _measure(
    attn_impl: str,
    T: int,
    *,
    B: int,
    vocab: int,
    num_layers: int,
    num_heads: int,
    head_dim: int,
    steps: int,
    warm: int = 2,
) -> tuple[float, float]:
    """Returns (tokens_per_sec, seconds_per_step) at steady state."""
    from distributed_learning_tpu.models import TransformerLM

    model = TransformerLM(
        vocab_size=vocab,
        num_layers=num_layers,
        num_heads=num_heads,
        head_dim=head_dim,
        max_len=T,
        attn_impl=attn_impl,
        dtype=jnp.bfloat16,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, vocab, size=(B, T)), jnp.int32)
    y = jnp.asarray(rng.integers(0, vocab, size=(B, T)), jnp.int32)

    params = jax.jit(model.init)(jax.random.key(0), x)["params"]
    tx = optax.adamw(3e-4, weight_decay=0.01)
    opt_state = jax.jit(tx.init)(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for _ in range(warm):
        params, opt_state, loss = step(params, opt_state, x, y)
    sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
    sync(loss)
    dt = (time.perf_counter() - t0) / steps
    return B * T / dt, dt


def _measure_decode(
    T_prompt: int, steps: int, *, B: int, vocab: int, num_layers: int,
    num_heads: int, head_dim: int, num_kv_heads=None,
) -> tuple[float, float]:
    """Steady-state autoregressive generation rate (tokens/sec summed
    over the batch) through the KV-cache decode path."""
    from distributed_learning_tpu.models import TransformerLM
    from distributed_learning_tpu.models.transformer import generate

    model = TransformerLM(
        vocab_size=vocab, num_layers=num_layers, num_heads=num_heads,
        head_dim=head_dim, max_len=T_prompt + steps, attn_impl="full",
        num_kv_heads=num_kv_heads, dtype=jnp.bfloat16,
    )
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, vocab, size=(B, T_prompt)), jnp.int32
    )
    params = jax.jit(model.init)(jax.random.key(0), prompt)["params"]
    return _time_decode(
        lambda p, n: generate(model, params, p, n), prompt, steps
    )


def _time_decode(gen_fn, prompt, steps: int) -> tuple[float, float]:
    """Prefill-subtracted decode timing, shared by the single-device and
    tensor-parallel paths so the MHA/GQA-vs-TP comparison uses ONE
    protocol.  Subtract the prefill (one O(T^2) forward, identical
    across configurations) from the timed window so the reported rate
    is the steady-state single-token decode loop; steps=1 ≈ prefill +
    one step."""
    B = prompt.shape[0]
    for n in (1, steps):
        sync(gen_fn(prompt, n))  # compile both programs
    t0 = time.perf_counter()
    sync(gen_fn(prompt, 1))
    dt_prefill = time.perf_counter() - t0
    t0 = time.perf_counter()
    sync(gen_fn(prompt, steps))
    dt = time.perf_counter() - t0
    decode_dt = dt - dt_prefill
    if decode_dt <= 0.1 * dt_prefill:
        # Noise-dominated difference (possible in single-shot smoke
        # timing): a clamped divisor would emit an astronomically
        # inflated rate indistinguishable from a real one.
        raise RuntimeError(
            f"decode window not resolvable: total {dt:.4f}s vs prefill "
            f"{dt_prefill:.4f}s"
        )
    return B * (steps - 1) / decode_dt, dt


def _measure_decode_tp(
    T_prompt: int, steps: int, *, B: int, vocab: int, num_layers: int,
    num_heads: int, head_dim: int, num_kv_heads=None,
) -> tuple[float, float]:
    """Like :func:`_measure_decode` but through the tensor-parallel
    path on a (data=1, model=2) mesh — prefill-subtracted steady-state
    rate with the KV cache head-sharded."""
    from jax.sharding import Mesh

    from distributed_learning_tpu.models import TransformerLM
    from distributed_learning_tpu.training.tp import (
        make_tp_generate,
        shard_transformer_params,
    )

    model = TransformerLM(
        vocab_size=vocab, num_layers=num_layers, num_heads=num_heads,
        head_dim=head_dim, max_len=T_prompt + steps, attn_impl="full",
        num_kv_heads=num_kv_heads, dtype=jnp.bfloat16,
    )
    mesh = Mesh(
        np.array(jax.devices()[:2]).reshape(1, 2), ("data", "model")
    )
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, vocab, size=(B, T_prompt)), jnp.int32
    )
    params = shard_transformer_params(
        jax.jit(model.init)(jax.random.key(0), prompt)["params"], mesh
    )
    gen = make_tp_generate(mesh, model)
    return _time_decode(
        lambda p, n: gen(params, p, n), prompt, steps
    )


def run() -> None:
    full = full_scale()
    if full:
        cases = [
            ("full", 4096), ("flash", 4096), ("flash", 8192),
        ]
        kw = dict(B=2, vocab=8192, num_layers=8, num_heads=8,
                  head_dim=128, steps=8)
    else:
        cases = [("full", 128), ("flash", 128)]
        kw = dict(B=2, vocab=64, num_layers=2, num_heads=2, head_dim=16,
                  steps=1 if smoke() else 2)
    results = {}
    for impl, T in cases:
        try:
            toks, dt = _measure(impl, T, **kw)
        except Exception as e:  # OOM at the quadratic sizes
            emit({
                "metric": f"lm_train_tokens_per_sec_{impl}_T{T}",
                "value": None,
                "unit": "tokens/sec",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {str(e)[:120]}",
            })
            continue
        results[(impl, T)] = toks
        emit({
            "metric": f"lm_train_tokens_per_sec_{impl}_T{T}",
            "value": round(toks, 1),
            "unit": "tokens/sec",
            "vs_baseline": None,
            "config": (
                f"TransformerLM L{kw['num_layers']} H{kw['num_heads']}x"
                f"{kw['head_dim']} vocab{kw['vocab']} B{kw['B']} bf16, "
                f"attn={impl}, single chip"
            ),
            "seconds_per_step": round(dt, 4),
            "platform": platform(),
        })
    # Autoregressive decode throughput (the KV-cache path), MHA vs GQA.
    if full:
        dec_cases = [(None, 2048, 256), (2, 2048, 256)]
    else:
        dec_cases = [(None, 32, 8), (1, 32, 8)]
    for hkv, tp, steps in dec_cases:
        # Tag by the measured grouping, not a fixed label: smoke and
        # full-scale configs have different head counts.
        tag = "mha" if hkv is None else f"gqa{kw['num_heads'] // hkv}"
        try:
            toks, dt = _measure_decode(
                tp, steps, B=kw["B"], vocab=kw["vocab"],
                num_layers=kw["num_layers"], num_heads=kw["num_heads"],
                head_dim=kw["head_dim"], num_kv_heads=hkv,
            )
        except Exception as e:
            emit({
                "metric": f"lm_decode_tokens_per_sec_{tag}",
                "value": None,
                "unit": "tokens/sec",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {str(e)[:120]}",
            })
            continue
        emit({
            "metric": f"lm_decode_tokens_per_sec_{tag}",
            "value": round(toks, 1),
            "unit": "tokens/sec",
            "vs_baseline": None,
            "config": (
                f"prefill {tp} + {steps} greedy steps, B{kw['B']} "
                f"L{kw['num_layers']} H{kw['num_heads']}x"
                f"{kw['head_dim']} kv_heads={hkv or kw['num_heads']}, "
                "KV-cache decode"
            ),
            "seconds_total": round(dt, 3),
            "platform": platform(),
        })

    # Tensor-parallel decode (training/tp.py::make_tp_generate): the
    # head-sharded KV-cache serving path on a (data, model) mesh.  Needs
    # >= 2 devices — the tunneled chip is single-device, so on it this
    # emits a skip record; the 8-virtual-device CPU smoke run rot-guards
    # the path, and a pod slice would measure it for real.
    n_dev = len(jax.devices())
    if n_dev >= 2:
        try:
            toks, dt = _measure_decode_tp(
                *(dec_cases[0][1:]), B=kw["B"], vocab=kw["vocab"],
                num_layers=kw["num_layers"], num_heads=kw["num_heads"],
                head_dim=kw["head_dim"],
                num_kv_heads=kw["num_heads"] // 2 or None,
            )
            emit({
                "metric": "lm_decode_tp_tokens_per_sec",
                "value": round(toks, 1),
                "unit": "tokens/sec",
                "vs_baseline": None,
                "config": (
                    f"(data=1, model=2) mesh, head-sharded KV cache, "
                    f"B{kw['B']} L{kw['num_layers']} "
                    f"H{kw['num_heads']}x{kw['head_dim']}"
                ),
                "seconds_total": round(dt, 3),
                "platform": platform(),
            })
        except Exception as e:
            emit({
                "metric": "lm_decode_tp_tokens_per_sec",
                "value": None,
                "unit": "tokens/sec",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {str(e)[:120]}",
            })
    else:
        emit({
            "metric": "lm_decode_tp_tokens_per_sec",
            "value": None,
            "unit": "tokens/sec",
            "vs_baseline": None,
            "config": "skipped: single device (TP decode needs >= 2)",
            "platform": platform(),
        })

    # Headline ratio: the kernel's end-to-end training win at matched T.
    for T in sorted({t for _, t in cases}):
        fu, fl = results.get(("full", T)), results.get(("flash", T))
        if fu and fl:
            emit({
                "metric": f"lm_train_flash_speedup_T{T}",
                "value": round(fl / fu, 3),
                "unit": "x vs full attention",
                "vs_baseline": None,
                "platform": platform(),
            })


if __name__ == "__main__":
    run()
