"""Fused compressed gossip (CHOCO) vs the per-leaf oracle.

PR 3 fused the dense *mixing* family onto one contiguous ``(N, P)``
buffer per dtype bucket but left compression per leaf, so a CHOCO round
on a model-shaped state still paid O(leaves) ``lax.top_k`` sorts,
scatters, and RNG splits per agent per round — dwarfing the single fused
GEMM they feed.  This benchmark measures what routing compression through
the ``FusedCompressor`` (segment-aware selection in O(dtype-buckets x
size-classes) device ops, ``parallel/compression.py``) buys, on two
64-leaf mixed-dtype (bf16 + f32) trees:

* ``tail`` — leaf sizes in the bias/norm-scale range (4-45 elements),
  the regime where per-op overhead dominates a compressed round and the
  fusion pays most (the same regime ``bench_fast_averaging.py`` uses for
  the mixing fusion).  The >= 2x acceptance gate (ISSUE 5) applies here.
* ``conv`` — leaf sizes in the small-conv range (4-~280), where the
  selection FLOPs themselves (identical in both layouts) take a larger
  share; the fused win is correspondingly smaller (~1.3-1.7x measured)
  and is REPORTED, not gated — no silent cherry-picking.

Also recorded: the nominal sparse-wire bytes one round's corrections
occupy (``FusedCompressor.wire_bytes_per_round`` — what the TCP fused
sparse frame ships) next to the dense state volume.

The tier-1 rot guard in ``tests/test_benchmarks.py`` gates the tail
speedup at a looser 1.5x so shared-CI timing noise cannot flake tier-1.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.ops import mixing as mixing_ops
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.parallel.compression import (
    ChocoGossipEngine,
    FusedCompressor,
    top_k,
)


def _tail_stack(n_agents: int, leaves: int, base: int) -> dict:
    """``leaves`` bias/norm-scale-sized mixed-dtype leaves: pairs of a
    ``(N, base..base+6)`` scale and a ``(N, 4)`` bias, every fourth pair
    stored bf16 — the per-op-overhead-dominated tail regime."""
    rng = np.random.default_rng(11)
    tree = {}
    for i in range(leaves // 2):
        d = base + (i % 7)
        dt = jnp.bfloat16 if i % 4 == 3 else jnp.float32
        tree[f"l{i:03d}"] = {
            "s": jnp.asarray(rng.normal(size=(n_agents, d)), dt),
            "b": jnp.asarray(rng.normal(size=(n_agents, 4)), dt),
        }
    return tree


def _conv_stack(n_agents: int, leaves: int, width: int) -> dict:
    """``leaves`` small-conv-sized leaves (w/b pairs of varying fan-in,
    every fourth pair bf16): per-element selection work takes a larger
    share, so this is the fused path's UNFAVORABLE regime."""
    rng = np.random.default_rng(11)
    tree = {}
    for i in range(leaves // 2):
        d = width + (i % 7)
        dt = jnp.bfloat16 if i % 4 == 3 else jnp.float32
        tree[f"l{i:03d}"] = {
            "w": jnp.asarray(rng.normal(size=(n_agents, d, 4)), dt),
            "b": jnp.asarray(rng.normal(size=(n_agents, 4)), dt),
        }
    return tree


def _measure(
    x: dict, n_agents: int, rounds: int, fraction: float, label: str
) -> dict:
    layout = mixing_ops.fused_layout(x)
    W = Topology.ring(n_agents).metropolis_weights()
    comp = top_k(fraction)
    out: dict = {}
    for mode, fused in (("fused", True), ("perleaf", False)):
        eng = ChocoGossipEngine(W, comp, gamma=0.3, fused=fused)
        state = eng.init(x, seed=3)
        warm, _ = eng.run(state, rounds)  # compile at the timed length
        common.sync(warm.x)
        best = 0.0
        for _ in range(3):  # best-of-3: rounds are ~ms-scale on CPU
            with common.stopwatch() as t:
                done, _trace = eng.run(state, rounds)
                common.sync(done.x)
            best = max(best, rounds / t["s"])
        out[mode] = best
    out["speedup"] = out["fused"] / out["perleaf"]
    wire = FusedCompressor(comp).wire_bytes_per_round(layout, n_agents)
    out["wire_bytes_per_round"] = wire
    out["dense_bytes_per_round"] = layout.bytes_per_round(n_agents)
    common.emit(
        {
            "metric": f"choco_fused_rounds_per_sec_{label}",
            "value": round(out["fused"], 2),
            "unit": "rounds/sec",
            "vs_baseline": None,
            "config": "choco-ring-metropolis-topk",
            "tree_regime": label,
            "rounds_per_sec_perleaf": round(out["perleaf"], 2),
            "speedup_vs_perleaf": round(out["speedup"], 3),
            "top_k_fraction": fraction,
            "leaf_count": layout.leaf_count,
            "fused_buckets": layout.bucket_count,
            "wire_bytes_per_round": wire,
            "dense_bytes_per_round": layout.bytes_per_round(n_agents),
            "rounds_timed": rounds,
            "n_agents": n_agents,
        }
    )
    return out


def run_fused_vs_perleaf(
    n_agents: int = 8,
    leaves: int = 64,
    rounds: int | None = None,
    fraction: float = 0.1,
) -> dict:
    """Compressed rounds/sec fused vs per-leaf on the tail tree (the
    gated headline) and the conv tree (the disclosed unfavorable
    regime); returns ``{"fused", "perleaf", "speedup", ...}`` of the
    tail tree plus ``conv_speedup``."""
    if rounds is None:
        # Enough rounds that per-call fixed cost (dispatch, flatten
        # prologue) amortizes and the per-ROUND cost — what fused
        # compression changes — is what the clock sees.
        rounds = 100 if common.smoke() else 200
    base = 16 if common.smoke() else 32
    out = _measure(
        _tail_stack(n_agents, leaves, base), n_agents, rounds, fraction,
        "tail",
    )
    conv = _measure(
        _conv_stack(n_agents, leaves, base), n_agents, rounds, fraction,
        "conv",
    )
    out["conv_speedup"] = conv["speedup"]
    return out


def run(n_agents: int = 8, leaves: int = 64) -> dict:
    return run_fused_vs_perleaf(n_agents, leaves)


if __name__ == "__main__":
    run()
