"""Fleet-scale obs-plane load harness (ISSUE 17 tentpole gate).

Drives 500+ synthetic per-agent ``obs.delta`` streams through a
two-tier aggregator tree (agents -> :class:`SubAggregator` pods ->
root) and gates the plane's fleet contract:

* **merge throughput** — payloads/sec through a root
  :class:`RunAggregator` (the sharded-master control plane budgets
  telemetry merging out of the master's round loop);
* **bounded memory** — the root's merged sketch state is O(metrics),
  not O(agents x samples): doubling the per-agent sample count must
  not grow the bucket footprint, and fleet-mode deltas
  (``raw_series=False``) must keep sketched series out of the raw
  point rings entirely;
* **bounded delta bytes** — a pack's encoded size stays flat as the
  per-agent sample count grows 10x, and a pod's upstream export stays
  flat as its agent count grows (label rollups fold the per-agent
  counter dimension);
* **aggregate-of-aggregates oracle** — the two-tier merge produces
  exactly the same rendered straggler quantiles as the flat
  single-aggregator merge of the same streams, and every sketch
  quantile matches the exact nearest-rank oracle within the sketch's
  documented relative-error bound.

Jax-free by construction (the obs plane never touches a jitted
program); ``benchmarks/common.py`` is used only for sizing and the
JSON metric-line contract.  ``out_dir=`` additionally dumps each pod's
merged registry as ``<token>.jsonl``, so the whole run is inspectable
with ``obs-report --merge <out_dir>``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from benchmarks.common import emit, full_scale, smoke, stopwatch
from distributed_learning_tpu.obs.aggregate import (
    RunAggregator,
    SubAggregator,
    ObsDeltaSource,
)
from distributed_learning_tpu.obs.registry import MetricsRegistry
from distributed_learning_tpu.obs.sketch import DEFAULT_ALPHA, QuantileSketch

#: Tier-1 gate: a root aggregator must merge at least this many delta
#: payloads per second (the headline run on the measurement box shows
#: orders of magnitude more; the gate is loose so shared-CI timing
#: noise cannot flake).
MERGE_GATE_PAYLOADS_PER_SEC = 50.0


def _pct_exact(sorted_vals: List[float], q: float) -> float:
    """The exact nearest-rank oracle (same rank convention as the
    sketch and ``aggregate._pct``)."""
    import math

    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[rank - 1]


def _synth_streams(n_agents: int, packs: int, points_per_pack: int):
    """Deterministic synthetic fleet: per-agent delta payload lists plus
    the exact per-agent sample record (the oracle).  Agent 0 is the
    injected straggler (10x latencies); the rest draw a heavy-tail
    lognormal — the adversarial shape for a quantile sketch."""
    payloads: List[List[dict]] = [[] for _ in range(packs)]
    exact: Dict[str, List[float]] = {}
    regs: Dict[str, MetricsRegistry] = {}
    for i in range(n_agents):
        token = f"a{i:04d}"
        rng = np.random.default_rng(1000 + i)
        reg = MetricsRegistry(clock=lambda: 0.0)
        # Fleet mode: sketched series travel as sketches only.
        src = ObsDeltaSource(reg, raw_series=False)
        vals: List[float] = []
        for p in range(packs):
            scale = 10.0 if i == 0 else 1.0
            draws = scale * rng.lognormal(mean=-3.0, sigma=1.0,
                                          size=points_per_pack)
            for v in draws:
                reg.observe("comm.agent.round_s", float(v))
                vals.append(float(v))
            reg.inc("comm.agent.rounds_run", points_per_pack)
            reg.observe("comm.agent.staleness", float(p % 3))
            payloads[p].append((token, src.pack()))
        exact[token] = sorted(vals)
        regs[token] = reg
        src.close()
    return payloads, exact, regs


def _sketch_footprint(agg: RunAggregator) -> int:
    """Total bucket entries across the aggregator's merged sketches —
    the O(metrics) quantity the memory gate tracks."""
    with agg._lock:
        return sum(
            len(sk.buckets) + len(sk.neg)
            for sk in agg.sketches.values()
        )


def run(n_agents: Optional[int] = None, packs: Optional[int] = None,
        points_per_pack: Optional[int] = None, n_subs: int = 10,
        out_dir: Optional[str] = None) -> dict:
    if n_agents is None:
        n_agents = 500 if full_scale() else (64 if smoke() else 128)
    if packs is None:
        packs = 2 if smoke() else 4
    if points_per_pack is None:
        points_per_pack = 20 if smoke() else 50
    n_subs = max(1, min(int(n_subs), n_agents))

    payloads, exact, regs = _synth_streams(n_agents, packs,
                                           points_per_pack)
    flat_payloads = [tp for pack in payloads for tp in pack]

    # ---- flat single-aggregator merge (the oracle topology) --------- #
    flat = RunAggregator(registry=MetricsRegistry(clock=lambda: 0.0))
    half_mark = None
    for k, (token, payload) in enumerate(flat_payloads):
        flat.process(token, payload)
        if k + 1 == len(flat_payloads) // 2:
            half_mark = _sketch_footprint(flat)
    full_mark = _sketch_footprint(flat)

    # ---- two-tier: agents -> pods -> root --------------------------- #
    subs = [
        SubAggregator(
            registry=MetricsRegistry(clock=lambda: 0.0),
            forward_raw_series=False,
        )
        for _ in range(n_subs)
    ]
    root = RunAggregator(registry=MetricsRegistry(clock=lambda: 0.0))
    for pack in payloads:
        for j, (token, payload) in enumerate(pack):
            subs[j % n_subs].process(token, payload)
        # One bounded upstream export per pod per pack round.
        for s, sub in enumerate(subs):
            root.process(f"pod{s}", sub.export_delta())

    # ---- oracle: two-tier == flat on every rendered quantile -------- #
    flat_prof = flat.straggler_profile()
    root_prof = root.straggler_profile()
    assert flat_prof["quantiles"] == root_prof["quantiles"] == "sketch"
    mismatches = 0
    rel_err_max = 0.0
    for token, entry in flat_prof["per_agent"].items():
        other = root_prof["per_agent"][token]
        for key in ("count", "p50_s", "p95_s", "max_s"):
            if entry[key] != other[key]:
                mismatches += 1
        # Sketch-vs-exact relative error on the quantiles the report
        # renders (the documented DDSketch-style alpha bound).
        vals = exact[token]
        for q, key in ((0.50, "p50_s"), (0.95, "p95_s")):
            truth = _pct_exact(vals, q)
            err = abs(entry[key] - truth) / truth
            rel_err_max = max(rel_err_max, err)
    two_tier_exact = mismatches == 0
    alpha_ok = rel_err_max <= DEFAULT_ALPHA + 1e-12

    # Counter totals agree up to float-summation order.
    flat_total = flat.registry.counters["comm.agent.rounds_run"]
    root_total = root.registry.counters["comm.agent.rounds_run"]
    counters_ok = (
        abs(flat_total - root_total) <= 1e-9 * max(1.0, flat_total)
    )

    # ---- bounded memory --------------------------------------------- #
    # Bucket saturation: 10x the samples from a stationary
    # distribution must not meaningfully grow a sketch's bucket
    # footprint (the occupied log-buckets saturate; only the counts in
    # them keep rising).  This is the O(metrics)-not-O(samples)
    # memory contract measured directly.
    sat_rng = np.random.default_rng(42)
    sat_sk = QuantileSketch()
    for v in sat_rng.lognormal(mean=-3.0, sigma=1.0, size=1000):
        sat_sk.add(float(v))
    sat_1k = len(sat_sk.buckets) + len(sat_sk.neg)
    for v in sat_rng.lognormal(mean=-3.0, sigma=1.0, size=9000):
        sat_sk.add(float(v))
    sat_10k = len(sat_sk.buckets) + len(sat_sk.neg)
    memory_flat = sat_10k <= sat_1k * 1.75
    # Fleet mode kept sketched series out of the raw rings entirely.
    no_raw_series = (
        len(flat.registry.series.get("comm.agent.round_s/a0000", ()))
        == 0
    )

    # ---- bounded delta bytes ---------------------------------------- #
    # Per-agent pack: 10x the samples must not 10x the payload.
    def _pack_bytes(points: int) -> int:
        reg = MetricsRegistry(clock=lambda: 0.0)
        src = ObsDeltaSource(reg, raw_series=False)
        rng = np.random.default_rng(7)
        for v in rng.lognormal(mean=-3.0, sigma=1.0, size=points):
            reg.observe("comm.agent.round_s", float(v))
        payload = src.pack()
        src.close()
        return len(json.dumps(payload).encode())

    bytes_1x = _pack_bytes(200)
    bytes_10x = _pack_bytes(2000)
    # Sub-linear, bucket-saturation growth: 10x the samples stays well
    # under 3x the bytes (a raw-series payload would be ~10x).
    delta_bytes_flat = bytes_10x <= bytes_1x * 3.0

    # Pod export: 4x the agents must not 4x the upstream delta (label
    # rollups fold the per-agent counter dimension).
    def _export_bytes(agents: int) -> int:
        sub = SubAggregator(
            registry=MetricsRegistry(clock=lambda: 0.0),
            forward_raw_series=False, rollup_labels=16,
        )
        for p in range(2):
            for i in range(agents):
                token = f"b{i:04d}"
                reg = MetricsRegistry(clock=lambda: 0.0)
                src = ObsDeltaSource(reg, raw_series=False)
                rng = np.random.default_rng(i)
                for v in rng.lognormal(size=20):
                    reg.observe("comm.agent.round_s", float(v))
                reg.inc("comm.agent.rounds_run", 20)
                sub.process(token, src.pack())
                src.close()
        return len(json.dumps(sub.export_delta()).encode())

    export_small = _export_bytes(16)
    export_large = _export_bytes(64)
    # The sketch section still carries per-agent labeled sketches (the
    # straggler profile needs per-agent attribution), so the export is
    # O(agents x metrics) there by design — but NOT O(samples): the
    # gate is that 4x agents with the same per-agent volume stays
    # comfortably under 4x bytes (rollups folded the counter rows).
    export_bounded = export_large <= export_small * 4

    # ---- merge throughput gate -------------------------------------- #
    sink = RunAggregator(registry=MetricsRegistry(clock=lambda: 0.0))
    with stopwatch() as t:
        for token, payload in flat_payloads:
            sink.process(token, payload)
    payloads_per_sec = len(flat_payloads) / max(t["s"], 1e-9)
    gate_passed = payloads_per_sec >= MERGE_GATE_PAYLOADS_PER_SEC

    # ---- optional artifact dir for obs-report --merge --------------- #
    # Per-agent registry dumps (the local rings retain the raw series
    # even in fleet mode, so the offline merge re-derives sketches and
    # renders the same per-agent picture): the whole fleet run is
    # inspectable with one ``obs-report --merge <out_dir>``.
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        for token, reg in regs.items():
            reg.dump_jsonl(os.path.join(out_dir, f"{token}.jsonl"))

    out = {
        "n_agents": n_agents,
        "packs": packs,
        "points_per_pack": points_per_pack,
        "n_subs": n_subs,
        "payloads_merged": len(flat_payloads),
        "payloads_per_sec": payloads_per_sec,
        "gate": MERGE_GATE_PAYLOADS_PER_SEC,
        "gate_passed": bool(gate_passed),
        "two_tier_exact": bool(two_tier_exact),
        "counters_ok": bool(counters_ok),
        "sketch_rel_err_max": rel_err_max,
        "alpha": DEFAULT_ALPHA,
        "alpha_ok": bool(alpha_ok),
        "sketch_footprint_half": half_mark,
        "sketch_footprint_full": full_mark,
        "sat_buckets_1k": sat_1k,
        "sat_buckets_10k": sat_10k,
        "memory_flat": bool(memory_flat),
        "no_raw_series": bool(no_raw_series),
        "pack_bytes_1x": bytes_1x,
        "pack_bytes_10x": bytes_10x,
        "delta_bytes_flat": bool(delta_bytes_flat),
        "export_bytes_16": export_small,
        "export_bytes_64": export_large,
        "export_bounded": bool(export_bounded),
        "slowest_agent": flat_prof["slowest_agent"],
    }
    emit({
        "metric": "obs_plane_merge_payloads_per_sec",
        "value": payloads_per_sec,
        "unit": "payloads/sec",
        "vs_baseline": None,
        "bench": "obs_plane",
        "n_agents": n_agents,
        "gate": MERGE_GATE_PAYLOADS_PER_SEC,
        "gate_passed": bool(gate_passed),
        "two_tier_exact": bool(two_tier_exact),
        "sketch_rel_err_max": rel_err_max,
        "alpha_ok": bool(alpha_ok),
        "memory_flat": bool(memory_flat),
        "delta_bytes_flat": bool(delta_bytes_flat),
        "export_bounded": bool(export_bounded),
    })
    emit({
        "metric": "obs_plane_export_bytes",
        "value": float(export_large),
        "unit": "bytes",
        "vs_baseline": None,
        "bench": "obs_plane",
        "export_bytes_16_agents": export_small,
        "export_bytes_64_agents": export_large,
        "pack_bytes_1x": bytes_1x,
        "pack_bytes_10x": bytes_10x,
    })
    return out


if __name__ == "__main__":
    run()
