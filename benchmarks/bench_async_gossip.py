"""Straggler gate of the asynchronous gossip runtime (ISSUE 8).

Lock-step gossip runs at the pace of the slowest agent: with one of 4
loopback agents injected 10x slow, every ``run_once`` round costs the
straggler's compute time on ALL agents.  The async runtime
(``comm/async_runtime.py``) lets the fast agents mix the straggler's
last *received* state at staleness-decayed weight (bound tau, deadline-
bounded waits) and keep their own pace — the straggler costs its own
progress only.

Measured here on the real TCP loopback wire, compute injected as
``asyncio.sleep`` (base 5 ms, straggler 50 ms — sleep-dominated, so
shared-CI scheduling noise stays second order):

* ``lockstep_rounds_per_sec`` — ``run_once`` rounds, all 4 agents in
  lock step (each round waits for the straggler).
* ``async_rounds_per_sec`` — async rounds of the FAST agents
  (tau=2, deadline 10 ms): the straggler is mixed while its staleness
  is within bound, dropped-and-poked beyond it.

**Gate (acceptance): async >= 2x lock-step.**  Expected ~5-8x — the
fast agents' round time falls from ~the straggler's 50 ms to ~their own
5 ms.  The tier-1 rot guard in ``tests/test_benchmarks.py`` gates at
the same 2x (the margin is several-x, and both sides time the same
injected sleeps).  Also recorded: the straggler's own completed rounds
and the staleness counters (``comm.agent.async_stale_mixed`` /
``async_stale_dropped`` / ``pokes_sent``) — the observability the
convergence-vs-staleness analysis reads.

**Trace-plane gate (ISSUE 14): tracing ON costs <= 5% rounds/sec.**
The async measurement repeats with ``ConsensusAgent(trace=True)`` —
every frame stamped with a wire ``TraceContext`` and the full
encode/send/recv/decode/mix flow-event chain emitted per frame.  Both
modes take the best of ``repeats`` runs (noise pushes rates DOWN, so
max-of-N is the stable estimator for a sleep-dominated workload), and
``trace_overhead_pct`` must stay within ``trace_gate`` (5%).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict

import numpy as np

from benchmarks import common
from distributed_learning_tpu.comm import (
    AsyncGossipRunner,
    ConsensusAgent,
    ConsensusMaster,
)

RING4 = [("1", "2"), ("2", "3"), ("3", "4"), ("4", "1")]
TOKENS = ("1", "2", "3", "4")
SLOW = "4"


async def _deploy(trace: bool = False):
    master = ConsensusMaster(RING4, convergence_eps=1e-6)
    host, port = await master.start()
    agents = {
        t: ConsensusAgent(t, host, port, trace=trace, trace_run_id=14)
        for t in TOKENS
    }
    await asyncio.gather(*(a.start() for a in agents.values()))
    return master, agents


async def _teardown(master, agents):
    await master.shutdown()
    for a in agents.values():
        await a.close(drain=0.1)


def _values() -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(8)
    return {t: rng.normal(size=64).astype(np.float32) for t in TOKENS}


async def _lockstep(rounds: int, base_s: float, slow_s: float) -> float:
    master, agents = await _deploy()
    vals = dict(_values())

    async def one(t):
        # Injected local compute, then the synchronous exchange: the
        # per-round barrier IS the lock-step model being measured —
        # every agent's round completes at the straggler's pace.
        await asyncio.sleep(slow_s if t == SLOW else base_s)
        vals[t] = await agents[t].run_once(vals[t])

    t0 = time.perf_counter()
    for _ in range(rounds):
        await asyncio.gather(*(one(t) for t in TOKENS))
    elapsed = time.perf_counter() - t0
    await _teardown(master, agents)
    return rounds / elapsed


async def _async_mode(
    rounds: int, base_s: float, slow_s: float,
    tau: int, deadline_s: float, trace: bool = False,
):
    master, agents = await _deploy(trace=trace)
    runners = {
        t: AsyncGossipRunner(
            agents[t], staleness_bound=tau, deadline_s=deadline_s
        )
        for t in TOKENS
    }
    vals = _values()
    stop = asyncio.Event()

    async def fast(t):
        x = vals[t]
        for _ in range(rounds):
            x = await runners[t].run_async_round(
                x, local=lambda: asyncio.sleep(base_s)
            )
        return x

    async def slow(t):
        x = vals[t]
        while not stop.is_set():
            x = await runners[t].run_async_round(
                x, local=lambda: asyncio.sleep(slow_s)
            )
        return x

    t0 = time.perf_counter()
    slow_task = asyncio.ensure_future(slow(SLOW))
    await asyncio.gather(*(fast(t) for t in TOKENS if t != SLOW))
    elapsed = time.perf_counter() - t0
    stop.set()
    await slow_task
    rate = rounds / elapsed
    counters = {
        name: sum(a.counters.get(name, 0) for a in agents.values())
        for name in (
            "async_stale_mixed", "async_stale_dropped",
            "async_deadline_drops", "pokes_sent",
        )
    }
    slow_rounds = runners[SLOW].round
    await _teardown(master, agents)
    return rate, slow_rounds, counters


def run(
    rounds: int | None = None,
    base_s: float = 0.005,
    slow_s: float = 0.05,
    tau: int = 2,
    deadline_s: float = 0.01,
    repeats: int = 2,
) -> dict:
    """Lock-step vs async rounds/sec with the 10x straggler; emits one
    record with the >= 2x gate verdict and the trace-plane <= 5%
    overhead verdict."""
    if rounds is None:
        rounds = 12 if common.smoke() else 40

    async def main():
        lock = await _lockstep(rounds, base_s, slow_s)
        # Best-of-N per mode: the workload is sleep-dominated, so
        # scheduling noise only ever DEPRESSES a measured rate — the max
        # over repeats is the low-variance estimator for both modes.
        rate = 0.0
        slow_rounds, counters = 0, {}
        for _ in range(max(1, repeats)):
            r, sr, cs = await _async_mode(
                rounds, base_s, slow_s, tau, deadline_s
            )
            if r > rate:
                rate, slow_rounds, counters = r, sr, cs
        traced = 0.0
        for _ in range(max(1, repeats)):
            r, _, _ = await _async_mode(
                rounds, base_s, slow_s, tau, deadline_s, trace=True
            )
            traced = max(traced, r)
        return lock, rate, slow_rounds, counters, traced

    lock, rate, slow_rounds, counters, traced = asyncio.run(
        asyncio.wait_for(main(), 600)
    )
    speedup = rate / lock
    trace_overhead_pct = (rate - traced) / rate * 100.0
    return common.emit(
        {
            "bench": "async_gossip_straggler",
            "lockstep_rounds_per_sec": lock,
            "async_rounds_per_sec": rate,
            "async_speedup": speedup,
            "gate": 2.0,
            "gate_passed": bool(speedup >= 2.0),
            "traced_rounds_per_sec": traced,
            "trace_overhead_pct": trace_overhead_pct,
            "trace_gate": 5.0,
            "trace_gate_passed": bool(trace_overhead_pct <= 5.0),
            "rounds": rounds,
            "straggler_rounds": slow_rounds,
            "staleness_bound": tau,
            "deadline_s": deadline_s,
            "base_compute_s": base_s,
            "straggler_compute_s": slow_s,
            **{f"counters.{k}": v for k, v in counters.items()},
        }
    )


if __name__ == "__main__":
    run()
