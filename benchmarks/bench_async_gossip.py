"""Straggler gate of the asynchronous gossip runtime (ISSUE 8).

Lock-step gossip runs at the pace of the slowest agent: with one of 4
loopback agents injected 10x slow, every ``run_once`` round costs the
straggler's compute time on ALL agents.  The async runtime
(``comm/async_runtime.py``) lets the fast agents mix the straggler's
last *received* state at staleness-decayed weight (bound tau, deadline-
bounded waits) and keep their own pace — the straggler costs its own
progress only.

Measured here on the real TCP loopback wire, compute injected as
``asyncio.sleep`` (base 5 ms, straggler 50 ms — sleep-dominated, so
shared-CI scheduling noise stays second order):

* ``lockstep_rounds_per_sec`` — ``run_once`` rounds, all 4 agents in
  lock step (each round waits for the straggler).
* ``async_rounds_per_sec`` — async rounds of the FAST agents
  (tau=2, deadline 10 ms): the straggler is mixed while its staleness
  is within bound, dropped-and-poked beyond it.

**Gate (acceptance): async >= 2x lock-step.**  Expected ~5-8x — the
fast agents' round time falls from ~the straggler's 50 ms to ~their own
5 ms.  The tier-1 rot guard in ``tests/test_benchmarks.py`` gates at
the same 2x (the margin is several-x, and both sides time the same
injected sleeps).  Also recorded: the straggler's own completed rounds
and the staleness counters (``comm.agent.async_stale_mixed`` /
``async_stale_dropped`` / ``pokes_sent``) — the observability the
convergence-vs-staleness analysis reads.

**Overlap gate (ISSUE 18): pipelined dispatch >= 1.3x serial.**  The
same straggler scenario repeats at a multi-MB value width under the
bf16 wire (so every received frame pays a real decode), once with
``AsyncGossipRunner(overlap=False)`` — serial decode-then-mix, frames
densified inline at dispatch on the shared event loop — and once with
``overlap=True`` — frames stay lazy and ``_mix_pipelined`` decodes the
next neighbor on an executor thread while the previous one is mixed.
``overlap_speedup`` (best-of-N both sides) carries the >= 1.3x verdict;
on a host without a second core for the decode worker
(``overlap_cpus < 2``) the ratio is recorded and the verdict is
``null`` — the hard gate belongs to the multi-core measurement host.

**Trace-plane gate (ISSUE 14): tracing ON costs <= 5% rounds/sec.**
The async measurement repeats with ``ConsensusAgent(trace=True)`` —
every frame stamped with a wire ``TraceContext`` and the full
encode/send/recv/decode/mix flow-event chain emitted per frame.  Both
modes take the best of ``repeats`` runs (noise pushes rates DOWN, so
max-of-N is the stable estimator for a sleep-dominated workload), and
``trace_overhead_pct`` must stay within ``trace_gate`` (5%).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Dict

import numpy as np

from benchmarks import common
from distributed_learning_tpu.comm import (
    AsyncGossipRunner,
    ConsensusAgent,
    ConsensusMaster,
)

RING4 = [("1", "2"), ("2", "3"), ("3", "4"), ("4", "1")]
TOKENS = ("1", "2", "3", "4")
SLOW = "4"
#: Overlap-gate scenario (ISSUE 18): a value width where per-frame bf16
#: decode is real work, compute scaled to match (still a 10x straggler)
#: and a deadline past the multi-MB frame transfer time — the gate
#: measures decode-on-the-loop vs decode-behind-compute, not deadline
#: stalls.  The >= 1.3x verdict needs a second core for the decode
#: worker to run ON (``run_in_executor`` + GIL-dropping decode): on a
#: 1-CPU host the speedup is recorded but the verdict is ``null`` —
#: same discipline as the full-width gates that need the TPU host.
OVERLAP_WIDTH = 1 << 22
OVERLAP_SMOKE_WIDTH = 1 << 21
OVERLAP_BASE_S = 0.002
OVERLAP_SLOW_S = 0.02
OVERLAP_DEADLINE_S = 0.02


async def _deploy(trace: bool = False, bf16: bool = False):
    master = ConsensusMaster(RING4, convergence_eps=1e-6)
    host, port = await master.start()
    agents = {
        t: ConsensusAgent(
            t, host, port, trace=trace, trace_run_id=14, bf16_wire=bf16
        )
        for t in TOKENS
    }
    await asyncio.gather(*(a.start() for a in agents.values()))
    return master, agents


async def _teardown(master, agents):
    await master.shutdown()
    for a in agents.values():
        await a.close(drain=0.1)


def _values(width: int = 64) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(8)
    return {t: rng.normal(size=width).astype(np.float32) for t in TOKENS}


async def _lockstep(rounds: int, base_s: float, slow_s: float) -> float:
    master, agents = await _deploy()
    vals = dict(_values())

    async def one(t):
        # Injected local compute, then the synchronous exchange: the
        # per-round barrier IS the lock-step model being measured —
        # every agent's round completes at the straggler's pace.
        await asyncio.sleep(slow_s if t == SLOW else base_s)
        vals[t] = await agents[t].run_once(vals[t])

    t0 = time.perf_counter()
    for _ in range(rounds):
        await asyncio.gather(*(one(t) for t in TOKENS))
    elapsed = time.perf_counter() - t0
    await _teardown(master, agents)
    return rounds / elapsed


async def _async_mode(
    rounds: int, base_s: float, slow_s: float,
    tau: int, deadline_s: float, trace: bool = False,
    overlap: bool = False, width: int = 64, bf16: bool = False,
):
    master, agents = await _deploy(trace=trace, bf16=bf16)
    runners = {
        t: AsyncGossipRunner(
            agents[t], staleness_bound=tau, deadline_s=deadline_s,
            overlap=overlap,
        )
        for t in TOKENS
    }
    vals = _values(width)
    stop = asyncio.Event()

    async def fast(t):
        x = vals[t]
        for _ in range(rounds):
            x = await runners[t].run_async_round(
                x, local=lambda: asyncio.sleep(base_s)
            )
        return x

    async def slow(t):
        x = vals[t]
        while not stop.is_set():
            x = await runners[t].run_async_round(
                x, local=lambda: asyncio.sleep(slow_s)
            )
        return x

    t0 = time.perf_counter()
    slow_task = asyncio.ensure_future(slow(SLOW))
    await asyncio.gather(*(fast(t) for t in TOKENS if t != SLOW))
    elapsed = time.perf_counter() - t0
    stop.set()
    await slow_task
    rate = rounds / elapsed
    counters = {
        name: sum(a.counters.get(name, 0) for a in agents.values())
        for name in (
            "async_stale_mixed", "async_stale_dropped",
            "async_deadline_drops", "pokes_sent",
        )
    }
    slow_rounds = runners[SLOW].round
    await _teardown(master, agents)
    return rate, slow_rounds, counters


def run(
    rounds: int | None = None,
    base_s: float = 0.005,
    slow_s: float = 0.05,
    tau: int = 2,
    deadline_s: float = 0.01,
    repeats: int = 2,
) -> dict:
    """Lock-step vs async rounds/sec with the 10x straggler; emits one
    record with the >= 2x gate verdict and the trace-plane <= 5%
    overhead verdict."""
    if rounds is None:
        rounds = 12 if common.smoke() else 40

    async def main():
        lock = await _lockstep(rounds, base_s, slow_s)
        # Best-of-N per mode: the workload is sleep-dominated, so
        # scheduling noise only ever DEPRESSES a measured rate — the max
        # over repeats is the low-variance estimator for both modes.
        rate = 0.0
        slow_rounds, counters = 0, {}
        for _ in range(max(1, repeats)):
            r, sr, cs = await _async_mode(
                rounds, base_s, slow_s, tau, deadline_s
            )
            if r > rate:
                rate, slow_rounds, counters = r, sr, cs
        traced = 0.0
        for _ in range(max(1, repeats)):
            r, _, _ = await _async_mode(
                rounds, base_s, slow_s, tau, deadline_s, trace=True
            )
            traced = max(traced, r)
        # Overlap gate (ISSUE 18): the same 10x-straggler scenario at a
        # width where decode is real work (bf16 wire, so every received
        # frame pays a convert), serial decode-then-mix
        # (``overlap=False``: frames densify inline at dispatch, on the
        # event loop) vs the pipelined loop (``overlap=True``: frames
        # stay lazy, ``_mix_pipelined`` decodes the next neighbor on an
        # executor thread while mixing the previous one).  All four
        # agents share this one event loop, so serial mode serializes
        # every decode in the deployment on it — exactly the cost the
        # pipelined loop takes off the critical path.
        o_width = OVERLAP_SMOKE_WIDTH if common.smoke() else OVERLAP_WIDTH
        o_rounds = max(8, rounds // 2) if common.smoke() else max(12, rounds)
        serial = overlapped = 0.0
        for _ in range(max(1, repeats)):
            r, _, _ = await _async_mode(
                o_rounds, OVERLAP_BASE_S, OVERLAP_SLOW_S, tau,
                OVERLAP_DEADLINE_S, width=o_width, bf16=True,
                overlap=False,
            )
            serial = max(serial, r)
            r, _, _ = await _async_mode(
                o_rounds, OVERLAP_BASE_S, OVERLAP_SLOW_S, tau,
                OVERLAP_DEADLINE_S, width=o_width, bf16=True,
                overlap=True,
            )
            overlapped = max(overlapped, r)
        return (
            lock, rate, slow_rounds, counters, traced,
            serial, overlapped, o_width, o_rounds,
        )

    (
        lock, rate, slow_rounds, counters, traced,
        serial, overlapped, o_width, o_rounds,
    ) = asyncio.run(asyncio.wait_for(main(), 600))
    speedup = rate / lock
    trace_overhead_pct = (rate - traced) / rate * 100.0
    return common.emit(
        {
            "bench": "async_gossip_straggler",
            "lockstep_rounds_per_sec": lock,
            "async_rounds_per_sec": rate,
            "async_speedup": speedup,
            "gate": 2.0,
            "gate_passed": bool(speedup >= 2.0),
            "traced_rounds_per_sec": traced,
            "trace_overhead_pct": trace_overhead_pct,
            "trace_gate": 5.0,
            "trace_gate_passed": bool(trace_overhead_pct <= 5.0),
            "overlap_width": o_width,
            "overlap_rounds": o_rounds,
            "overlap_cpus": os.cpu_count(),
            "serial_rounds_per_sec": serial,
            "overlapped_rounds_per_sec": overlapped,
            "overlap_speedup": overlapped / serial,
            "overlap_gate": 1.3,
            # Verdict only where the decode worker can physically run in
            # parallel; a 1-CPU harness records the ratio undecided.
            "overlap_gate_passed": (
                bool(overlapped / serial >= 1.3)
                if (os.cpu_count() or 1) >= 2 else None
            ),
            "rounds": rounds,
            "straggler_rounds": slow_rounds,
            "staleness_bound": tau,
            "deadline_s": deadline_s,
            "base_compute_s": base_s,
            "straggler_compute_s": slow_s,
            **{f"counters.{k}": v for k, v in counters.items()},
        }
    )


if __name__ == "__main__":
    run()
