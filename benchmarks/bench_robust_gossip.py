"""Robust-mixing cost and byzantine containment (ISSUE 13).

Two records:

* ``robust_mix_rounds_per_sec`` — the overhead of the robust estimators
  (adaptive clip / trimmed mean / coordinate median,
  ``parallel/robust.py``) over the plain fused ``ConsensusEngine.mix``
  on the same two-bucket (f32 + bf16) flat buffer: every variant runs
  ``times=rounds`` fused into one dispatch, so the ratio measures the
  device-side estimator cost, not host dispatch.

* ``robust_async_byzantine_honest_error`` — convergence of the
  stale-weighted async path (``mix_async_robust``) under a seeded
  persistent byzantine peer (agent ``n-1`` publishes a constant 1e3
  poison vector every round) versus the undefended ``mix_async``:
  plain weighted averaging has breakdown point zero, so the honest
  agents' error versus their own initial mean blows up to the poison
  scale; the clipped/trimmed runs contain it.  **Gate: defended error
  <= undefended / 50**, with the redirected-mass detection signal
  strictly positive.  The whole run is seed-deterministic
  (``np.random.default_rng``), matching the replayability contract of
  the fault harness (``comm/faults.py``).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.parallel.consensus import ConsensusEngine

SPECS = {
    "clip": {"kind": "clip", "radius": 2.0, "adaptive": True},
    "trim": {"kind": "trim", "trim": 1},
    "median": "median",
}


def _state(n: int, dim: int, seed: int = 0) -> Dict[str, jnp.ndarray]:
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32)),
        "h": jnp.asarray(
            rng.normal(size=(n, max(dim // 2, 1))).astype(np.float32)
        ).astype(jnp.bfloat16),
    }


def run_overhead(
    n: int = 8,
    dim: Optional[int] = None,
    rounds: Optional[int] = None,
    reps: int = 3,
) -> dict:
    """Rounds/sec of each robust estimator vs the plain fused mix."""
    if dim is None:
        dim = 1 << 12 if not common.full_scale() else 1 << 18
    if rounds is None:
        rounds = 20 if common.smoke() else 200
    eng = ConsensusEngine(Topology.complete(n).metropolis_weights())
    x = _state(n, dim)

    def timed(fn) -> float:
        common.sync(fn())  # warmup: compile outside the timed region
        best = float("inf")
        for _ in range(reps):
            with common.stopwatch() as t:
                common.sync(fn())
            best = min(best, t["s"])
        return rounds / best

    plain = timed(lambda: eng.mix(x, times=rounds))
    rates = {
        name: timed(lambda s=spec: eng.mix_robust(x, s, times=rounds)[0])
        for name, spec in SPECS.items()
    }
    return common.emit(
        {
            "metric": "robust_mix_rounds_per_sec",
            "value": rates["clip"],
            "unit": "rounds/s",
            "vs_baseline": None,
            "bench": "robust_gossip_overhead",
            "rounds_per_sec_plain": plain,
            **{f"rounds_per_sec_{k}": v for k, v in rates.items()},
            **{f"overhead_{k}": plain / v for k, v in rates.items()},
            "n_agents": n,
            "dim": dim,
            "rounds": rounds,
        }
    )


def run_byzantine(
    n: int = 8,
    dim: int = 256,
    iters: Optional[int] = None,
    poison: float = 1e3,
    seed: int = 0,
    gate: float = 50.0,
) -> dict:
    """Async honest-agent error under one byzantine peer, defended vs
    not; the defended runs must contain the error by ``gate``x."""
    if iters is None:
        iters = 60 if common.smoke() else 400
    liar = n - 1
    honest = [i for i in range(n) if i != liar]
    topo = Topology.complete(n).metropolis_weights()
    x0 = np.random.default_rng(seed).normal(size=(n, dim)).astype(np.float32)
    target = x0[honest].mean(axis=0)
    # One slow honest publisher + the liar: the straggler model the
    # async path exists for, so staleness decay is exercised too.
    periods = (1,) * (n - 2) + (2, 1)

    def mode(spec):
        eng = ConsensusEngine(topo)
        x, st, total = {"w": jnp.asarray(x0)}, None, 0.0
        for _ in range(iters):
            arr = np.array(x["w"])  # copy: jax buffers are read-only
            arr[liar] = poison  # constant poison vector, every round
            x = {"w": jnp.asarray(arr)}
            if spec is None:
                x, st = eng.mix_async(x, st, tau=2, periods=periods, times=1)
            else:
                x, st, mass = eng.mix_async_robust(
                    x, st, spec=spec, tau=2, periods=periods, times=1
                )
                total += float(mass)
        err = float(np.abs(np.asarray(x["w"])[honest] - target).max())
        return err, total

    un_err, _ = mode(None)
    cl_err, cl_mass = mode(SPECS["clip"])
    tr_err, tr_mass = mode(SPECS["trim"])
    contained = bool(cl_err <= un_err / gate and tr_err <= un_err / gate)
    return common.emit(
        {
            "metric": "robust_async_byzantine_honest_error",
            "value": cl_err,
            "unit": "max|x - honest_mean|",
            "vs_baseline": None,
            "bench": "robust_gossip_byzantine_async",
            "undefended_error": un_err,
            "clipped_error": cl_err,
            "trimmed_error": tr_err,
            "containment_clipped": un_err / cl_err,
            "containment_trimmed": un_err / tr_err,
            "redirected_mass_clipped": cl_mass,
            "redirected_mass_trimmed": tr_mass,
            "gate": gate,
            "gate_passed": contained,
            "iters": iters,
            "poison_scale": poison,
            "n_agents": n,
            "dim": dim,
            "seed": seed,
        }
    )


def run(**kwargs) -> dict:
    return {
        "overhead": run_overhead(
            **{k: v for k, v in kwargs.items() if k in ("n", "dim", "rounds")}
        ),
        "byzantine": run_byzantine(
            **{k: v for k, v in kwargs.items() if k in ("n", "iters", "seed")}
        ),
    }


if __name__ == "__main__":
    run()
