#!/usr/bin/env bash
# TPU measurement session: every record still pending after the tunnel
# wedges of rounds 2-3, highest-value first so a short healthy window
# still captures the top of the list.  Serialized (the tunneled chip is
# single-process); every stage runs under `timeout` so one wedge cannot
# eat the window.
#
#   bash benchmarks/tpu_session2.sh [outdir]
#
# Stages:
#   0.  60s liveness probe (tiny jit) — abort early on a dead tunnel
#   0b. bench.py — the HEADLINE number (driver-parity record; bench.py
#       has its own probe, provisional bank, and deadline so a wedge
#       mid-stage still leaves a record in the capture)
#   1.  flash-attention TFLOP/s, fwd + bwd, incl. the upstream
#       pallas-ops rival at the same shapes (the >= upstream bar)
#   2.  WRN profile ablations (+ a profiler trace with top-ops summary)
#   2c. LM training throughput (full vs flash) + decode (MHA vs GQA)
#   3.  WRN accuracy stage (synthetic stand-in unless DLT_CIFAR_DIR)
#   4.  compression rounds/bytes at the TPU-sized dim (incl. atopk)
#   5.  publish everything captured into BASELINE.json
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
OUT="${1:-benchmarks/results}"
mkdir -p "$OUT"
STAMP=$(date +%Y%m%d_%H%M%S)
CAPTURE="$OUT/session2_$STAMP.jsonl"

# Append $1's ["summary"] (or, with -last-line, its last stdout line) to
# the capture — ONE guarded implementation so a malformed file can never
# abort stage 5's publish (and fixes to the guard can't drift between
# stages).
emit_summary() {
  python - "$1" >>"$CAPTURE" <<'EOF' || true
import json, sys
rec = json.load(open(sys.argv[1]))["summary"]
assert "metric" in rec
print(json.dumps(rec))
EOF
}

echo "== stage 0: liveness probe" >&2
# Same probe bench.py runs (benchmarks/probe.py): seconds-cheap matmul
# with a host-copy sync, outcome appended to TPU_HEALTH.jsonl either
# way — wedge windows are dated in the ledger, not folklore.  The probe
# self-times; the outer timeout is only the belt to its suspenders.
if ! timeout 90 python -u -m benchmarks.probe --timeout 60; then
  echo "tunnel not alive; aborting session2" >&2
  exit 3
fi

echo "== stage 0b: headline gossip-SGD throughput (bench.py)" >&2
timeout 3900 python -u bench.py > "$OUT/bench_$STAMP.out" \
  2>"$OUT/bench_$STAMP.err" || echo "stage 0b rc=$?" >&2
# Append only a well-formed record: a garbage last line (e.g. a print
# cut mid-write by the timeout) would make stage 5's publish abort and
# lose the WHOLE session's records.
python - "$OUT/bench_$STAMP.out" >> "$CAPTURE" <<'EOF' || true
import json, sys
lines = [l for l in open(sys.argv[1]) if l.strip()]
rec = json.loads(lines[-1])
assert "metric" in rec
print(json.dumps(rec))
EOF

echo "== stage 1a: flash attention QUICK post-fix point" >&2
# One fwd+bwd record at the measured-best config in <=10 min: even if
# the tunnel wedges mid-sweep below, the post-fix kernel has a number.
BENCH_OUT="$CAPTURE" timeout 900 python -m benchmarks.run_attention_only \
  --quick 2>"$OUT/attention_quick_$STAMP.err" || echo "stage 1a rc=$?" >&2

echo "== stage 1: flash attention fwd+bwd TFLOP/s (+ upstream rival)" >&2
# 3600s: the rival pass adds up to 12 compile+measure runs at 8k/32k on
# top of the original sweep, and the 131k points are minutes each.
BENCH_OUT="$CAPTURE" timeout 3600 python -m benchmarks.run_attention_only \
  2>"$OUT/attention_$STAMP.err" || echo "stage 1 rc=$?" >&2

echo "== stage 2: WRN profile ablations" >&2
timeout 3600 python -m benchmarks.profile_wrn \
  2>"$OUT/profile_$STAMP.err" | tee -a "$OUT/profile_$STAMP.out" \
  || echo "stage 2 rc=$?" >&2
echo "== stage 2b: profiler trace + top-ops summary" >&2
timeout 1200 python -m benchmarks.profile_wrn --trace \
  2>>"$OUT/profile_$STAMP.err" | tee -a "$OUT/profile_$STAMP.out" \
  || echo "stage 2b rc=$?" >&2

echo "== stage 2c: LM training throughput (full vs flash attention)" >&2
BENCH_OUT="$CAPTURE" timeout 1800 python -m benchmarks.bench_lm \
  2>"$OUT/lm_$STAMP.err" || echo "stage 2c rc=$?" >&2

echo "== stage 3: WRN accuracy" >&2
ACC_JSON="$OUT/wrn_accuracy_$STAMP.json"
if timeout 4500 python -m benchmarks.train_wrn_accuracy --out "$ACC_JSON" \
  2>"$OUT/wrn_accuracy_$STAMP.err"; then
  emit_summary "$ACC_JSON"
else
  echo "stage 3 rc=$?" >&2
fi

if [ "${WRN_CIFAR100:-0}" = "1" ]; then
  echo "== stage 3b: WRN accuracy, cifar100 shape (reference's 2nd anchor)" >&2
  ACC100_JSON="$OUT/wrn_accuracy_cifar100_$STAMP.json"
  if timeout 4500 python -m benchmarks.train_wrn_accuracy \
    --dataset cifar100 --out "$ACC100_JSON" \
    2>"$OUT/wrn_accuracy100_$STAMP.err"; then
    emit_summary "$ACC100_JSON"
  else
    echo "stage 3b rc=$?" >&2
  fi
fi

echo "== stage 4: compression (TPU-sized, incl. atopk)" >&2
BENCH_OUT="$CAPTURE" timeout 1800 python -c \
  "from benchmarks import bench_compression; bench_compression.run()" \
  2>"$OUT/compression_$STAMP.err" || echo "stage 4 rc=$?" >&2

echo "== stage 5: publish" >&2
[ -s "$CAPTURE" ] && python -m benchmarks.publish "$CAPTURE"
echo "session2 artifacts in $OUT (stamp $STAMP)" >&2
