"""Shared utilities for the benchmark harness.

Each script in this package is one of the five target configurations from
the driver's ``BASELINE.json`` (``configs`` list).  Every script prints one
JSON line per recorded metric:

    {"metric": str, "value": float, "unit": str, "vs_baseline": float|null,
     "config": str, "platform": str, ...}

``vs_baseline`` is the ratio versus the corresponding recorded reference
number from ``BASELINE.md`` when one exists (>1.0 = better), else null.

Sizing: on TPU (or with ``BENCH_FULL=1``) the full problem sizes run; on CPU
each script shrinks to a smoke-test size so the whole harness stays runnable
anywhere (the CI smoke test uses ``BENCH_SMOKE=1`` for the smallest sizes).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np

__all__ = [
    "platform",
    "full_scale",
    "smoke",
    "emit",
    "stopwatch",
    "sync",
    "agent_mesh_or_none",
]


def platform() -> str:
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        with contextlib.suppress(Exception):
            jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def full_scale() -> bool:
    """Full problem sizes: on real TPU hardware or when forced."""
    if os.environ.get("BENCH_SMOKE") == "1":
        return False
    return platform() == "tpu" or os.environ.get("BENCH_FULL") == "1"


def smoke() -> bool:
    return os.environ.get("BENCH_SMOKE") == "1"


def emit(record: Dict[str, Any]) -> Dict[str, Any]:
    """Print one JSON metric line (and append to $BENCH_OUT if set).

    Every emitted metric also lands in the persistent perf ledger
    (``PERF_LEDGER.jsonl`` / ``$DLT_PERF_LEDGER``, ``obs/cost.py``) so
    ``obs-report --ledger`` renders the cross-session trend; the append
    is best-effort and cannot fail the benchmark."""
    record = dict(record)
    record.setdefault("platform", platform())
    line = json.dumps(record)
    print(line, flush=True)
    out = os.environ.get("BENCH_OUT")
    if out:
        with open(out, "a") as f:
            f.write(line + "\n")
    from distributed_learning_tpu.obs.cost import ledger_append

    ledger_append({
        "source": "benchmarks",
        "env": {"platform": record.get("platform")},
        **record,
    })
    return record


def sync(x) -> None:
    """Drain the device pipeline by host-copying one element of ``x``.

    The timing sync for every benchmark: ``jax.block_until_ready`` can
    return before execution drains on tunneled PJRT backends (measured on
    the axon-tunneled v5e: a 17 TFLOP step "completed" in 0.6 ms), which
    would silently time dispatch instead of execution.  A device->host
    copy cannot complete until the producing computation has.
    """
    for leaf in jax.tree.leaves(x):
        # Every leaf: independent dispatches would otherwise still be in
        # flight after the first leaf's copy lands.
        np.asarray(
            jax.device_get(leaf.ravel()[:1] if hasattr(leaf, "ravel") else leaf)
        )


@contextlib.contextmanager
def stopwatch() -> Iterator[Dict[str, float]]:
    """``with stopwatch() as t: ...; t['s']`` — wall seconds of the block."""
    box: Dict[str, float] = {}
    t0 = time.perf_counter()
    try:
        yield box
    finally:
        box["s"] = time.perf_counter() - t0


def agent_mesh_or_none(n: int):
    """An n-agent mesh when n devices exist, else None (dense fallback)."""
    from distributed_learning_tpu.parallel.consensus import make_agent_mesh

    if len(jax.devices()) >= n:
        return make_agent_mesh(n)
    return None
