"""Real-data non-IID accuracy evidence: label-skewed Titanic at matched
budgets.

The reference's accuracy anchors are IID-ish contiguous Titanic shards
(``notebooks/Titanic Consensus GD test.ipynb`` cells 14-15; the CIFAR
non-IID axis is environment-blocked — see BASELINE.md).  This benchmark
makes the decentralized claim on real data under the HARD sharding:
label-sorted shards (two agents see only survivors, two only casualties)
with every arm given the identical gradient budget and step schedule:

* **centralized** — GD on the union shard (the upper anchor);
* **isolated**    — each agent alone on its skewed shard (the damage);
* **gossip**      — per-step neighbor averaging on a ring (the claim:
  gossip recovers centralized-level accuracy from maximally non-IID
  shards);
* **dsgt**        — gradient tracking on the same ring (removes the
  constant-step heterogeneity bias, tracking the centralized *iterates*,
  not just the accuracy).

Emits one record per arm plus a per-iteration accuracy curve saved to
``benchmarks/results/titanic_noniid_curves.json`` — committed evidence,
re-generatable anywhere (CPU-scale data; the reference's own anchors are
CPU runs).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.data import load_titanic, split_data, titanic_source
from distributed_learning_tpu.models import logreg_loss
from distributed_learning_tpu.models.logreg import accuracy as logreg_accuracy
from distributed_learning_tpu.parallel import (
    GradientTrackingEngine,
    Topology,
)
from distributed_learning_tpu.parallel.consensus import ConsensusEngine

ALPHA, TAU = 0.5, 1e-2  # constant step: exposes the non-IID gossip bias
N_AGENTS = 4
REFERENCE_ACC = 0.7978  # the reference's recorded centralized/K4 anchor


def _label_skewed_shards(X, y, n):
    order = np.argsort(y, kind="stable")
    shards = split_data(X[order], y[order], n)
    m = min(len(s[0]) for s in shards.values())
    Xs = jnp.stack([jnp.asarray(shards[i][0][:m]) for i in range(n)])
    ys = jnp.stack([jnp.asarray(shards[i][1][:m], jnp.float32) for i in range(n)])
    return Xs, ys


def run(
    iters: int | None = None,
    eval_every: int | None = None,
    out_path: str | None = None,
):
    if iters is None:
        iters = 100 if common.smoke() else 3000
    if eval_every is None:
        eval_every = max(1, iters // 60)
    data_source = titanic_source()
    X_tr, y_tr, X_te, y_te = load_titanic()
    Xs, ys = _label_skewed_shards(X_tr, y_tr, N_AGENTS)
    dim = Xs.shape[-1]
    Xte = jnp.asarray(X_te)
    yte = jnp.asarray(y_te, jnp.float32)
    W = Topology.ring(N_AGENTS).metropolis_weights()
    engine = ConsensusEngine(W)
    Xall = Xs.reshape(-1, dim)
    yall = ys.reshape(-1)

    grad = jax.grad(logreg_loss)

    def centralized_chunk(w, k):
        return jax.lax.fori_loop(
            0, k, lambda i, w: w - ALPHA * grad(w, Xall, yall, TAU), w
        )

    vstep = jax.vmap(
        lambda w, X, y: w - ALPHA * grad(w, X, y, TAU), in_axes=(0, 0, 0)
    )

    def isolated_chunk(w, k):
        return jax.lax.fori_loop(0, k, lambda i, w: vstep(w, Xs, ys), w)

    def gossip_chunk(w, k):
        return jax.lax.fori_loop(
            0, k, lambda i, w: engine._dense_mix_once(vstep(w, Xs, ys)), w
        )

    dsgt = GradientTrackingEngine(
        W,
        lambda w, a, s: grad(w, Xs[a], ys[a], TAU),
        learning_rate=ALPHA,
    )

    jcent = jax.jit(centralized_chunk, static_argnums=1)
    jiso = jax.jit(isolated_chunk, static_argnums=1)
    jgos = jax.jit(gossip_chunk, static_argnums=1)

    w_cent = jnp.zeros((dim,))
    w_iso = jnp.zeros((N_AGENTS, dim))
    w_gos = jnp.zeros((N_AGENTS, dim))
    st_dsgt = dsgt.init(jnp.zeros((N_AGENTS, dim), jnp.float32))

    def acc1(w):
        return float(logreg_accuracy(w, Xte, yte))

    def acc_mean(ws):
        return float(np.mean([acc1(ws[a]) for a in range(N_AGENTS)]))

    curves = {"iters": [], "centralized": [], "isolated": [], "gossip": [],
              "dsgt": []}
    done = 0
    while done < iters:
        k = min(eval_every, iters - done)
        w_cent = jcent(w_cent, k)
        w_iso = jiso(w_iso, k)
        w_gos = jgos(w_gos, k)
        st_dsgt, _ = dsgt.run(st_dsgt, k)
        done += k
        curves["iters"].append(done)
        curves["centralized"].append(acc1(w_cent))
        curves["isolated"].append(acc_mean(w_iso))
        curves["gossip"].append(acc_mean(w_gos))
        curves["dsgt"].append(acc_mean(np.asarray(st_dsgt.x)))

    gossip_gap = float(np.abs(np.asarray(w_gos) - np.asarray(w_cent)[None]).max())
    dsgt_gap = float(
        np.abs(np.asarray(st_dsgt.x) - np.asarray(w_cent)[None]).max()
    )
    final = {k: v[-1] for k, v in curves.items() if k != "iters"}

    common.emit(
        {
            "metric": "titanic_noniid_gossip_test_accuracy",
            "value": round(final["gossip"], 4),
            "unit": "accuracy",
            "vs_baseline": round(final["gossip"] / REFERENCE_ACC, 4),
            "config": f"titanic-labelskew-ring{N_AGENTS}-alpha{ALPHA}",
            "data_source": data_source,
            "centralized": round(final["centralized"], 4),
            "isolated": round(final["isolated"], 4),
            "dsgt": round(final["dsgt"], 4),
            "iters": iters,
            "gossip_param_gap_vs_centralized": gossip_gap,
            "dsgt_param_gap_vs_centralized": dsgt_gap,
        }
    )

    if out_path is None:
        # The canonical filename is committed real-data full-scale
        # evidence (cited by BASELINE.md); a smoke run or a synthetic
        # fallback must never overwrite it, so those land in a
        # disambiguated sibling instead.
        canonical = data_source.startswith("real:") and iters >= 3000
        name = (
            "titanic_noniid_curves.json" if canonical
            else f"titanic_noniid_curves_{'real' if data_source.startswith('real:') else 'synthetic'}_{iters}it.json"
        )
        out_path = os.path.join(os.path.dirname(__file__), "results", name)
    out = out_path
    record = {
        "description": (
            "Label-sorted (maximally non-IID) Titanic shards, 4 agents, "
            "ring graph, constant alpha — all arms at the identical "
            "gradient budget; test accuracy per evaluation point"
        ),
        "alpha": ALPHA,
        "tau": TAU,
        "iters": iters,
        "data_source": data_source,
        "platform": common.platform(),
        "curves": curves,
        "final": final,
        "reference_anchor": REFERENCE_ACC,
    }
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"curves -> {out}", flush=True)
    return record


if __name__ == "__main__":
    run()
