"""BASELINE config 5: CIFAR-100 WRN, time-varying random graph +
Chebyshev-accelerated averaging.

Every epoch resamples a connected G(n, p) graph; mixing runs through the
engine's traced-W path (no recompilation per graph) with the Chebyshev
semi-iteration schedule computed host-side from that epoch's gamma.

Reference anchor: CIFAR-100 WRN-28-10 single-node, 100 epochs, 4h11m35s on
a Tesla P100 = 331.7 samples/sec (``CIFAR_100_Baseline.ipynb`` cell 9).
The second record isolates the Chebyshev benefit: rounds-to-1e-4 residual
with and without acceleration over the same sequence of random graphs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.data import load_cifar, normalize, shard_dataset
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.parallel.consensus import ConsensusEngine
from distributed_learning_tpu.parallel.schedule import chebyshev_omegas
from distributed_learning_tpu.parallel.topology import gamma as exact_gamma
from distributed_learning_tpu.training import MasterNode

P100_SAMPLES_PER_SEC = 100 * 50_000 / 15_095.0  # BASELINE.md wall-clock


def run(
    n_agents: int | None = None,
    depth: int | None = None,
    widen: int | None = None,
    batch_size: int | None = None,
    epochs: int = 2,
    edge_p: float = 0.4,
):
    full = common.full_scale()
    n_agents = n_agents or (8 if full else (2 if common.smoke() else 4))
    depth = depth or (28 if full else 10)
    widen = widen or (10 if full else 1)
    batch_size = batch_size or (128 if full else 8)
    n_train = 50_000 if full else (256 if common.smoke() else 1024)

    (X, y), (Xt, yt) = load_cifar("cifar100")
    X, y = X[:n_train], y[:n_train]
    Xt, yt = Xt[:256], yt[:256]
    Xn = np.asarray(normalize(jnp.asarray(X), dataset="cifar100"))
    Xtn = np.asarray(normalize(jnp.asarray(Xt), dataset="cifar100"))
    names = list(range(n_agents))
    shards = shard_dataset(Xn, y, names, batch_size=batch_size, seed=0)

    def schedule(epoch: int) -> Topology:
        return Topology.erdos_renyi(n_agents, edge_p, seed=1000 + epoch)

    master = MasterNode(
        node_names=names,
        model="wide-resnet",
        model_args=[100],
        model_kwargs={
            "depth": depth,
            "widen_factor": widen,
            "dropout_rate": 0.3,
            "dtype": jnp.bfloat16,
        },
        optimizer="sgd",
        optimizer_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
        learning_rate=0.1,
        error="cross_entropy",
        train_loaders=shards,
        test_loader=(Xtn, yt),
        stat_step=100,
        epoch=epochs + 1,
        epoch_cons_num=1,
        batch_size=batch_size,
        mix_times=4,
        topology_schedule=schedule,
        chebyshev=True,
        mesh=common.agent_mesh_or_none(n_agents),
    )
    master.initialize_nodes()
    master.train_epoch()  # compile + warm
    with common.stopwatch() as t:
        outs = [master.train_epoch() for _ in range(epochs)]
    samples = n_agents * master.epoch_len * batch_size * epochs
    sps = samples / t["s"]
    common.emit(
        {
            "metric": f"cifar100_wrn{depth}x{widen}_timevarying_cheby_throughput",
            "value": round(sps, 2),
            "unit": "samples/sec",
            "vs_baseline": round(sps / P100_SAMPLES_PER_SEC, 3)
            if (depth, widen) == (28, 10)
            else None,
            "config": "cifar100-wrn-timevarying-chebyshev",
            "n_agents": n_agents,
            "consensus_residual": float(outs[-1]["deviation"]),
        }
    )

    # Isolate the averaging acceleration: same random-graph sequence, plain
    # vs Chebyshev mixing on a synthetic divergent state.
    engine = ConsensusEngine(Topology.ring(n_agents).metropolis_weights())
    rng = np.random.default_rng(0)
    dim = 1 << 16 if full else 1 << 12
    x0 = jnp.asarray(rng.normal(size=(n_agents, dim)).astype(np.float32))
    k_per_graph = 3
    target = 1e-4

    def rounds_to_target(cheby: bool) -> int:
        x = x0
        for e in range(200):
            W = schedule(e).metropolis_weights()
            if cheby:
                om = chebyshev_omegas(exact_gamma(W), k_per_graph)
                x = engine.mix_chebyshev_with(x, W, om)
            else:
                x = engine.mix_with(x, W, times=k_per_graph)
            if float(engine.max_deviation(x)) < target:
                return (e + 1) * k_per_graph
        return 200 * k_per_graph

    plain = rounds_to_target(False)
    cheby = rounds_to_target(True)
    common.emit(
        {
            "metric": "timevarying_chebyshev_round_reduction",
            "value": round(plain / max(cheby, 1), 3),
            "unit": "x fewer rounds",
            "vs_baseline": None,
            "config": "cifar100-wrn-timevarying-chebyshev",
            "rounds_plain": plain,
            "rounds_chebyshev": cheby,
            "target_residual": target,
        }
    )
    return {
        "samples_per_sec": sps,
        "rounds_plain": plain,
        "rounds_chebyshev": cheby,
    }


if __name__ == "__main__":
    run()
