"""BASELINE config 3: CIFAR-10 ``ann_model`` gossip-SGD, 8 workers, 2D torus.

Reference scenario: the torch MLP (``networks/ann_model.py``) trained with
the (missing) ``MasterNode`` gossip driver — ``Man_Colab.ipynb`` cell 21
documents the surface; no wall-clock was ever recorded for it.  Here the
same workflow runs through :class:`MasterNode`: 8 nodes on a 2x4 torus,
local epoch then gossip, all under jit.

Metrics: steady-state training throughput (samples/sec over all agents) and
the post-epoch consensus residual.
"""

from __future__ import annotations

import jax
import numpy as np

from benchmarks import common
from distributed_learning_tpu.data import normalize, shard_dataset, load_cifar
from distributed_learning_tpu.data.cifar import real_cifar_present
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.training import MasterNode

import jax.numpy as jnp


def run(
    n_agents: int = 8,
    batch_size: int | None = None,
    epochs: int = 2,
    n_train: int | None = None,
):
    full = common.full_scale()
    if batch_size is None:
        batch_size = 128 if full else (16 if common.smoke() else 64)
    if n_train is None:
        n_train = 50_000 if full else (512 if common.smoke() else 4096)
    (X, y), (Xt, yt) = load_cifar("cifar10")
    X, y = X[:n_train], y[:n_train]
    Xt, yt = Xt[: max(n_train // 8, 128)], yt[: max(n_train // 8, 128)]
    Xn = np.asarray(normalize(jnp.asarray(X)))
    Xtn = np.asarray(normalize(jnp.asarray(Xt)))
    names = list(range(n_agents))
    shards = shard_dataset(Xn, y, names, batch_size=batch_size, seed=0)

    master = MasterNode(
        node_names=names,
        model="ann",
        model_args=[10],
        model_kwargs={"hidden_dim": 512},
        optimizer="sgd",
        optimizer_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
        learning_rate=0.05,
        error="cross_entropy",
        weights=Topology.torus2d(2, n_agents // 2),
        train_loaders=shards,
        test_loader=(Xtn, yt),
        stat_step=50,
        epoch=epochs + 1,
        epoch_cons_num=1,
        batch_size=batch_size,
        mix_times=2,
        mesh=common.agent_mesh_or_none(n_agents),
        dropout=False,
    )
    master.initialize_nodes()
    first = master.train_epoch()  # compile + warm
    with common.stopwatch() as t:
        outs = [master.train_epoch() for _ in range(epochs)]
    samples = n_agents * master.epoch_len * batch_size * epochs
    sps = samples / t["s"]
    final = outs[-1]
    common.emit(
        {
            "metric": "cifar10_ann_gossip_sgd_throughput",
            "value": round(sps, 2),
            "unit": "samples/sec",
            # No reference wall-clock exists for this config (the driver is
            # absent from the reference snapshot).
            "vs_baseline": None,
            "config": "cifar10-ann-torus8",
            "n_agents": n_agents,
            "batch_size": batch_size,
            "consensus_residual": float(final["deviation"]),
            "mean_test_acc": None
            if final["test_acc"] is None
            else round(float(np.mean(final["test_acc"])), 4),
            # Accuracy is only meaningful as a CIFAR number on real data;
            # the zero-egress environment falls back to the learnable
            # synthetic stand-in, which this field discloses.
            "data_source": "real-cifar10" if real_cifar_present("cifar10")
            else "synthetic-stand-in",
        }
    )
    return {"samples_per_sec": sps, "final": final, "first": first}


if __name__ == "__main__":
    run()
