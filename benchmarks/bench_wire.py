"""Native wire engine vs the Python codec: measured bytes/sec.

ISSUE 9's acceptance benchmark.  PR 8's async runtime made the TCP
stack the fleet's hot path, and the last Python stage on it was the
frame codec: every fused sparse frame round-tripped through a per-bucket
numpy pipeline (arange/concatenate positions, gather, flatnonzero,
gather again, convert, ``tobytes``, join) in
``comm/tensor_codec.py``, while ``native/codec.cpp`` only accelerated
the element-wise conversions.  The native wire engine
(``native/wire.cpp``) collapses a whole frame to one call — two linear
passes for encode (measure, then fused gather+convert+crc into an
exact-size buffer) and validate-then-scatter for decode.

Measured here, native vs the pure-Python oracle (the ``DLT_NO_NATIVE=1``
fallback, forced per call), at FULL MODEL WIDTH (the WRN-28-10 ravel,
~36.5M elements) on TPU/BENCH_FULL and a smoke width on CI:

* fused-sparse encode and decode bytes/sec (frame bytes moved per wall
  second) at the nominal 10% top-k density — the per-round gossip frame;
* dense encode and decode bytes/sec under the bf16 wire mode — the
  dense ``ValueResponse`` path;
* the combined fused encode+decode speedup, gated >= 5x at full width
  by ISSUE 9 (the tier-1 rot guard in ``tests/test_benchmarks.py``
  gates a looser 2x at smoke width so CI timing noise cannot flake);
* ISSUE 18 per-lever attribution for the zero-copy receive path:
  alloc-per-frame decode vs ``decode(out=scratch)``
  (``scratch_decode_speedup``), the production native+scratch decode vs
  the Python codec (``zero_copy_decode_speedup`` — full width >= 3x,
  smoke-width tier-1 gate >= 2x on decode alone), densify-then-add vs
  the fused ``decode_apply`` scatter (``apply_vs_densify_speedup``),
  and the two-thread decode ∥ mix microbench (``overlap_speedup``).

Byte-identity is asserted in-run: the native frame must equal the
Python oracle's frame bit for bit, both directions — a fast wrong codec
is worthless.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import numpy as np

from benchmarks import common
from distributed_learning_tpu.comm import tensor_codec as tc
from distributed_learning_tpu.native import wire as native_wire

#: WRN-28-10's parameter count — "full model width" for this repo's
#: headline model (bench.py).
FULL_WIDTH = 36_479_194
SMOKE_WIDTH = 1 << 19
#: CHOCO's nominal top-k fraction (the density bench.py accounts wire
#: bytes at).
DENSITY = 0.1


def _model_ravel(total: int, leaves: int = 64, seed: int = 7):
    """A model-shaped (flat, buckets) pair: ``leaves`` spans of varying
    sizes tiling the ravel, alternating bf16/f32 storage origin — the
    shape ``TreeSpec.dtype_buckets()`` produces for a real mixed tree."""
    rng = np.random.default_rng(seed)
    cuts = np.sort(rng.choice(np.arange(1, total), leaves - 1, replace=False))
    bounds = np.concatenate([[0], cuts, [total]])
    bf16_spans, f32_spans = [], []
    for i in range(leaves):
        span = (int(bounds[i]), int(bounds[i + 1] - bounds[i]))
        (bf16_spans if i % 4 == 3 else f32_spans).append(span)
    buckets = (
        ("bfloat16", tuple(bf16_spans)),
        ("float32", tuple(f32_spans)),
    )
    flat = rng.normal(size=total).astype(np.float32)
    flat[rng.random(total) >= DENSITY] = 0.0
    return flat, buckets


def _timed(fn, *, min_s: float = 0.3, max_reps: int = 50) -> float:
    """Seconds per call: one warmup, then enough reps to fill ~min_s."""
    fn()
    t0 = time.perf_counter()
    fn()
    once = max(time.perf_counter() - t0, 1e-9)
    reps = max(1, min(max_reps, int(min_s / once)))
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


class _forced_python:
    """Force the pure-Python codec path (the DLT_NO_NATIVE discipline,
    honored per call by the dispatcher)."""

    def __enter__(self):
        self._prev = os.environ.get("DLT_NO_NATIVE")
        os.environ["DLT_NO_NATIVE"] = "1"

    def __exit__(self, *exc):
        if self._prev is None:
            os.environ.pop("DLT_NO_NATIVE", None)
        else:
            os.environ["DLT_NO_NATIVE"] = self._prev


def _measure_fused(flat, buckets) -> Dict[str, float]:
    frame = tc.encode_fused_sparse(flat, buckets, bf16_wire=True)
    # Per-lever attribution (ISSUE 18): alloc-per-frame decode vs decode
    # into a pinned scratch ravel (lever 1), and densify-then-add vs the
    # fused in-place scatter (lever 2).  The repeated apply/add targets
    # only accumulate ~reps * 0.5 * |x| — no overflow at _timed's caps.
    scratch = np.empty(flat.size, np.float32)
    target = np.zeros(flat.size, np.float32)
    enc = lambda: tc.encode_fused_sparse(flat, buckets, bf16_wire=True)
    dec = lambda: tc.decode_fused_sparse(frame)
    dec_out = lambda: tc.decode_fused_sparse(frame, out=scratch)
    apply_ = lambda: tc.decode_fused_apply(frame, target, scale=0.5)

    def densify_add():
        np.add(
            target, np.float32(0.5) * tc.decode_fused_sparse(frame),
            out=target,
        )

    t_enc = _timed(enc)
    t_dec = _timed(dec)
    t_dec_out = _timed(dec_out)
    t_apply = _timed(apply_)
    t_densify_add = _timed(densify_add)
    return {
        "frame_bytes": float(len(frame)),
        "encode_s": t_enc,
        "decode_s": t_dec,
        "decode_out_s": t_dec_out,
        "apply_s": t_apply,
        "densify_add_s": t_densify_add,
        "encode_bytes_per_sec": len(frame) / t_enc,
        "decode_bytes_per_sec": len(frame) / t_dec,
        "decode_out_bytes_per_sec": len(frame) / t_dec_out,
        "apply_bytes_per_sec": len(frame) / t_apply,
        "roundtrip_bytes_per_sec": 2 * len(frame) / (t_enc + t_dec),
    }


def _measure_dense(flat) -> Dict[str, float]:
    frame = tc.encode_tensor(flat, bf16_wire=True)
    scratch = np.empty(flat.size, np.float32)
    enc = lambda: tc.encode_tensor(flat, bf16_wire=True)
    dec = lambda: tc.decode_tensor(frame)
    dec_out = lambda: tc.decode_tensor(frame, out=scratch)
    t_enc = _timed(enc)
    t_dec = _timed(dec)
    t_dec_out = _timed(dec_out)
    return {
        "frame_bytes": float(len(frame)),
        "decode_s": t_dec,
        "decode_out_s": t_dec_out,
        "encode_bytes_per_sec": len(frame) / t_enc,
        "decode_bytes_per_sec": len(frame) / t_dec,
        "decode_out_bytes_per_sec": len(frame) / t_dec_out,
        "roundtrip_bytes_per_sec": 2 * len(frame) / (t_enc + t_dec),
    }


def _measure_overlap(frame, total: int) -> Dict[str, float]:
    """Lever 3 microbench: decode-into-scratch on a worker thread while
    the caller runs a memory-bound mix step (the ``_mix_pipelined``
    shape) vs the same two steps back to back.  Both the native decode
    (a ctypes call) and numpy's f32 ufunc loops drop the GIL, so the
    ideal overlapped time is max(decode, mix), not their sum."""
    from concurrent.futures import ThreadPoolExecutor

    scratch = np.empty(total, np.float32)
    y = np.zeros(total, np.float32)
    x = np.ones(total, np.float32)
    dec = lambda: tc.decode_fused_sparse(frame, out=scratch)
    mix = lambda: np.add(y, x, out=y)
    t_dec = _timed(dec)
    t_mix = _timed(mix)
    pool = ThreadPoolExecutor(max_workers=1)

    def both():
        fut = pool.submit(dec)
        mix()
        fut.result()

    t_both = _timed(both)
    pool.shutdown()
    return {
        "decode_s": t_dec,
        "mix_s": t_mix,
        "serial_s": t_dec + t_mix,
        "overlapped_s": t_both,
        "overlap_speedup": (t_dec + t_mix) / t_both,
    }


def run(total: Optional[int] = None) -> dict:
    if total is None:
        total = FULL_WIDTH if common.full_scale() else SMOKE_WIDTH
    flat, buckets = _model_ravel(total)
    native_up = native_wire.available()
    out: dict = {
        "total_elems": total,
        "density": DENSITY,
        "native": native_up,
        "fused": {},
        "dense": {},
    }

    # Byte-identity first: a fast wrong codec is worthless.  The oracle
    # (forced-Python) frame must equal the native frame bit for bit, and
    # each side must decode the other's bytes to the same ravel.
    with _forced_python():
        frame_py = tc.encode_fused_sparse(flat, buckets, bf16_wire=True)
        dense_py = tc.encode_tensor(flat, bf16_wire=True)
    frame_nat = tc.encode_fused_sparse(flat, buckets, bf16_wire=True)
    dense_nat = tc.encode_tensor(flat, bf16_wire=True)
    out["fused"]["byte_identical"] = frame_nat == frame_py
    out["dense"]["byte_identical"] = dense_nat == dense_py
    with _forced_python():
        ravel_py = tc.decode_fused_sparse(frame_nat)
    identical_decode = bool(
        np.array_equal(
            tc.decode_fused_sparse(frame_py), ravel_py, equal_nan=True
        )
    )
    out["fused"]["decode_identical"] = identical_decode
    # Zero-copy levers must preserve the same identity: decode into a
    # DIRTY scratch (stale bytes must never leak into untouched
    # positions) and the fused scatter-add vs decode-then-add.
    dirty = np.full(total, np.float32(np.nan))
    out["fused"]["decode_out_identical"] = bool(
        np.array_equal(
            tc.decode_fused_sparse(frame_nat, out=dirty), ravel_py,
            equal_nan=True,
        )
    )
    base = np.arange(total, dtype=np.float32)
    applied = base.copy()
    tc.decode_fused_apply(frame_nat, applied, scale=0.5)
    with _forced_python():
        applied_py = base.copy()
        tc.decode_fused_apply(frame_nat, applied_py, scale=0.5)
    out["fused"]["apply_identical"] = bool(
        np.array_equal(applied, applied_py, equal_nan=True)
    )

    with _forced_python():
        fused_py = _measure_fused(flat, buckets)
        dense_py_m = _measure_dense(flat)
    if native_up:
        fused_nat = _measure_fused(flat, buckets)
        dense_nat_m = _measure_dense(flat)
    else:
        fused_nat, dense_nat_m = fused_py, dense_py_m

    for section, nat, py in (
        ("fused", fused_nat, fused_py),
        ("dense", dense_nat_m, dense_py_m),
    ):
        out[section].update(
            frame_bytes=nat["frame_bytes"],
            encode_bytes_per_sec=nat["encode_bytes_per_sec"],
            decode_bytes_per_sec=nat["decode_bytes_per_sec"],
            decode_out_bytes_per_sec=nat["decode_out_bytes_per_sec"],
            roundtrip_bytes_per_sec=nat["roundtrip_bytes_per_sec"],
            python_encode_bytes_per_sec=py["encode_bytes_per_sec"],
            python_decode_bytes_per_sec=py["decode_bytes_per_sec"],
            encode_speedup=(
                nat["encode_bytes_per_sec"] / py["encode_bytes_per_sec"]
            ),
            decode_speedup=(
                nat["decode_bytes_per_sec"] / py["decode_bytes_per_sec"]
            ),
            # Lever 1 attribution: alloc-per-frame vs pinned scratch on
            # the SAME engine, and the production receive path (native,
            # out=) vs the Python codec — the ISSUE 18 decode gate.
            scratch_decode_speedup=nat["decode_s"] / nat["decode_out_s"],
            zero_copy_decode_speedup=(
                nat["decode_out_bytes_per_sec"] / py["decode_bytes_per_sec"]
            ),
            roundtrip_speedup=(
                nat["roundtrip_bytes_per_sec"] / py["roundtrip_bytes_per_sec"]
            ),
        )
    # Lever 2 attribution: the fused in-place scatter vs densify-then-add
    # (native side; the python column is the oracle's own apply rate).
    out["fused"].update(
        apply_bytes_per_sec=fused_nat["apply_bytes_per_sec"],
        apply_vs_densify_speedup=(
            fused_nat["densify_add_s"] / fused_nat["apply_s"]
        ),
        python_apply_bytes_per_sec=fused_py["apply_bytes_per_sec"],
    )
    # Lever 3 attribution: decode ∥ mix on two threads vs back to back.
    out["overlap"] = _measure_overlap(frame_nat, total)

    for section in ("fused", "dense"):
        s = out[section]
        common.emit({
            "metric": f"wire_{section}_roundtrip_bytes_per_sec",
            "value": round(s["roundtrip_bytes_per_sec"], 1),
            "unit": "bytes/sec",
            "vs_baseline": None,
            "config": (
                f"{total} elems, density {DENSITY}, bf16 wire, "
                f"native={native_up}"
            ),
            "native": native_up,
            "byte_identical": s["byte_identical"],
            "encode_bytes_per_sec": round(s["encode_bytes_per_sec"], 1),
            "decode_bytes_per_sec": round(s["decode_bytes_per_sec"], 1),
            "python_encode_bytes_per_sec": round(
                s["python_encode_bytes_per_sec"], 1
            ),
            "python_decode_bytes_per_sec": round(
                s["python_decode_bytes_per_sec"], 1
            ),
            "speedup_vs_python": round(s["roundtrip_speedup"], 2),
            "encode_speedup": round(s["encode_speedup"], 2),
            "decode_speedup": round(s["decode_speedup"], 2),
            # ISSUE 18 per-lever attribution columns.
            "decode_out_bytes_per_sec": round(
                s["decode_out_bytes_per_sec"], 1
            ),
            "scratch_decode_speedup": round(s["scratch_decode_speedup"], 2),
            "zero_copy_decode_speedup": round(
                s["zero_copy_decode_speedup"], 2
            ),
            **(
                {
                    "decode_out_identical": s["decode_out_identical"],
                    "apply_identical": s["apply_identical"],
                    "apply_bytes_per_sec": round(s["apply_bytes_per_sec"], 1),
                    "apply_vs_densify_speedup": round(
                        s["apply_vs_densify_speedup"], 2
                    ),
                    "python_apply_bytes_per_sec": round(
                        s["python_apply_bytes_per_sec"], 1
                    ),
                    "overlap_speedup": round(
                        out["overlap"]["overlap_speedup"], 2
                    ),
                    "overlap_serial_s": round(out["overlap"]["serial_s"], 6),
                    "overlap_overlapped_s": round(
                        out["overlap"]["overlapped_s"], 6
                    ),
                }
                if section == "fused"
                else {}
            ),
        })
    return out


if __name__ == "__main__":
    run()
