"""Merge benchmark JSONL output into ``BASELINE.json:"published"``.

The harness scripts emit one JSON object per metric (``common.emit``; set
``BENCH_OUT=path`` to capture them).  This tool folds such a capture into
the repo's ``BASELINE.json`` so the judge-facing record and the raw run
stay in sync:

    BENCH_OUT=/tmp/bench.jsonl python -m benchmarks.run_all
    python -m benchmarks.publish /tmp/bench.jsonl

Each record must carry ``metric``; the published key is
``<metric>[__<qualifier>]`` where an optional ``publish_key`` in the record
overrides the metric name.  Records with ``value: null`` (skipped configs)
are dropped.  Existing entries for the same key are overwritten — the
latest measurement wins — and every merged entry is stamped with the
source file (``common.emit`` records already carry their run platform,
which passes through untouched).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List


def load_records(path: str) -> List[Dict[str, Any]]:
    records = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                raise SystemExit(f"{path}:{line_no}: not JSON: {exc}")
            if not isinstance(rec, dict) or "metric" not in rec:
                raise SystemExit(
                    f"{path}:{line_no}: record needs a 'metric' field"
                )
            records.append(rec)
    return records


def merge(baseline: Dict[str, Any], records: List[Dict[str, Any]], *,
          source: str) -> Dict[str, Any]:
    published = baseline.setdefault("published", {})
    merged = 0
    for rec in records:
        if rec.get("value") is None:
            continue  # skipped config (e.g. needs-TPU on a CPU run)
        key = rec.get("publish_key") or rec["metric"]
        entry = {k: v for k, v in rec.items() if k not in ("metric", "publish_key")}
        entry["source"] = source
        published[key] = entry
        merged += 1
    return {"merged": merged, "total": len(records)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl", help="BENCH_OUT capture to merge")
    ap.add_argument(
        "--baseline", default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BASELINE.json",
        ),
    )
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args(argv)

    records = load_records(args.jsonl)
    with open(args.baseline) as f:
        baseline = json.load(f)
    stats = merge(baseline, records, source=os.path.basename(args.jsonl))
    if args.dry_run:
        print(json.dumps(baseline["published"], indent=1))
    else:
        tmp = args.baseline + ".tmp"
        with open(tmp, "w") as f:
            json.dump(baseline, f, indent=1)
            f.write("\n")
        os.replace(tmp, args.baseline)
    print(
        f"merged {stats['merged']}/{stats['total']} records into "
        f"{args.baseline}{' (dry run)' if args.dry_run else ''}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
