"""Run only the flash-attention benchmark (fwd + bwd TFLOP/s).

Split out of ``run_all`` so the recovery session can put the kernels'
first on-chip validation ahead of the longer stages.
"""

from benchmarks import bench_attention

if __name__ == "__main__":
    bench_attention.run()
