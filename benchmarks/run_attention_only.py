"""Run only the flash-attention benchmark (fwd + bwd TFLOP/s).

Split out of ``run_all`` so the recovery session can put the kernels'
first on-chip validation ahead of the longer stages.  ``--quick`` runs
the single post-fix point (``bench_attention.quick``) instead of the
full sweep — the <=10-minute record for short healthy windows.
"""

import sys

from benchmarks import bench_attention

if __name__ == "__main__":
    if "--quick" in sys.argv[1:]:
        bench_attention.quick()
    else:
        bench_attention.run()
