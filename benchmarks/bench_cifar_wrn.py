"""BASELINE config 4: CIFAR-10 WRN-28-10 gossip-SGD on a v5e-8 ring.

The reference's only recorded wall-clock for this model is the *single
node* torch run: WRN-28-10, 100 CIFAR-10 epochs, 8h18m07s on a Tesla T4 =
167.3 samples/sec (``CIFAR_10_Baseline.ipynb`` cell 9).  Its gossip driver
for this model is absent from the snapshot, so the centralized number is
the anchor; our run additionally pays for gossip every epoch, which only
handicaps the comparison.

Also records the north-star residual metric: after an epoch of divergent
local SGD, how many gossip rounds until the consensus residual < 1e-4
(BASELINE.json: "<= 1e-4 consensus residual ... in <= 200 rounds").

On non-TPU hosts the model shrinks (depth/widen/agents) so the script runs
anywhere; the recorded headline number is the TPU configuration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.data import load_cifar, normalize, shard_dataset
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.training import MasterNode

T4_SAMPLES_PER_SEC = 100 * 50_000 / 29_887.0  # BASELINE.md wall-clock


def run(
    n_agents: int | None = None,
    depth: int | None = None,
    widen: int | None = None,
    batch_size: int | None = None,
    epochs: int = 1,
):
    full = common.full_scale()
    n_agents = n_agents or (8 if full else (2 if common.smoke() else 4))
    depth = depth or (28 if full else 10)
    widen = widen or (10 if full else 1)
    batch_size = batch_size or (128 if full else 8)
    n_train = 50_000 if full else (256 if common.smoke() else 1024)

    (X, y), (Xt, yt) = load_cifar("cifar10")
    X, y = X[:n_train], y[:n_train]
    Xt, yt = Xt[:256], yt[:256]
    Xn = np.asarray(normalize(jnp.asarray(X)))
    Xtn = np.asarray(normalize(jnp.asarray(Xt)))
    names = list(range(n_agents))
    shards = shard_dataset(Xn, y, names, batch_size=batch_size, seed=0)

    master = MasterNode(
        node_names=names,
        model="wide-resnet",
        model_args=[10],
        model_kwargs={
            "depth": depth,
            "widen_factor": widen,
            "dropout_rate": 0.3,
            "dtype": jnp.bfloat16,
        },
        optimizer="sgd",
        optimizer_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
        learning_rate=0.1,
        error="cross_entropy",
        weights=Topology.ring(n_agents),
        train_loaders=shards,
        test_loader=(Xtn, yt),
        stat_step=100,
        epoch=epochs + 1,
        epoch_cons_num=1,
        batch_size=batch_size,
        mix_times=1,
        mesh=common.agent_mesh_or_none(n_agents),
    )
    master.initialize_nodes()
    master.train_epoch()  # compile + warm
    with common.stopwatch() as t:
        outs = [master.train_epoch() for _ in range(epochs)]
    samples = n_agents * master.epoch_len * batch_size * epochs
    sps = samples / t["s"]
    n_chips = max(len(set(jax.devices())), 1) if common.platform() == "tpu" else 1
    common.emit(
        {
            "metric": f"cifar10_wrn{depth}x{widen}_gossip_sgd_throughput",
            "value": round(sps, 2),
            "unit": "samples/sec",
            "vs_baseline": round(sps / T4_SAMPLES_PER_SEC, 3)
            if (depth, widen) == (28, 10)
            else None,
            "config": "cifar10-wrn-ring",
            "n_agents": n_agents,
            "batch_size": batch_size,
            "samples_per_sec_per_chip": round(sps / n_chips, 2),
            "consensus_residual": float(outs[-1]["deviation"]),
        }
    )

    # North-star: rounds to 1e-4 residual from post-local-SGD divergence.
    # Re-run one epoch without mixing to get genuinely divergent replicas.
    master2 = MasterNode(
        node_names=names,
        model="wide-resnet",
        model_args=[10],
        model_kwargs={
            "depth": depth,
            "widen_factor": widen,
            "dropout_rate": 0.3,
            "dtype": jnp.bfloat16,
        },
        optimizer="sgd",
        optimizer_kwargs={"momentum": 0.9},
        learning_rate=0.1,
        error="cross_entropy",
        weights=Topology.ring(n_agents),
        train_loaders=shards,
        stat_step=100,
        epoch=2,
        epoch_cons_num=10**9,  # never mix during the epoch
        batch_size=batch_size,
        mesh=common.agent_mesh_or_none(n_agents),
    )
    master2.initialize_nodes()
    master2.train_epoch()
    params = master2.state[0]
    r0 = float(master2.engine.max_deviation(params))
    _, rounds, res = master2.engine.mix_until(params, eps=1e-4, max_rounds=500)
    common.emit(
        {
            "metric": "cifar10_wrn_rounds_to_1e-4_residual",
            "value": int(rounds),
            "unit": "rounds",
            "vs_baseline": round(200.0 / max(int(rounds), 1), 3),  # target <= 200
            "config": "cifar10-wrn-ring",
            "initial_residual": r0,
            "final_residual": float(res),
        }
    )
    return {"samples_per_sec": sps, "rounds_to_residual": int(rounds)}


if __name__ == "__main__":
    run()
