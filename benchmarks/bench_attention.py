"""Flash-attention kernel throughput: TFLOP/s at 8k/32k/131k tokens.

Substantiates the Pallas kernel's performance on the real chip
(``ops/flash_attention.py``): for each context length, sweeps
(block_q, block_k) and reports the best configuration's sustained TFLOP/s.
Causal FLOPs are counted as 4*B*H*T^2*D/2 (two matmuls, two FLOPs per MAC,
half the score matrix live).

The reference has no attention anywhere (SURVEY.md §5: "long-context /
sequence parallelism entirely absent"), so ``vs_baseline`` is null; the
yardstick is fraction of the chip's bf16 peak (~197 TFLOP/s on v5e).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.common import emit, full_scale, platform, smoke, sync

V5E_BF16_PEAK_TFLOPS = 197.0


def _time(fn, iters: int) -> float:
    """Shared compile/warm/measure protocol: one compile call, one warm
    call, then ``iters`` timed calls synced by a host copy.  Both our
    kernel and the upstream rival go through THIS function so the
    ours/upstream ratio can never be skewed by protocol drift."""
    out = fn()
    sync(out)  # compile
    out = fn()
    sync(out)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    sync(out)
    return (time.perf_counter() - t0) / iters


def _qkv(T: int, B: int, H: int, D: int, *, heads_second: bool):
    """bf16 inputs from the shared seed — drawn once in our (B, T, H, D)
    layout and TRANSPOSED for upstream's (B, H, T, D), so both kernels
    see the same values and an output cross-check stays meaningful."""
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.normal(size=(B, T, H, D)).astype(np.float32),
        dtype=jnp.bfloat16,
    )
    q, k, v = mk(), mk(), mk()
    if heads_second:
        q, k, v = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    return q, k, v


def _measure(
    T: int, block_q: int, block_k: int, *, B=1, H=8, D=128, iters=8,
    interpret=False, backward=False, window=None,
):
    from distributed_learning_tpu.ops.flash_attention import flash_attention

    q, k, v = _qkv(T, B, H, D, heads_second=False)
    if backward:
        # Forward (with lse) + all three backward kernels via custom_vjp.
        grad_fn = jax.jit(jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=block_q, block_k=block_k,
                interpret=interpret, window=window,
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        ))
        fn = lambda: grad_fn(q, k, v)[0]
    else:
        fn = lambda: flash_attention(
            q, k, v, causal=True, block_q=block_q, block_k=block_k,
            window=window,
            interpret=interpret,
        )
    dt = _time(fn, iters)
    if window is None:
        live_pairs = T * T / 2  # causal triangle
    else:
        W = min(window, T)
        live_pairs = W * (W + 1) / 2 + (T - W) * W  # causal band
    fwd_flops = 4 * B * H * D * live_pairs
    # USEFUL-FLOPs convention (the standard flash accounting): backward =
    # 2.5x forward (5 gradient matmuls vs 2), plus the lse-producing
    # forward, = 3.5x.  The kernels EXECUTE more than that — the split
    # into dQ and dK/dV kernels recomputes scores and dP in both, ~9
    # matmuls per block pair — so true MXU utilization is ~20-25% above
    # the reported fraction; the reported number is comparable across
    # implementations precisely because it counts algorithmic work.
    flops = fwd_flops * (1 + 2.5) if backward else fwd_flops
    return flops / dt / 1e12, dt


def _measure_upstream(T: int, *, B=1, H=8, D=128, iters=8, backward=False,
                      blocks=None):
    """Same-shape rival: ``jax.experimental.pallas.ops.tpu.flash_attention``
    (the upstream TPU kernel shipped in site-packages), measured with the
    identical FLOPs accounting.  Its layout is (B, H, T, D) and its
    default sm_scale is 1.0, so the shared inputs are transposed and the
    1/sqrt(D) scale passed explicitly — same values, same function."""
    from jax.experimental.pallas.ops.tpu import flash_attention as upstream

    q, k, v = _qkv(T, B, H, D, heads_second=True)
    bs = None
    if blocks is not None:
        bq, bk = blocks
        bs = upstream.BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
            block_q_dq=bq,
        )
    sm = 1.0 / (D ** 0.5)
    if backward:
        grad_fn = jax.jit(jax.grad(
            lambda q, k, v: upstream.flash_attention(
                q, k, v, causal=True, sm_scale=sm, block_sizes=bs
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        ))
        fn = lambda: grad_fn(q, k, v)[0]
    else:
        fn = jax.jit(lambda: upstream.flash_attention(
            q, k, v, causal=True, sm_scale=sm, block_sizes=bs
        ))
    dt = _time(fn, iters)
    fwd_flops = 4 * B * H * D * (T * T / 2)
    flops = fwd_flops * 3.5 if backward else fwd_flops
    return flops / dt / 1e12, dt


def _rival_pass(T: int, iters: int, ours_best, ours_grad) -> None:
    """Measure the upstream kernel at the same shapes and emit the
    side-by-side records VERDICT asks for (ours >= upstream is the bar)."""
    for tag, backward, ours in (("fwd", False, ours_best),
                                ("grad", True, ours_grad)):
        best = None
        for blocks in (None, (256, 512), (512, 512)):
            if blocks is not None and (T % blocks[0] or T % blocks[1]):
                continue
            try:
                tflops, dt = _measure_upstream(
                    T, iters=iters, backward=backward, blocks=blocks
                )
            except Exception as e:
                emit({
                    "metric": f"upstream_flash_{tag}_T{T}_"
                              f"{'default' if blocks is None else 'x'.join(map(str, blocks))}",
                    "value": None,
                    "unit": "TFLOP/s",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {str(e)[:120]}",
                })
                continue
            if best is None or tflops > best[0]:
                best = (tflops, blocks, dt)
        if best is None:
            continue
        rec = {
            "metric": f"upstream_flash_{tag}_T{T}_best",
            "value": round(best[0], 2),
            "unit": "TFLOP/s",
            "vs_baseline": None,
            "config": "jax.experimental.pallas.ops.tpu.flash_attention, "
                      f"blocks={best[1] or 'default(128)'}",
            "seconds_per_call": round(best[2], 4),
        }
        if ours is not None:
            rec["ours_over_upstream"] = round(ours / best[0], 3)
        emit(rec)


def quick() -> None:
    """ONE post-fix forward point (plus the bwd if time allows the
    second compile) at the measured-best config — sized so a ~10-minute
    healthy tunnel window still yields a post-fix TFLOP/s record before
    the full sweep (VERDICT r4 next-#5; ``tpu_session2.sh`` stage 1a).
    Off-TPU this smoke-runs tiny interpreted shapes like ``run``."""
    on_tpu = platform() == "tpu"
    if not on_tpu and not smoke():
        return
    interpret = not on_tpu
    T, bq, bk, iters = (32768, 256, 512, 4) if on_tpu else (256, 128, 128, 1)
    for backward in (False, True):
        name = "grad_" if backward else ""
        try:
            tflops, dt = _measure(T, bq, bk, iters=iters,
                                  interpret=interpret, backward=backward)
        except Exception as e:
            emit({
                "metric": f"flash_attention_quick_{name}T{T}",
                "value": None,
                "unit": "TFLOP/s",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {str(e)[:120]}",
            })
            continue
        emit({
            "metric": f"flash_attention_quick_{name}T{T}",
            "value": round(tflops, 2),
            "unit": "TFLOP/s",
            "vs_baseline": None,
            "config": f"B1 H8 D128 bf16, block_q={bq} block_k={bk}, "
                      "post-native-dtype-fix quick point",
            "seconds_per_call": round(dt, 4),
            "fraction_of_v5e_peak": round(tflops / V5E_BF16_PEAK_TFLOPS, 3),
        })


def run() -> None:
    on_tpu = platform() == "tpu"
    if not on_tpu and not smoke():
        emit({
            "metric": "flash_attention_tflops",
            "value": None,
            "unit": "TFLOP/s",
            "vs_baseline": None,
            "config": "skipped: needs a TPU (kernel falls back off-chip)",
        })
        return
    # Off-TPU smoke runs the real kernel under interpret=True (tiny sizes;
    # without it flash_attention would silently time the einsum fallback).
    interpret = not on_tpu
    if on_tpu and full_scale():
        lengths = [8192, 32768, 131072]
        blocks = [(128, 128), (128, 256), (256, 256), (256, 512), (512, 512)]
        iters = 8
    else:
        lengths = [256]
        blocks = [(128, 128), (128, 256)]
        iters = 1
    for T in lengths:
        best = None
        for bq, bk in blocks:
            if T % bq or T % bk:
                continue
            if T >= 131072 and min(bq, bk) < 256:
                # O(T^2) at 131k: the small-block points are minutes of
                # chip time each and have never won any sweep (block
                # 512/512 won at every measured T) — spend the window on
                # configurations that can.
                continue
            try:
                tflops, dt = _measure(T, bq, bk, iters=iters,
                                      interpret=interpret)
            except Exception as e:  # OOM/VMEM overflow at big blocks
                emit({
                    "metric": f"flash_attention_{T}_bq{bq}_bk{bk}",
                    "value": None,
                    "unit": "TFLOP/s",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {str(e)[:120]}",
                })
                continue
            emit({
                "metric": f"flash_attention_{T}_bq{bq}_bk{bk}",
                "value": round(tflops, 2),
                "unit": "TFLOP/s",
                "vs_baseline": None,
                "seconds_per_call": round(dt, 4),
            })
            if best is None or tflops > best[0]:
                best = (tflops, bq, bk)
        if best is not None:
            emit({
                "metric": f"flash_attention_causal_T{T}_best",
                "value": round(best[0], 2),
                "unit": "TFLOP/s",
                "vs_baseline": None,
                "config": f"B1 H8 D128 bf16, block_q={best[1]} block_k={best[2]}",
                "fraction_of_v5e_peak": round(best[0] / V5E_BF16_PEAK_TFLOPS, 3),
            })
            # Training step (fwd-with-lse + dQ + dK/dV kernels) at the
            # best forward block configuration.
            grad_tflops = None
            try:
                tflops, dt = _measure(T, best[1], best[2], iters=iters,
                                      interpret=interpret, backward=True)
            except Exception as e:
                emit({
                    "metric": f"flash_attention_grad_T{T}",
                    "value": None,
                    "unit": "TFLOP/s",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {str(e)[:120]}",
                })
            else:
                grad_tflops = tflops
                emit({
                    "metric": f"flash_attention_grad_T{T}",
                    "value": round(tflops, 2),
                    "unit": "TFLOP/s",
                    "vs_baseline": None,
                    "config": f"B1 H8 D128 bf16 fwd+bwd, block_q={best[1]} "
                              f"block_k={best[2]}",
                    "seconds_per_call": round(dt, 4),
                    "fraction_of_v5e_peak": round(
                        tflops / V5E_BF16_PEAK_TFLOPS, 3
                    ),
                })
            if on_tpu and full_scale() and T <= 32768:
                # Upstream rival at the same shapes (131k skipped: the
                # upstream kernel's all-T backward at 131k is many
                # minutes of chip time; the VERDICT bar names 8k/32k).
                _rival_pass(T, iters, best[0], grad_tflops)

    # Sliding-window long context: the O(T * W) path that makes 131k+
    # affordable.  One record (tiny interpreted sizes off-TPU, so the
    # path stays rot-guarded by the smoke test).
    if on_tpu and full_scale():
        Tw, W, bq, bk = 131072, 4096, 256, 512
    else:
        Tw, W, bq, bk = 256, 64, 128, 128
    try:
        tflops, dt = _measure(Tw, bq, bk, iters=iters, window=W,
                              interpret=interpret)
    except Exception as e:
        emit({
            "metric": f"flash_attention_window{W}_T{Tw}",
            "value": None,
            "unit": "TFLOP/s",
            "vs_baseline": None,
            "error": f"{type(e).__name__}: {str(e)[:120]}",
        })
    else:
        emit({
            "metric": f"flash_attention_window{W}_T{Tw}",
            "value": round(tflops, 2),
            "unit": "TFLOP/s",
            "vs_baseline": None,
            "config": (
                f"B1 H8 D128 bf16, sliding window {W}, "
                f"block_q={bq} block_k={bk}"
            ),
            "seconds_per_call": round(dt, 4),
            "fraction_of_v5e_peak": round(
                tflops / V5E_BF16_PEAK_TFLOPS, 3
            ),
        })


if __name__ == "__main__":
    run()
