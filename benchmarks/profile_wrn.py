"""Profile the WRN gossip-SGD epoch on hardware: trace + ablations.

Round-2 verdict: at ~50% of the counted roofline, batch tuning is
exhausted — the next lever must come from a measurement.  Two
instruments, both driving ``bench.py``'s own harness
(:func:`bench.measure_throughput`), so what is profiled is exactly the
shipped epoch program:

1. ``jax.profiler`` trace (``--trace``): a TensorBoard/xprof-loadable
   device timeline under ``benchmarks/results/profile_<stamp>/``.
2. Timed ablations (default): re-measure throughput with one element
   removed or altered at a time.  The throughput delta attributes the
   cost of each element without needing trace parsing:

   - ``baseline``      the shipped configuration as-is
   - ``no_mix``        skip the per-epoch gossip round
   - ``no_dropout``    dropout_rate=0 (removes RNG + mask apply)
   - ``no_weight_decay`` drop the decoupled weight-decay chain link
   - ``unroll1/4``     scan unroll factor (shipped: 2)
   - ``remat``         rematerialized backward (HBM for FLOPs trade)
   - ``pregather``     one big batch gather before the scan (vs per-step)
   - ``f32_conv``      params/compute in f32 (quantifies the bf16 win)

Usage (serialized on the tunneled chip — never concurrently with other
TPU work):

    python -m benchmarks.profile_wrn                 # ablations
    python -m benchmarks.profile_wrn --trace         # profiler trace
    BENCH_AGENTS=2 BENCH_BATCH=512 ...               # same knobs as bench.py

Each ablation prints one JSON line; a summary table lands in
``benchmarks/results/profile_ablations_<stamp>.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax

jax.config.update(
    "jax_default_prng_impl", os.environ.get("BENCH_PRNG", "rbg")
)

import jax.numpy as jnp
import optax

import bench
from distributed_learning_tpu.models import WideResNet
from distributed_learning_tpu.parallel.consensus import ConsensusEngine
from distributed_learning_tpu.parallel.topology import Topology


def _measure_config(
    *,
    n_agents: int,
    batch: int,
    steps: int,
    epochs: int,
    depth: int = 28,
    widen: int = 10,
    dropout: float = 0.3,
    mix: bool = True,
    weight_decay: bool = True,
    unroll: int = 2,
    remat: bool = False,
    pregather: bool = False,
    dtype=jnp.bfloat16,
    trace_dir: str | None = None,
) -> float:
    model = WideResNet(
        depth=depth, widen_factor=widen, dropout_rate=dropout,
        num_classes=10, dtype=dtype,
    )
    links = [optax.sgd(0.1, momentum=0.9)]
    if weight_decay:
        links.insert(0, optax.add_decayed_weights(5e-4))
    tx = optax.chain(*links)
    engine = ConsensusEngine(Topology.ring(n_agents).metropolis_weights())
    return bench.measure_throughput(
        model, tx, engine, n_agents=n_agents, batch=batch, steps=steps,
        epochs=epochs, unroll=unroll, remat=remat, mix=mix,
        pregather=pregather, trace_dir=trace_dir,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", action="store_true",
                    help="capture a jax.profiler trace of the baseline config")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of ablation names")
    args = ap.parse_args()

    base = dict(
        n_agents=int(os.environ.get("BENCH_AGENTS", 4)),
        batch=int(os.environ.get("BENCH_BATCH", 256)),
        steps=int(os.environ.get("BENCH_STEPS", 16)),
        epochs=int(os.environ.get("BENCH_EPOCHS", 3)),
        depth=int(os.environ.get("BENCH_DEPTH", 28)),
        widen=int(os.environ.get("BENCH_WIDEN", 10)),
    )

    stamp = time.strftime("%Y%m%d_%H%M%S")
    outdir = os.path.join(os.path.dirname(__file__), "results")
    os.makedirs(outdir, exist_ok=True)

    if args.trace:
        trace_dir = os.path.join(outdir, f"profile_{stamp}")
        sps = _measure_config(**base, trace_dir=trace_dir)
        rec = {
            "metric": "profile_trace", "samples_per_sec": round(sps, 1),
            "trace_dir": trace_dir,
        }
        try:
            from distributed_learning_tpu.utils.profiling import (
                format_trace_summary, summarize_trace,
            )
            rows = summarize_trace(trace_dir, top=20)
            rec["top_ops"] = rows
            # Persist the computed table BEFORE the cosmetic print: a
            # formatting hiccup must not discard the summary artifact.
            with open(os.path.join(outdir,
                                   f"profile_summary_{stamp}.json"), "w") as f:
                json.dump(rec, f, indent=1)
            print(format_trace_summary(rows))
        except Exception as exc:  # missing xprof / empty trace: keep the dir
            rec["summary_error"] = f"{type(exc).__name__}: {exc}"
        print(json.dumps({k: v for k, v in rec.items() if k != "top_ops"}))
        return

    ablations: dict[str, dict] = {
        "baseline": {},
        "no_mix": {"mix": False},
        "no_dropout": {"dropout": 0.0},
        "no_weight_decay": {"weight_decay": False},
        "unroll1": {"unroll": 1},
        "unroll4": {"unroll": 4},
        "remat": {"remat": True},
        "pregather": {"pregather": True},
        "f32_conv": {"dtype": jnp.float32},
    }
    if args.only:
        keep = set(args.only.split(","))
        ablations = {k: v for k, v in ablations.items() if k in keep}

    results = {}
    for name, overrides in ablations.items():
        try:
            sps = _measure_config(**{**base, **overrides})
        except Exception as exc:
            results[name] = {"error": f"{type(exc).__name__}: {str(exc)[:160]}"}
            print(json.dumps({"ablation": name, **results[name]}), flush=True)
            continue
        results[name] = {"samples_per_sec": round(sps, 1)}
        rec = {"ablation": name, **results[name]}
        if "baseline" in results and name != "baseline" \
                and "samples_per_sec" in results["baseline"]:
            rec["delta_vs_baseline_pct"] = round(
                100.0 * (sps / results["baseline"]["samples_per_sec"] - 1), 2
            )
        print(json.dumps(rec), flush=True)

    out = os.path.join(outdir, f"profile_ablations_{stamp}.json")
    with open(out, "w") as f:
        json.dump({
            "config": {**base, "prng": os.environ.get("BENCH_PRNG", "rbg"),
                       "platform": jax.devices()[0].platform},
            "results": results,
        }, f, indent=1)
    print(json.dumps({"written": out}))


if __name__ == "__main__":
    main()
