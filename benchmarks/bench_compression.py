"""Compressed vs dense gossip: rounds-to-residual and wire bytes.

Beyond the five BASELINE configs: quantifies the CHOCO-GOSSIP trade
(``parallel/compression.py``) on WRN-sized parameter vectors — how many
extra rounds compressed consensus needs to hit the 1e-4 north-star
residual, and how many fewer bytes per round cross the links.  Wire bytes
are computed with the real codec sizes (``comm/tensor_codec``): dense
bf16 = 2 B/entry; sparse = 6 B/non-zero (u32 index + bf16 value).

Hardware-independent math metrics (like the fast-averaging config): the
recorded numbers come from the 8-virtual-device CPU mesh / dense engine
and are identical on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.common import emit, full_scale, smoke
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.parallel.compression import (
    ChocoGossipEngine,
    approx_top_k,
    top_k,
)

TARGET = 1e-4  # BASELINE.json north-star consensus residual


def run() -> None:
    n = 8
    # Full-scale dim sized for TPU wall-clock: exact top-k is a sort, and
    # a 65k sort per agent per round made the original full-scale choice
    # take the better part of an hour on the chip for zero extra insight.
    # 16k keeps the vectors WRN-block-sized; the atopk case below shows
    # the hardware-aware escape hatch at the same dim.
    dim = 16_384 if full_scale() else (256 if smoke() else 2_048)
    W = Topology.ring(n).metropolis_weights()
    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    x0 = x0 / float(jnp.abs(x0).max())  # residual starts O(1)

    # Dense gossip reference: rounds to target via the consensus engine.
    from distributed_learning_tpu.parallel.consensus import ConsensusEngine

    eng = ConsensusEngine(W)
    _, rounds_dense, res = eng.mix_until(x0, eps=TARGET, max_rounds=10_000)
    rounds_dense = int(rounds_dense)
    if float(res) >= TARGET:
        raise SystemExit(
            f"dense baseline failed to reach {TARGET} in {rounds_dense} "
            "rounds; byte-ratio comparisons would be fictitious"
        )
    dense_bytes_per_round = 2 * dim  # bf16 per directed edge message

    # (label, compressor factory, fraction, gamma); the atopk case is the
    # TPU-native approximate selection (lax.approx_max_k) at the identical
    # fraction — same bytes, cheaper selection, marginally smaller delta.
    cases = [("topk", top_k, 0.1, 0.2)]
    if not smoke():
        cases += [
            ("topk", top_k, 0.01, 0.02),
            ("atopk", approx_top_k, 0.1, 0.2),
        ]
    for label, factory, fraction, gamma in cases:
        choco = ChocoGossipEngine(W, factory(fraction), gamma=gamma)
        state = choco.init(x0)
        rounds, chunk = 0, 200
        reached = False
        last_res = float("inf")
        while rounds < 60_000:
            state, r = choco.run(state, chunk)
            trace = np.asarray(r)
            below = np.flatnonzero(trace < TARGET)
            if below.size:
                # Exact crossing round inside this chunk.
                rounds += int(below[0]) + 1
                last_res = float(trace[below[0]])
                reached = True
                break
            rounds += chunk
            last_res = float(trace[-1])
        k = max(1, int(round(fraction * dim)))
        sparse_bytes_per_round = 6 * k
        emit({
            "metric": f"choco_{label}{fraction}_rounds_to_{TARGET}",
            "value": rounds if reached else None,
            "unit": "rounds",
            "vs_baseline": None,
            "config": f"ring-{n}, dim {dim}, gamma {gamma}; dense gossip "
                      f"needs {rounds_dense} rounds",
            "publish_key": f"choco_{label}{fraction}_ring8",
            "rounds_dense": rounds_dense,
            "bytes_per_round_sparse": sparse_bytes_per_round,
            "bytes_per_round_dense": dense_bytes_per_round,
            "byte_reduction": round(dense_bytes_per_round / sparse_bytes_per_round, 1),
            "total_bytes_ratio_vs_dense": (
                round(
                    (rounds * sparse_bytes_per_round)
                    / (rounds_dense * dense_bytes_per_round),
                    3,
                )
                if reached
                else None
            ),
            "final_residual": last_res,
        })


if __name__ == "__main__":
    run()
