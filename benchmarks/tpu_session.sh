#!/usr/bin/env bash
# One serialized TPU measurement session (the tunneled chip is single-
# process: never run two of these stages concurrently).
#
#   bash benchmarks/tpu_session.sh [outdir]
#
# Stages:
#   1. headline bench.py at the shipped configuration
#   2. the five BASELINE configs + flash-attention TFLOP/s (run_all)
#   3. WRN-28-10 training-to-accuracy (synthetic stand-in when no real
#      CIFAR at $DLT_CIFAR_DIR) — the long stage, ~30-60 min
#   4. fold stages 1-3 into BASELINE.json:"published"
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
OUT="${1:-benchmarks/results}"
mkdir -p "$OUT"
STAMP=$(date +%Y%m%d_%H%M%S)
CAPTURE="$OUT/session_$STAMP.jsonl"

echo "== stage 1: headline bench" >&2
if python bench.py 2>"$OUT/bench_$STAMP.err" | tee "$OUT/bench_$STAMP.json"; then
  cat "$OUT/bench_$STAMP.json" >>"$CAPTURE"   # one JSON metric line
else
  echo "stage 1 (bench.py) FAILED rc=$? — see $OUT/bench_$STAMP.err" >&2
fi

echo "== stage 2: five configs + attention" >&2
BENCH_OUT="$CAPTURE" python -m benchmarks.run_all \
  2>"$OUT/run_all_$STAMP.err" || echo "stage 2 (run_all) rc=$?" >&2

echo "== stage 3: WRN accuracy" >&2
ACC_JSON="$OUT/wrn_accuracy_$STAMP.json"
if python -m benchmarks.train_wrn_accuracy --out "$ACC_JSON" \
  2>"$OUT/wrn_accuracy_$STAMP.err"; then
  # Lift the summary record into the capture so it publishes too.
  python - "$ACC_JSON" >>"$CAPTURE" <<'EOF'
import json, sys
print(json.dumps(json.load(open(sys.argv[1]))["summary"]))
EOF
else
  echo "stage 3 (accuracy) rc=$?" >&2
fi

echo "== stage 4: publish" >&2
python -m benchmarks.publish "$CAPTURE"
echo "session artifacts in $OUT (stamp $STAMP)" >&2
