"""BASELINE config 2: synthetic-vector consensus, ring + Metropolis W.

Three measurements:

1. Gossip throughput & convergence — N agents each hold a large random
   vector; gossip until the max deviation drops below 1e-4.  Records
   rounds-to-1e-4 (the BASELINE.json north-star residual) and gossip
   rounds/sec on both engine paths (dense MXU matmul; sharded ppermute when
   a big-enough device mesh exists).

2. Fused flat-buffer consensus — a model-shaped MANY-LEAF stack (the
   WRN-like regime of ~100 leaves where per-op overhead dominates):
   gossip rounds/sec with the fused ``(N, P)``-per-dtype layout
   (``fused=True``, the default) versus the per-leaf oracle
   (``fused=False``), plus the per-round byte volume.  The fused path
   collapses O(leaves) skinny GEMMs/collectives per round into
   O(dtype-buckets).

3. Fastest-mixing weight solve — the 25-node Watts-Strogatz graph timed in
   ``Fast Averaging.ipynb`` cell 4 at 176 ms wall (cvxpy SDP).  Our
   projected-spectral solver is timed on the same graph;
   ``vs_baseline`` = reference_time / our_time (>1 = faster).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.ops import mixing as mixing_ops
from distributed_learning_tpu.parallel import Topology, solve_fastest_mixing
from distributed_learning_tpu.parallel.consensus import ConsensusEngine

SDP_REFERENCE_S = 0.176  # Fast Averaging.ipynb cell 4 (%time wall)


def _model_shaped_stack(n_agents: int, leaves: int, width: int) -> dict:
    """A stacked pytree with ``leaves`` small mixed-shape leaves (w/b
    pairs of varying fan-in) — the many-leaf regime the fused layout
    targets, as opposed to measurement 1's single fat vector.  Leaf sizes
    sit in the bias/norm-scale/small-conv range where per-op overhead,
    not bandwidth, dominates a gossip round (the WRN tail: of its ~100
    leaves all but a handful are this size)."""
    rng = np.random.default_rng(7)
    tree = {}
    for i in range(leaves // 2):
        d = width + (i % 7)
        tree[f"layer{i:03d}"] = {
            "w": jnp.asarray(
                rng.normal(size=(n_agents, d, 4)).astype(np.float32)
            ),
            "b": jnp.asarray(
                rng.normal(size=(n_agents, 4)).astype(np.float32)
            ),
        }
    return tree


def run_fused_vs_perleaf(
    n_agents: int = 8, leaves: int = 64, rounds: int | None = None
) -> dict:
    """Measurement 2: fused vs per-leaf gossip rounds/sec on a many-leaf
    tree; returns ``{"fused": rps, "perleaf": rps, "speedup": x}``."""
    if rounds is None:
        # Enough rounds that the per-call fixed cost (dispatch, spans) is
        # amortized and the per-ROUND cost — what fusion changes — is
        # what the clock sees; still well under a second on 1 CPU core.
        rounds = 500
    width = 16 if common.smoke() else 64
    W = Topology.ring(n_agents).metropolis_weights()
    x = _model_shaped_stack(n_agents, leaves, width)
    layout = mixing_ops.fused_layout(x)
    out = {}
    for mode, fused in (("fused", True), ("perleaf", False)):
        engine = ConsensusEngine(W, fused=fused)
        xs = engine.shard(x)
        warm = engine.mix(xs, times=2)
        common.sync(warm)
        best = 0.0
        for _ in range(3):  # best-of-3: rounds are ~ms-scale on CPU
            with common.stopwatch() as t:
                mixed = engine.mix(xs, times=rounds)
                common.sync(mixed)
            best = max(best, rounds / t["s"])
        out[mode] = best
    out["speedup"] = out["fused"] / out["perleaf"]
    common.emit(
        {
            "metric": "consensus_fused_rounds_per_sec",
            "value": round(out["fused"], 2),
            "unit": "rounds/sec",
            "vs_baseline": None,
            "config": "fast-averaging-ring-metropolis",
            "rounds_per_sec_perleaf": round(out["perleaf"], 2),
            "speedup_vs_perleaf": round(out["speedup"], 3),
            "leaf_count": layout.leaf_count,
            "fused_buckets": layout.bucket_count,
            "bytes_mixed_per_round": layout.bytes_per_round(n_agents),
            "rounds_timed": rounds,
            "n_agents": n_agents,
        }
    )
    return out


def run(n_agents: int = 8, dim: int | None = None, eps: float = 1e-4):
    if dim is None:
        dim = 1 << 22 if common.full_scale() else (1 << 12 if common.smoke() else 1 << 16)
    topo = Topology.ring(n_agents)
    W = topo.metropolis_weights()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_agents, dim)).astype(np.float32))

    results = {}
    modes = [("dense", None)]
    mesh = common.agent_mesh_or_none(n_agents)
    if mesh is not None:
        modes.append(("ppermute", mesh))
    for mode, m in modes:
        engine = ConsensusEngine(W, mesh=m)
        xs = engine.shard(x)
        out, t_rounds, res = engine.mix_until(xs, eps=eps, max_rounds=5000)
        common.sync(out)
        rounds = int(t_rounds)
        # Timed fixed-rounds run (pure gossip, no residual checks).
        warm = engine.mix(xs, times=2)
        common.sync(warm)
        with common.stopwatch() as t:
            out2 = engine.mix(xs, times=rounds)
            common.sync(out2)
        rps = rounds / t["s"]
        common.emit(
            {
                "metric": f"vector_consensus_rounds_per_sec_{mode}",
                "value": round(rps, 2),
                "unit": "rounds/sec",
                "vs_baseline": None,
                "config": "fast-averaging-ring-metropolis",
                "rounds_to_eps": rounds,
                "eps": eps,
                "residual": float(res),
                "dim": dim,
                "n_agents": n_agents,
                "bytes_gossiped_per_round": int(dim * 4 * n_agents),
            }
        )
        results[mode] = {"rounds": rounds, "rounds_per_sec": rps}

    # Chebyshev acceleration on the same problem.
    engine = ConsensusEngine(W)
    k_plain = results["dense"]["rounds"]
    xs = engine.shard(x)
    lo, hi = 1, k_plain
    while lo < hi:  # smallest k with residual < eps (cheby is monotone-ish)
        mid = (lo + hi) // 2
        resid = float(engine.max_deviation(engine.mix_chebyshev(xs, times=mid)))
        if resid < eps:
            hi = mid
        else:
            lo = mid + 1
    k_cheby = lo
    common.emit(
        {
            "metric": "vector_consensus_chebyshev_round_reduction",
            "value": round(k_plain / max(k_cheby, 1), 3),
            "unit": "x fewer rounds",
            "vs_baseline": None,
            "config": "fast-averaging-ring-metropolis",
            "rounds_plain": k_plain,
            "rounds_chebyshev": k_cheby,
        }
    )

    # Fused flat-buffer consensus vs the per-leaf oracle (many-leaf tree).
    fused = run_fused_vs_perleaf(n_agents)
    results["fused_rounds_per_sec"] = fused["fused"]
    results["fused_speedup"] = fused["speedup"]

    # SDP solve wall-clock on the reference's 25-node Watts-Strogatz graph.
    ws = Topology.watts_strogatz(25, 4, 0.3, seed=0)
    solve_fastest_mixing(ws)  # warm (first call may pay numpy setup)
    with common.stopwatch() as t:
        weights, gamma = solve_fastest_mixing(ws)
    common.emit(
        {
            "metric": "fastest_mixing_solve_ws25",
            "value": round(t["s"] * 1e3, 2),
            "unit": "ms",
            "vs_baseline": round(SDP_REFERENCE_S / t["s"], 3),
            "config": "fast-averaging-ring-metropolis",
            "gamma": float(gamma),
        }
    )
    results["sdp_ms"] = t["s"] * 1e3
    results["cheby_reduction"] = k_plain / max(k_cheby, 1)
    return results


if __name__ == "__main__":
    run()
