"""Epoch-superstep throughput: K epochs of local SGD + gossip per dispatch.

The trainer's per-epoch loop pays fixed host costs every epoch: an index
transfer, the epoch-program dispatch, a separate gossip-engine dispatch,
the chunk flush, and the consensus-residual readout.  On small models
those costs dominate the epoch's math.  ``GossipTrainer.train_epochs``
(``superstep=K``) compiles K epochs of scan+gossip into ONE donated
dispatch, so the per-epoch host cost amortizes by 1/K while the
trajectory stays bit-identical (``tests/test_trainer.py`` oracle).

This benchmark measures epochs/sec of the SAME MLP (``ann``) / Titanic
gossip configuration at ``K in {1, 4, 16}`` — K=1 is the per-epoch
path — and reads host dispatches per epoch off the obs
``trainer.dispatches`` counter (>=3 per epoch at K=1, exactly 1 per
superstep, i.e. 1/K per epoch, fused).

``run_lifted`` sweeps the ISSUE 20 lift: the configs that used to fall
back to the per-epoch loop (CHOCO compression, per-epoch round
schedule, async gossip, robust mixing) now compile into the superstep,
so each gets the same K=16-vs-K=1 dispatch amortization.  ``run_adaptive``
measures the residual-adaptive controller's communication saving:
rounds spent to hold a matched consensus residual, adaptive vs static
(arXiv:1910.13598's adaptive periodic averaging, in-program).

Run: ``python -m benchmarks.bench_superstep``
"""

from __future__ import annotations

import time
from typing import Dict, Sequence

import numpy as np

from benchmarks import common
from distributed_learning_tpu.obs import MetricsRegistry


def _titanic_shards(n_nodes: int):
    from distributed_learning_tpu.data import load_titanic, split_data

    X_tr, y_tr, X_te, y_te = load_titanic()
    names = list(range(n_nodes))
    return names, split_data(X_tr, y_tr, names), (X_te, y_te)


def _build_trainer(superstep: int, names, shards, registry, **overrides):
    from distributed_learning_tpu.parallel.topology import Topology
    from distributed_learning_tpu.training import GossipTrainer

    kw = dict(
        node_names=names,
        model="ann",
        model_kwargs={"hidden_dim": 16, "output_dim": 1},
        error="binary_logistic",
        optimizer="sgd",
        learning_rate=0.05,
        weights=Topology.ring(len(names)),
        train_data=shards,
        test_data=None,  # eval is boundary reporting, not the hot path
        epoch=10_000,    # schedule bound; we drive train_epochs directly
        epoch_len=4,
        batch_size=32,
        mix_times=1,
        stat_step=1000,
        dropout=False,
        superstep=superstep,
        obs=registry,
        seed=0,
    )
    kw.update(overrides)
    return GossipTrainer(**kw)


def run(epochs: int | None = None, ks: Sequence[int] = (1, 4, 16)) -> Dict:
    """Epochs/sec + host dispatches/epoch per superstep K; returns
    ``{"epochs_per_sec": {K: eps}, "dispatches_per_epoch": {K: d},
    "speedup": eps[max_k]/eps[1]}``."""
    if epochs is None:
        epochs = 32 if common.full_scale() else 16
    kmax = max(ks)
    if any(epochs % k for k in ks):
        raise ValueError(f"epochs={epochs} must be divisible by each K in {ks}")
    n_nodes = 4
    names, shards, _test = _titanic_shards(n_nodes)

    eps: Dict[int, float] = {}
    dispatches: Dict[int, float] = {}
    for k in ks:
        reg = MetricsRegistry()
        trainer = _build_trainer(k, names, shards, reg)
        trainer.initialize_nodes()
        trainer.train_epochs(k)  # compile + warm the K-epoch program
        best = 0.0
        for _ in range(3):  # best-of-3: epochs are ~ms-scale on CPU
            t0 = reg.counters.get("trainer.dispatches", 0)
            with common.stopwatch() as t:
                done = 0
                while done < epochs:
                    trainer.train_epochs(k)
                    done += k
            best = max(best, epochs / t["s"])
            d = (reg.counters.get("trainer.dispatches", 0) - t0) / epochs
        eps[k] = best
        dispatches[k] = d
    out = {
        "epochs_per_sec": eps,
        "dispatches_per_epoch": dispatches,
        "speedup": eps[kmax] / eps[1],
    }
    common.emit(
        {
            "metric": "trainer_superstep_epochs_per_sec",
            "value": round(eps[kmax], 2),
            "unit": "epochs/sec",
            "vs_baseline": round(out["speedup"], 3),  # vs this run's K=1
            "config": f"ann(16)/titanic, {n_nodes}-node ring, mix 1/epoch, "
                      f"superstep K={kmax}",
            "epochs_per_sec_by_k": {str(k): round(v, 2)
                                    for k, v in eps.items()},
            "dispatches_per_epoch_by_k": {str(k): round(v, 4)
                                          for k, v in dispatches.items()},
            "speedup_vs_per_epoch": round(out["speedup"], 3),
            "epochs_timed": epochs,
        }
    )
    return out


# The ISSUE 20 lift: configs that used to fall back to the per-epoch
# loop, now fused into the superstep scan (schedules as traced data,
# CHOCO/async/robust state as scan carries).
LIFTED_CONFIGS: Dict[str, Dict] = {
    "choco": {"compression": "top_k:0.5", "compression_gamma": 0.3},
    "sched": {"mix_times_schedule": lambda e: 1 + (e % 2)},
    "async": {"async_gossip": {"staleness_bound": 2,
                               "publish_period": [1, 2, 1, 3]}},
    "robust": {"robust_mixing": {"kind": "clip", "radius": 0.1}},
}


def run_lifted(epochs: int | None = None, ks: Sequence[int] = (1, 16),
               configs: Sequence[str] | None = None) -> Dict:
    """K=max(ks) vs K=1 epochs/sec for each previously chunk-hostile
    config; returns ``{name: {"epochs_per_sec": {K: eps}, "speedup"}}``
    and emits one record per config.  ``configs`` selects a subset of
    ``LIFTED_CONFIGS`` (the smoke gate runs the two headline configs;
    the full sweep is the __main__ / session path)."""
    if epochs is None:
        epochs = 32 if common.full_scale() else 16
    kmax = max(ks)
    if any(epochs % k for k in ks):
        raise ValueError(f"epochs={epochs} must be divisible by each K in {ks}")
    n_nodes = 4
    names, shards, _test = _titanic_shards(n_nodes)

    out: Dict[str, Dict] = {}
    for name, cfg in LIFTED_CONFIGS.items():
        if configs is not None and name not in configs:
            continue
        eps: Dict[int, float] = {}
        for k in ks:
            trainer = _build_trainer(
                k, names, shards, MetricsRegistry(), **cfg
            )
            trainer.initialize_nodes()
            trainer.train_epochs(k)  # compile + warm
            best = 0.0
            for _ in range(3):
                with common.stopwatch() as t:
                    done = 0
                    while done < epochs:
                        trainer.train_epochs(k)
                        done += k
                best = max(best, epochs / t["s"])
            eps[k] = best
        out[name] = {
            "epochs_per_sec": eps,
            "speedup": eps[kmax] / eps[1],
        }
        common.emit(
            {
                "metric": f"trainer_superstep_{name}_epochs_per_sec",
                "value": round(eps[kmax], 2),
                "unit": "epochs/sec",
                "vs_baseline": round(out[name]["speedup"], 3),  # vs K=1
                "config": f"ann(16)/titanic, {n_nodes}-node ring, "
                          f"{name} gossip, superstep K={kmax}",
                "speedup_vs_per_epoch": round(out[name]["speedup"], 3),
                "epochs_timed": epochs,
            }
        )
    return out


def run_adaptive(epochs: int | None = None, superstep: int = 8) -> Dict:
    """Rounds communicated at matched final residual, adaptive vs
    static: a static over-provisioned budget (mix_times=6) sets the
    residual bar; the adaptive controller (same base budget, residual
    target slightly above the static steady state) sheds rounds until
    the residual sits at the target.  Returns rounds/residual for both
    phases + the saving; the matched-residual claim is
    ``adaptive_final_residual <= target``."""
    if epochs is None:
        epochs = 32 if common.full_scale() else 16
    if epochs % superstep:
        raise ValueError(f"epochs={epochs} not divisible by K={superstep}")
    n_nodes = 4
    names, shards, _test = _titanic_shards(n_nodes)
    mix_times = 6

    def phase(adaptive_cfg):
        reg = MetricsRegistry()
        trainer = _build_trainer(
            superstep, names, shards, reg, mix_times=mix_times,
            adaptive_comm=adaptive_cfg,
        )
        trainer.initialize_nodes()
        devs = []
        for _ in range(epochs // superstep):
            devs += [o["deviation"] for o in trainer.train_epochs(superstep)]
        rounds = float(reg.counters.get("consensus.rounds_run", 0.0))
        return rounds, devs

    static_rounds, static_devs = phase(None)
    static_dev = float(static_devs[-1])
    # Matched-residual bar: a whisker above the static run's FINAL
    # residual.  The controller can only shed rounds on epochs whose
    # residual already sits under this line (late training, where the
    # shrinking local drift makes the static budget over-provisioned),
    # so the saving is exactly the over-service — and the adaptive run
    # must END at or under the same bar.  Everything is deterministic
    # on the CPU harness: the rounds counts and residuals are exact
    # reproducible numbers, not a timing race.  (A mid-training bar
    # saves more rounds but un-matches the final residual: the
    # proportional controller equilibrates AROUND its target.)
    target = max(static_dev * 1.5, 1e-12)
    adaptive_rounds, adaptive_devs = phase(
        {"target": target, "gain": 1.0, "min_times": 1,
         "max_times": mix_times}
    )
    adaptive_dev = float(adaptive_devs[-1])
    out = {
        "static_rounds": static_rounds,
        "adaptive_rounds": adaptive_rounds,
        "static_final_residual": static_dev,
        "adaptive_final_residual": adaptive_dev,
        "residual_target": target,
        "rounds_saved": static_rounds - adaptive_rounds,
        "matched": adaptive_dev <= target,
    }
    common.emit(
        {
            "metric": "trainer_superstep_adaptive_rounds_saved",
            "value": round(out["rounds_saved"], 1),
            "unit": "gossip rounds",
            "vs_baseline": round(static_rounds / max(adaptive_rounds, 1.0),
                                 3),
            "config": f"ann(16)/titanic, {n_nodes}-node ring, mix_times="
                      f"{mix_times} static vs residual-adaptive, "
                      f"K={superstep}, {epochs} epochs",
            "static_rounds": static_rounds,
            "adaptive_rounds": adaptive_rounds,
            "residual_target": target,
            "adaptive_final_residual": adaptive_dev,
            "matched_residual": out["matched"],
            "epochs_timed": epochs,
        }
    )
    return out


if __name__ == "__main__":
    run()
    run_lifted()
    run_adaptive()
