"""Epoch-superstep throughput: K epochs of local SGD + gossip per dispatch.

The trainer's per-epoch loop pays fixed host costs every epoch: an index
transfer, the epoch-program dispatch, a separate gossip-engine dispatch,
the chunk flush, and the consensus-residual readout.  On small models
those costs dominate the epoch's math.  ``GossipTrainer.train_epochs``
(``superstep=K``) compiles K epochs of scan+gossip into ONE donated
dispatch, so the per-epoch host cost amortizes by 1/K while the
trajectory stays bit-identical (``tests/test_trainer.py`` oracle).

This benchmark measures epochs/sec of the SAME MLP (``ann``) / Titanic
gossip configuration at ``K in {1, 4, 16}`` — K=1 is the per-epoch
path — and reads host dispatches per epoch off the obs
``trainer.dispatches`` counter (>=3 per epoch at K=1, exactly 1 per
superstep, i.e. 1/K per epoch, fused).

Run: ``python -m benchmarks.bench_superstep``
"""

from __future__ import annotations

import time
from typing import Dict, Sequence

import numpy as np

from benchmarks import common
from distributed_learning_tpu.obs import MetricsRegistry


def _titanic_shards(n_nodes: int):
    from distributed_learning_tpu.data import load_titanic, split_data

    X_tr, y_tr, X_te, y_te = load_titanic()
    names = list(range(n_nodes))
    return names, split_data(X_tr, y_tr, names), (X_te, y_te)


def _build_trainer(superstep: int, names, shards, registry):
    from distributed_learning_tpu.parallel.topology import Topology
    from distributed_learning_tpu.training import GossipTrainer

    return GossipTrainer(
        node_names=names,
        model="ann",
        model_kwargs={"hidden_dim": 16, "output_dim": 1},
        error="binary_logistic",
        optimizer="sgd",
        learning_rate=0.05,
        weights=Topology.ring(len(names)),
        train_data=shards,
        test_data=None,  # eval is boundary reporting, not the hot path
        epoch=10_000,    # schedule bound; we drive train_epochs directly
        epoch_len=4,
        batch_size=32,
        mix_times=1,
        stat_step=1000,
        dropout=False,
        superstep=superstep,
        obs=registry,
        seed=0,
    )


def run(epochs: int | None = None, ks: Sequence[int] = (1, 4, 16)) -> Dict:
    """Epochs/sec + host dispatches/epoch per superstep K; returns
    ``{"epochs_per_sec": {K: eps}, "dispatches_per_epoch": {K: d},
    "speedup": eps[max_k]/eps[1]}``."""
    if epochs is None:
        epochs = 32 if common.full_scale() else 16
    kmax = max(ks)
    if any(epochs % k for k in ks):
        raise ValueError(f"epochs={epochs} must be divisible by each K in {ks}")
    n_nodes = 4
    names, shards, _test = _titanic_shards(n_nodes)

    eps: Dict[int, float] = {}
    dispatches: Dict[int, float] = {}
    for k in ks:
        reg = MetricsRegistry()
        trainer = _build_trainer(k, names, shards, reg)
        trainer.initialize_nodes()
        trainer.train_epochs(k)  # compile + warm the K-epoch program
        best = 0.0
        for _ in range(3):  # best-of-3: epochs are ~ms-scale on CPU
            t0 = reg.counters.get("trainer.dispatches", 0)
            with common.stopwatch() as t:
                done = 0
                while done < epochs:
                    trainer.train_epochs(k)
                    done += k
            best = max(best, epochs / t["s"])
            d = (reg.counters.get("trainer.dispatches", 0) - t0) / epochs
        eps[k] = best
        dispatches[k] = d
    out = {
        "epochs_per_sec": eps,
        "dispatches_per_epoch": dispatches,
        "speedup": eps[kmax] / eps[1],
    }
    common.emit(
        {
            "metric": "trainer_superstep_epochs_per_sec",
            "value": round(eps[kmax], 2),
            "unit": "epochs/sec",
            "vs_baseline": round(out["speedup"], 3),  # vs this run's K=1
            "config": f"ann(16)/titanic, {n_nodes}-node ring, mix 1/epoch, "
                      f"superstep K={kmax}",
            "epochs_per_sec_by_k": {str(k): round(v, 2)
                                    for k, v in eps.items()},
            "dispatches_per_epoch_by_k": {str(k): round(v, 4)
                                          for k, v in dispatches.items()},
            "speedup_vs_per_epoch": round(out["speedup"], 3),
            "epochs_timed": epochs,
        }
    )
    return out


if __name__ == "__main__":
    run()
