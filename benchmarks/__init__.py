"""Benchmark harness: one script per BASELINE.json target configuration.

Run everything with ``python -m benchmarks.run_all``; each script also runs
standalone (``python -m benchmarks.bench_titanic`` etc.).  See
``common.py`` for the output format and sizing rules.
"""
