"""BASELINE config 1: Titanic logreg consensus-GD, 4 workers, ring graph.

Reference scenario: ``notebooks/Titanic Consensus GD test.ipynb`` cells
14-15 — 4 agents with contiguous shards, manual-gradient logistic
regression with the ``alpha * (it+1)^-0.5`` schedule, full gossip
convergence after every SGD step; recorded test accuracy 0.7978 for both
the centralized and the K4 consensus runs (BASELINE.md).

Here the entire iterate-then-gossip loop is one jitted ``fori_loop``: a
vmapped subgradient step for the 4 replicas and a ``mix_until`` inner
``while_loop`` per iteration (the reference's asyncio message rounds).
Metrics: iterations/sec of the full consensus-GD loop, final per-agent test
accuracy (vs the recorded 0.7978), and the final parameter spread.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks import common
from distributed_learning_tpu.data import load_titanic, split_data
from distributed_learning_tpu.models import logreg_loss
from distributed_learning_tpu.models.logreg import accuracy as logreg_accuracy
from distributed_learning_tpu.parallel import Topology
from distributed_learning_tpu.parallel.consensus import ConsensusEngine

ALPHA, TAU = 0.1, 1e-4
REFERENCE_ACC = 0.7978  # Titanic nb cell 15 (K4 / 4-agent recorded value)


def run(n_agents: int = 4, iters: int | None = None, mix_eps: float = 1e-9):
    if iters is None:
        iters = 4000 if common.full_scale() else (100 if common.smoke() else 1000)
    X_tr, y_tr, X_te, y_te = load_titanic()
    shards = split_data(X_tr, y_tr, n_agents)
    m = min(len(s[0]) for s in shards.values())
    Xs = jnp.stack([jnp.asarray(shards[i][0][:m]) for i in range(n_agents)])
    ys = jnp.stack(
        [jnp.asarray(shards[i][1][:m], jnp.float32) for i in range(n_agents)]
    )
    engine = ConsensusEngine(
        Topology.ring(n_agents).metropolis_weights(),
        mesh=common.agent_mesh_or_none(n_agents),
    )

    def local_step(w, X, y, lr):
        g = jax.grad(logreg_loss)(w, X, y, TAU)
        return w - lr * g

    vstep = jax.vmap(local_step, in_axes=(0, 0, 0, None))

    @jax.jit
    def run_loop(w0, iters):
        def body(it, w):
            lr = ALPHA * (it + 1.0) ** -0.5
            w = vstep(w, Xs, ys, lr)
            w, _, _ = engine.mix_until(w, eps=mix_eps, max_rounds=300)
            return w

        return jax.lax.fori_loop(0, iters, body, w0)

    w0 = engine.shard(jnp.zeros((n_agents, Xs.shape[-1])))
    w = run_loop(w0, 2)  # compile + warm
    common.sync(w)
    with common.stopwatch() as t:
        w = run_loop(w0, iters)
        common.sync(w)

    accs = [
        float(logreg_accuracy(w[a], jnp.asarray(X_te), jnp.asarray(y_te, jnp.float32)))
        for a in range(n_agents)
    ]
    spread = float(jnp.max(jnp.abs(w - w.mean(axis=0))))
    its_per_sec = iters / t["s"]
    common.emit(
        {
            "metric": "titanic_consensus_gd_iters_per_sec",
            "value": round(its_per_sec, 2),
            "unit": "iters/sec",
            # The reference records no wall clock for this run; accuracy is
            # the recorded anchor (next record).
            "vs_baseline": None,
            "config": "titanic-logreg-ring4",
            "iters": iters,
            "n_agents": n_agents,
        }
    )
    common.emit(
        {
            "metric": "titanic_consensus_gd_test_accuracy",
            "value": round(float(np.mean(accs)), 4),
            "unit": "accuracy",
            "vs_baseline": round(float(np.mean(accs)) / REFERENCE_ACC, 4),
            "config": "titanic-logreg-ring4",
            "per_agent": [round(a, 4) for a in accs],
            "param_spread": spread,
        }
    )
    return {"accs": accs, "spread": spread, "iters_per_sec": its_per_sec}


if __name__ == "__main__":
    run()
