"""Run all five BASELINE.json benchmark configurations in sequence.

Each emits JSON metric lines (see ``common.py``); set ``BENCH_OUT=path`` to
also append every line to a file.
"""

from __future__ import annotations

import sys
import traceback

from benchmarks import (
    bench_titanic,
    bench_titanic_noniid,
    bench_fast_averaging,
    bench_cifar_mlp,
    bench_cifar_wrn,
    bench_timevarying,
    bench_attention,
    bench_compression,
    bench_lm,
)

CONFIGS = [
    ("1: Titanic logreg consensus-GD (4 workers, ring)", bench_titanic.run),
    ("2: synthetic-vector consensus (ring + Metropolis)", bench_fast_averaging.run),
    ("3: CIFAR-10 ann_model gossip-SGD (8 workers, torus)", bench_cifar_mlp.run),
    ("4: CIFAR-10 WRN gossip-SGD (ring)", bench_cifar_wrn.run),
    ("5: CIFAR-100 WRN time-varying + Chebyshev", bench_timevarying.run),
    ("+: flash-attention kernel TFLOP/s (beyond-parity)", bench_attention.run),
    ("+: compressed gossip rounds/bytes (beyond-parity)", bench_compression.run),
    ("+: LM training tokens/sec, full vs flash attention", bench_lm.run),
    ("+: label-skewed Titanic non-IID accuracy (real data)", bench_titanic_noniid.run),
]


def main() -> int:
    failed = []
    for name, fn in CONFIGS:
        print(f"# config {name}", file=sys.stderr, flush=True)
        try:
            fn()
        except Exception:
            traceback.print_exc()
            failed.append(name)
    if failed:
        print(f"# FAILED: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
