"""Accelerator liveness probe + the ``TPU_HEALTH.jsonl`` health ledger.

The tunneled TPU wedges for hours at a time, and until now each wedge
window survived only as folklore ("r02–r05 hit the tunnel").  This
module makes every probe outcome a dated JSONL record so wedge windows
are queryable after the fact:

* :func:`record_health` — append one ``{"ts", "event": "probe",
  "outcome": ...}`` line to ``TPU_HEALTH.jsonl`` (``$DLT_TPU_HEALTH``
  overrides the path; appends are best-effort and never fail the
  caller).  Outcomes: ``healthy`` (first device op completed, with
  ``probe_s``), ``wedged`` (watchdog expired with no completed op),
  ``timeout`` (this CLI's own deadline passed), ``error`` (the op
  raised).
* ``python -m benchmarks.probe [--timeout S]`` — the session scripts'
  stage-0 probe (``benchmarks/tpu_session2.sh``): run a seconds-cheap
  matmul with a host-copy sync, record the outcome, exit 0 when alive /
  3 when not (the session aborts on 3).  The probe self-times: a
  wedged tunnel is *recorded* as such, not just killed silently by an
  outer ``timeout``.
* ``bench.py`` records through the same :func:`record_health`, so the
  driver's rounds and the manual sessions share one health history.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

__all__ = ["HEALTH_ENV", "DEFAULT_HEALTH", "health_path", "record_health",
           "probe_device", "main"]

HEALTH_ENV = "DLT_TPU_HEALTH"
DEFAULT_HEALTH = "TPU_HEALTH.jsonl"


def health_path(path: Optional[str] = None) -> str:
    """Explicit arg > $DLT_TPU_HEALTH > ``TPU_HEALTH.jsonl`` next to the
    repo root (where the driver's BENCH_r*.json artifacts live)."""
    if path:
        return path
    env = os.environ.get(HEALTH_ENV)
    if env:
        return env
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(repo_root, DEFAULT_HEALTH)


def record_health(outcome: str, path: Optional[str] = None,
                  **fields) -> bool:
    """Append one probe-outcome record; best-effort (a read-only
    checkout or full disk must never fail the measurement run that is
    reporting its health).  Returns whether the line landed."""
    rec = {"ts": time.time(), "event": "probe", "outcome": str(outcome)}
    rec.update(fields)
    try:
        with open(health_path(path), "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return True
    except OSError:
        return False


def probe_device() -> dict:
    """One seconds-cheap matmul with a host-copy sync (the only sync the
    tunneled backend honors — bench.py's probe, shared).  Returns
    ``{"probe_s", "platform"}``; raises on device failure.  May hang on
    a wedged tunnel: callers own the timeout (see :func:`main`)."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    a = jnp.ones((512, 512), jnp.bfloat16)
    # float() forces the host copy that proves execution completed.
    value = float((a @ a)[0, 0])
    return {
        "probe_s": round(time.perf_counter() - t0, 3),
        "platform": jax.devices()[0].platform,
        "sum": value,
    }


def main(argv=None) -> int:
    """CLI: probe with a self-timeout, record the outcome, exit 0/3."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m benchmarks.probe",
        description="accelerator liveness probe; appends its outcome "
                    "to the TPU_HEALTH.jsonl ledger",
    )
    ap.add_argument("--timeout", type=float, default=55.0,
                    help="seconds before the probe is declared wedged")
    ap.add_argument("--ledger", default=None,
                    help="health ledger path (default: $DLT_TPU_HEALTH "
                         "or TPU_HEALTH.jsonl at the repo root)")
    args = ap.parse_args(argv)

    result: dict = {}
    error: list = []

    def run():
        try:
            result.update(probe_device())
        except BaseException as exc:  # recorded, then re-raised as rc 3
            error.append(repr(exc))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(args.timeout)
    if t.is_alive():
        record_health("timeout", args.ledger, timeout_s=args.timeout,
                      source="benchmarks.probe")
        print(f"probe: no completed device op within {args.timeout:.0f}s "
              "— tunnel wedged", file=sys.stderr, flush=True)
        # The jax call may never return; a normal exit would block on
        # runtime teardown behind the wedged op.
        os._exit(3)
    if error:
        record_health("error", args.ledger, error=error[0][:500],
                      source="benchmarks.probe")
        print(f"probe: device op failed: {error[0]}", file=sys.stderr,
              flush=True)
        return 3
    record_health("healthy", args.ledger, source="benchmarks.probe",
                  **{k: v for k, v in result.items() if k != "sum"})
    print(f"probe: alive — first op in {result['probe_s']:.1f}s on "
          f"{result['platform']}", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
