"""``python -m distributed_learning_tpu`` — the training CLI."""

from distributed_learning_tpu.cli import main

raise SystemExit(main())
