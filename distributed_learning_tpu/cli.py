"""Command-line trainer.

Flag-for-flag parity with the training script the reference baselines ran
(the wide-resnet submodule's ``main.py``, invoked by
``CIFAR_10_Baseline.ipynb`` cell 9 as ``python main.py --lr 0.1 --net_type
wide-resnet --depth 28 --widen_factor 10 --dropout 0.3 --dataset
cifar10``), extended with the gossip options that script never had
(``--nodes``, ``--topology``, ``--epoch-cons-num``, ...) and config-file
reproducibility (``--config``/``--dump-config``).

    python -m distributed_learning_tpu --net_type wide-resnet --depth 28 \
        --widen_factor 10 --dropout 0.3 --dataset cifar10 --nodes 4

Subcommands (dispatched before the trainer flag surface):

    python -m distributed_learning_tpu.cli obs-report <run.jsonl>
    python -m distributed_learning_tpu.cli obs-report --merge <a.jsonl> <b.jsonl>
    python -m distributed_learning_tpu.cli obs-report --bench BENCH_r*.json
    python -m distributed_learning_tpu.cli obs-report --ledger PERF_LEDGER.jsonl
    python -m distributed_learning_tpu.cli obs-monitor <aggregate.jsonl>

summarize JSONL observability event logs — single-process, merged
run-wide (per-agent labels + straggler profile), the driver's bench
trajectory, or the persistent perf ledger (compiled-program cost
profiles + measured MFU per run, regression-flagged) — and tail the
run-wide aggregate live (``docs/observability.md``), all without
importing jax or touching any device.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

import numpy as np

from distributed_learning_tpu.training.config import DATASET_DEFAULTS, ExperimentConfig

__all__ = ["main", "build_parser", "config_from_args"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_learning_tpu",
        description="gossip-SGD training (reference main.py surface + gossip)",
    )
    # Every overridable flag defaults to None: a value appears in the
    # resolved config ONLY when given on the command line, so a --config
    # file is never silently clobbered by parser defaults.
    # -- reference main.py flags --
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--net_type", default=None,
                   choices=["lenet", "vggnet", "resnet", "wide-resnet", "ann"])
    p.add_argument("--depth", type=int, default=None)
    p.add_argument("--widen_factor", type=int, default=None)
    p.add_argument("--dropout", type=float, default=None)
    p.add_argument("--dataset", default=None,
                   choices=sorted(DATASET_DEFAULTS))
    p.add_argument("--resume", "-r", action="store_true",
                   help="resume from the checkpoint dir")
    p.add_argument("--testOnly", "-t", action="store_true",
                   help="evaluate the checkpoint, no training")
    # -- gossip extensions --
    p.add_argument("--nodes", type=int, default=None)
    p.add_argument("--topology", default=None)
    p.add_argument("--weight-mode", default=None,
                   choices=["metropolis", "sdp"])
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--epoch-cons-num", type=int, default=None)
    p.add_argument("--mix-times", type=int, default=None)
    p.add_argument("--mix-eps", type=float, default=None)
    p.add_argument("--chebyshev", action="store_true")
    p.add_argument("--time-varying-p", type=float, default=None)
    p.add_argument("--superstep", type=int, default=None,
                   help="epochs fused into one compiled dispatch "
                        "(train_epochs; every config compiles in — "
                        "schedules ride as traced data, CHOCO/async/"
                        "robust state as scan carries; checkpoints land "
                        "on superstep boundaries)")
    p.add_argument("--global-avg-every", type=int, default=None,
                   help="Gossip-PGA: exact all-reduce every H-th epoch")
    p.add_argument("--compression", default=None,
                   help="CHOCO-SGD compressed gossip: topk:F | atopk:F | randk:F | sign | int8 | none (disables, overriding a saved config)")
    p.add_argument("--compression-gamma", type=float, default=None)
    p.add_argument("--compression-budget", default=None,
                   choices=["per-leaf", "global"],
                   help="fused CHOCO k budget: per-leaf keeps each "
                        "tensor's fraction (oracle-identical), global "
                        "spends one budget per fused dtype bucket")
    p.add_argument("--compression-error-feedback", action="store_true",
                   help="bank the mass the compressor drops and re-offer "
                        "it next round (EF-SGD; keeps aggressive global "
                        "budgets convergent)")
    p.add_argument("--adaptive-target", type=float, default=None,
                   help="residual-adaptive communication: scale each "
                        "epoch's gossip round budget by last epoch's "
                        "consensus residual relative to this target "
                        "(1 + gain*(res/target - 1), clipped)")
    p.add_argument("--adaptive-gain", type=float, default=None,
                   help="adaptive_comm gain (default 1.0; 0 = static)")
    p.add_argument("--adaptive-max-times", type=int, default=None,
                   help="adaptive_comm round-budget ceiling")
    p.add_argument("--augment", action="store_true",
                   help="jitted RandomCrop+Flip train augmentation")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize activations in backward (saves HBM)")
    p.add_argument("--no-donate", action="store_true",
                   help="keep epoch state buffers alive instead of donating "
                        "them (needed to hold trainer.state across epochs)")
    p.add_argument("--lr-schedule", default=None, choices=["wrn_step"])
    p.add_argument("--n-train", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--stat-step", type=int, default=None)
    p.add_argument("--checkpoint-dir", default=None)
    # -- config file reproducibility --
    p.add_argument("--config", default=None,
                   help="load an ExperimentConfig JSON (CLI flags override)")
    p.add_argument("--dump-config", default=None,
                   help="write the resolved config JSON here and exit")
    return p


def config_from_args(args: argparse.Namespace) -> ExperimentConfig:
    """Resolve precedence: CLI flag > --config file > dataset defaults."""
    from_file = bool(args.config)
    cfg = ExperimentConfig.load(args.config) if from_file else ExperimentConfig()
    if args.dataset is not None:
        cfg.dataset = args.dataset
    elif not from_file:
        cfg.dataset = "cifar10"
    defaults = DATASET_DEFAULTS[cfg.dataset]

    if args.nodes is not None:
        cfg.node_names = list(range(args.nodes))
    if args.topology is not None:
        cfg.topology = args.topology
        cfg.topology_args = []
    if args.weight_mode is not None:
        cfg.weight_mode = args.weight_mode
    if args.net_type is not None or not from_file:
        # Choosing a net type (or starting fresh) rebuilds the model spec
        # so kwargs from another architecture never leak across.
        net = args.net_type or ("lenet" if not from_file else cfg.model)
        cfg.model = net
        cfg.model_args = [defaults["num_classes"]]
        if net == "wide-resnet":
            cfg.model_kwargs = {
                "depth": args.depth if args.depth is not None else 28,
                "widen_factor": (
                    args.widen_factor if args.widen_factor is not None else 10
                ),
                "dropout_rate": args.dropout if args.dropout is not None else 0.3,
            }
        else:
            cfg.model_kwargs = {}
    elif args.net_type is None and cfg.model == "wide-resnet":
        # Tweak a config-file WRN in place.
        if args.depth is not None:
            cfg.model_kwargs["depth"] = args.depth
        if args.widen_factor is not None:
            cfg.model_kwargs["widen_factor"] = args.widen_factor
        if args.dropout is not None:
            cfg.model_kwargs["dropout_rate"] = args.dropout
    if args.dropout is not None:
        cfg.dropout = args.dropout > 0
    if args.lr is not None:
        cfg.learning_rate = args.lr
    elif not from_file:
        cfg.learning_rate = defaults["lr"]
    if args.lr_schedule is not None:
        cfg.lr_schedule = args.lr_schedule
    if args.epochs is not None:
        cfg.epoch = args.epochs
    elif not from_file:
        cfg.epoch = defaults["num_epochs"]
    if args.batch_size is not None:
        cfg.batch_size = args.batch_size
    elif not from_file:
        cfg.batch_size = defaults["batch_size"]
    for field, value in (
        ("epoch_cons_num", args.epoch_cons_num),
        ("mix_times", args.mix_times),
        ("mix_eps", args.mix_eps),
        ("time_varying_p", args.time_varying_p),
        ("global_avg_every", args.global_avg_every),
        ("superstep", args.superstep),
        ("compression", args.compression),
        ("compression_gamma", args.compression_gamma),
        ("compression_budget", args.compression_budget),
        ("n_train", args.n_train),
        ("seed", args.seed),
        ("stat_step", args.stat_step),
        ("checkpoint_dir", args.checkpoint_dir),
    ):
        if value is not None:
            setattr(cfg, field, value)
    if args.chebyshev:
        cfg.chebyshev = True
    if args.compression_error_feedback:
        cfg.compression_error_feedback = True
    if args.adaptive_target is not None:
        adaptive = {"target": args.adaptive_target}
        if args.adaptive_gain is not None:
            adaptive["gain"] = args.adaptive_gain
        if args.adaptive_max_times is not None:
            adaptive["max_times"] = args.adaptive_max_times
        cfg.adaptive_comm = adaptive
    if args.augment:
        cfg.augment = True
    if args.remat:
        cfg.remat = True
    if args.no_donate:
        cfg.donate_state = False
    if cfg.checkpoint_dir is None and not from_file:
        cfg.checkpoint_dir = "checkpoint"
    return cfg


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "obs-report":
        # jax-free path: replay + summarize obs JSONL event logs.
        from distributed_learning_tpu.obs.report import obs_report_main

        return obs_report_main(argv[1:])
    if argv and argv[0] == "obs-monitor":
        # jax-free path: tail the run-wide aggregate stream live.
        from distributed_learning_tpu.obs.report import obs_monitor_main

        return obs_monitor_main(argv[1:])
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.dump_config:
        cfg.save(args.dump_config)
        # graftlint: disable=no-print-in-library -- CLI progress lines: stdout is this command's user interface
        print(f"wrote {args.dump_config}")
        return 0

    ckpt = os.path.abspath(cfg.checkpoint_dir) if cfg.checkpoint_dir else None
    cfg_path = ckpt + ".config.json" if ckpt else None
    if (args.resume or args.testOnly) and cfg_path and os.path.exists(cfg_path):
        # A checkpoint is only restorable into the exact experiment that
        # wrote it (model/optimizer state structures must match), so the
        # config saved beside it is authoritative; only the schedule
        # length may be extended on resume.
        saved = ExperimentConfig.load(cfg_path)
        if args.epochs is not None:
            saved.epoch = args.epochs
        cfg = saved
        # graftlint: disable=no-print-in-library -- CLI progress lines: stdout is this command's user interface
        print(f"loaded experiment config from {cfg_path}")

    master = cfg.build()
    master.initialize_nodes()
    if (args.resume or args.testOnly) and ckpt and os.path.exists(ckpt):
        master.restore_checkpoint(ckpt)
        # graftlint: disable=no-print-in-library -- CLI progress lines: stdout is this command's user interface
        print(f"restored checkpoint from {ckpt} "
              f"(epoch {master._epochs_done})")

    if args.testOnly:
        params, bs = master.state[0], master.state[1]
        accs = master._eval_accuracy(params, bs)
        for name, acc in zip(master.node_names, accs):
            # graftlint: disable=no-print-in-library -- testOnly's result lines: stdout is this command's user interface
            print(f"node {name}: test acc {acc:.4f}")
        return 0

    if cfg_path:
        cfg.save(cfg_path)
    while master._epochs_done < cfg.epoch:
        # Superstep chunks (one compiled dispatch per chunk, K=1 = the
        # per-epoch loop); checkpoints land on chunk boundaries.
        k = min(max(cfg.superstep, 1), cfg.epoch - master._epochs_done)
        for out in master.train_epochs(k):
            accs = (
                "n/a"
                if out["test_acc"] is None
                else " ".join(f"{a:.4f}" for a in np.asarray(out["test_acc"]))
            )
            residual = (
                "   n/a  " if out["deviation"] is None
                else f"{out['deviation']:.2e}"
            )
            # graftlint: disable=no-print-in-library -- per-epoch training log: stdout is this command's user interface
            print(
                f"| epoch {out['epoch'] + 1:3d}/{cfg.epoch}  "
                f"loss {float(np.mean(out['train_loss'])):.4f}  "
                f"acc {float(np.mean(out['train_acc'])):.4f}  "
                f"test [{accs}]  residual {residual}",
                flush=True,
            )
        if ckpt:
            master.save_checkpoint(ckpt)
    return 0


if __name__ == "__main__":
    sys.exit(main())
