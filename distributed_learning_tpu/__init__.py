"""distributed_learning_tpu — a TPU-native decentralized-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
``Malkovsky/distributed-learning`` repository: decentralized consensus
(gossip) optimization — N agents, each holding a data shard and a local model
replica, run local (sub)gradient steps and repeatedly average parameters with
graph neighbors, with edge weights optionally optimized for fastest mixing.

Where the reference runs agents as asyncio tasks or TCP processes exchanging
pickled values, this framework compiles the whole gossip round into a single
SPMD program over a ``jax.sharding.Mesh``: neighbor exchange is
``jax.lax.ppermute`` over the ICI interconnect, mixing weights are baked into
a precompiled matching schedule, and the local-SGD + gossip loop is jitted
end to end.

Subpackages
-----------
``parallel``  topology, fastest-mixing weights, mixing schedules, consensus
              engines (single-device vmap and multi-device shard_map), mesh
              helpers, multi-host init.
``ops``       jitted mixing/residual primitives operating on pytrees.
``models``    logreg / MLP / LeNet / VGG / ResNet / WideResNet (flax linen).
``data``      Titanic and CIFAR pipelines with per-agent sharding.
``training``  gossip-SGD trainer (the reference's documented ``MasterNode``
              surface), checkpointing, telemetry.
``obs``       unified observability: metrics registry (JSONL + run-report
              exporters), device-side metrics carry, span tracing, gossip
              counters (see ``docs/observability.md``).
``utils``     logging, metrics, tree utilities.
"""

from distributed_learning_tpu.parallel.topology import Topology, gamma, spectral_gap
from distributed_learning_tpu.parallel.consensus import (
    ConsensusEngine,
    Mixer,
    make_agent_mesh,
)
from distributed_learning_tpu.parallel.fast_averaging import (
    find_optimal_weights,
    solve_fastest_mixing,
)
from distributed_learning_tpu.parallel.pushsum import (
    PushSumEngine,
    push_sum_matrix,
)

__version__ = "0.1.0"

__all__ = [
    "ConsensusEngine",
    "Mixer",
    "make_agent_mesh",
    "Topology",
    "gamma",
    "spectral_gap",
    "find_optimal_weights",
    "solve_fastest_mixing",
    "PushSumEngine",
    "push_sum_matrix",
    "__version__",
]
