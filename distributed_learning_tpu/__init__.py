"""distributed_learning_tpu — a TPU-native decentralized-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
``Malkovsky/distributed-learning`` repository: decentralized consensus
(gossip) optimization — N agents, each holding a data shard and a local model
replica, run local (sub)gradient steps and repeatedly average parameters with
graph neighbors, with edge weights optionally optimized for fastest mixing.

Where the reference runs agents as asyncio tasks or TCP processes exchanging
pickled values, this framework compiles the whole gossip round into a single
SPMD program over a ``jax.sharding.Mesh``: neighbor exchange is
``jax.lax.ppermute`` over the ICI interconnect, mixing weights are baked into
a precompiled matching schedule, and the local-SGD + gossip loop is jitted
end to end.

Subpackages
-----------
``parallel``  topology, fastest-mixing weights, mixing schedules, consensus
              engines (single-device vmap and multi-device shard_map), mesh
              helpers, multi-host init.
``ops``       jitted mixing/residual primitives operating on pytrees.
``models``    logreg / MLP / LeNet / VGG / ResNet / WideResNet (flax linen).
``data``      Titanic and CIFAR pipelines with per-agent sharding.
``training``  gossip-SGD trainer (the reference's documented ``MasterNode``
              surface), checkpointing, telemetry.
``obs``       unified observability: metrics registry (JSONL + run-report
              exporters), device-side metrics carry, span tracing, gossip
              counters (see ``docs/observability.md``).
``utils``     logging, metrics, tree utilities.
"""

import importlib

__version__ = "0.1.0"

# PEP 562 lazy re-exports.  The package root must stay importable
# without jax: the graftlint sched stage (and every other bare-run-safe
# surface) imports ``distributed_learning_tpu.comm.*`` on boxes with no
# accelerator stack, and an eager ``parallel.*`` import here would drag
# jax in transitively.  Attribute access resolves (and caches) the real
# symbol on first use; eager `from distributed_learning_tpu import X`
# call sites are unchanged.
_LAZY = {
    "Topology": ("distributed_learning_tpu.parallel.topology", "Topology"),
    "gamma": ("distributed_learning_tpu.parallel.topology", "gamma"),
    "spectral_gap": (
        "distributed_learning_tpu.parallel.topology", "spectral_gap"
    ),
    "ConsensusEngine": (
        "distributed_learning_tpu.parallel.consensus", "ConsensusEngine"
    ),
    "Mixer": ("distributed_learning_tpu.parallel.consensus", "Mixer"),
    "make_agent_mesh": (
        "distributed_learning_tpu.parallel.consensus", "make_agent_mesh"
    ),
    "find_optimal_weights": (
        "distributed_learning_tpu.parallel.fast_averaging",
        "find_optimal_weights",
    ),
    "solve_fastest_mixing": (
        "distributed_learning_tpu.parallel.fast_averaging",
        "solve_fastest_mixing",
    ),
    "PushSumEngine": (
        "distributed_learning_tpu.parallel.pushsum", "PushSumEngine"
    ),
    "push_sum_matrix": (
        "distributed_learning_tpu.parallel.pushsum", "push_sum_matrix"
    ),
}


def __getattr__(name):
    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))

__all__ = [
    "ConsensusEngine",
    "Mixer",
    "make_agent_mesh",
    "Topology",
    "gamma",
    "spectral_gap",
    "find_optimal_weights",
    "solve_fastest_mixing",
    "PushSumEngine",
    "push_sum_matrix",
    "__version__",
]
