"""Binary logistic regression with L2 regularization (pure JAX).

Parity: ``networks/logreg_model_titanic.py:4-29`` (``LogRegTitanic``) — the
reference's pure-numpy model with labels in {-1, +1}, ridge term ``tau``, a
manual gradient, one GD step per ``fit`` call returning the train loss, and a
0.5-thresholded accuracy.  Here the gradient comes from ``jax.grad`` of the
same loss, everything is jittable, and the step works unchanged under
``vmap`` (one agent per batch row) or ``shard_map`` (one agent per device).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["LogisticRegression", "loss_fn", "grad_step", "predict", "accuracy"]


def loss_fn(w: jax.Array, X: jax.Array, y: jax.Array, tau: float) -> jax.Array:
    """Ridge-regularized logistic loss, labels in {-1, +1}.

    ``tau/2 ||w||^2 - mean(log sigmoid(y * Xw))`` — identical to the
    reference's train loss (``logreg_model_titanic.py:23-24``).
    """
    margins = y * (X @ w)
    return tau / 2.0 * jnp.sum(w**2) + jnp.mean(jax.nn.softplus(-margins))


def grad_step(
    w: jax.Array, X: jax.Array, y: jax.Array, *, lr: float, tau: float
) -> Tuple[jax.Array, jax.Array]:
    """One gradient-descent step; returns ``(new_w, loss_before_step)``
    (parity: ``LogRegTitanic.fit``, one step per call, loss returned)."""
    loss, g = jax.value_and_grad(loss_fn)(w, X, y, tau)
    return w - lr * g, loss


def predict(w: jax.Array, X: jax.Array) -> jax.Array:
    """{-1, +1} predictions via the 0.5 sigmoid threshold
    (parity: ``logreg_model_titanic.py:28``)."""
    return jnp.where(jax.nn.sigmoid(X @ w) >= 0.5, 1, -1)


def accuracy(w: jax.Array, X: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.mean((predict(w, X) == y).astype(jnp.float32))


@dataclasses.dataclass
class LogisticRegression:
    """Object-style wrapper mirroring the reference class surface."""

    dim: int
    lr: float = 5e-4
    tau: float = 1e-4

    def __post_init__(self):
        self.W = jnp.zeros(self.dim, dtype=jnp.float32)
        self._step = jax.jit(
            lambda w, X, y: grad_step(w, X, y, lr=self.lr, tau=self.tau)
        )
        self._acc = jax.jit(accuracy)

    def parameters(self) -> jax.Array:
        return self.W

    def fit(self, x_train, y_train) -> float:
        self.W, loss = self._step(
            self.W, jnp.asarray(x_train), jnp.asarray(y_train)
        )
        return float(loss)

    def calc_accuracy(self, x_test, y_test) -> float:
        return float(self._acc(self.W, jnp.asarray(x_test), jnp.asarray(y_test)))
