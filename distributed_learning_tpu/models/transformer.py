"""Decoder-only transformer with pluggable sequence-parallel attention.

No counterpart exists in the reference (its models are tabular/image nets,
SURVEY.md §2 C11-C13); this model exists so the framework's long-context
machinery (``ops/ring_attention.py``) has a first-class consumer: the same
gossip-SGD trainer can train a language model whose attention runs
sequence-parallel over the device ring.

Knobs:

* ``attn_impl`` — ``"full"`` (reference), ``"flash"`` (Pallas kernels),
  ``"ring"`` / ``"ring_flash"`` / ``"ulysses"`` (inside ``shard_map``
  with ``seq_axis`` sharded);
* ``attn_window`` — causal sliding-window attention (full/flash);
* ``pos_emb`` — learned table or rotary (``"rope"``, global positions,
  sequence-parallel safe);
* ``num_kv_heads`` — grouped-query attention (KV cache shrinks H/Hkv);
* ``mlp`` / ``num_experts`` / ``moe_top_k`` — dense or expert-parallel
  MoE feed-forward;
* ``dropout_rate`` — residual-branch dropout under ``train=True`` (the
  trainer already threads dropout rngs);
* ``decode`` + :func:`generate` — KV-cache autoregressive generation.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_learning_tpu.models.moe import MoEMLP
from distributed_learning_tpu.ops.ring_attention import (
    attention_reference,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

__all__ = ["TransformerLM", "generate", "sample_fn", "validate_sampling"]


def _rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding (arXiv:2104.09864) over the head dim,
    in the half-split (GPT-NeoX) layout: dimension ``j`` pairs with
    ``j + Dh/2`` and the pair rotates by ``pos / base^(2j/Dh)``.  (The
    paper's interleaved consecutive-pair layout is a fixed permutation
    of this one — self-consistent here, but checkpoints ported from
    interleaved-layout models would need that permutation applied.)

    ``x`` is (B, T, H, Dh) with even Dh; ``positions`` is (T,) GLOBAL
    token positions — under sequence parallelism each shard passes its
    offset slice, and in decode mode the cache write index, so the same
    rotation is applied no matter how the sequence is split.  Applied to
    Q and K before attention; relative-position structure then lives in
    the dot products and no learned position table is needed.
    """
    B, T, H, Dh = x.shape
    if Dh % 2:
        raise ValueError(f"rope needs an even head_dim, got {Dh}")
    half = Dh // 2
    freqs = positions[:, None].astype(jnp.float32) / (
        base ** (jnp.arange(half, dtype=jnp.float32) / half)
    )  # (T, half)
    cos = jnp.cos(freqs)[None, :, None, :]
    sin = jnp.sin(freqs)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


class _Attention(nn.Module):
    num_heads: int
    head_dim: int
    attn_impl: str = "full"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32
    window: int | None = None  # sliding window (full/flash paths only)
    decode: bool = False       # autoregressive KV-cache mode
    cache_len: int = 0         # static KV-cache length (decode mode)
    rope: bool = False         # rotary Q/K (positions arg required)
    num_kv_heads: int | None = None  # GQA: kv heads < query heads
    # MANUAL megatron tensor parallelism (shard_map contexts — the
    # pipeline's stages, where GSPMD annotation can't reach): when set,
    # this module declares only its LOCAL H/n heads' kernels (the
    # caller shards the stacked kernels over the axis), attention runs
    # head-local, and the out-projection's partial product exits
    # through one raw lax.psum — the shard_map transpose rules supply
    # the Megatron f/g pair (training/tp.py's NOTE).
    tp_axis: str | None = None

    def _tp_shard(self, n_global: int, what: str) -> int:
        if self.tp_axis is None:
            return n_global
        n = jax.lax.axis_size(self.tp_axis)
        if n_global % n:
            raise ValueError(
                f"{what} {n_global} must be divisible by the "
                f"{self.tp_axis!r} axis size {n}"
            )
        return n_global // n

    @nn.compact
    def __call__(self, x, positions=None):
        # QKV as ONE DenseGeneral with structured (3, H, Dh) output
        # features — the kernel is (d_model, 3, H, Dh), so tensor
        # parallelism shards it on the HEAD axis (training/tp.py) and
        # every downstream attention op is head-local: no activation
        # resharding inside the block.  A flat Dense(3*H*Dh) kernel can
        # only be split contiguously over the concatenated [Q|K|V]
        # columns, which straddles heads and forces XLA to re-gather.
        if self.tp_axis is not None and self.decode:
            raise ValueError(
                "manual tp_axis is a training-stage mode; decode uses "
                "the GSPMD path (training/tp.py::make_tp_generate)"
            )
        H = self._tp_shard(self.num_heads, "num_heads")
        Hkv = (self._tp_shard(self.num_kv_heads, "num_kv_heads")
               if self.num_kv_heads is not None else H)
        if Hkv == H:
            qkv = nn.DenseGeneral(
                features=(3, H, self.head_dim),
                use_bias=False, dtype=self.dtype,
            )(x)  # (B, T, 3, H, Dh)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            # Grouped-query attention (arXiv:2305.13245): Hkv shared K/V
            # heads serve H/Hkv query heads each.  Projections, decode
            # cache, and (in decode) the cache WRITE all carry only Hkv
            # heads — the KV-cache shrinks by H/Hkv, which is the point;
            # compute paths broadcast K/V up to H just before attention.
            if H % Hkv:
                raise ValueError(
                    f"num_heads {H} must divide by num_kv_heads {Hkv}"
                )
            q = nn.DenseGeneral(
                features=(H, self.head_dim), use_bias=False,
                dtype=self.dtype, name="q_proj",
            )(x)  # (B, T, H, Dh)
            kv = nn.DenseGeneral(
                features=(2, Hkv, self.head_dim), use_bias=False,
                dtype=self.dtype, name="kv_proj",
            )(x)  # (B, T, 2, Hkv, Dh)
            k, v = kv[:, :, 0], kv[:, :, 1]
        if self.rope:
            # One rope application for BOTH modes: the caller always
            # passes global positions (decode mode derives them from the
            # top-level position counter), so no per-layer recompute.
            q, k = _rope(q, positions), _rope(k, positions)
        if self.window is not None and self.attn_impl not in ("full", "flash"):
            raise ValueError(
                f"window is only supported for full/flash attention, "
                f"not {self.attn_impl!r}"
            )
        if self.decode:
            return self._decode_step(q, k, v, x)
        k, v = self._expand_kv(k, v, H)
        if self.attn_impl == "full":
            out = attention_reference(q, k, v, causal=True,
                                      window=self.window)
        elif self.attn_impl == "flash":
            from distributed_learning_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True, window=self.window)
        elif self.attn_impl == "ring":
            out = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        elif self.attn_impl == "ring_flash":
            out = ring_flash_attention(
                q, k, v, axis_name=self.seq_axis, causal=True
            )
        elif self.attn_impl == "ulysses":
            out = ulysses_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        else:
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        # Out-projection contracts (H, Dh) directly — kernel (H, Dh, d),
        # head-sharded under TP with one psum placed by the partitioner.
        return self._out_proj(out, x.shape[-1])

    def _expand_kv(self, k, v, H: int | None = None):
        """Broadcast Hkv K/V heads up to the H query heads (no-op when
        equal): repeat each kv head for its group of queries.  ``H`` is
        the query-head count actually in play — the LOCAL shard under
        manual tp, where ``num_heads`` would be the global count."""
        if H is None:
            H = self.num_heads
        if k.shape[2] == H:
            return k, v
        g = H // k.shape[2]
        return (jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2))

    def _out_proj(self, out, d):
        y = nn.DenseGeneral(
            features=d, axis=(-2, -1),
            use_bias=False, dtype=self.dtype, name="DenseGeneral_1",
        )(out)
        if self.tp_axis is not None:
            # Local heads contracted a partial product; one psum totals
            # it (bias-free, so nothing to de-duplicate).
            # graftlint: disable=raw-collective-in-shard-map -- megatron g exit: attention out-projection psum over tp_axis (training/tp.py NOTE)
            y = jax.lax.psum(y, self.tp_axis)
        return y

    def _decode_step(self, q, k, v, x):
        """Autoregressive attention against a static KV cache.

        One method covers prefill (T = prompt length at write index 0)
        and stepping (T = 1): this call's K/V are written at positions
        ``[i, i+T)`` of a fixed ``(B, cache_len, H, Dh)`` cache pair,
        and each query row ``t`` attends to cached positions
        ``<= i + t`` (inside ``window`` if set) — masking by position
        instead of slicing keeps every shape static for jit.

        Stepping past ``cache_len`` poisons the output with NaN: the
        clamped ``dynamic_update_slice`` would otherwise land the write
        on the last slot while the position counter keeps advancing —
        silently wrong attention.  ``generate()`` never reaches this;
        the guard is for direct ``apply`` users driving the cache
        themselves (the index is a traced value, so a Python raise
        cannot see it under jit).
        """
        B, T, _, Dh = q.shape
        Hkv = k.shape[2]  # under GQA the cache holds only the kv heads
        L = self.cache_len
        if T > L:
            raise ValueError(
                f"prefill length {T} exceeds the cache ({L}); a longer "
                "prompt would silently clamp the cache write"
            )
        ck = self.variable(
            "cache", "key",
            lambda: jnp.zeros((B, L, Hkv, Dh), self.dtype),
        )
        cv = self.variable(
            "cache", "value",
            lambda: jnp.zeros((B, L, Hkv, Dh), self.dtype),
        )
        idx = self.variable(
            "cache", "index", lambda: jnp.zeros((), jnp.int32)
        )
        i = idx.value
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k.astype(self.dtype), (0, i, 0, 0)
        )
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v.astype(self.dtype), (0, i, 0, 0)
        )
        idx.value = i + T
        scale = 1.0 / (Dh ** 0.5)
        # Grouped attention against the Hkv-head cache: reshape queries
        # to (B, T, Hkv, group, Dh) and contract against the cache
        # directly — the expanded (B, L, H, Dh) copy jnp.repeat would
        # materialize per generated token is exactly the memory GQA
        # exists to avoid.
        g = q.shape[2] // Hkv
        qg = q.reshape(B, T, Hkv, g, Dh)
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, ck.value
        ).astype(jnp.float32) * scale
        qpos = i + jnp.arange(T)                      # (T,)
        kpos = jnp.arange(L)                          # (L,)
        live = kpos[None, :] <= qpos[:, None]         # (T, L)
        if self.window is not None:
            live &= kpos[None, :] > qpos[:, None] - self.window
        s = jnp.where(live[None, None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum(
            "bhgqk,bkhd->bqhgd", p.astype(cv.value.dtype), cv.value
        ).reshape(B, T, Hkv * g, Dh)
        # Overflow guard (see docstring): once i + T walks past the
        # cache the write has been clamped, so every subsequent output
        # is garbage — make it loud, and keep it loud (idx only grows).
        out = jnp.where(i + T > L, jnp.nan, out)
        return self._out_proj(out, x.shape[-1])


class _RowDense(nn.Module):
    """Row-parallel Dense for the manual-TP MLP exit: the kernel holds
    this shard's ROWS (the caller shards dim 0 over ``tp_axis``), the
    partial product exits through one psum, and the (replicated) bias
    is added AFTER it — added before, every shard would contribute a
    copy and the psum would scale it by the axis size.  Param names and
    initializers match ``nn.Dense`` exactly so the tree is
    checkpoint-compatible with the unsharded block."""

    features: int
    tp_axis: str
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), self.dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), self.dtype
        )
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype
        )
        # graftlint: disable=raw-collective-in-shard-map -- megatron g exit: row-sharded kernel's partial matmul psum'd over tp_axis before the (replicated) bias
        return jax.lax.psum(x @ kernel, self.tp_axis) + bias


class _Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    attn_impl: str = "full"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32
    mlp: str = "dense"
    num_experts: int = 4
    moe_top_k: int = 1
    attn_window: int | None = None
    decode: bool = False
    cache_len: int = 0
    rope: bool = False
    num_kv_heads: int | None = None
    dropout_rate: float = 0.0
    moe_expert_axis: str | None = None  # manual ep (models/moe.py)
    tp_axis: str | None = None          # manual megatron tp (_Attention)
    moe_capacity_factor: float = 1.25   # GShard slots per expert

    @nn.compact
    def __call__(self, x, positions=None, train: bool = False):
        def drop(h):
            # Residual-branch dropout (the GPT placement), gated like the
            # WRN blocks: deterministic unless training.
            if self.dropout_rate > 0:
                h = nn.Dropout(
                    self.dropout_rate, deterministic=not train
                )(h)
            return h

        if self.tp_axis is not None and self.mlp == "moe":
            raise ValueError(
                "manual tp_axis with mlp='moe' is not supported: shard "
                "experts over an expert axis instead (moe_expert_axis)"
            )
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + drop(_Attention(
            self.num_heads, self.head_dim, self.attn_impl, self.seq_axis,
            self.dtype, self.attn_window, self.decode, self.cache_len,
            self.rope, self.num_kv_heads, tp_axis=self.tp_axis,
        )(h, positions))
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.mlp == "moe":
            # Expert-parallel feed-forward (models/moe.py): params become
            # stacked (E, ...) kernels shardable over an expert mesh axis.
            return x + drop(MoEMLP(
                num_experts=self.num_experts, mlp_ratio=self.mlp_ratio,
                capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k, dtype=self.dtype,
                drop_tokens=not self.decode,
                expert_axis=self.moe_expert_axis,
            )(h))
        if self.mlp != "dense":
            raise ValueError(f"unknown mlp {self.mlp!r} (want dense|moe)")
        d = x.shape[-1]
        if self.tp_axis is not None:
            # Megatron column-then-row MLP: the up-projection declares
            # only this shard's COLUMNS (nn.Dense with local features —
            # kernel (d, h/n), bias (h/n): the same tree paths as the
            # unsharded block, locally shaped), gelu stays elementwise
            # local, and the row-parallel exit psums before its bias.
            n = jax.lax.axis_size(self.tp_axis)
            h_f = self.mlp_ratio * d
            if h_f % n:
                raise ValueError(
                    f"mlp width {h_f} must be divisible by the "
                    f"{self.tp_axis!r} axis size {n}"
                )
            h = nn.Dense(h_f // n, dtype=self.dtype, name="Dense_0")(h)
            h = nn.gelu(h)
            return x + drop(_RowDense(
                d, self.tp_axis, self.dtype, name="Dense_1"
            )(h))
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype)(h)
        return x + drop(h)


class TransformerLM(nn.Module):
    """Small causal LM: token embedding + learned positions + N blocks.

    ``__call__(tokens, train=False) -> logits`` matches the framework's
    shared model interface (``models/__init__.py``), so it drops straight
    into :class:`~distributed_learning_tpu.training.trainer.GossipTrainer`.
    """

    vocab_size: int = 256
    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 16
    max_len: int = 1024
    mlp_ratio: int = 4
    attn_impl: str = "full"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32
    mlp: str = "dense"       # "dense" | "moe" (expert-parallel blocks)
    num_experts: int = 4
    moe_top_k: int = 1       # router choices per token (1=Switch, 2=GShard)
    # GShard capacity: slots per expert = ceil(tokens/E * factor).
    # NOTE training (drop_tokens=True) DROPS overflow while decode
    # (drop-free) runs every expert, so a capacity-constrained model is
    # a slightly different function at decode time; raise the factor
    # (e.g. 8.0 at toy sizes) when train/generate agreement matters
    # more than the capacity behavior.
    moe_capacity_factor: float = 1.25
    attn_window: int | None = None  # sliding-window attention (full/flash)
    dropout_rate: float = 0.0  # residual-branch dropout (train=True only)
    pos_emb: str = "learned"  # "learned" table | "rope" rotary Q/K
    num_kv_heads: int | None = None  # GQA: shared K/V heads (cache /Hkv)
    decode: bool = False     # KV-cache autoregressive mode (see generate).
                             # Direct decode users must keep prompt+steps
                             # <= max_len; past it the dynamic cache write
                             # clamps (generate() enforces the bound).

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.attn_window is not None and \
                self.attn_impl not in ("full", "flash"):
            # Checked here (not only in _Attention) so the error fires
            # before the sequence-parallel paths touch their mesh axis.
            raise ValueError(
                f"attn_window is only supported for full/flash attention, "
                f"not {self.attn_impl!r}"
            )
        d_model = self.num_heads * self.head_dim
        T = tokens.shape[1]
        x = nn.Embed(self.vocab_size, d_model, dtype=self.dtype)(tokens)
        # Positions must be GLOBAL: under shard_map (ring/ulysses) each
        # shard sees only its local T, so offset by the shard index.
        # "full" and "flash" are single-device paths (no mesh axis bound).
        if self.decode:
            if self.attn_impl not in ("full", "flash"):
                raise ValueError("decode mode requires full/flash attention")
            pos_v = self.variable(
                "cache", "pos", lambda: jnp.zeros((), jnp.int32)
            )
            positions = pos_v.value + jnp.arange(T)
            pos_v.value = pos_v.value + T
        elif self.attn_impl in ("full", "flash"):
            if T > self.max_len:
                raise ValueError(
                    f"sequence length {T} exceeds max_len {self.max_len}; "
                    "out-of-range positions would silently clamp"
                )
            positions = jnp.arange(T)
        else:
            # Local T * axis size must fit max_len; checked per-shard
            # statically (axis size is known at trace time).
            n_shards = jax.lax.axis_size(self.seq_axis)
            if T * n_shards > self.max_len:
                raise ValueError(
                    f"global sequence length {T * n_shards} (local {T} x "
                    f"{n_shards} shards) exceeds max_len {self.max_len}"
                )
            positions = jax.lax.axis_index(self.seq_axis) * T + jnp.arange(T)
        if self.pos_emb == "rope":
            use_rope = True
        elif self.pos_emb == "learned":
            use_rope = False
            pos = nn.Embed(self.max_len, d_model, dtype=self.dtype)(positions)
            x = x + pos[None]
        else:
            raise ValueError(
                f"unknown pos_emb {self.pos_emb!r} (want learned|rope)"
            )
        for _ in range(self.num_layers):
            x = _Block(
                self.num_heads, self.head_dim, self.mlp_ratio,
                self.attn_impl, self.seq_axis, self.dtype,
                self.mlp, self.num_experts, self.moe_top_k,
                self.attn_window, self.decode, self.max_len,
                use_rope, self.num_kv_heads, self.dropout_rate,
                moe_capacity_factor=self.moe_capacity_factor,
            )(x, positions if use_rope else None, train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


def generate(
    model: TransformerLM,
    params,
    prompt: jax.Array,
    steps: int,
    *,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Autoregressive generation with a KV cache: prefill the prompt in
    one pass, then one jitted single-token step per new token under
    ``lax.scan``.

    ``prompt`` is (B, Tp) int32; returns (B, steps) generated tokens.
    ``temperature=0`` is greedy argmax; otherwise tokens are sampled
    from ``softmax(logits / temperature)`` (``key`` required), with the
    candidate set optionally truncated FIRST by ``top_k`` (keep the k
    highest-logit tokens) and/or ``top_p`` (nucleus sampling,
    arXiv:1904.09751: the smallest set whose cumulative probability
    reaches p — the top token always survives).  The decode-mode model
    reuses the TRAINING parameters unchanged — the cache is a flax
    ``cache`` collection threaded through the scan, so the whole loop
    compiles to one program with static shapes.
    """
    validate_sampling(model, prompt.shape[1], steps, key, temperature,
                      top_k, top_p)
    run = _generate_runner(model.clone(decode=True), steps,
                           float(temperature),
                           None if top_k is None else int(top_k),
                           None if top_p is None else float(top_p))
    return run(params, prompt, key)


def validate_sampling(model: "TransformerLM", prompt_len: int, steps: int,
                      key, temperature: float, top_k: int | None,
                      top_p: float | None) -> None:
    """The :func:`generate` argument contract, shared with the
    tensor-parallel decode path."""
    if prompt_len + steps > model.max_len:
        raise ValueError(
            f"prompt ({prompt_len}) + steps ({steps}) exceeds max_len "
            f"{model.max_len}"
        )
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    if (top_k is not None or top_p is not None) and temperature <= 0.0:
        raise ValueError(
            "top_k/top_p shape the SAMPLING distribution; greedy decoding "
            "(temperature=0) ignores them — pass temperature > 0"
        )
    if top_k is not None and not 1 <= top_k <= model.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={model.vocab_size}], "
            f"got {top_k}"
        )
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def sample_fn(temperature: float, top_k: int | None = None,
              top_p: float | None = None):
    """Build ``pick(logits, key, dtype) -> token`` for one sampling
    configuration — greedy argmax at temperature 0, else temperature/
    top-k/nucleus sampling.  Shared by :func:`generate` and the
    tensor-parallel decode path (``training/tp.py::make_tp_generate``)
    so the two cannot drift."""

    def pick(logits, k, dtype):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(dtype)
        scaled = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        if top_p is not None:
            # Nucleus cutoff on the (possibly top_k-truncated) logits:
            # rank tokens by probability, keep every token whose
            # cumulative mass BEFORE it is < p (so the top token always
            # survives), and mask the rest via the kept-set's smallest
            # logit — all static shapes.
            srt = jnp.sort(scaled, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # (cum - probs) is the EXCLUSIVE prefix sum: < p keeps every
            # token whose predecessors haven't reached the nucleus yet,
            # so n_keep >= 1 always.
            n_keep = jnp.sum((cum - probs) < top_p, axis=-1, keepdims=True)
            thresh = jnp.take_along_axis(srt, n_keep - 1, axis=-1)
            scaled = jnp.where(scaled < thresh, -jnp.inf, scaled)
        return jax.random.categorical(k, scaled, axis=-1).astype(dtype)

    return pick


@functools.lru_cache(maxsize=64)
def _generate_runner(dec: TransformerLM, steps: int, temperature: float,
                     top_k: int | None = None, top_p: float | None = None):
    """The jitted prefill+scan program for one (model, steps,
    temperature, top_k, top_p) configuration.  Cached by the module's
    (frozen, hashable) dataclass identity so repeated :func:`generate`
    calls with the same settings reuse the compile instead of
    re-tracing — jit caches by function object, and a closure built
    inside ``generate`` would be fresh every call."""

    pick = sample_fn(temperature, top_k, top_p)

    @jax.jit
    def _run(params, prompt, key):
        logits, state = dec.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        key0 = key if key is not None else jax.random.key(0)
        k_first, k_scan = jax.random.split(key0)
        tok = pick(logits[:, -1], k_first, prompt.dtype)

        def step(carry, k_t):
            cache, tok = carry
            logits, st = dec.apply(
                {"params": params, "cache": cache["cache"]},
                tok[:, None], mutable=["cache"],
            )
            nxt = pick(logits[:, -1], k_t, tok.dtype)
            return (st, nxt), tok

        keys = jax.random.split(k_scan, steps)
        # Each iteration collects the token ENTERING it, so toks is
        # exactly [t_1 .. t_steps]; the final carry (t_steps+1) is
        # unneeded lookahead.
        _, toks = jax.lax.scan(step, (state, tok), keys)
        return toks.T

    return _run
