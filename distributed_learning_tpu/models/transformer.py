"""Decoder-only transformer with pluggable sequence-parallel attention.

No counterpart exists in the reference (its models are tabular/image nets,
SURVEY.md §2 C11-C13); this model exists so the framework's long-context
machinery (``ops/ring_attention.py``) has a first-class consumer: the same
gossip-SGD trainer can train a language model whose attention runs
sequence-parallel over the device ring.

``attn_impl``: ``"full"`` (single-device reference), ``"ring"`` or
``"ulysses"`` (inside ``shard_map`` with ``seq_axis`` sharded).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_learning_tpu.models.moe import MoEMLP
from distributed_learning_tpu.ops.ring_attention import (
    attention_reference,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

__all__ = ["TransformerLM"]


class _Attention(nn.Module):
    num_heads: int
    head_dim: int
    attn_impl: str = "full"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # QKV as ONE DenseGeneral with structured (3, H, Dh) output
        # features — the kernel is (d_model, 3, H, Dh), so tensor
        # parallelism shards it on the HEAD axis (training/tp.py) and
        # every downstream attention op is head-local: no activation
        # resharding inside the block.  A flat Dense(3*H*Dh) kernel can
        # only be split contiguously over the concatenated [Q|K|V]
        # columns, which straddles heads and forces XLA to re-gather.
        qkv = nn.DenseGeneral(
            features=(3, self.num_heads, self.head_dim),
            use_bias=False, dtype=self.dtype,
        )(x)  # (B, T, 3, H, Dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.attn_impl == "full":
            out = attention_reference(q, k, v, causal=True)
        elif self.attn_impl == "flash":
            from distributed_learning_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif self.attn_impl == "ring":
            out = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        elif self.attn_impl == "ring_flash":
            out = ring_flash_attention(
                q, k, v, axis_name=self.seq_axis, causal=True
            )
        elif self.attn_impl == "ulysses":
            out = ulysses_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        else:
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        # Out-projection contracts (H, Dh) directly — kernel (H, Dh, d),
        # head-sharded under TP with one psum placed by the partitioner.
        return nn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1),
            use_bias=False, dtype=self.dtype,
        )(out)


class _Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    attn_impl: str = "full"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32
    mlp: str = "dense"
    num_experts: int = 4
    moe_top_k: int = 1

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + _Attention(
            self.num_heads, self.head_dim, self.attn_impl, self.seq_axis,
            self.dtype,
        )(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.mlp == "moe":
            # Expert-parallel feed-forward (models/moe.py): params become
            # stacked (E, ...) kernels shardable over an expert mesh axis.
            return x + MoEMLP(
                num_experts=self.num_experts, mlp_ratio=self.mlp_ratio,
                top_k=self.moe_top_k, dtype=self.dtype,
            )(h)
        if self.mlp != "dense":
            raise ValueError(f"unknown mlp {self.mlp!r} (want dense|moe)")
        d = x.shape[-1]
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype)(h)
        return x + h


class TransformerLM(nn.Module):
    """Small causal LM: token embedding + learned positions + N blocks.

    ``__call__(tokens, train=False) -> logits`` matches the framework's
    shared model interface (``models/__init__.py``), so it drops straight
    into :class:`~distributed_learning_tpu.training.trainer.GossipTrainer`.
    """

    vocab_size: int = 256
    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 16
    max_len: int = 1024
    mlp_ratio: int = 4
    attn_impl: str = "full"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32
    mlp: str = "dense"       # "dense" | "moe" (expert-parallel blocks)
    num_experts: int = 4
    moe_top_k: int = 1       # router choices per token (1=Switch, 2=GShard)

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        d_model = self.num_heads * self.head_dim
        T = tokens.shape[1]
        x = nn.Embed(self.vocab_size, d_model, dtype=self.dtype)(tokens)
        # Positions must be GLOBAL: under shard_map (ring/ulysses) each
        # shard sees only its local T, so offset by the shard index.
        # "full" and "flash" are single-device paths (no mesh axis bound).
        if self.attn_impl in ("full", "flash"):
            if T > self.max_len:
                raise ValueError(
                    f"sequence length {T} exceeds max_len {self.max_len}; "
                    "out-of-range positions would silently clamp"
                )
            positions = jnp.arange(T)
        else:
            # Local T * axis size must fit max_len; checked per-shard
            # statically (axis size is known at trace time).
            n_shards = jax.lax.axis_size(self.seq_axis)
            if T * n_shards > self.max_len:
                raise ValueError(
                    f"global sequence length {T * n_shards} (local {T} x "
                    f"{n_shards} shards) exceeds max_len {self.max_len}"
                )
            positions = jax.lax.axis_index(self.seq_axis) * T + jnp.arange(T)
        pos = nn.Embed(self.max_len, d_model, dtype=self.dtype)(positions)
        x = x + pos[None]
        for _ in range(self.num_layers):
            x = _Block(
                self.num_heads, self.head_dim, self.mlp_ratio,
                self.attn_impl, self.seq_axis, self.dtype,
                self.mlp, self.num_experts, self.moe_top_k,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)
