"""CIFAR-scale vision model zoo: LeNet, VGG, ResNet, Wide-ResNet.

Parity: the reference trains lenet / vggnet / resnet / wide-resnet from the
``meliketoy/wide-resnet.pytorch`` git submodule (``.gitmodules:1-3``; model
selection in ``Man_Colab.ipynb`` cell 19/21, WRN-28-10 baselines in
``CIFAR_10_Baseline.ipynb`` cell 9).  The submodule is not even checked out
in the reference snapshot, so these are written fresh from the standard
architecture definitions, TPU-first: NHWC layouts, ``nn.Conv`` 3x3s that
XLA tiles onto the MXU, optional bf16 compute dtype with f32 params, and
BatchNorm statistics kept **per agent** (only parameters are gossiped —
matching the reference's behavior of mixing every model parameter while each
node keeps its own running stats, ``mixer.py:68-76``).

All modules share the call convention
``apply({'params': p, 'batch_stats': s}, x, train=...)`` with
``mutable=['batch_stats']`` during training.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["LeNet", "VGG", "ResNet", "WideResNet"]

ModuleDef = Any


class LeNet(nn.Module):
    """Classic LeNet-5 (the submodule's ``lenet`` option)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


_VGG_CFG = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    13: (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
         512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    """VGG-{11,13,16,19} with BatchNorm (the submodule's ``vggnet``)."""

    depth: int = 16
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.depth not in _VGG_CFG:
            raise ValueError(f"VGG depth must be one of {sorted(_VGG_CFG)}")
        x = x.astype(self.dtype)
        for v in _VGG_CFG[self.depth]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
                x = nn.BatchNorm(
                    use_running_average=not train, momentum=0.9, dtype=self.dtype
                )(x)
                x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class _BasicBlock(nn.Module):
    filters: int
    stride: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            dtype=self.dtype,
        )
        residual = x
        y = nn.Conv(
            self.filters, (3, 3), strides=self.stride, padding=1,
            use_bias=False, dtype=self.dtype,
        )(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), strides=self.stride, use_bias=False,
                dtype=self.dtype,
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """CIFAR-style ResNet (the submodule's ``resnet`` option): 3 stages of
    BasicBlocks, depth = 6n + 2 (20/32/44/56/110) or 18/34 ImageNet-style
    block counts on CIFAR inputs."""

    depth: int = 18
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if (self.depth - 2) % 6 == 0:
            n = (self.depth - 2) // 6
            blocks = (n, n, n)
        elif self.depth == 18:
            blocks = (2, 2, 2)
        elif self.depth == 34:
            blocks = (3, 4, 6)
        else:
            raise ValueError(f"unsupported ResNet depth {self.depth}")
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage, num in enumerate(blocks):
            for b in range(num):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = _BasicBlock(16 * (2**stage), stride, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class _WideBasic(nn.Module):
    """Pre-activation wide basic block: BN-ReLU-conv-(dropout)-BN-ReLU-conv
    plus projection shortcut — the ``wide_basic`` of the submodule."""

    filters: int
    stride: int
    dropout_rate: float
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            dtype=self.dtype,
        )
        y = nn.relu(norm()(x))
        shortcut = x
        if x.shape[-1] != self.filters or self.stride != 1:
            shortcut = nn.Conv(
                self.filters, (1, 1), strides=self.stride, use_bias=True,
                dtype=self.dtype,
            )(y)
        y = nn.Conv(self.filters, (3, 3), padding=1, use_bias=True,
                    dtype=self.dtype)(y)
        if self.dropout_rate > 0:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.filters, (3, 3), strides=self.stride, padding=1,
                    use_bias=True, dtype=self.dtype)(y)
        return y + shortcut


class WideResNet(nn.Module):
    """WRN-d-k (default 28-10): the reference's flagship model.

    Baselines to match (BASELINE.md): CIFAR-10 93.77% / CIFAR-100 75.71%
    test Acc@1 at depth 28, widen factor 10, dropout 0.3.
    """

    depth: int = 28
    widen_factor: int = 10
    dropout_rate: float = 0.3
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if (self.depth - 4) % 6 != 0:
            raise ValueError("WideResNet depth must be 6n + 4")
        n = (self.depth - 4) // 6
        k = self.widen_factor
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding=1, use_bias=True, dtype=self.dtype)(x)
        for stage, width in enumerate((16 * k, 32 * k, 64 * k)):
            for b in range(n):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = _WideBasic(
                    width, stride, self.dropout_rate, self.dtype
                )(x, train)
        x = nn.relu(
            nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype)(x)
        )
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
