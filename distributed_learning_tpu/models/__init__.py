"""Model zoo: logreg / MLP / LeNet / VGG / ResNet / WideResNet.

``get_model(name, *args, **kwargs)`` resolves the reference's string model
names (``MasterNode(model='lenet' | 'vggnet' | 'resnet' | 'wide-resnet')``,
``Man_Colab.ipynb`` cell 21) to flax modules.
"""

from __future__ import annotations

from typing import Any

from distributed_learning_tpu.models.logreg import (
    LogisticRegression,
    accuracy as logreg_accuracy,
    grad_step as logreg_grad_step,
    loss_fn as logreg_loss,
)
from distributed_learning_tpu.models.mlp import ANNModel
from distributed_learning_tpu.models.moe import MoEMLP
from distributed_learning_tpu.models.transformer import (
    TransformerLM,
    generate,
)
from distributed_learning_tpu.models.vision import LeNet, ResNet, VGG, WideResNet

_REGISTRY = {
    "lenet": LeNet,
    "vggnet": VGG,
    "resnet": ResNet,
    "wide-resnet": WideResNet,
    "wide_resnet": WideResNet,
    "ann": ANNModel,
    "mlp": ANNModel,
    "transformer": TransformerLM,
}


def get_model(name: str, *args: Any, **kwargs: Any):
    """Build a model by reference-compatible name.

    Positional args mirror the reference's ``model(*model_args)`` convention
    — e.g. ``get_model('lenet', 10)`` is LeNet with 10 classes.
    """
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(set(_REGISTRY))}"
        )
    cls = _REGISTRY[key]
    if args:
        # Reference convention: model_args = [num_classes].
        if cls is ANNModel:
            size_key = "output_dim"
        elif cls is TransformerLM:
            size_key = "vocab_size"
        else:
            size_key = "num_classes"
        if size_key in kwargs:
            raise ValueError(
                f"{size_key} given both positionally ({args[0]}) and as a "
                f"keyword ({kwargs[size_key]})"
            )
        kwargs[size_key] = args[0]
        if len(args) > 1:
            raise ValueError(
                "positional model_args beyond num_classes are not supported; "
                "use keyword arguments"
            )
    return cls(**kwargs)


__all__ = [
    "ANNModel",
    "TransformerLM",
    "generate",
    "MoEMLP",
    "LeNet",
    "VGG",
    "ResNet",
    "WideResNet",
    "LogisticRegression",
    "logreg_loss",
    "logreg_grad_step",
    "logreg_accuracy",
    "get_model",
]
