"""4-layer MLP (parity: ``networks/ann_model.py:4-45`` ``ANNModel``).

The reference's torch module is Linear->ReLU->Linear->Tanh->Linear->ELU->
Linear, sized for MNIST-like 784 -> hidden -> 10.  Same topology here in
flax linen, with an optional compute dtype for bf16 MXU execution.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ANNModel"]


class ANNModel(nn.Module):
    """Linear/ReLU, Linear/Tanh, Linear/ELU, Linear readout."""

    hidden_dim: int = 150
    output_dim: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.Dense(self.hidden_dim, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden_dim, dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = nn.Dense(self.hidden_dim, dtype=self.dtype)(x)
        x = nn.elu(x)
        x = nn.Dense(self.output_dim, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
