"""Mixture-of-experts MLP with expert parallelism (GShard-style).

The fifth axis of the parallelism matrix: expert weights are a stacked
``(E, ...)`` tree whose leading axis shards over an ``expert`` mesh
axis, and the layer is written as dense einsums against a one-hot
dispatch tensor — the GShard formulation (arXiv:2006.16668) that keeps
shapes static so the XLA partitioner can place the token all-to-alls
itself.  No dynamic routing control flow anywhere: top-k gating
becomes k stacked ``(tokens, E, C)`` one-hots (k is a small static
constant — 1 = Switch routing, 2 = the GShard default), dispatch and
combine are einsum contractions against them.

Capacity: each expert processes at most ``C = ceil(tokens/E * factor)``
tokens; overflow tokens fall through the residual (their MoE
contribution is zero) — the standard GShard drop policy, exposed in the
returned aux so tests and training can watch it.

``shard_moe_params`` places the stacked expert kernels over the mesh;
everything else in the layer is replicated.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "MoEMLP",
    "shard_moe_params",
    "moe_param_spec",
    "collect_load_balance_loss",
    "apply_collecting_moe_aux",
]


def apply_collecting_moe_aux(model, params, x, **apply_kwargs):
    """``model.apply`` with the MoE stat collection open, returning
    ``(output, aux)`` where ``aux`` is the per-layer-mean load-balance
    loss or ``None`` for dense models.

    The shared forward for every step builder that regularizes routing:
    one place owns the ``mutable=["moe_stats"]`` plumbing so the
    builders cannot drift apart.
    """
    out, state = model.apply(
        {"params": params}, x, mutable=["moe_stats"], **apply_kwargs
    )
    return out, collect_load_balance_loss(state)


def collect_load_balance_loss(state: Any):
    """Mean over MoE layers of the sown ``moe_stats/load_balance_loss``.

    ``state`` is the mutable-collection dict returned by
    ``model.apply(..., mutable=["moe_stats"])``.  A model with several
    MoE blocks sows one scalar per block under its own module path; the
    step builders regularize with the MEAN across blocks (the Switch
    convention — arXiv:2101.03961 reports per-layer aux averaged into
    one coefficient) so the coefficient's meaning doesn't change with
    depth.

    Returns ``None`` when the model sowed nothing (a dense model run
    through an MoE-aware step builder) — a trace-time structural fact,
    so step builders can skip the aux term entirely under ``jit``.
    """
    from collections.abc import Mapping

    col = state.get("moe_stats") if isinstance(state, Mapping) else None
    if not col:
        return None
    leaves = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(col)[0]
        if any(getattr(k, "key", None) == "load_balance_loss" for k in path)
    ]
    if not leaves:
        return None
    total = leaves[0]
    for leaf in leaves[1:]:
        total = total + leaf
    return total / len(leaves)


class MoEMLP(nn.Module):
    """Top-k MoE feed-forward block: gate -> dispatch -> per-expert MLP
    -> combine.  Input/output (B, T, d).

    ``top_k=1`` is the Switch-style router; ``top_k=2`` the GShard
    default (second choice queues for capacity AFTER every first
    choice, the standard priority rule).  Selected gates renormalize to
    sum to one.  The router's load-balance auxiliary
    (``aux = E * sum_e f_e * P_e`` — arXiv:2101.03961 eq. 4, where
    ``f_e`` is the fraction of tokens first-routed to expert ``e`` and
    ``P_e`` the mean router probability) is sown as
    ``moe_stats/load_balance_loss`` for the training loss to pick up.
    """

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    top_k: int = 1
    drop_tokens: bool = True
    dtype: jnp.dtype = jnp.float32
    # MANUAL expert parallelism (for shard_map contexts — the pipeline's
    # stages, where GSPMD auto-sharding can't reach): when set, this
    # module's expert kernels hold only the LOCAL E/n shard (the caller
    # shards the stacked (E, ...) kernels over the axis), routing is
    # computed against the GLOBAL expert set from the replicated gate,
    # each shard runs its own experts on the (replicated) tokens, and
    # one ``lax.psum`` over the axis combines — no all-to-all at all,
    # because tokens are replicated across the expert axis here (the
    # pp x ep layout).  ``None`` keeps the GSPMD-auto formulation the
    # fsdp/tp/data-sharded paths use.
    expert_axis: str | None = None

    def _local_experts(self, E: int) -> tuple[int, int]:
        """(E_local, my first global expert index) under manual ep."""
        if self.expert_axis is None:
            return E, 0
        n = jax.lax.axis_size(self.expert_axis)
        if E % n:
            raise ValueError(
                f"num_experts {E} must be divisible by the "
                f"{self.expert_axis!r} axis size {n}"
            )
        return E // n, jax.lax.axis_index(self.expert_axis) * (E // n)

    @nn.compact
    def __call__(self, x):
        B, T, d = x.shape
        E = self.num_experts
        S = B * T
        if not 1 <= self.top_k <= E:
            raise ValueError(f"top_k {self.top_k} not in [1, {E}]")
        C = max(1, math.ceil(S / E * self.capacity_factor))
        tokens = x.reshape(S, d)

        gate_logits = nn.Dense(E, use_bias=False, dtype=self.dtype,
                               name="gate")(tokens)  # (S, E)
        probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

        # k routing choices, each a one-hot over experts; choice j+1 is
        # the argmax with previous choices masked out (static shapes —
        # this is a Python loop over a small constant k).
        masked = probs
        onehots, gates = [], []
        for _ in range(self.top_k):
            expert_j = jnp.argmax(masked, axis=-1)             # (S,)
            oh = jax.nn.one_hot(expert_j, E, dtype=jnp.float32)
            onehots.append(oh)
            gates.append(jnp.sum(probs * oh, axis=-1))         # (S,)
            masked = masked * (1.0 - oh)
        if self.top_k > 1:
            # Renormalize the selected gates (GShard): combine weights
            # sum to 1 over the chosen experts.
            gsum = sum(gates)
            gates = [g / jnp.maximum(gsum, 1e-9) for g in gates]
        # top_k == 1 keeps the RAW router probability as the combine
        # weight (Switch-style) — renormalizing would make it constant
        # 1.0 and cut the router out of the gradient entirely.

        # Load-balance aux on FIRST choices (Switch eq. 4).  Sown before
        # the routing-branch split so both branches expose the identical
        # stat surface — the aux depends only on the router, not on how
        # tokens are dispatched.
        f_e = jnp.mean(onehots[0], axis=0)                     # (E,)
        p_e = jnp.mean(probs, axis=0)                          # (E,)
        self.sow(
            "moe_stats", "load_balance_loss",
            E * jnp.sum(f_e * p_e),
            reduce_fn=lambda a, b: b,
        )

        if not self.drop_tokens:
            return self._dense_dropfree(
                x, tokens, onehots, gates, B, T, d, E, S
            )

        # Capacity slots with choice priority: choice j's tokens queue
        # behind ALL tokens of choices < j for the same expert.
        occupancy = jnp.zeros((E,), jnp.float32)
        dispatches = []
        for oh in onehots:
            pos = (jnp.cumsum(oh, axis=0) - oh) * oh           # (S, E)
            pos_in_e = (
                jnp.sum(pos, axis=-1) + jnp.sum(oh * occupancy, axis=-1)
            ).astype(jnp.int32)                                # (S,)
            kept = pos_in_e < C
            dispatches.append(
                oh[:, :, None]
                * jax.nn.one_hot(pos_in_e, C, dtype=jnp.float32)[:, None, :]
                * kept[:, None, None]
            )
            occupancy = occupancy + jnp.sum(oh, axis=0)
        # (S, E, C) combined dispatch, gate-weighted combine tensor.
        dispatch = sum(dispatches)
        combine_w = sum(
            g[:, None, None] * dsp for g, dsp in zip(gates, dispatches)
        )

        # Manual ep: routing above used the GLOBAL expert set; this
        # shard computes only its E/n experts, so slice its columns of
        # the dispatch/combine tensors and declare the LOCAL kernels.
        E_loc, e0 = self._local_experts(E)
        disp_total = jnp.sum(dispatch)  # global (pre-slice) kept count
        if self.expert_axis is not None:
            dispatch = jax.lax.dynamic_slice_in_dim(dispatch, e0, E_loc, 1)
            combine_w = jax.lax.dynamic_slice_in_dim(combine_w, e0, E_loc, 1)

        # Expert buffers: (E, C, d) — the all-to-all XLA inserts when
        # tokens are data-sharded and experts expert-sharded (under
        # manual ep tokens are replicated across the axis, so this is
        # pure local compute instead).
        buffers = jnp.einsum("sec,sd->ecd", dispatch,
                             tokens.astype(jnp.float32))

        h = self.mlp_ratio * d
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(batch_axis=(0,)),
            (E_loc, d, h), self.dtype,
        )
        b_up = self.param("b_up", nn.initializers.zeros, (E_loc, h),
                          self.dtype)
        w_dn = self.param(
            "w_dn", nn.initializers.lecun_normal(batch_axis=(0,)),
            (E_loc, h, d), self.dtype,
        )
        b_dn = self.param("b_dn", nn.initializers.zeros, (E_loc, d),
                          self.dtype)

        act = jnp.einsum("ecd,edh->ech", buffers, w_up.astype(jnp.float32))
        act = nn.gelu(act + b_up.astype(jnp.float32)[:, None, :])
        out_e = jnp.einsum("ech,ehd->ecd", act, w_dn.astype(jnp.float32))
        out_e = out_e + b_dn.astype(jnp.float32)[:, None, :]

        # Combine with the gate-weighted tensor: out_s = sum over the
        # token's kept choices of gate_j * expert_out.  Under manual ep
        # each shard contributes its experts' share; the psum exit is
        # the whole combine (and, like the TP stages, transposes to the
        # correct cotangent broadcast automatically — training/tp.py's
        # NOTE).
        out = jnp.einsum("sec,ecd->sd", combine_w, out_e)
        if self.expert_axis is not None:
            # graftlint: disable=raw-collective-in-shard-map -- manual-EP combine exit: psum over expert_axis totals the shards' gate-weighted expert outputs; entry-cast transpose is the cotangent broadcast (training/tp.py NOTE)
            out = jax.lax.psum(out, self.expert_axis)
        self.sow(
            "moe_stats", "dropped_fraction",
            1.0 - disp_total / (S * self.top_k),
            reduce_fn=lambda a, b: b,
        )
        return out.reshape(B, T, d).astype(x.dtype)

    def _dense_dropfree(self, x, tokens, onehots, gates, B, T, d,
                        E, S):
        """Drop-free path (``drop_tokens=False`` — autoregressive
        decode): run EVERY expert on every token and combine with the
        top-k gate weights.  Capacity drops depend on the other tokens
        sharing the flattened batch (order-dependent), so decode must
        not drop or incremental and from-scratch computations of the
        same position diverge.  Dense all-experts costs E*S*d*h — less
        than the (S, E, S)-dispatch alternative whenever S > ratio*d —
        and keeps every shape static.
        """
        h = self.mlp_ratio * d
        # Declare the SAME params as the dropping branch (names, shapes,
        # initializers) so a drop-free module inits/shards identically
        # (LOCAL shard shapes under manual ep, exactly as there).
        E_loc, e0 = self._local_experts(E)
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(batch_axis=(0,)),
            (E_loc, d, h), self.dtype,
        )
        b_up = self.param("b_up", nn.initializers.zeros, (E_loc, h),
                          self.dtype)
        w_dn = self.param(
            "w_dn", nn.initializers.lecun_normal(batch_axis=(0,)),
            (E_loc, h, d), self.dtype,
        )
        b_dn = self.param("b_dn", nn.initializers.zeros, (E_loc, d),
                          self.dtype)
        act = jnp.einsum(
            "sd,edh->seh", tokens.astype(jnp.float32),
            w_up.astype(jnp.float32),
        ) + b_up.astype(jnp.float32)[None]
        act = nn.gelu(act)
        out_e = jnp.einsum(
            "seh,ehd->sed", act, w_dn.astype(jnp.float32)
        ) + b_dn.astype(jnp.float32)[None]
        weight = sum(
            g[:, None] * oh for g, oh in zip(gates, onehots)
        )  # (S, E) over the GLOBAL experts; slice this shard's columns.
        if self.expert_axis is not None:
            weight = jax.lax.dynamic_slice_in_dim(weight, e0, E_loc, 1)
        out = jnp.einsum("se,sed->sd", weight, out_e)
        if self.expert_axis is not None:
            # graftlint: disable=raw-collective-in-shard-map -- manual-EP combine exit (dense top-k path): same psum-over-expert_axis combine as above
            out = jax.lax.psum(out, self.expert_axis)
        self.sow(
            "moe_stats", "dropped_fraction", jnp.zeros(()),
            reduce_fn=lambda a, b: b,
        )
        return out.reshape(B, T, d).astype(x.dtype)


def moe_param_spec(path: tuple, leaf, expert_axis: str) -> P:
    """Stacked expert kernels shard over the expert axis; the gate and
    everything else replicate."""
    names = [getattr(k, "key", str(k)) for k in path]
    if names and names[-1] in ("w_up", "b_up", "w_dn", "b_dn"):
        return P(expert_axis, *([None] * (leaf.ndim - 1)))
    return P()


def shard_moe_params(params: Any, mesh: Mesh,
                     expert_axis: str = "expert") -> Any:
    """Device-put an :class:`MoEMLP`-bearing param tree with the expert
    kernels split over ``expert_axis``."""
    def place(path, leaf):
        return jax.device_put(
            leaf, NamedSharding(mesh, moe_param_spec(path, leaf, expert_axis))
        )

    return jax.tree_util.tree_map_with_path(place, params)
