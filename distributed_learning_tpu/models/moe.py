"""Mixture-of-experts MLP with expert parallelism (GShard-style).

The fifth axis of the parallelism matrix: expert weights are a stacked
``(E, ...)`` tree whose leading axis shards over an ``expert`` mesh
axis, and the layer is written as dense einsums against a one-hot
dispatch tensor — the GShard formulation (arXiv:2006.16668) that keeps
shapes static so the XLA partitioner can place the token all-to-alls
itself.  No dynamic routing control flow anywhere: ``top-1`` gating
becomes a ``(tokens, E, C)`` one-hot, dispatch and combine are its two
einsum contractions.

Capacity: each expert processes at most ``C = ceil(tokens/E * factor)``
tokens; overflow tokens fall through the residual (their MoE
contribution is zero) — the standard GShard drop policy, exposed in the
returned aux so tests and training can watch it.

``shard_moe_params`` places the stacked expert kernels over the mesh;
everything else in the layer is replicated.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["MoEMLP", "shard_moe_params", "moe_param_spec"]


class MoEMLP(nn.Module):
    """Top-1 MoE feed-forward block: gate -> dispatch -> per-expert MLP
    -> combine.  Input/output (B, T, d)."""

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        B, T, d = x.shape
        E = self.num_experts
        S = B * T
        C = max(1, math.ceil(S / E * self.capacity_factor))
        tokens = x.reshape(S, d)

        gate_logits = nn.Dense(E, use_bias=False, dtype=self.dtype,
                               name="gate")(tokens)  # (S, E)
        probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        expert = jnp.argmax(probs, axis=-1)           # (S,)
        gate = jnp.max(probs, axis=-1)                # (S,)

        # Position of each token within its expert's capacity buffer:
        # rank among same-expert tokens in sequence order (static shapes:
        # a cumsum over the one-hot).
        onehot_e = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (S, E)
        pos = (jnp.cumsum(onehot_e, axis=0) - onehot_e) * onehot_e  # (S, E)
        pos_in_e = jnp.sum(pos, axis=-1).astype(jnp.int32)  # (S,)
        kept = pos_in_e < C
        # (S, E, C) dispatch: one-hot over both expert and slot, zeroed
        # for dropped tokens.
        dispatch = (
            onehot_e[:, :, None]
            * jax.nn.one_hot(pos_in_e, C, dtype=jnp.float32)[:, None, :]
            * kept[:, None, None]
        )

        # Expert buffers: (E, C, d) — the all-to-all XLA inserts when
        # tokens are data-sharded and experts expert-sharded.
        buffers = jnp.einsum("sec,sd->ecd", dispatch,
                             tokens.astype(jnp.float32))

        h = self.mlp_ratio * d
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(batch_axis=(0,)), (E, d, h),
            self.dtype,
        )
        b_up = self.param("b_up", nn.initializers.zeros, (E, h), self.dtype)
        w_dn = self.param(
            "w_dn", nn.initializers.lecun_normal(batch_axis=(0,)), (E, h, d),
            self.dtype,
        )
        b_dn = self.param("b_dn", nn.initializers.zeros, (E, d), self.dtype)

        act = jnp.einsum("ecd,edh->ech", buffers, w_up.astype(jnp.float32))
        act = nn.gelu(act + b_up.astype(jnp.float32)[:, None, :])
        out_e = jnp.einsum("ech,ehd->ecd", act, w_dn.astype(jnp.float32))
        out_e = out_e + b_dn.astype(jnp.float32)[:, None, :]

        combined = jnp.einsum("sec,ecd->sd", dispatch, out_e)
        out = combined * gate[:, None]                 # top-1 scaling
        self.sow(
            "moe_stats", "dropped_fraction",
            1.0 - jnp.sum(dispatch) / S,
            reduce_fn=lambda a, b: b,
        )
        return out.reshape(B, T, d).astype(x.dtype)


def moe_param_spec(path: tuple, leaf, expert_axis: str) -> P:
    """Stacked expert kernels shard over the expert axis; the gate and
    everything else replicate."""
    names = [getattr(k, "key", str(k)) for k in path]
    if names and names[-1] in ("w_up", "b_up", "w_dn", "b_dn"):
        return P(expert_axis, *([None] * (leaf.ndim - 1)))
    return P()


def shard_moe_params(params: Any, mesh: Mesh,
                     expert_axis: str = "expert") -> Any:
    """Device-put an :class:`MoEMLP`-bearing param tree with the expert
    kernels split over ``expert_axis``."""
    def place(path, leaf):
        return jax.device_put(
            leaf, NamedSharding(mesh, moe_param_spec(path, leaf, expert_axis))
        )

    return jax.tree_util.tree_map_with_path(place, params)
