"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence models (SURVEY.md §5: "long-context/sequence
parallelism: entirely absent"), but its gossip ring is exactly the
communication structure ring attention uses — each device passes its block
to the next neighbor every step.  This module makes that structure a
first-class capability so the framework handles long sequences at
multi-chip scale:

* :func:`ring_attention` — blockwise attention with online-softmax
  accumulation; K/V blocks rotate around the device ring via
  ``jax.lax.ppermute`` while every device keeps its resident Q shard.
  Peak memory per device is O(T_local^2) instead of O(T^2), enabling
  sequences n_devices times longer at the same memory.
* :func:`ulysses_attention` — all-to-all sequence parallelism: resharding
  from sequence-sharded to head-sharded via ``jax.lax.all_to_all``, local
  full attention, and the inverse resharding.  Cheaper than the ring when
  heads >= devices and the all-to-all fits ICI.

Both are pure functions designed for use inside ``shard_map`` over a mesh
axis (the same ``agents``/sequence axis the consensus engine uses) and are
exact: outputs match full single-device attention to float tolerance.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["attention_reference", "ring_attention", "ulysses_attention", "ring_flash_attention", "make_ring_attention"]


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Plain full attention (B, T, H, D) — the correctness oracle.

    ``window`` (requires ``causal``) restricts row ``r`` to keys in
    ``[r - window + 1, r]`` — causal sliding-window attention."""
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if causal:
        T, S = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        if window is not None:
            mask &= ~jnp.tril(jnp.ones((T, S), bool), k=S - T - window)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _block_accumulate(carry, q, k, v, q_pos, kv_pos, scale, causal):
    """One online-softmax accumulation step against a single K/V block.

    carry = (acc, l, m): running weighted values (B, Tq, H, D), softmax
    denominator (B, H, Tq), and row max (B, H, Tq) — the standard
    flash/blockwise-attention recurrence, computed in f32.
    """
    acc, l, m = carry
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]  # (Tq, Tk)
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # Rows with nothing unmasked so far keep m=-inf; guard the exps.
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return acc_new, l_new, m_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Exact blockwise ring attention on sequence-sharded (B, T/n, H, D)
    inputs; call inside ``shard_map`` with the sequence axis sharded over
    ``axis_name``.

    Every step each device attends its resident Q against the K/V block it
    currently holds, then passes that block one hop around the ring
    (``ppermute`` — an ICI-neighbor transfer on a TPU torus, the same
    collective the consensus engine gossips with).  After ``n`` steps every
    Q row has seen every key exactly once.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    B, _, H, D = q.shape

    q_pos = idx * t_local + jnp.arange(t_local)
    # The loop body makes every carry component device-varying (it mixes in
    # ppermuted data), so the initial accumulators must be marked varying
    # too (shard_map's vma check rejects unvarying->varying carries).  On a
    # multi-axis mesh (e.g. the 2D agents x seq step) the inputs vary over
    # EVERY sharded axis, so the carries must match q's full vma, not just
    # the ring axis.
    vary_axes = tuple(getattr(jax.typeof(q), "vma", None) or (axis_name,))
    # graftlint: disable=raw-collective-in-shard-map -- vma cast: fresh carries marked varying over the ring axes so cotangents stay LOCAL (the pcast-before-local-cotangent rule, training/pp.py head_seed)
    pvary = lambda x: lax.pcast(x, vary_axes, to="varying")
    acc0 = pvary(jnp.zeros((B, t_local, H, D), jnp.float32))
    l0 = pvary(jnp.zeros((B, H, t_local), jnp.float32))
    m0 = pvary(jnp.full((B, H, t_local), -jnp.inf, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry_kv):
        (acc, l, m), (k_blk, v_blk, src) = carry_kv
        kv_pos = src * t_local + jnp.arange(t_local)
        acc, l, m = _block_accumulate(
            (acc, l, m), q, k_blk, v_blk, q_pos, kv_pos, scale, causal
        )
        # Rotate the K/V block (and its origin index) one hop.
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (acc, l, m), (k_blk, v_blk, src)

    carry = ((acc0, l0, m0), (k, v, idx))
    carry = lax.fori_loop(0, n, lambda i, c: step(i, c), carry)
    (acc, l, _m), _ = carry
    l = jnp.maximum(l, 1e-30)  # causal first row always attends to itself
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all sequence parallelism (Ulysses): inputs arrive
    sequence-sharded (B, T/n, H, D); one ``all_to_all`` makes them
    head-sharded with the full sequence (B, T, H/n, D); local full
    attention; inverse ``all_to_all`` back.  Requires H % n == 0."""
    n = lax.axis_size(axis_name)
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(f"ulysses needs heads ({H}) divisible by axis size ({n})")

    def seq_to_heads(x):
        # (B, T/n, H, D) -> concat over seq of (B, T/n, H/n, D) -> (B, T, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_reference(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(out)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the per-block
    compute: the long-context composition where the ppermute ring moves
    K/V between devices and each local (Q-shard x K/V-block) attention
    runs fused on the MXU instead of as XLA einsums.

    Exactness: each block call returns (out_i, lse_i); blocks combine by
    the same max-shifted recurrence flash uses internally —
    ``out = sum_i out_i * exp(lse_i - lse_total) ``, which is the full
    softmax over all keys.  Gradients flow end-to-end: the lse consumer
    makes d loss/d lse nonzero, which the kernel backward folds in as
    the ``dadj`` row term (``ops/flash_attention.py``).

    Block structure under causality: a rotating K/V block is entirely in
    this shard's past (full attention), entirely in its future (skipped
    — no FLOPs, via ``lax.cond``), or the resident diagonal (causal
    kernel).  Off-TPU without ``interpret`` the block calls fall back to
    the reference path, so this stays runnable (and differentiable) on
    the CPU mesh.
    """
    from distributed_learning_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, t_local, H, D = q.shape
    scale = sm_scale if sm_scale is not None else float(1.0 / np.sqrt(D))

    def fit_block(request: int) -> int:
        # Largest divisor of the shard length <= the requested block,
        # preferring multiples of 8 (the TPU lowering also needs the
        # second-to-last block dim sublane-aligned).  A shard length not
        # divisible by 8 has no aligned divisor; the best unaligned one
        # still serves CPU/interpret, and the TPU guard below rejects it
        # with a clear message instead of a Mosaic lowering error.
        b = min(request, t_local)
        aligned = next(
            (c for c in range(b, 7, -1) if t_local % c == 0 and c % 8 == 0),
            None,
        )
        if aligned is not None:
            return aligned
        while t_local % b:
            b -= 1
        return b

    if jax.devices()[0].platform == "tpu" and t_local % 8 and not interpret:
        raise ValueError(
            f"ring_flash_attention on TPU needs the per-device shard "
            f"length divisible by 8, got {t_local}; use the einsum ring "
            "(strategy='ring') or repad the sequence"
        )

    kernel = functools.partial(
        flash_attention_with_lse, sm_scale=scale,
        block_q=fit_block(block_q), block_k=fit_block(block_k),
        interpret=interpret,
    )

    def diag_block(q, k_blk, v_blk):
        return kernel(q, k_blk, v_blk, causal=True)

    def full_block(q, k_blk, v_blk):
        return kernel(q, k_blk, v_blk, causal=False)

    def dead_block(q, k_blk, v_blk):
        # Fully-masked: contributes nothing.  lse = -inf zeroes its
        # weight in the combine (guarded exp below).  pcast: the live
        # branches consume the ppermuted (device-varying) K/V, so cond
        # needs this branch's fresh constants marked varying too (over
        # q's full vma — multi-axis meshes vary over more than the ring).
        # graftlint: disable=raw-collective-in-shard-map -- vma cast: cond branch constants must match the live branches' varying set (local-cotangent rule, training/pp.py head_seed)
        pv = lambda x: lax.pcast(x, vary_axes, to="varying")
        return (
            pv(jnp.zeros((B, t_local, H, D), q.dtype)),
            pv(jnp.full((B, H, t_local), -jnp.inf, jnp.float32)),
        )

    vary_axes = tuple(getattr(jax.typeof(q), "vma", None) or (axis_name,))
    # graftlint: disable=raw-collective-in-shard-map -- vma cast: fresh carries marked varying over the ring axes so cotangents stay LOCAL (the pcast-before-local-cotangent rule, training/pp.py head_seed)
    pvary = lambda x: lax.pcast(x, vary_axes, to="varying")
    acc0 = pvary(jnp.zeros((B, t_local, H, D), jnp.float32))
    l0 = pvary(jnp.zeros((B, H, t_local), jnp.float32))
    m0 = pvary(jnp.full((B, H, t_local), -jnp.inf, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry_kv):
        (acc, l, m), (k_blk, v_blk, src) = carry_kv
        if causal:
            out_i, lse_i = lax.cond(
                src > idx,
                dead_block,
                lambda q, kb, vb: lax.cond(
                    src == idx, diag_block, full_block, q, kb, vb
                ),
                q, k_blk, v_blk,
            )
        else:
            out_i, lse_i = full_block(q, k_blk, v_blk)

        # Max-shifted combine; guards mirror _block_accumulate's so
        # -inf - -inf never produces a NaN.
        m_new = jnp.maximum(m, lse_i)
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        beta = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - safe_m), 0.0)
        to_t = lambda x: x.transpose(0, 2, 1)[..., None]  # (B,H,t)->(B,t,H,1)
        acc = acc * to_t(alpha) + out_i.astype(jnp.float32) * to_t(beta)
        l = l * alpha + beta
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (acc, l, m_new), (k_blk, v_blk, src)

    carry = ((acc0, l0, m0), (k, v, idx))
    carry = lax.fori_loop(0, n, lambda i, c: step(c), carry)
    (acc, l, _m), _ = carry
    l = jnp.maximum(l, 1e-30)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    strategy: str = "ring",
    causal: bool = True,
    interpret: bool = False,
):
    """Jitted sequence-parallel attention over globally-shaped arrays.

    Returns ``fn(q, k, v) -> out`` taking full (B, T, H, D) arrays with T
    sharded over ``axis_name``; internally a ``shard_map`` of
    :func:`ring_attention`, :func:`ulysses_attention`, or
    :func:`ring_flash_attention` (``strategy="ring_flash"`` — the Pallas
    per-block kernel; ``interpret`` reaches its block calls for CPU
    testing).
    """
    impl = {
        "ring": ring_attention,
        "ulysses": ulysses_attention,
        "ring_flash": ring_flash_attention,
    }[strategy]
    spec = P(None, axis_name, None, None)

    # Pallas INTERPRET mode evaluates the kernel jaxpr with its own
    # dynamic_slice block indexing, which mixes varying and unvarying
    # operands in a way the shard_map vma checker rejects inside its
    # machinery (JAX's error text prescribes check_vma=False as the
    # workaround).  Scoped to exactly that combination: the compiled TPU
    # path and the einsum strategies keep the check.
    check_vma = not (strategy == "ring_flash" and interpret)

    @jax.jit
    def fn(q, k, v):
        local = functools.partial(impl, axis_name=axis_name, causal=causal)
        if strategy == "ring_flash":
            local = functools.partial(local, interpret=interpret)
        sharded = jax.shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=check_vma,
        )
        sharding = NamedSharding(mesh, spec)
        q_, k_, v_ = (jax.lax.with_sharding_constraint(x, sharding) for x in (q, k, v))
        return sharded(q_, k_, v_)

    return fn
