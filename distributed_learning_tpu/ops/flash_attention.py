"""Fused flash attention as a Pallas TPU kernel.

The single-device hot op behind the transformer path: O(T^2) attention
computed blockwise with the online-softmax recurrence, so neither the
(T, T) score matrix nor the full K/V ever sits in VMEM.  Grid =
(batch*heads, q-blocks, k-blocks): the innermost k dimension iterates
sequentially on a TPU core, so the (block_q, D) accumulator and the
running max/denominator live in VMEM scratch across k steps — initialized
at k==0, finalized into the output block at the last k.  K/V blocks
stream HBM->VMEM via the grid's implicit double-buffered DMA, matmuls hit
the MXU with f32 accumulation, and the causal path skips the compute for
fully-masked blocks.

Context length is bounded by HBM, not VMEM.  Measured throughput comes
from ``benchmarks/bench_attention.py`` (TFLOP/s at 8k/32k/131k with a
block-size sweep); numbers live in ``BASELINE.json:"published"``, not
here.  On CPU the same kernel runs under ``interpret=True`` for the
tests; correctness bar: match
:func:`~distributed_learning_tpu.ops.ring_attention.attention_reference`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_learning_tpu.ops.ring_attention import attention_reference

__all__ = ["flash_attention"]

_NEG_INF = -1e30  # large-but-finite: exp(-1e30 - m) underflows to 0 cleanly
_LANES = 128  # scratch vectors are lane-replicated to the native tile width


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale, causal
):
    """One (bh, qi, kj) grid step of the online-softmax recurrence."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: blocks whose first key is beyond this q block's last query
    # are fully masked — skip their FLOPs entirely.
    live = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:, :1]  # lane-replicated; any lane is the value
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention on (B, T, H, D); T must divide by the block sizes.

    Off-TPU without ``interpret`` this falls back to the reference
    einsum/softmax path (XLA fuses it well enough on CPU; the kernel is
    the TPU fast path).
    """
    B, T, H, D = q.shape
    scale = sm_scale if sm_scale is not None else float(1.0 / np.sqrt(D))
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        return attention_reference(q, k, v, causal=causal, sm_scale=scale)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(
            f"sequence length {T} must be divisible by block sizes "
            f"({block_q}, {block_k})"
        )

    # (B, T, H, D) -> (B*H, T, D): one grid row per (batch, head).
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    kernel = functools.partial(_flash_kernel, sm_scale=scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, kj: (bh, kj, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
