"""Fused flash attention as Pallas TPU kernels — forward AND backward.

The single-device hot op behind the transformer path: O(T^2) attention
computed blockwise with the online-softmax recurrence, so neither the
(T, T) score matrix nor the full K/V ever sits in VMEM.  Grid =
(batch*heads, q-blocks, k-blocks): the innermost k dimension iterates
sequentially on a TPU core, so the (block_q, D) accumulator and the
running max/denominator live in VMEM scratch across k steps — initialized
at k==0, finalized into the output block at the last k.  K/V blocks
stream HBM->VMEM via the grid's implicit double-buffered DMA, matmuls hit
the MXU with f32 accumulation, and the causal path skips the compute for
fully-masked blocks.

Training works through the kernel: a ``jax.custom_vjp`` supplies the
standard recompute-based flash backward.  The forward additionally saves
the per-row logsumexp of the scaled scores — lane-replicated to shape
``(BH, T, 128)``, the layout the TPU Pallas lowering requires (the last
two block dims must tile to (8, 128); a ``(1, block_q)`` block does not
lower, as the real compiler taught this module the hard way).  The
backward recomputes each score block from (Q, K) on the MXU instead of
materializing the (T, T) probability matrix, and splits into two kernels
so every accumulator is a sequential reduction over its innermost grid
axis:

* dQ kernel  — grid (BH, q-blocks, k-blocks): for one Q block, walk K/V
  blocks accumulating dQ += scale * dS @ K with dS = P * (dP - delta),
  P = exp(S - lse), dP = dO @ V^T, delta = rowsum(dO * O)  (computed
  in-kernel from the O block — cheaper than materializing a (BH, T, 128)
  delta tensor in HBM).
* dK/dV kernel — grid (BH, k-blocks, q-blocks): for one K/V block, walk
  Q blocks accumulating dV += P^T @ dO and dK += scale * dS^T @ Q.

Head dims that do not fill a 128-lane tile are zero-padded to 128 before
the kernels and sliced after — scores and softmax are unchanged by zero
columns, and the pad/slice pair is differentiable, so the padding
composes with the custom VJP.

Context length is bounded by HBM, not VMEM.  Measured throughput comes
from ``benchmarks/bench_attention.py`` (TFLOP/s at 8k/32k/131k with a
block-size sweep); numbers live in ``BASELINE.json:"published"``, not
here.  On CPU the same kernels run under ``interpret=True`` for the
tests; correctness bar: values and gradients match
:func:`~distributed_learning_tpu.ops.ring_attention.attention_reference`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_learning_tpu.ops.ring_attention import attention_reference

__all__ = ["flash_attention", "flash_attention_with_lse"]

_NEG_INF = -1e30  # large-but-finite: exp(-1e30 - m) underflows to 0 cleanly
_LANES = 128  # native tile width: scratch vectors and lse are lane-replicated


def _sds(shape, dtype, like):
    """ShapeDtypeStruct matching ``like``'s varying-manual-axes: under
    ``shard_map`` (ring flash attention) pallas outputs must declare
    their vma or the shard_map vma check rejects the call; under plain
    jit the vma set is empty and this is an ordinary SDS."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _causal_live(qi, kj, block_q, block_k, window=None):
    """Whether block (qi, kj) holds any unmasked (row >= col) pair —
    and, with a sliding ``window``, any pair inside the band
    ``col >= row - window + 1``.  Blocks entirely below the band are as
    dead as blocks above the diagonal: skipping both is what turns the
    windowed kernel's cost from O(T^2) into O(T * window)."""
    live = kj * block_k <= (qi + 1) * block_q - 1
    if window is not None:
        # program ids are traced: combine with &, not `and`.
        live = live & ((kj + 1) * block_k - 1 >= qi * block_q - (window - 1))
    return live


def _masked_scores(q, k_blk, qi, kj, block_q, block_k, sm_scale, causal,
                   window=None):
    """Scaled (block_q, block_k) scores with causal masking applied.

    The Q@K^T matmul runs in the refs' native dtype (bf16 in the training
    path) with f32 accumulation — upcasting the inputs first would force
    an f32 MXU pass at a fraction of bf16 throughput (measured on v5e:
    the all-f32 variant of this kernel sustained 10.9 TFLOP/s vs 197
    peak).  ``sm_scale`` is applied to the f32 scores after the matmul,
    which also preserves more precision than scaling bf16 queries."""
    s = jax.lax.dot_general(
        q, k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        keep = cols <= rows
        if window is not None:
            keep &= cols >= rows - (window - 1)
        s = jnp.where(keep, s, _NEG_INF)
    return s


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, sm_scale, causal, window=None,
):
    """One (bh, qi, kj) grid step of the online-softmax recurrence."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: blocks whose first key is beyond this q block's last query
    # are fully masked — skip their FLOPs entirely.
    live = (_causal_live(qi, kj, block_q, block_k, window)
            if causal else True)

    @pl.when(live)
    def _step():
        s = _masked_scores(
            q_ref[0], k_ref[0], qi, kj, block_q, block_k, sm_scale, causal,
            window,
        )
        m_prev = m_ref[:, :1]  # lane-replicated; any lane is the value
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        # l is summed from the f32 probabilities above; only the matmul
        # operand drops to V's dtype, so the normalizer stays exact while
        # P@V hits the MXU at native-dtype rate (identity cast for f32 V).
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, :1]).astype(o_ref.dtype)
        if lse_ref is not None:
            # Per-row logsumexp of the SCALED scores — the backward's
            # softmax normalizer, so P is recomputed without a second
            # online pass.  Lane-replicated (block_q, 128): pure
            # elementwise on the already-replicated m/l scratch, which the
            # Mosaic lowering takes.  The primal (inference) path omits
            # this output entirely rather than write-and-discard it.
            lse_ref[0] = m_ref[...] + jnp.log(l)


def _flash_dq_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dadj_ref, dq_ref, dq_acc,
    *, sm_scale, causal, window=None,
):  # dadj_ref is None on the plain path (no lse consumer): zero term.
    """dQ for one Q block: sequential accumulation over K/V blocks.

    ``dadj`` is a per-row additive adjustment to the softmax backward:
    ``dS = P * (dP - delta + dadj)``.  Zero for plain attention; the lse
    cotangent when the caller consumes the logsumexp output too (ring
    flash attention combines blocks through their lse, so d loss/d lse
    is generally nonzero — the math folds it into exactly this term).
    """
    qi, kj = pl.program_id(1), pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (_causal_live(qi, kj, block_q, block_k, window)
            if causal else True)

    @pl.when(live)
    def _step():
        s = _masked_scores(
            q_ref[0], k_ref[0], qi, kj, block_q, block_k, sm_scale, causal,
            window,
        )
        p = jnp.exp(s - lse_ref[0][:, :1])  # (bq, bk); masked entries -> 0
        # Matmuls run on native-dtype operands with f32 accumulation (see
        # _masked_scores); delta's (bq, D) multiply-reduce stays f32 on
        # the VPU — noise next to the two MXU matmuls.
        delta = jnp.sum(
            do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        dp = jax.lax.dot_general(  # dO @ V^T -> (bq, bk)
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        adj = 0.0 if dadj_ref is None else dadj_ref[0][:, :1]
        ds = p * (dp - delta + adj)
        dq_acc[...] += sm_scale * jax.lax.dot_general(  # dS @ K -> (bq, D)
            ds.astype(k_ref.dtype), k_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dadj_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, sm_scale, causal, window=None,
):
    """dK and dV for one K/V block: sequential accumulation over Q blocks.
    ``dadj`` as in :func:`_flash_dq_kernel`."""
    kj, qi = pl.program_id(1), pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (_causal_live(qi, kj, block_q, block_k, window)
            if causal else True)

    @pl.when(live)
    def _step():
        q_blk = q_ref[0]
        s = _masked_scores(
            q_blk, k_ref[0], qi, kj, block_q, block_k, sm_scale, causal,
            window,
        )
        p = jnp.exp(s - lse_ref[0][:, :1])  # (bq, bk)
        delta = jnp.sum(
            do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        dv_acc[...] += jax.lax.dot_general(  # P^T @ dO -> (bk, D)
            p.astype(do_ref.dtype), do_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(  # dO @ V^T -> (bq, bk)
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        adj = 0.0 if dadj_ref is None else dadj_ref[0][:, :1]
        ds = p * (dp - delta + adj)
        dk_acc[...] += sm_scale * jax.lax.dot_general(  # dS^T @ Q -> (bk, D)
            ds.astype(q_blk.dtype), q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fwd_call(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret,
              *, with_lse, window=None):
    """Forward pallas_call; ``with_lse=False`` (the inference/primal path)
    omits the lse output entirely so forward-only callers don't pay a
    (BH, T, 128) f32 HBM write they would immediately discard."""
    BH, T, D = qb.shape
    if with_lse:
        kernel = functools.partial(
            _flash_kernel, sm_scale=sm_scale, causal=causal, window=window
        )
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
            _flash_kernel(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref,
                          l_ref, sm_scale=sm_scale, causal=causal,
                          window=window)
    o_spec = pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0))
    lse_spec = pl.BlockSpec(
        (1, block_q, _LANES), lambda bh, qi, kj: (bh, qi, 0)
    )
    o_shape = _sds((BH, T, D), qb.dtype, qb)
    lse_shape = _sds((BH, T, _LANES), jnp.float32, qb)
    return pl.pallas_call(
        kernel,
        grid=(BH, T // block_q, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, kj: (bh, kj, 0)),
        ],
        out_specs=[o_spec, lse_spec] if with_lse else o_spec,
        out_shape=[o_shape, lse_shape] if with_lse else o_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qb, kb, vb)


def _bwd_call(qb, kb, vb, out, do, lse, dadj, sm_scale, causal, block_q,
              block_k, interpret, window=None):
    """The two backward pallas_calls, shared by both custom VJPs.

    ``dadj=None`` (the plain path — no lse consumer) omits the extra
    kernel input entirely instead of streaming a known-zero tensor
    through both kernels' grids."""
    BH, T, D = qb.shape
    lse_spec_q = pl.BlockSpec(
        (1, block_q, _LANES), lambda bh, qi, kj: (bh, qi, 0)
    )
    lse_spec_kv = pl.BlockSpec(
        (1, block_q, _LANES), lambda bh, kj, qi: (bh, qi, 0)
    )
    extra = [] if dadj is None else [dadj]

    dq_kernel = functools.partial(
        _flash_dq_kernel, sm_scale=sm_scale, causal=causal, window=window
    )
    if dadj is None:
        def dq_kernel(q, k, v, o, do_, lse_, dq_, acc):
            _flash_dq_kernel(q, k, v, o, do_, lse_, None, dq_, acc,
                             sm_scale=sm_scale, causal=causal,
                             window=window)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, T // block_q, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
            lse_spec_q,
        ] + ([] if dadj is None else [lse_spec_q]),
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0)),
        out_shape=_sds((BH, T, D), qb.dtype, qb),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qb, kb, vb, out, do, lse, *extra)

    dkv_kernel = functools.partial(
        _flash_dkv_kernel, sm_scale=sm_scale, causal=causal, window=window
    )
    if dadj is None:
        def dkv_kernel(q, k, v, o, do_, lse_, dk_, dv_, ka, va):
            _flash_dkv_kernel(q, k, v, o, do_, lse_, None, dk_, dv_, ka, va,
                              sm_scale=sm_scale, causal=causal,
                              window=window)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, T // block_k, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, kj, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, kj, qi: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, kj, qi: (bh, kj, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, kj, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, kj, qi: (bh, qi, 0)),
            lse_spec_kv,
        ] + ([] if dadj is None else [lse_spec_kv]),
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, kj, qi: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, kj, qi: (bh, kj, 0)),
        ],
        out_shape=[
            _sds((BH, T, D), kb.dtype, qb),
            _sds((BH, T, D), vb.dtype, qb),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qb, kb, vb, out, do, lse, *extra)

    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret,
           window):
    return _fwd_call(qb, kb, vb, sm_scale, causal, block_q, block_k,
                     interpret, with_lse=False, window=window)


def _flash_fwd(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret,
               window):
    out, lse = _fwd_call(qb, kb, vb, sm_scale, causal, block_q, block_k,
                         interpret, with_lse=True, window=window)
    return out, (qb, kb, vb, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, window, res,
               do):
    qb, kb, vb, out, lse = res
    # dadj=None: no lse consumer, so the kernels omit the input entirely
    # instead of streaming a known-zero tensor through both grids.
    return _bwd_call(qb, kb, vb, out, do, lse, None, sm_scale, causal,
                     block_q, block_k, interpret, window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret):
    """Like :func:`_flash` but also returns the per-row logsumexp
    (lane-replicated (BH, T, 128) f32) — the building block for ring
    flash attention, whose cross-block combine differentiates through
    lse.  d lse/d s_rc = p_rc, which folds into the shared backward as
    the ``dadj`` row term."""
    return _fwd_call(qb, kb, vb, sm_scale, causal, block_q, block_k,
                     interpret, with_lse=True)


def _flash_lse_fwd(qb, kb, vb, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd_call(qb, kb, vb, sm_scale, causal, block_q, block_k,
                         interpret, with_lse=True)
    return (out, lse), (qb, kb, vb, out, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_k, interpret, res, cts):
    qb, kb, vb, out, lse = res
    do, dlse = cts
    # The primal lse is lane-replicated: the true per-row cotangent is the
    # SUM over lanes of the replicated output's cotangents (a consumer
    # that only read lane 0 leaves the rest zero — summing is exact
    # either way).  Re-broadcast so the kernel can read any lane.
    dadj = jnp.broadcast_to(
        jnp.sum(dlse, axis=-1, keepdims=True), dlse.shape
    )
    return _bwd_call(qb, kb, vb, out, do, lse, dadj, sm_scale, causal,
                     block_q, block_k, interpret)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)



def _prep_blocks(q, k, v, block_q, block_k):
    """Shared wrapper preprocessing: clamp block sizes to T (callers
    must forward the returned sizes to the kernel), validate
    divisibility, pad the head dim to the 128-lane grid, and flatten
    (B, T, H, D) -> (B*H, T, Dp).  Returns (qb, kb, vb, block_q,
    block_k, unpack) where ``unpack`` restores a (B*H, T, Dp) result to
    (B, T, H, D) and slices off the head-dim padding."""
    B, T, H, D = q.shape

    def _fit(request: int) -> int:
        # Largest block <= request that divides T, preferring 8-aligned
        # (the TPU sublane tile) — so the measured-best large defaults
        # degrade gracefully for any T instead of raising (same policy
        # as ring_flash_attention's fit_block).
        b = min(request, T)
        aligned = next(
            (c for c in range(b, 7, -1) if T % c == 0 and c % 8 == 0),
            None,
        )
        if aligned is not None:
            return aligned
        while T % b:
            b -= 1
        return b

    block_q = _fit(block_q)
    block_k = _fit(block_k)
    if jax.devices()[0].platform == "tpu" and T % 8:
        # Unaligned T cannot produce 8-aligned blocks; fail with a clear
        # message instead of a Mosaic lowering error.
        raise ValueError(
            f"flash_attention on TPU needs T divisible by 8, got {T}; "
            "pad the sequence or use attention_reference"
        )
    # The TPU lowering tiles the last two block dims to (8, 128): pad the
    # head dim up to a lane multiple.  Zero K/Q columns leave every score
    # unchanged; zero V columns produce zero output columns, sliced off.
    Dp = max(_LANES, -(-D // _LANES) * _LANES)
    if Dp != D:
        pad = [(0, 0), (0, 0), (0, 0), (0, Dp - D)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, Dp)

    def unpack(out):
        out = out.reshape(B, H, T, Dp).transpose(0, 2, 1, 3)
        return out[..., :D] if Dp != D else out

    return to_bh(q), to_bh(k), to_bh(v), block_q, block_k, unpack


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "sm_scale", "block_q", "block_k", "interpret", "window"
    ),
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Fused attention on (B, T, H, D); T must divide by the block sizes.

    Differentiable: gradients run through the Pallas backward kernels
    (``jax.custom_vjp``), so the transformer's ``attention="flash"`` mode
    trains on TPU.  Head dims off the 128-lane grid are zero-padded
    through the kernels and sliced back.  Off-TPU without ``interpret``
    this falls back to the reference einsum/softmax path (XLA fuses it
    well enough on CPU; the kernel is the TPU fast path).

    Default blocks (256, 512) are the measured-best forward
    configuration from the on-chip sweep at 8k-131k tokens
    (``BASELINE.json: flash_attention_*``); for any T they degrade to
    the largest 8-aligned blocks that divide T, so every previously
    valid sequence length keeps working.

    ``window`` (requires ``causal``) is sliding-window attention: row
    ``r`` attends to keys ``[r - window + 1, r]``.  Blocks entirely
    outside the band are skipped in the forward AND both backward
    kernels, so cost scales O(T * window) instead of O(T^2) — the
    standard long-context local-attention trade (Mistral-style).
    """
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else float(1.0 / np.sqrt(D))
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        return attention_reference(q, k, v, causal=causal, sm_scale=scale,
                                   window=window)
    qb, kb, vb, block_q, block_k, unpack = _prep_blocks(
        q, k, v, block_q, block_k
    )
    return unpack(
        _flash(qb, kb, vb, scale, causal, block_q, block_k, interpret,
               window)
    )


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k", "interpret")
)
def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp of the scaled scores, shape (B, H, T) f32 — the quantity
    that lets independent attention pieces be combined exactly
    (``ops.ring_attention.ring_flash_attention`` merges per-device block
    results through it).  Fully differentiable: the lse cotangent folds
    into the backward kernels' ``dadj`` row term.

    Off-TPU without ``interpret`` this computes the reference path plus a
    JAX logsumexp — same semantics, XLA-fused, differentiable.
    """
    B, T, H, D = q.shape
    scale = sm_scale if sm_scale is not None else float(1.0 / np.sqrt(D))
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        # One O(T^2) score tensor feeds both outputs (attention_reference
        # would compute the same scores a second time).
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
        probs = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        lse = jax.scipy.special.logsumexp(s, axis=-1)  # (B, H, T)
        return out, lse
    qb, kb, vb, block_q, block_k, unpack = _prep_blocks(
        q, k, v, block_q, block_k
    )
    out, lse = _flash_lse(
        qb, kb, vb, scale, causal, block_q, block_k, interpret
    )
    return unpack(out), lse[:, :, 0].reshape(B, H, T)
