"""Jitted mixing and disagreement primitives on stacked parameter pytrees.

State convention: per-agent values live in one pytree whose every leaf has a
leading *agent* axis of size N ("stacked" layout).  On a single device this
axis is a batch dimension and one gossip round is a single MXU matmul; over a
device mesh the axis is sharded (one agent per device) and the same functions
are applied under ``shard_map`` with ``ppermute`` doing the neighbor exchange
(see ``parallel/consensus.py``).

These primitives replace the reference's host-side numpy path
(``utils/consensus_simple/mixer.py``): its flatten -> O(N^2 P) dense mixing ->
unflatten round-trip (``mixer.py:43-49, 68-76``) becomes a device-resident
``W @ x`` per leaf with no reshape churn, and its deviation metrics
(``mixer.py:51-66, 78-84``) become jitted tree reductions.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

__all__ = [
    "stack_trees",
    "unstack_tree",
    "dense_mix",
    "agent_deviations",
    "max_deviation",
    "max_std",
    "weighted_lift",
    "weighted_readout",
]


def stack_trees(trees: Sequence[Pytree]) -> Pytree:
    """Stack N per-agent pytrees into one tree with a leading agent axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def unstack_tree(stacked: Pytree, n: int) -> List[Pytree]:
    """Split the leading agent axis back into N per-agent pytrees."""
    return [jax.tree.map(lambda x: x[i] if hasattr(x, "__getitem__") else x, stacked) for i in range(n)]


def dense_mix(
    stacked: Pytree,
    W: jax.Array,
    *,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> Pytree:
    """One gossip round on the whole stacked state: ``x_a <- sum_b W[a,b] x_b``.

    The mixing math of ``mixer.py:43-49`` / ``consensus_asyncio.py:295`` as a
    single batched matmul per leaf — on TPU this rides the MXU.  ``precision``
    defaults to HIGHEST because consensus residuals are driven to ~1e-4 and
    below, which bf16 matmul accumulation would floor.
    """

    def mix_leaf(x: jax.Array) -> jax.Array:
        # Mix in float32 regardless of storage dtype (matches the sharded
        # path); cast back so bf16/int leaves keep their layout.
        xf = x.reshape(x.shape[0], -1).astype(jnp.float32)
        out = jnp.matmul(W.astype(jnp.float32), xf, precision=precision)
        return out.reshape(x.shape).astype(x.dtype)

    return jax.tree.map(mix_leaf, stacked)


def _sq_dev_from_mean(stacked: Pytree) -> jax.Array:
    """Per-agent squared L2 distance from the across-agent mean, summed over
    every leaf (i.e. over the agent's whole flattened parameter vector)."""
    leaves = jax.tree.leaves(stacked)
    total = None
    for x in leaves:
        mean = x.mean(axis=0, keepdims=True)
        d = (x - mean).astype(jnp.float32)
        sq = jnp.sum(d * d, axis=tuple(range(1, d.ndim)))
        total = sq if total is None else total + sq
    return total


def agent_deviations(stacked: Pytree) -> jax.Array:
    """(N,) array: each agent's L2 distance from the mean parameter vector.

    Parity: ``basic_deviation_metric`` + ``_get_deviation_dict``
    (``mixer.py:5-6, 57-66``) — the norm is over the agent's *entire*
    flattened parameter vector.
    """
    return jnp.sqrt(_sq_dev_from_mean(stacked))


def max_deviation(stacked: Pytree) -> jax.Array:
    """Scalar: max over agents of :func:`agent_deviations` — the residual the
    eps-stopping rule compares against (``mixer.py:40-41, 51-55``)."""
    return jnp.max(agent_deviations(stacked))


def max_std(stacked: Pytree) -> jax.Array:
    """Max over parameters of the across-agent standard deviation.

    Parity: ``Mixer.get_max_parameters_std`` (``mixer.py:82-84``).
    """
    leaves = jax.tree.leaves(stacked)
    return jnp.max(
        jnp.stack([jnp.max(jnp.std(x.astype(jnp.float32), axis=0)) for x in leaves])
    )


def weighted_lift(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Rescale each agent's value by ``w_i / mean(w)`` so that plain average
    consensus computes the *weighted* average.

    This is the reference's weighting trick: ``y_i = x_i w_i / mean_w``
    implies ``(1/n) sum y_i = (sum w_i x_i) / (sum w_i)``
    (``consensus_asyncio.py:231`` and the derivation at :288-293).
    """
    w = weights / jnp.mean(weights)

    def lift(x: jax.Array) -> jax.Array:
        return x * w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)

    return jax.tree.map(lift, stacked)


def weighted_readout(stacked_num: Pytree, stacked_den: jax.Array) -> Pytree:
    """Finish a push-sum style weighted consensus: divide the mixed numerator
    by the mixed scalar weight channel.

    Used when per-agent weights are themselves gossiped alongside the values
    (the generalization of the reference's master-computed ``mean_weight``,
    which a masterless SPMD program cannot get for free).
    """

    def div(x: jax.Array) -> jax.Array:
        return x / stacked_den.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)

    return jax.tree.map(div, stacked_num)
