"""Jitted mixing and disagreement primitives on stacked parameter pytrees.

State convention: per-agent values live in one pytree whose every leaf has a
leading *agent* axis of size N ("stacked" layout).  On a single device this
axis is a batch dimension and one gossip round is a single MXU matmul; over a
device mesh the axis is sharded (one agent per device) and the same functions
are applied under ``shard_map`` with ``ppermute`` doing the neighbor exchange
(see ``parallel/consensus.py``).

These primitives replace the reference's host-side numpy path
(``utils/consensus_simple/mixer.py``): its flatten -> O(N^2 P) dense mixing ->
unflatten round-trip (``mixer.py:43-49, 68-76``) becomes a device-resident
``W @ x`` per leaf with no reshape churn, and its deviation metrics
(``mixer.py:51-66, 78-84``) become jitted tree reductions.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

__all__ = [
    "stack_trees",
    "unstack_tree",
    "dense_mix",
    "agent_deviations",
    "max_deviation",
    "max_std",
    "weighted_lift",
    "weighted_readout",
    "FusedLayout",
    "fused_layout",
    "flatten_stacked",
    "unflatten_stacked",
    "fused_dense_mix",
    "fused_max_deviation",
    "stale_weight_matrix",
    "presence_weight_matrix",
    "stale_weighted_mix",
    "pairwise_sq_dists",
    "clip_weight_matrix",
    "adaptive_clip_radius",
    "clipped_mix",
    "trim_counts",
    "trimmed_mix",
]


def stack_trees(trees: Sequence[Pytree]) -> Pytree:
    """Stack N per-agent pytrees into one tree with a leading agent axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def unstack_tree(stacked: Pytree, n: int) -> List[Pytree]:
    """Split the leading agent axis back into N per-agent pytrees.

    Every leaf must carry the leading agent axis of size ``n`` (the
    :func:`stack_trees` invariant).  A leaf without it — a python scalar,
    a 0-d array, or an array whose leading dimension is not ``n`` — is
    rejected: silently handing the SAME value to all agents (the old
    ``hasattr(x, "__getitem__")`` fallback) turns a shape bug into n-way
    state aliasing.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(stacked)
    for path, leaf in flat:
        shape = getattr(leaf, "shape", None)
        if shape is None or len(shape) == 0 or shape[0] != n:
            raise ValueError(
                f"unstack_tree: leaf {jax.tree_util.keystr(path)} has "
                f"shape {shape} — every leaf of a stacked tree must have "
                f"a leading agent axis of size {n} (stack scalars with "
                "stack_trees first)"
            )
    return [
        jax.tree_util.tree_unflatten(
            treedef, [leaf[i] for _, leaf in flat]
        )
        for i in range(n)
    ]


# --------------------------------------------------------------------- #
# Fused flat-buffer layout                                              #
# --------------------------------------------------------------------- #
class _LeafSlot(NamedTuple):
    """Where one stacked leaf lives inside its dtype bucket."""

    bucket: str            # canonical dtype name, e.g. "float32"
    offset: int            # column offset inside the (N, P_bucket) buffer
    shape: Tuple[int, ...]  # trailing (per-agent) shape; () for (N,) leaves
    size: int              # prod(shape)


class FusedLayout(NamedTuple):
    """Static (host-side, hashable) metadata of a fused flat-buffer state.

    A stacked pytree is raveled into ONE contiguous ``(N, P)`` buffer per
    storage dtype ("bucket"), so a gossip round is O(buckets) collectives
    and matmuls instead of O(leaves).  The layout is leading-axis
    agnostic: the same object serves the global ``(N, ...)`` tree and the
    per-device ``(1, ...)`` shards inside ``shard_map``.  Hashable on
    purpose — jit caches may key on it.
    """

    treedef: Any
    slots: Tuple[_LeafSlot, ...]          # one per leaf, in tree order
    buckets: Tuple[Tuple[str, int], ...]  # (dtype name, width P), sorted

    @property
    def leaf_count(self) -> int:
        return len(self.slots)

    @property
    def bucket_count(self) -> int:
        return len(self.buckets)

    def bytes_per_round(self, n: int) -> int:
        """Bytes of state one gossip round touches for ``n`` agents."""
        return sum(
            n * width * np.dtype(name).itemsize for name, width in self.buckets
        )

    def bucket_width(self, bucket: str) -> int:
        """Column count P of one dtype bucket."""
        for name, width in self.buckets:
            if name == bucket:
                return width
        raise KeyError(bucket)

    def bucket_spans(self, bucket: str) -> Tuple[Tuple[int, int], ...]:
        """``(offset, size)`` leaf spans of one dtype bucket, ascending.

        Offsets are column positions inside the bucket's ``(N, P)``
        buffer; spans tile ``[0, P)`` exactly (leaves of a bucket are
        laid out consecutively in tree order).  This is the static
        segment map fused *compression* selects against
        (``parallel/compression.py::FusedCompressor``): a per-leaf k
        budget is a per-span budget over these columns.
        """
        spans = tuple(
            (s.offset, s.size) for s in self.slots if s.bucket == bucket
        )
        if not spans:
            raise KeyError(bucket)
        return spans


def fused_layout(stacked: Pytree) -> FusedLayout:
    """Compute the fused flat-buffer layout of a stacked pytree.

    Works on concrete arrays and on tracers (shapes are static under
    jit).  Leaves are grouped by *storage* dtype — bf16/f32 leaves keep
    their dtype at the buffer boundary; the mixing math stays f32 either
    way (see :func:`dense_mix`).
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(stacked)
    if not flat:
        return FusedLayout(treedef, (), ())
    lead = None
    widths: Dict[str, int] = {}
    slots: List[_LeafSlot] = []
    for path, leaf in flat:
        shape = getattr(leaf, "shape", None)
        if shape is None or len(shape) == 0:
            raise ValueError(
                f"fused_layout: leaf {jax.tree_util.keystr(path)} has "
                f"shape {shape} — every leaf of a stacked tree must have "
                "a leading agent axis (stack scalars with stack_trees "
                "first)"
            )
        if lead is None:
            lead = shape[0]
        elif shape[0] != lead:
            raise ValueError(
                f"fused_layout: leaf {jax.tree_util.keystr(path)} has "
                f"leading axis {shape[0]}, expected {lead} (inconsistent "
                "agent axis across leaves)"
            )
        bucket = str(np.dtype(leaf.dtype))
        size = int(np.prod(shape[1:], dtype=np.int64))
        slots.append(
            _LeafSlot(bucket, widths.get(bucket, 0), tuple(shape[1:]), size)
        )
        widths[bucket] = widths.get(bucket, 0) + size
    return FusedLayout(
        treedef, tuple(slots), tuple(sorted(widths.items()))
    )


def flatten_stacked(
    stacked: Pytree, layout: FusedLayout | None = None
) -> Tuple[Dict[str, jax.Array], FusedLayout]:
    """Ravel a stacked pytree into its fused ``{dtype: (N, P)}`` buffers.

    Inside jit this is a one-time reshape+concatenate at program entry —
    the whole point of the layout is that the gossip ``while_loop`` body
    then runs on O(buckets) buffers instead of O(leaves) arrays.  Returns
    ``(buffers, layout)``; pass a precomputed ``layout`` to skip
    revalidation (the CHOCO scan does, per cached program).
    """
    if layout is None:
        layout = fused_layout(stacked)
    leaves = jax.tree.leaves(stacked)
    by_bucket: Dict[str, List[jax.Array]] = {}
    for slot, leaf in zip(layout.slots, leaves):
        by_bucket.setdefault(slot.bucket, []).append(
            leaf.reshape(leaf.shape[0], slot.size)
        )
    buffers = {
        name: (parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1))
        for name, parts in by_bucket.items()
    }
    return buffers, layout


def unflatten_stacked(
    buffers: Dict[str, jax.Array], layout: FusedLayout
) -> Pytree:
    """Inverse of :func:`flatten_stacked`: slice each leaf back out of its
    dtype bucket and restore the tree structure (one-time exit cost)."""
    leaves = []
    for slot in layout.slots:
        buf = buffers[slot.bucket]
        piece = jax.lax.slice_in_dim(
            buf, slot.offset, slot.offset + slot.size, axis=1
        )
        leaves.append(piece.reshape((buf.shape[0],) + slot.shape))
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def fused_dense_mix(
    stacked: Pytree,
    W: jax.Array,
    *,
    times: int = 1,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> Pytree:
    """Traceable fused gossip for embedding in a caller's own compiled
    program (``bench.py``'s epoch): flatten once, ``times`` (static) dense
    rounds on the fused buffers, unflatten once."""
    buffers, layout = flatten_stacked(stacked)
    for _ in range(int(times)):
        buffers = dense_mix(buffers, W, precision=precision)
    return unflatten_stacked(buffers, layout)


def fused_max_deviation(stacked: Pytree, *, fused: bool = True) -> jax.Array:
    """:func:`max_deviation` computed on the fused flat-buffer view —
    O(dtype-buckets) reductions instead of O(leaves) — for embedding in a
    caller's compiled program (the trainer's epoch superstep reads the
    post-mix consensus residual out of the same dispatch that mixed).
    ``fused=False`` keeps the per-leaf reduction; the statistic is
    leaf-order invariant, so both layouts agree to accumulation order.
    """
    return max_deviation(flatten_stacked(stacked)[0] if fused else stacked)


def dense_mix(
    stacked: Pytree,
    W: jax.Array,
    *,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> Pytree:
    """One gossip round on the whole stacked state: ``x_a <- sum_b W[a,b] x_b``.

    The mixing math of ``mixer.py:43-49`` / ``consensus_asyncio.py:295`` as a
    single batched matmul per leaf — on TPU this rides the MXU.  ``precision``
    defaults to HIGHEST because consensus residuals are driven to ~1e-4 and
    below, which bf16 matmul accumulation would floor.
    """

    def mix_leaf(x: jax.Array) -> jax.Array:
        # Mix in float32 regardless of storage dtype (matches the sharded
        # path); cast back so bf16/int leaves keep their layout.
        xf = x.reshape(x.shape[0], -1).astype(jnp.float32)
        out = jnp.matmul(W.astype(jnp.float32), xf, precision=precision)
        return out.reshape(x.shape).astype(x.dtype)

    return jax.tree.map(mix_leaf, stacked)


# --------------------------------------------------------------------- #
# Stale-weighted mixing (the async gossip runtime's device program)      #
# --------------------------------------------------------------------- #
def stale_weight_matrix(
    W: jax.Array, age: jax.Array, *, tau
) -> jax.Array:
    """Effective mixing matrix under per-agent publication staleness.

    ``age[j]`` counts rounds since agent ``j`` last published its
    parameters (the async runtime's double-buffer model: local compute
    runs on buffer A while neighbors mix against the last *published*
    buffer B).  Stale contributions are down-weighted by ``1/(1+age)``
    (the stale-tolerant mixing of arXiv:2002.01119 §3) and DROPPED
    beyond the hard staleness bound ``tau``; the dropped/decayed mass
    of each row moves onto the self edge, so every row still sums to
    exactly what it did before — row-stochasticity is restored on
    device, no host round-trip.

    Self edges never decay (an agent is never stale to itself).  With
    ``age == 0`` everywhere the scale is exactly 1.0 and the result is
    bitwise ``W`` — the async-with-neutral-knobs oracle rides on this.
    """
    W = jnp.asarray(W, jnp.float32)
    n = W.shape[0]
    agef = jnp.asarray(age).astype(jnp.float32)
    scale = jnp.where(agef <= jnp.float32(tau), 1.0 / (1.0 + agef), 0.0)
    eye = jnp.eye(n, dtype=bool)
    off = jnp.where(eye, 0.0, W)
    off_eff = jnp.where(eye, 0.0, W * scale[None, :])
    dropped = jnp.sum(off - off_eff, axis=1)
    # where-placement (not addition) keeps surviving off-diagonal
    # entries bitwise untouched.
    return jnp.where(
        eye, (jnp.diagonal(W) + dropped)[:, None], off_eff
    )


def presence_weight_matrix(W: jax.Array, present: jax.Array) -> jax.Array:
    """Effective mixing matrix when some agents sit a round out.

    ``present[j]`` is 1.0/True for agents participating in this round
    (deadline-enforced rounds drop rather than wait: a straggler that
    missed the round deadline contributes nothing).  Edges to absent
    agents get zero weight with the mass moved to the self edge (row
    sums preserved on device); an absent agent's own row becomes the
    identity — it keeps its value and re-joins next round.  With
    everyone present the result is bitwise ``W``.
    """
    W = jnp.asarray(W, jnp.float32)
    n = W.shape[0]
    p = jnp.asarray(present).astype(jnp.float32)
    eye = jnp.eye(n, dtype=bool)
    off = jnp.where(eye, 0.0, W)
    off_eff = jnp.where(eye, 0.0, W * p[None, :])
    dropped = jnp.sum(off - off_eff, axis=1)
    W_eff = jnp.where(eye, (jnp.diagonal(W) + dropped)[:, None], off_eff)
    return jnp.where(
        p[:, None] > 0.0, W_eff, jnp.eye(n, dtype=jnp.float32)
    )


def stale_weighted_mix(
    stacked: Pytree,
    published: Pytree,
    W_eff: jax.Array,
    *,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> Pytree:
    """One stale-weighted gossip round on double-buffered state:
    ``x_i <- W_eff[i, i] * x_i + sum_{j != i} W_eff[i, j] * pub_j``.

    Neighbor contributions come from the *published* buffer (the last
    state each agent shipped), the self term from the live buffer (an
    agent always has its own fresh value).  Computed as one GEMM per
    leaf/bucket plus a rank-local diagonal correction,
    ``W_eff @ pub + diag(W_eff) * (x - pub)`` — when ``pub`` carries
    the same bits as ``x`` (every agent just published) the correction
    is exactly zero and the round is bitwise :func:`dense_mix` under
    ``W_eff``.
    """
    d = jnp.diagonal(jnp.asarray(W_eff, jnp.float32))

    def leaf(xv: jax.Array, pv: jax.Array) -> jax.Array:
        xf = xv.reshape(xv.shape[0], -1).astype(jnp.float32)
        pf = pv.reshape(pv.shape[0], -1).astype(jnp.float32)
        out = jnp.matmul(
            jnp.asarray(W_eff, jnp.float32), pf, precision=precision
        )
        out = out + d[:, None] * (xf - pf)
        return out.reshape(xv.shape).astype(xv.dtype)

    return jax.tree.map(leaf, stacked, published)


# --------------------------------------------------------------------- #
# Byzantine-robust aggregation kernels (clipped / trimmed / median)     #
# --------------------------------------------------------------------- #
# The robust family follows the effective-matrix discipline of
# :func:`stale_weight_matrix`: each defense is expressed as either an
# effective mixing matrix (clipping) or a zero-at-neutral additive
# correction on top of the plain GEMM (trimming), so that at the neutral
# knobs — ``radius=inf`` / ``trim=0`` — the computation runs the exact
# same ops as :func:`dense_mix` / :func:`stale_weighted_mix` and the
# result is bitwise identical.  All kernels are layout-agnostic: they
# serve the stacked tree and the fused ``{dtype: (N, P)}`` buffer dict
# alike, and the clipping radius is measured over the agent's WHOLE
# flattened parameter vector (summed across leaves/buckets).


def pairwise_sq_dists(
    stacked: Pytree,
    neighbors: Pytree | None = None,
    *,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> jax.Array:
    """(N, N) squared L2 distances between agents' full parameter vectors.

    ``sq[i, j] = || row_i(stacked) - row_j(neighbors or stacked) ||^2``
    summed over every leaf — computed per leaf/bucket as one Gram GEMM
    (``X Y^T``) plus rank-1 corrections, so the fused layout pays
    O(dtype-buckets) GEMMs, never materializing an (N, N, P) tensor.
    ``neighbors`` defaults to ``stacked`` (synchronous gossip); the async
    double-buffer path passes the *published* buffers so ``sq[i, j]`` is
    the distance from agent i's live value to agent j's publication.
    """
    xs = jax.tree.leaves(stacked)
    ys = xs if neighbors is None else jax.tree.leaves(neighbors)
    total = None
    for xv, yv in zip(xs, ys):
        xf = xv.reshape(xv.shape[0], -1).astype(jnp.float32)
        yf = yv.reshape(yv.shape[0], -1).astype(jnp.float32)
        g = jnp.matmul(xf, yf.T, precision=precision)
        sx = jnp.sum(xf * xf, axis=1)
        sy = jnp.sum(yf * yf, axis=1)
        sq = sx[:, None] + sy[None, :] - 2.0 * g
        total = sq if total is None else total + sq
    return jnp.maximum(total, 0.0)


def clip_weight_matrix(
    W: jax.Array, sq_dists: jax.Array, radius
) -> Tuple[jax.Array, jax.Array]:
    """Effective mixing matrix with neighbor deltas clipped at ``radius``.

    Clipped gossip rewrites ``x_i + sum_j W_ij * clip_r(x_j - x_i)`` as a
    row-stochastic GEMM: scaling a neighbor delta by
    ``s_ij = min(1, r_i / ||x_j - x_i||)`` is exactly the edge reweighting
    ``W_ij <- W_ij * s_ij`` with the lost mass moved onto the self edge —
    so one clipped round is :func:`dense_mix` under this matrix, and a
    lying agent's arbitrarily large pull is bounded by ``r_i * W_ij``
    (the Gorbunov/Karimireddy clipped-gossip estimator family).

    ``radius`` is a scalar or per-receiver ``(N,)`` vector (see
    :func:`adaptive_clip_radius`).  NaN distances (a poisoned payload)
    clip to zero weight.  With ``radius=inf`` the scale is exactly 1.0
    and the result is bitwise ``W`` — the robust-with-neutral-knobs
    oracle rides on this, same discipline as :func:`stale_weight_matrix`.
    Returns ``(W_eff, clipped_mass)`` where ``clipped_mass`` is the total
    absolute edge weight moved onto self edges (0.0 when nothing
    clipped) — the obs plane's detection signal.
    """
    W = jnp.asarray(W, jnp.float32)
    n = W.shape[0]
    r = jnp.broadcast_to(jnp.asarray(radius, jnp.float32), (n,))
    norm = jnp.sqrt(sq_dists)
    norm = jnp.where(jnp.isnan(norm), jnp.inf, norm)
    s = jnp.where(
        norm <= r[:, None],
        jnp.float32(1.0),
        r[:, None] / jnp.maximum(norm, jnp.float32(1e-30)),
    )
    # A non-finite or negative radius row clips everything to self-hold.
    s = jnp.where(jnp.isnan(s) | (s < 0.0), jnp.float32(0.0), s)
    eye = jnp.eye(n, dtype=bool)
    off = jnp.where(eye, 0.0, W)
    off_eff = jnp.where(eye, 0.0, W * s)
    dropped = jnp.sum(off - off_eff, axis=1)
    # where-placement (not addition) keeps surviving off-diagonal
    # entries bitwise untouched (stale_weight_matrix discipline).
    W_eff = jnp.where(eye, (jnp.diagonal(W) + dropped)[:, None], off_eff)
    clipped_mass = jnp.sum(jnp.abs(off) - jnp.abs(off_eff))
    return W_eff, clipped_mass


def adaptive_clip_radius(
    W: jax.Array, sq_dists: jax.Array, multiplier
) -> jax.Array:
    """Per-receiver adaptive clipping radius: ``multiplier`` times the
    median neighbor-delta norm.

    A fixed radius must be tuned to the (drifting) scale of honest
    disagreement; anchoring it to each receiver's *median* incident delta
    norm keeps honest edges unclipped (s=1 for at least half the
    neighborhood) while an outlier sits far above the median and gets
    clipped to median-scale pull — robust as long as the honest
    neighbors are the majority, the same f < n/2 breakdown point as
    trimming.  ``multiplier=inf`` returns ``inf`` rows exactly (the
    neutral knob survives the composition), and an isolated agent's
    radius is 0.
    """
    W = jnp.asarray(W, jnp.float32)
    n = W.shape[0]
    eye = jnp.eye(n, dtype=bool)
    support = jnp.logical_and(W != 0.0, ~eye)
    norm = jnp.sqrt(jnp.maximum(sq_dists, 0.0))
    norm = jnp.where(jnp.isnan(norm), jnp.inf, norm)
    med = jnp.nanmedian(jnp.where(support, norm, jnp.nan), axis=1)
    med = jnp.where(jnp.isnan(med), jnp.float32(0.0), med)
    mult = jnp.asarray(multiplier, jnp.float32)
    return jnp.where(
        jnp.isinf(mult), jnp.float32(jnp.inf), mult * med
    ) * jnp.ones((n,), jnp.float32)


def clipped_mix(
    stacked: Pytree,
    W: jax.Array,
    radius,
    *,
    adaptive: bool = False,
    published: Pytree | None = None,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> Tuple[Pytree, jax.Array]:
    """One clipped-gossip round; returns ``(mixed, clipped_mass)``.

    ``published=None`` is the synchronous round (:func:`dense_mix` under
    the clipped matrix); passing the async double buffer composes with
    staleness — hand the *stale-decayed* ``W_eff`` in as ``W`` and the
    clip applies on top of the decay, measuring each delta from the
    receiver's live value to the neighbor's publication.  ``adaptive``
    reinterprets ``radius`` as the :func:`adaptive_clip_radius`
    multiplier.  With ``radius=inf`` (adaptive or not) the effective
    matrix is bitwise ``W`` and the round is bitwise the plain one.
    """
    sq = pairwise_sq_dists(
        stacked, published, precision=precision
    )
    r = adaptive_clip_radius(W, sq, radius) if adaptive else radius
    W_eff, mass = clip_weight_matrix(W, sq, r)
    if published is None:
        return dense_mix(stacked, W_eff, precision=precision), mass
    return (
        stale_weighted_mix(stacked, published, W_eff, precision=precision),
        mass,
    )


def trim_counts(W, trim) -> jax.Array:
    """Per-receiver trim depth ``t_i`` for :func:`trimmed_mix`.

    An integer ``trim`` applies uniformly; ``trim="median"`` picks the
    maximal depth ``(deg_i - 1) // 2`` that still keeps the central one
    (odd degree) or two (even degree) neighbor contributions — the
    coordinate-wise median aggregator as the extreme of the trimmed-mean
    family (degree 2 keeps both neighbors: the median of two values IS
    their mean, so a ring is already at its breakdown point).
    """
    W = jnp.asarray(W, jnp.float32)
    n = W.shape[0]
    eye = jnp.eye(n, dtype=bool)
    deg = jnp.sum(
        jnp.logical_and(W != 0.0, ~eye).astype(jnp.int32), axis=1
    )
    if isinstance(trim, str):
        if trim != "median":
            raise ValueError(
                f"trim must be an int or 'median', got {trim!r}"
            )
        return jnp.maximum((deg - 1) // 2, 0)
    return jnp.full((n,), int(trim), jnp.int32)


def trimmed_mix(
    stacked: Pytree,
    W: jax.Array,
    trim: jax.Array,
    *,
    published: Pytree | None = None,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
) -> Tuple[Pytree, jax.Array]:
    """One coordinate-wise trimmed-mean gossip round; returns
    ``(mixed, trimmed_mass)``.

    For each receiver i and coordinate p, the ``t_i`` highest and ``t_i``
    lowest neighbor contributions (ranked per coordinate among i's
    in-neighbors, index tie-break) are redirected onto the self edge —
    rows stay stochastic, and with ``f <= t_i`` liars per neighborhood
    every adversarial coordinate is discarded (the Yin et al. 2018
    coordinate-trimmed-mean estimator on gossip weights).  Computed as
    the plain GEMM plus a correction
    ``sum_j W_ij m_ijp (x_i[p] - nb_j[p])`` that is exactly 0.0 at
    ``trim=0`` — the round is then bitwise :func:`dense_mix` (sync) /
    :func:`stale_weighted_mix` (async, via ``published``).  ``trim`` is
    the per-receiver ``(N,)`` depth from :func:`trim_counts` (pass
    ``trim_counts(W, "median")`` for the median aggregator).  Cost is
    O(N^2 P) comparisons per bucket — the price of per-coordinate ranks;
    N is the agent count, so the constant is small.

    ``trimmed_mass`` is the average per-coordinate edge weight redirected
    (summed over leaves; 0.0 when nothing trimmed).
    """
    W = jnp.asarray(W, jnp.float32)
    n = W.shape[0]
    eye = jnp.eye(n, dtype=bool)
    support = jnp.logical_and(W != 0.0, ~eye)
    supf = support.astype(jnp.float32)
    deg = jnp.sum(supf, axis=1)
    tf = jnp.asarray(trim, jnp.int32).astype(jnp.float32)
    W_off = jnp.where(support, W, 0.0)
    d = jnp.diagonal(W)
    idx = jnp.arange(n)
    tie_lo = (idx[:, None] < idx[None, :])[:, :, None]

    xs, treedef = jax.tree_util.tree_flatten(stacked)
    ps = xs if published is None else jax.tree.leaves(published)
    outs = []
    mass = jnp.float32(0.0)
    for xv, pv in zip(xs, ps):
        xf = xv.reshape(n, -1).astype(jnp.float32)
        pf = pv.reshape(n, -1).astype(jnp.float32)
        base = jnp.matmul(W, pf, precision=precision)
        if published is not None:
            base = base + d[:, None] * (xf - pf)
        # rank[i, j, p]: how many of receiver i's neighbors sort strictly
        # below contribution j at coordinate p (index tie-break keeps the
        # ranking a permutation under duplicates).
        lt = pf[:, None, :] < pf[None, :, :]
        tie = jnp.logical_and(pf[:, None, :] == pf[None, :, :], tie_lo)
        cmp = jnp.logical_or(lt, tie).astype(jnp.float32)
        rank = jnp.einsum("ik,kjp->ijp", supf, cmp)
        m = support[:, :, None] & (
            (rank < tf[:, None, None])
            | (rank >= (deg - tf)[:, None, None])
        )
        delta = xf[:, None, :] - pf[None, :, :]
        corr = jnp.einsum("ij,ijp->ip", W_off, jnp.where(m, delta, 0.0))
        mass = mass + jnp.einsum(
            "ij,ijp->", W_off, m.astype(jnp.float32)
        ) / jnp.float32(pf.shape[1])
        outs.append((base + corr).reshape(xv.shape).astype(xv.dtype))
    return jax.tree_util.tree_unflatten(treedef, outs), mass


def _sq_dev_from_mean(stacked: Pytree) -> jax.Array:
    """Per-agent squared L2 distance from the across-agent mean, summed over
    every leaf (i.e. over the agent's whole flattened parameter vector)."""
    leaves = jax.tree.leaves(stacked)
    total = None
    for x in leaves:
        mean = x.mean(axis=0, keepdims=True)
        d = (x - mean).astype(jnp.float32)
        sq = jnp.sum(d * d, axis=tuple(range(1, d.ndim)))
        total = sq if total is None else total + sq
    return total


def agent_deviations(stacked: Pytree) -> jax.Array:
    """(N,) array: each agent's L2 distance from the mean parameter vector.

    Parity: ``basic_deviation_metric`` + ``_get_deviation_dict``
    (``mixer.py:5-6, 57-66``) — the norm is over the agent's *entire*
    flattened parameter vector.
    """
    return jnp.sqrt(_sq_dev_from_mean(stacked))


def max_deviation(stacked: Pytree) -> jax.Array:
    """Scalar: max over agents of :func:`agent_deviations` — the residual the
    eps-stopping rule compares against (``mixer.py:40-41, 51-55``)."""
    return jnp.max(agent_deviations(stacked))


def max_std(stacked: Pytree) -> jax.Array:
    """Max over parameters of the across-agent standard deviation.

    Parity: ``Mixer.get_max_parameters_std`` (``mixer.py:82-84``).
    """
    leaves = jax.tree.leaves(stacked)
    return jnp.max(
        jnp.stack([jnp.max(jnp.std(x.astype(jnp.float32), axis=0)) for x in leaves])
    )


def weighted_lift(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Rescale each agent's value by ``w_i / mean(w)`` so that plain average
    consensus computes the *weighted* average.

    This is the reference's weighting trick: ``y_i = x_i w_i / mean_w``
    implies ``(1/n) sum y_i = (sum w_i x_i) / (sum w_i)``
    (``consensus_asyncio.py:231`` and the derivation at :288-293).
    """
    w = weights / jnp.mean(weights)

    def lift(x: jax.Array) -> jax.Array:
        return x * w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)

    return jax.tree.map(lift, stacked)


def weighted_readout(stacked_num: Pytree, stacked_den: jax.Array) -> Pytree:
    """Finish a push-sum style weighted consensus: divide the mixed numerator
    by the mixed scalar weight channel.

    Used when per-agent weights are themselves gossiped alongside the values
    (the generalization of the reference's master-computed ``mean_weight``,
    which a masterless SPMD program cannot get for free).
    """

    def div(x: jax.Array) -> jax.Array:
        return x / stacked_den.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)

    return jax.tree.map(div, stacked_num)
