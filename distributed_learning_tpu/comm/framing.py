"""Length-prefixed binary framing over asyncio streams.

Parity: ``utils/consensus_tcp/pickled_socket.py:3-23``
(``PickledSocketWrapper``: 16-byte little-endian length header + pickled
payload).  This replacement keeps the same role — ``send(msg)`` /
``recv()`` over an asyncio stream — with a safe frame:

    u32 body_len | u8 version | u8 msg_type | u16 reserved |
    body | u32 crc32(body)

No pickle anywhere; bodies are the typed messages of ``protocol.py`` and
the crc (native codec when available) rejects torn or corrupt frames.
"""

from __future__ import annotations

import asyncio
import errno
import struct
from typing import Callable, Optional, Tuple

import numpy as np

from distributed_learning_tpu import native
from distributed_learning_tpu.comm.protocol import Message, pack_message, unpack_message
from distributed_learning_tpu.obs import get_registry

#: graftsched hot-coroutine annotation (tools/graftlint/schedsim.py):
#: ``send`` holds the backoff sleep the virtual clock fires in simulated
#: time; ``recv`` holds the frame-boundary wait_for.  Their await-point
#: model pins under ``sched_model``.
SCHED_HOT = (
    "FramedStream.send",
    "FramedStream.recv",
)

__all__ = [
    "FramedStream",
    "FrameError",
    "FrameTimeout",
    "open_framed_connection",
]

#: v2: value-bearing bodies (ValueResponse*/AsyncValue/AsyncPoke) carry
#: the trace-context trailer of ``protocol.TraceContext`` — a layout
#: change, so v1 peers must be rejected at the frame header.
#: Cross-checked against ``native/wire.cpp``'s ``kWireVersion`` and
#: ``dlt_abi.h``'s ``DLT_WIRE_VERSION`` by graftlint's wire-contract
#: stage — bump all three together, then repin with ``--audit-write``.
WIRE_VERSION = 2
_HEADER = struct.Struct("<IBBH")
MAX_FRAME = 1 << 31  # 2 GiB: a full WRN-28-10 f32 vector is ~146 MB

#: OS errors a send may legitimately retry: the kernel was momentarily
#: out of buffer/queue space or the call was interrupted.  Connection
#: teardown errnos (ECONNRESET, EPIPE, ...) are NOT here on purpose —
#: retrying a dead socket only delays the death notice the caller's
#: heal path needs.
TRANSIENT_ERRNOS = frozenset(
    {errno.EAGAIN, errno.EWOULDBLOCK, errno.EINTR, errno.ENOBUFS}
)


class FrameError(ConnectionError):
    """Corrupt or protocol-violating frame."""


class FrameTimeout(TimeoutError):
    """``recv(timeout=...)`` expired while waiting for the NEXT frame to
    begin.  The stream is still healthy: ``readexactly`` consumes its
    bytes atomically (partial data stays in the reader's buffer), so the
    read simply resumes on the next ``recv`` call.  Deliberately NOT a
    ConnectionError — multiplexers/heal paths must not evict a live
    stream over a quiet period."""


class FramedStream:
    """``send(Message)`` / ``recv() -> Message`` over one TCP connection.

    Per-stream ``bytes_sent``/``bytes_received``/``frames_sent``/
    ``frames_received`` count whole frames (header + body + crc) — the
    "bytes framed" wire-volume metric; the totals also aggregate into
    the default obs registry (``comm.bytes_framed_out/in``,
    ``comm.frames_out/in``).

    When the owner labels the stream with its directed ``edge``
    (``(local_token, peer_token)``, set by ``ConsensusAgent`` at
    neighbor-install time), every frame is additionally attributed to
    that edge: ``comm.edge.bytes_out/<local>-><peer>``,
    ``comm.edge.frames_out/...``, the mirrored ``bytes_in``/
    ``frames_in`` under the reverse direction, and
    ``comm.edge.retries/...`` — the per-edge wire observatory
    (``obs/aggregate.py:edge_profile_from_registry``).  ``obs`` is an
    optional second registry (the owning agent's private one) the same
    counters mirror into so they ride the agent's telemetry deltas."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        send_retries: int = 0,
        retry_base_s: float = 0.02,
        retry_jitter_frac: float = 0.0,
        retry_seed: int = 0,
        on_retry: Optional[Callable[[], None]] = None,
    ):
        self.reader = reader
        self.writer = writer
        self._send_lock = asyncio.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.frames_sent = 0
        self.frames_received = 0
        # Bounded exponential-backoff retry of transient socket errors on
        # send (TRANSIENT_ERRNOS): attempt k sleeps retry_base_s * 2**k,
        # stretched by up to retry_jitter_frac (decorrelates retry storms
        # across streams sharing a congested kernel).  The jitter is a
        # pure function of (retry_seed, attempt) — the FaultPlan
        # counter-keyed rng idiom — so a retry schedule replays
        # bit-identically under the graftsched explorer and the fault
        # harness; 0.0 keeps the exact legacy powers-of-two schedule.
        # 0 retries = fail on first error (the pre-async behavior).
        # on_retry is the owner's counter hook (ConsensusAgent wires
        # comm.agent.retries).
        self.send_retries = int(send_retries)
        self.retry_base_s = float(retry_base_s)
        self.retry_jitter_frac = float(retry_jitter_frac)
        self.retry_seed = int(retry_seed)
        self.on_retry = on_retry
        # Directed-edge attribution (set post-construction by the owner
        # once the peer's token is known, e.g. after the Register
        # handshake): (local_token, peer_token), plus an optional extra
        # registry the edge counters mirror into.
        self.edge: Optional[Tuple[str, str]] = None
        self.obs = None

    def _edge_inc(self, name: str, forward: bool, v: float = 1.0) -> None:
        if self.edge is None:
            return
        a, b = self.edge if forward else (self.edge[1], self.edge[0])
        full = f"{name}/{a}->{b}"
        get_registry().inc(full, v)
        if self.obs is not None:
            self.obs.inc(full, v)

    @property
    def peername(self):
        return self.writer.get_extra_info("peername")

    def _retry_delay_s(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based).  Deterministic:
        the jitter draw is keyed on (retry_seed, attempt) exactly like
        ``FaultPlan.decide`` keys on (seed, frame index), never on
        shared-rng call order."""
        delay = self.retry_base_s * (2 ** attempt)
        if self.retry_jitter_frac:
            u = np.random.default_rng(
                [self.retry_seed, attempt]
            ).random()
            delay *= 1.0 + self.retry_jitter_frac * u
        return delay

    async def send(self, msg: Message) -> None:
        code, body = pack_message(msg)
        if len(body) > MAX_FRAME:
            raise FrameError(f"frame of {len(body)} bytes exceeds {MAX_FRAME}")
        crc = native.crc32(body)
        header = _HEADER.pack(len(body), WIRE_VERSION, code, 0)
        nbytes = len(header) + len(body) + 4
        async with self._send_lock:
            attempt = 0
            while True:
                try:
                    self.writer.write(header + body + struct.pack("<I", crc))
                    await self.writer.drain()
                    break
                except OSError as e:
                    transient = (
                        e.errno in TRANSIENT_ERRNOS
                        and not isinstance(e, ConnectionError)
                    )
                    if not transient or attempt >= self.send_retries:
                        raise
                    get_registry().inc("comm.frame_retries")
                    self._edge_inc("comm.edge.retries", forward=True)
                    if self.on_retry is not None:
                        self.on_retry()
                    await asyncio.sleep(self._retry_delay_s(attempt))
                    attempt += 1
        self.bytes_sent += nbytes
        self.frames_sent += 1
        reg = get_registry()
        reg.inc("comm.bytes_framed_out", nbytes)
        reg.inc("comm.frames_out")
        self._edge_inc("comm.edge.bytes_out", forward=True, v=nbytes)
        self._edge_inc("comm.edge.frames_out", forward=True)

    async def recv(self, timeout: Optional[float] = None) -> Message:
        if timeout is None:
            header = await self.reader.readexactly(_HEADER.size)
        else:
            # Frame-boundary timeout only: readexactly consumes its bytes
            # atomically (accumulated data stays buffered on cancel), so
            # an expiry here leaves the stream intact and retryable —
            # FrameTimeout, not FrameError.  Once the header is consumed
            # the frame must complete; a peer that wedges MID-frame is
            # indistinguishable from corruption and surfaces below as a
            # ConnectionError from the transport, never a torn decode
            # (the crc rejects those).
            try:
                header = await asyncio.wait_for(
                    self.reader.readexactly(_HEADER.size), timeout
                )
            except asyncio.TimeoutError:
                raise FrameTimeout(
                    f"no frame started within {timeout}s"
                ) from None
        length, version, code, _ = _HEADER.unpack(header)
        if version != WIRE_VERSION:
            raise FrameError(f"wire version {version} != {WIRE_VERSION}")
        if length > MAX_FRAME:
            raise FrameError(f"frame of {length} bytes exceeds {MAX_FRAME}")
        body = await self.reader.readexactly(length)
        (crc,) = struct.unpack("<I", await self.reader.readexactly(4))
        if native.crc32(body) != crc:
            raise FrameError("frame checksum mismatch")
        self.bytes_received += _HEADER.size + length + 4
        self.frames_received += 1
        reg = get_registry()
        reg.inc("comm.bytes_framed_in", _HEADER.size + length + 4)
        reg.inc("comm.frames_in")
        self._edge_inc(
            "comm.edge.bytes_in", forward=False,
            v=_HEADER.size + length + 4,
        )
        self._edge_inc("comm.edge.frames_in", forward=False)
        return unpack_message(code, body)

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass

    async def wait_closed(self) -> None:
        try:
            await self.writer.wait_closed()
        except Exception:
            pass


async def open_framed_connection(
    host: str, port: int, *, retries: int = 20, delay: float = 0.1,
    send_retries: int = 0, retry_jitter_frac: float = 0.0,
    retry_seed: int = 0, on_retry: Optional[Callable[[], None]] = None,
) -> FramedStream:
    """Connect with retry (peers race to start their servers)."""
    last: Optional[Exception] = None
    for _ in range(retries):
        try:
            reader, writer = await asyncio.open_connection(host, port)
            return FramedStream(
                reader, writer,
                send_retries=send_retries,
                retry_jitter_frac=retry_jitter_frac,
                retry_seed=retry_seed, on_retry=on_retry,
            )
        except OSError as e:
            last = e
            await asyncio.sleep(delay)
    raise ConnectionError(f"cannot connect to {host}:{port}: {last}")
