"""TCP consensus master: control plane for multi-process deployments.

Parity: ``utils/consensus_tcp/master.py:21-266`` (``ConsensusMaster``) —
agent registration (:70-97), back-channel neighborhood distribution with
solved mixing weights (:99-126), round lifecycle served off a socket
multiplexer (:128-203), telemetry dispatch (:192-199), shutdown broadcast
(:48-61) — with the recorded defects fixed:

* the round flag is initialized in ``__init__`` (the reference reads
  ``self.running_round`` which is never set, ``master.py:140`` — its round
  path crashes on first use);
* agents' convergence reports are tracked per round id, two-sided (the
  asyncio backend's one-sided ``(y - v) <= eps`` check at
  ``consensus_asyncio.py:297`` is another recorded defect);
* no pickle: framing is the typed binary protocol.

Where the reference opens a *back-connection* to each agent (master.py:
103-104), this master sends the neighborhood over the same registered
control stream — one fewer socket per agent with identical information
flow.

The master never sees gossip values (data plane is agent<->agent), exactly
like the reference.  On a TPU pod this whole control plane is replaced by
the compiled SPMD program (see ``parallel/consensus.py``); this backend
exists for heterogeneous CPU-host deployments and protocol parity.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, Hashable, List, Optional, Sequence, Tuple

import numpy as np

from distributed_learning_tpu.comm.framing import FramedStream
from distributed_learning_tpu.comm.multiplexer import StreamMultiplexer
from distributed_learning_tpu.comm import protocol as P
from distributed_learning_tpu.obs import (
    FlightRecorder,
    HealthSentinel,
    RunAggregator,
    get_registry,
)
from distributed_learning_tpu.parallel.fast_averaging import solve_fastest_mixing
from distributed_learning_tpu.parallel.topology import Topology
from distributed_learning_tpu.utils.telemetry import TelemetryProcessor

__all__ = ["ConsensusMaster"]

#: graftproto role annotation (tools/graftlint/proto_extract.py): the
#: protocol extractor recovers this module's send/handle message sets
#: (isinstance dispatch + ``P.<Class>(...)`` constructions) under this
#: role and cross-checks them against protocol.py's _REGISTRY.
PROTO_ROLE = "master"

#: graftsched hot-coroutine annotation (tools/graftlint/schedsim.py):
#: the round-lifecycle coroutines whose await-point model pins under
#: ``sched_model`` — the master-side suspension points the schedule
#: explorer permutes when replaying the PR 15 round-end counterexample
#: against the real ``_on_status`` accounting.
SCHED_HOT = (
    "_on_status",
    "_broadcast_round",
    "_maybe_start_round",
)


class ConsensusMaster:
    """Serve registration, weight distribution, and round lifecycle."""

    def __init__(
        self,
        topology: Topology | Sequence[Tuple[Hashable, Hashable]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        weight_mode: str = "metropolis",
        convergence_eps: float = 1e-4,
        telemetry: Optional[TelemetryProcessor] = None,
        elastic: bool = False,
        regenerate: bool = False,
        debug: bool = False,
        aggregator: Optional[RunAggregator] = None,
        flight: Optional[FlightRecorder] = None,
        sentinel: Optional["HealthSentinel"] = None,
        round_deadline_s: Optional[float] = None,
        enforce_round_deadline: bool = False,
        quarantine_quorum: int = 1,
    ):
        self.topology = (
            topology
            if isinstance(topology, Topology)
            else Topology.from_edges(topology)
        )
        self.host, self.port = host, port
        self.convergence_eps = float(convergence_eps)
        self.telemetry = telemetry
        self.debug = debug
        self.weight_mode = weight_mode
        if weight_mode not in ("metropolis", "sdp"):
            raise ValueError(f"unknown weight_mode {weight_mode!r}")
        self.W = self._solve_weights(self.topology)

        self._tokens = [str(t) for t in self.topology.tokens]
        self._index = {t: i for i, t in enumerate(self._tokens)}
        self._control: Dict[str, FramedStream] = {}
        self._listen_addr: Dict[str, Tuple[str, int]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._mux = StreamMultiplexer()
        self._serve_task: Optional[asyncio.Task] = None
        self._all_registered = asyncio.Event()
        self._stopped = asyncio.Event()

        # Round state — initialized here, unlike the reference (defect:
        # master.py:140 reads an attribute __init__ never sets).
        self._round_running = False
        self._round_id = 0
        self._round_weights: Dict[str, float] = {}
        self._converged: Dict[str, bool] = {}
        # iteration -> tokens that reported Converged AT that iteration.
        # The round ends on the first iteration EVERY participant
        # converged at — ANDing latest-arrival statuses instead (the
        # reference's implied rule) is racy: a transiently-zero
        # residual (symmetric initial values hit them) can leave every
        # agent's LATEST status Converged at different iterations and
        # end the round far from consensus.
        self._conv_at: Dict[int, set] = {}

        # Run-wide observability plane (docs/observability.md §Run-wide
        # plane): the aggregator merges per-agent obs.delta Telemetry
        # payloads; the flight recorder keeps per-agent event rings and
        # dumps a JSONL black box on abort / death / deadline expiry /
        # shutdown-with-reason.  round_deadline_s only OBSERVES (counts
        # + dumps when a round overstays) — deadline-based round
        # *termination* is the async runtime's job, not the plane's.
        self.aggregator = aggregator
        self.flight = flight
        if (aggregator is not None and flight is not None
                and aggregator.flight is None):
            aggregator.flight = flight  # merged events feed the rings
        # Online health sentinel (docs/observability.md §Health
        # sentinel): evaluated against the aggregator's merged registry
        # after every telemetry batch, so a stalled residual, a
        # staleness blow-up, or a wire error storm is detected DURING
        # the run — breaches emit health.* events and trigger
        # reason-tagged flight dumps.  Wired to the shared flight
        # recorder when the caller left the sentinel's own unset.
        self.sentinel = sentinel
        if (sentinel is not None and flight is not None
                and sentinel.flight is None):
            sentinel.flight = flight
        self.round_deadline_s = (
            None if round_deadline_s is None else float(round_deadline_s)
        )
        # Deadline ENFORCEMENT (docs/async_runtime.md §Deadline-enforced
        # rounds): promotes round_deadline_s from observe-only to
        # drop-rather-than-wait.  Formation phase: a round whose quorum
        # is still missing agents when the deadline fires starts WITHOUT
        # them — their edges get zero weight this round (the agents
        # renormalize on device/host, presence_weight_matrix semantics)
        # and their queued requests join the next round.  In-round: an
        # overstaying round is CUT with Done(deadline=True) — agents
        # return their current (partially converged) values.
        self.enforce_round_deadline = bool(enforce_round_deadline)
        if self.enforce_round_deadline and self.round_deadline_s is None:
            raise ValueError(
                "enforce_round_deadline=True needs round_deadline_s"
            )
        self._deadline_handle: Optional[asyncio.TimerHandle] = None
        self._round_participants: set = set()
        # Wall-clock arrival time of each agent's round request: the
        # straggler-attribution signal (the last arrival set the pace).
        self._round_arrivals: Dict[str, float] = {}
        self._round_t0 = 0.0
        self._round_wall_t0 = 0.0

        # Elastic recovery (beyond parity: the reference's only failure
        # handling is the shutdown broadcast, SURVEY.md §5).  With
        # elastic=True a dead agent does not tear the deployment down:
        # its token is marked down, any running round is aborted (Done
        # broadcast — agents keep their current values), and a fresh
        # process may re-register the same token to rejoin.
        #
        # regenerate=True (implies elastic) adds ELASTIC MEMBERSHIP
        # (docs/async_runtime.md §Membership generations): instead of
        # freezing the run until the dead token rejoins, the master
        # re-forms the topology over the LIVE members (induced original
        # edges, bridged back to connectivity if the death cut the
        # graph), re-solves the mixing weights, bumps the membership
        # generation, and broadcasts versioned NeighborhoodData — the
        # survivors keep making progress at N-1, and (re)joining agents
        # realign to the current generation.  Unknown tokens may JOIN a
        # running deployment (register with ConsensusAgent(rejoin=True)
        # so the joiner initiates every peer connection).
        self.regenerate = bool(regenerate)
        self.elastic = bool(elastic) or self.regenerate
        self._generation = 0
        # Original edge list over tokens: each generation's topology is
        # the induced subgraph over live members plus connectivity
        # bridges (new joiners attach via the bridge chain too).
        self._base_edges = [
            (self.topology.tokens[i], self.topology.tokens[j])
            for i, j in self.topology.edges
        ]
        # Tokens that (re)joined in the CURRENT generation: they dial all
        # their neighbors themselves, so everyone else sees port 0.
        self._dialing_in: set = set()
        self._down: set = set()

        # Quarantine bookkeeping (docs/robustness.md §Quarantine): async
        # runners report repeatedly-violating peers via Telemetry
        # payloads of kind QUARANTINE_PAYLOAD_KIND; when quorum DISTINCT
        # accusers agree on a token it is evicted (Shutdown + stream
        # closed), barred from re-registering, and — with regenerate=True
        # — excluded from the next membership generation.  quorum
        # defaults to 1: a single honest detector suffices because the
        # accusation is of objectively-checkable protocol violations, not
        # of value quality; raise it if byzantine agents might accuse
        # honest ones.
        self.quarantine_quorum = max(1, int(quarantine_quorum))
        self._accusations: Dict[str, set] = {}
        self._quarantined: set = set()

        # Observability: named logger + round/telemetry counters (the
        # gossip-round accounting the reference's _debug prints threw
        # away), mirrored into the default obs registry.
        self._log = logging.getLogger("dlt.comm.master")
        if debug:
            from distributed_learning_tpu.utils.profiling import (
                enable_debug_logging,
            )

            enable_debug_logging()
        self.counters: Dict[str, float] = {}

    # ------------------------------------------------------------------ #
    def _debug(self, msg: str, *args):
        """Lazy-formatted debug line on the master's named logger."""
        self._log.debug(msg, *args)

    def _count(self, name: str, value: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + value
        get_registry().inc(f"comm.master.{name}", value)

    def wire_stats(self) -> Dict[str, int]:
        """Whole-frame byte/frame totals over the master's live control
        streams — the control-plane counterpart of
        ``ConsensusAgent.wire_stats()``.  The master never carries gossip
        values, so these totals are pure coordination overhead; the
        fused-wire loopback test pins that per-leaf -> fused data-plane
        framing changes leave them untouched."""
        streams = list(self._control.values())
        return {
            "bytes_sent": sum(s.bytes_sent for s in streams),
            "bytes_received": sum(s.bytes_received for s in streams),
            "frames_sent": sum(s.frames_sent for s in streams),
            "frames_received": sum(s.frames_received for s in streams),
        }

    @property
    def address(self) -> Tuple[str, int]:
        assert self._server is not None, "master not started"
        return self._server.sockets[0].getsockname()[:2]

    @property
    def generation(self) -> int:
        """Current membership generation (0 = the seed deployment)."""
        return self._generation

    # ------------------------------------------------------------------ #
    # Elastic membership: topology/weight regeneration                   #
    # ------------------------------------------------------------------ #
    def _solve_weights(self, topology: Topology) -> np.ndarray:
        if topology.n_agents == 1:
            return np.ones((1, 1), dtype=np.float64)
        if self.weight_mode == "sdp":
            # Fastest-mixing weights (parity: _solve_fastest_convergence,
            # master.py:262-266 -> fast_averaging.py:4-32), re-solved for
            # every membership generation's graph.
            W, _ = solve_fastest_mixing(topology)
            return W
        return topology.metropolis_weights()

    def _form_topology(self, live: List[str]) -> Topology:
        """This generation's graph: the induced subgraph of the original
        topology over the live members, bridged back to connectivity.

        A death can cut the graph (a chain loses its middle) and a
        joiner may have no original edges at all; components are linked
        by a chain of bridges between their smallest tokens, so every
        generation's graph is connected and fastest-mixing weights
        exist."""
        live_set = set(live)
        edges = [
            (u, v) for (u, v) in self._base_edges
            if u in live_set and v in live_set
        ]
        if len(live) == 1:
            return Topology(n_agents=1, edges=(), tokens=(live[0],))
        # Union-find over live tokens to find components.
        parent = {t: t for t in live}

        def find(t):
            while parent[t] != t:
                parent[t] = parent[parent[t]]
                t = parent[t]
            return t

        for u, v in edges:
            parent[find(u)] = find(v)
        reps = sorted({find(t) for t in live})
        if len(reps) > 1:
            comps = {r: [] for r in reps}
            for t in live:
                comps[find(t)].append(t)
            anchors = [min(comps[r]) for r in reps]
            bridges = list(zip(anchors, anchors[1:]))
            edges.extend(bridges)
            self._debug("topology bridges added: %s", bridges)
        return Topology.from_edges(sorted(edges))

    async def _regenerate(self, cause: str, token: str) -> None:
        """Re-form the topology over the live membership, re-solve W,
        bump the generation, and broadcast versioned NeighborhoodData to
        every live agent (docs/async_runtime.md §Membership
        generations)."""
        live = sorted(self._control)
        if not live:
            return
        self._generation += 1
        self._dialing_in = {token} if cause != "death" else set()
        self.topology = self._form_topology(live)
        # Generation order follows the regenerated topology's token
        # order so W rows index consistently.
        self._tokens = [str(t) for t in self.topology.tokens]
        self._index = {t: i for i, t in enumerate(self._tokens)}
        self.W = self._solve_weights(self.topology)
        self._count("generations")
        self._debug(
            "membership generation %s (%s %s): members=%s",
            self._generation, cause, token, self._tokens,
        )
        if self.flight is not None:
            self.flight.note(
                "<master>", "generation", generation=self._generation,
                cause=cause, token=token, members=list(self._tokens),
            )
        for t in self._tokens:
            await self._send_neighborhood(t)

    async def start(self) -> Tuple[str, int]:
        """Start listening and serving; returns the bound (host, port)."""
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port
        )
        self._serve_task = asyncio.create_task(self._serve())
        return self.address

    async def _handle_connection(self, reader, writer):
        stream = FramedStream(reader, writer)
        try:
            msg = await stream.recv()
        except (ConnectionError, asyncio.IncompleteReadError):
            stream.close()
            return
        if not isinstance(msg, P.Register):
            await stream.send(P.ErrorException(message="expected Register"))
            stream.close()
            return
        token = msg.token
        if token in self._quarantined:
            # A quarantined token stays out until an operator clears it:
            # letting it re-register would hand the violator a fresh
            # violation budget every time it reconnects.
            self._count("quarantine_rejections")
            await stream.send(
                P.ErrorException(message=f"token {token!r} is quarantined")
            )
            stream.close()
            return
        joining = False
        if token not in self._index:
            # Elastic membership: an unknown token may JOIN a running
            # deployment (the next generation's topology attaches it).
            # Pre-initialization the member set is the constructor's.
            if not (self.regenerate and self._all_registered.is_set()):
                await stream.send(
                    P.ErrorException(message=f"unknown agent token {token!r}")
                )
                stream.close()
                return
            joining = True
        if token in self._control:
            await stream.send(
                P.ErrorException(message=f"token {token!r} already registered")
            )
            stream.close()
            return
        # A token that died BEFORE the deployment initialized re-registers
        # as a plain registration (its neighbors have no stale streams yet);
        # after initialization it is a rejoin.
        rejoining = (
            self.elastic
            and token in self._down
            and self._all_registered.is_set()
        )
        self._down.discard(token)
        self._control[token] = stream
        self._listen_addr[token] = (msg.host, msg.port)
        self._count("registrations")
        if self.flight is not None:
            self.flight.note(
                "<master>",
                "joined" if joining else (
                    "rejoined" if rejoining else "registered"
                ),
                token=token,
            )
        self._debug("registered %s @ %s:%s", token, msg.host, msg.port)
        await stream.send(
            P.Ok(
                info="joined" if joining else (
                    "rejoined" if rejoining else "registered"
                )
            )
        )
        # Into the mux immediately: deaths are then observable in every
        # phase, including the registration window, and the serve loop's
        # parked wait is woken for the new stream (elastic rejoin would
        # otherwise leave its round request unread until unrelated traffic
        # arrived).
        self._mux.add(token, stream)
        if (joining or rejoining) and self.regenerate:
            # Elastic membership: the member set changed — re-form the
            # topology, re-solve W, bump the generation, broadcast the
            # new epoch to EVERY live agent (the (re)joiner included).
            await self._regenerate(
                "join" if joining else "rejoin", token
            )
            self._count("rejoins" if rejoining else "joins")
            await self._maybe_start_round()
            return
        if rejoining:
            # Resend this agent's neighborhood; the rejoiner initiates all
            # its peer connections itself, so nobody else needs its new
            # address.
            await self._send_neighborhood(token)
            self._count("rejoins")
            self._debug("%s rejoined", token)
            return
        if len(self._control) == len(self._tokens):
            await self._initialize_agents()
            self._all_registered.set()

    async def _send_neighborhood(self, token: str) -> None:
        stream = self._control.get(token)
        if stream is None:
            # Agent died while initialization was in flight (the serve loop
            # pops dead tokens concurrently — it runs from startup, not from
            # all-registered).  Its rejoin re-requests the neighborhood, so
            # skipping here is safe; raising would kill the registration
            # handler and wedge the deployment.
            self._debug("skip neighborhood for %s: not connected", token)
            return
        i = self._index[token]
        nbs: List[P.Neighbor] = []
        for j in self.topology.neighbors(i):
            nb_token = self._tokens[j]
            host, port = self._listen_addr[nb_token]
            if nb_token in self._down or (
                nb_token in self._dialing_in and nb_token != token
            ):
                # Currently-down neighbor: its recorded address is stale.
                # port 0 tells a rejoiner not to dial — the neighbor's own
                # replacement will dial in when it re-registers.  This
                # generation's fresh (re)joiner is flagged the same way:
                # it initiates every one of its peer connections itself.
                host, port = "", 0
            nbs.append(
                P.Neighbor(
                    token=nb_token, host=host, port=port,
                    weight=float(self.W[i, j]),
                )
            )
        try:
            await stream.send(
                P.NeighborhoodData(
                    self_weight=float(self.W[i, i]),
                    convergence_eps=self.convergence_eps,
                    neighbors=nbs,
                    generation=self._generation,
                )
            )
        except (ConnectionError, OSError) as exc:
            # The death itself surfaces through the mux sentinel; here we
            # only keep the caller (registration handler or init loop) alive.
            self._debug("neighborhood send to %s failed: %s", token, exc)

    async def _initialize_agents(self) -> None:
        """Send every agent its neighborhood + mixing weights (parity:
        ``_initialize_agents`` + ``get_neighborhood_info_for_agent``,
        master.py:99-126, 227-243)."""
        for token in self._tokens:
            await self._send_neighborhood(token)
        self._debug("all agents initialized")

    # ------------------------------------------------------------------ #
    async def _serve(self) -> None:
        """Round lifecycle loop (parity: ``_serve``, master.py:128-203).

        Runs from startup (not from all-registered): control streams join
        the multiplexer at registration, so agent deaths are detected in
        every phase — the mux parks while the stream set is empty.
        """
        try:
            async for token, msg, _stream in self._mux:
                if msg is None:
                    if self.elastic:
                        # Agent died: mark it down, abort any running round
                        # (Done: agents keep their current values and may
                        # retry), keep serving so the token can rejoin.
                        dead = self._control.pop(token, None)
                        if dead is not None:
                            # Close our half of the accepted connection, or
                            # Server.wait_closed() (3.12+: waits for accepted
                            # conns) would hang at shutdown.
                            dead.close()
                        self._down.add(token)
                        self._round_weights.pop(token, None)
                        self._round_arrivals.pop(token, None)
                        aborted_round = None
                        if self._round_running:
                            self._round_running = False
                            self._cancel_deadline()
                            self._count("rounds_aborted")
                            aborted_round = self._round_id
                            await self._broadcast_round(
                                P.Done(round_id=self._round_id, aborted=True)
                            )
                            self._debug(
                                "round %s aborted: %s died",
                                self._round_id, token,
                            )
                        self._count("agents_down")
                        if self.flight is not None:
                            # One black box per fault: the abort dump
                            # subsumes the death that caused it.
                            self.flight.note(
                                "<master>", "agent_down", token=token,
                                round_id=aborted_round,
                            )
                            if aborted_round is not None:
                                self._flight_dump(
                                    "round_aborted",
                                    round_id=aborted_round, token=token,
                                )
                            else:
                                self._flight_dump("agent_down", token=token)
                        if self.regenerate and self._all_registered.is_set():
                            # Elastic membership: survivors keep going at
                            # N-1 under a fresh (topology, W) generation
                            # instead of stalling until the token rejoins.
                            await self._regenerate("death", token)
                            await self._maybe_start_round()
                        self._debug("agent %s down; awaiting rejoin", token)
                        continue
                    # Control connection lost.  No recovery protocol exists
                    # in non-elastic mode (parity: reference master's only
                    # failure handling is the shutdown broadcast): tear the
                    # deployment down.
                    raise RuntimeError(f"agent {token} disconnected")
                if isinstance(msg, P.NewRoundRequest):
                    await self._on_round_request(token, msg)
                elif isinstance(msg, (P.Converged, P.NotConverged)):
                    await self._on_status(token, msg)
                elif isinstance(msg, P.Telemetry):
                    self._count("telemetry_payloads")
                    if self._is_quarantine_report(msg.payload):
                        await self._on_quarantine_report(
                            msg.token or token, msg.payload
                        )
                    if self.aggregator is not None:
                        # The run-wide plane: obs.delta payloads merge
                        # into the run registry (+ flight rings); other
                        # payloads are recorded as plain telemetry.
                        self.aggregator.process(
                            msg.token or token, msg.payload
                        )
                        if self.sentinel is not None:
                            # Never-fatal, like _flight_dump: the health
                            # plane must not crash the control plane.
                            try:
                                self.sentinel.evaluate()
                            except Exception as exc:  # pragma: no cover
                                self._debug(
                                    "sentinel evaluate failed: %r", exc
                                )
                    if self.telemetry is not None:
                        self.telemetry.process(msg.token or token, msg.payload)
                elif isinstance(msg, P.ErrorException):
                    raise RuntimeError(f"agent {token}: {msg.message}")
                else:
                    self._debug(
                        "ignoring %s from %s", type(msg).__name__, token
                    )
        except asyncio.CancelledError:
            pass
        except Exception as e:  # parity: shutdown broadcast on master error
            self._debug("error: %r; broadcasting shutdown", e)
            if self.flight is not None:
                self._flight_dump("master_error", error=repr(e))
            await self._broadcast(P.Shutdown(reason=repr(e)))
        finally:
            self._stopped.set()

    # ------------------------------------------------------------------ #
    # Quarantine (docs/robustness.md §Quarantine)                        #
    # ------------------------------------------------------------------ #
    @staticmethod
    def _is_quarantine_report(payload) -> bool:
        from distributed_learning_tpu.comm.async_runtime import (
            QUARANTINE_PAYLOAD_KIND,
        )

        return (
            isinstance(payload, dict)
            and payload.get("kind") == QUARANTINE_PAYLOAD_KIND
        )

    async def _on_quarantine_report(self, accuser: str, payload) -> None:
        """One runner's quarantine report: tally the DISTINCT accusers of
        the accused token; at quorum, evict it (Shutdown, stream closed,
        registration barred) and — under elastic membership — regenerate
        the topology without it."""
        accused = str(payload.get("accused", ""))
        self._count("quarantine_reports")
        if not accused or accused == accuser:
            return  # malformed or self-accusation: recorded, not acted on
        if self.flight is not None:
            self.flight.note(
                "<master>", "quarantine_report",
                accuser=accuser, accused=accused,
                violations=payload.get("violations"),
            )
        accusers = self._accusations.setdefault(accused, set())
        accusers.add(accuser)
        if accused in self._quarantined:
            return
        if len(accusers) < self.quarantine_quorum:
            return
        self._quarantined.add(accused)
        self._count("agents_quarantined")
        self._debug(
            "quarantining %s (accused by %s)", accused, sorted(accusers)
        )
        # The black box records the detection even when the accused is
        # not currently connected (it may be mid-rejoin).
        self._flight_dump(
            "quarantine", token=accused, accusers=sorted(accusers),
            violations=payload.get("violations"),
        )
        stream = self._control.pop(accused, None)
        self._mux.remove(accused)
        self._down.discard(accused)  # not coming back: barred below
        self._round_weights.pop(accused, None)
        self._round_arrivals.pop(accused, None)
        if stream is not None:
            try:
                await stream.send(P.Shutdown(reason="quarantined"))
            except (ConnectionError, OSError):
                pass
            stream.close()
        if self._round_running:
            self._round_running = False
            self._cancel_deadline()
            self._count("rounds_aborted")
            await self._broadcast_round(
                P.Done(round_id=self._round_id, aborted=True)
            )
        if self.regenerate and self._all_registered.is_set():
            await self._regenerate("quarantine", accused)
            await self._maybe_start_round()

    def _flight_dump(self, reason: str, **context) -> None:
        """Trigger a flight-recorder dump (counted, never fatal — the
        black box must not be able to crash the plane it records)."""
        if self.flight is None:
            return
        try:
            path = self.flight.trigger(reason, **context)
            self._count("flight_dumps")
            self._debug("flight recorder dumped %s (%s)", path, reason)
        except OSError as exc:  # pragma: no cover - disk-full etc.
            self._debug("flight dump failed: %s", exc)

    def _cancel_deadline(self) -> None:
        if self._deadline_handle is not None:
            self._deadline_handle.cancel()
            self._deadline_handle = None

    def _on_round_deadline(self, round_id: int) -> None:
        """call_later callback: the round overstayed round_deadline_s.

        Observe-only by default — the lock-step protocol keeps waiting;
        the count and the dump make the stall diagnosable instead of
        silent.  With ``enforce_round_deadline`` the round is CUT:
        Done(deadline=True) goes to the participants, who return their
        current (partially converged) values — drop rather than wait."""
        self._deadline_handle = None
        if self._round_running and self._round_id == round_id:
            self._count("round_deadlines_expired")
            missing = sorted(
                t for t, ok in self._converged.items() if not ok
            )
            self._flight_dump(
                "round_deadline", round_id=round_id,
                deadline_s=self.round_deadline_s, waiting_on=missing,
            )
            if self.enforce_round_deadline:
                asyncio.ensure_future(self._deadline_cut(round_id))

    async def _deadline_cut(self, round_id: int) -> None:
        if not (self._round_running and self._round_id == round_id):
            return
        self._round_running = False
        self._count("rounds_deadline_cut")
        if self.aggregator is not None:
            self.aggregator.note_round_done(
                round_id,
                time.perf_counter() - self._round_t0,
                wall_t0=self._round_wall_t0,
            )
        await self._broadcast_round(P.Done(round_id=round_id, deadline=True))
        self._debug("round %s cut at the deadline", round_id)
        await self._maybe_start_round()

    def _on_formation_deadline(self) -> None:
        """call_later callback of the drop-rather-than-wait FORMATION
        deadline: the quorum has been incomplete for round_deadline_s —
        start the round with whoever showed up; the missing agents' edges
        get zero weight this round (NewRoundNotification.dropped) and
        their late requests queue for the next round."""
        self._deadline_handle = None
        if self._round_running or not self._round_weights:
            return
        asyncio.ensure_future(self._formation_deadline_start())

    async def _formation_deadline_start(self) -> None:
        if self._round_running:
            return
        present = sorted(
            t for t in self._round_weights
            if t in self._index and t in self._control
        )
        if not present:
            return
        self._count("round_formation_deadlines")
        if self.flight is not None:
            self.flight.note(
                "<master>", "formation_deadline",
                waiting_on=sorted(set(self._tokens) - set(present)),
            )
        await self._start_round(present)

    async def _on_round_request(self, token: str, msg: P.NewRoundRequest):
        if self._round_running:
            if self.enforce_round_deadline:
                # Drop-rather-than-wait: a straggler that missed this
                # round queues for the next one instead of erroring the
                # deployment.
                self._round_weights[token] = msg.weight
                self._round_arrivals[token] = time.time()
                self._count("round_requests_deferred")
                return
            # Parity intent of the "round already running" guard
            # (master.py:140-144), minus the crash.
            await self._control[token].send(
                P.ErrorException(message="round already running")
            )
            return
        self._round_weights[token] = msg.weight
        # Straggler signal: who kept the round waiting.  Wall clock on
        # purpose — arrivals are compared against agent-side wall
        # anchors on the merged timeline.
        self._round_arrivals[token] = time.time()
        await self._maybe_start_round()

    async def _maybe_start_round(self) -> None:
        """Start a round if the pending quorum allows it: complete quorum
        starts immediately; with deadline enforcement an incomplete one
        arms the formation deadline."""
        if self._round_running:
            return
        # Requests from members a later generation removed (death, or a
        # regenerated topology) no longer count toward any quorum.
        for t in list(self._round_weights):
            if t not in self._index or t not in self._control:
                self._round_weights.pop(t, None)
                self._round_arrivals.pop(t, None)
        if not self._round_weights:
            return
        if len(self._round_weights) == len(self._tokens):
            self._cancel_deadline()
            await self._start_round(sorted(self._round_weights))
        elif (
            self.enforce_round_deadline and self._deadline_handle is None
        ):
            self._deadline_handle = asyncio.get_event_loop().call_later(
                self.round_deadline_s, self._on_formation_deadline
            )

    async def _start_round(self, participants: List[str]) -> None:
        self._round_id += 1
        self._round_running = True
        self._round_participants = set(participants)
        dropped = sorted(set(self._tokens) - self._round_participants)
        self._converged = {t: False for t in participants}
        self._conv_at = {}
        mean_w = float(
            np.mean([self._round_weights[t] for t in participants])
        )
        arrivals = {
            t: self._round_arrivals.pop(t)
            for t in participants if t in self._round_arrivals
        }
        for t in participants:
            self._round_weights.pop(t, None)
        self._count("rounds_started")
        if dropped:
            self._count("round_agents_dropped", len(dropped))
        self._round_wall_t0 = time.time()
        self._round_t0 = time.perf_counter()
        if self.aggregator is not None:
            self.aggregator.note_round_arrivals(self._round_id, arrivals)
        if self.round_deadline_s:
            self._cancel_deadline()
            self._deadline_handle = (
                asyncio.get_event_loop().call_later(
                    self.round_deadline_s,
                    self._on_round_deadline, self._round_id,
                )
            )
        await self._broadcast_round(
            P.NewRoundNotification(
                round_id=self._round_id, mean_weight=mean_w,
                generation=self._generation, dropped=dropped,
            )
        )
        self._debug(
            "round %s started, mean_w=%s%s", self._round_id, mean_w,
            f", dropped={dropped}" if dropped else "",
        )

    async def _on_status(self, token: str, msg):
        if msg.round_id != self._round_id or not self._round_running:
            return  # stale report from a finished round
        if token not in self._converged:
            return  # not a participant of this round
        # Latest-status view: the deadline dump's "waiting_on" picture.
        self._converged[token] = isinstance(msg, P.Converged)
        if isinstance(msg, P.Converged):
            at = self._conv_at.setdefault(msg.iteration, set())
            at.add(token)
        # Done iff some single iteration saw EVERY participant converge
        # (once truly converged, agents report Converged every
        # iteration, so the first common iteration always arrives).
        if isinstance(msg, P.Converged) and (
            self._conv_at[msg.iteration] >= self._round_participants
        ):
            self._round_running = False
            self._cancel_deadline()
            self._count("rounds_done")
            if self.aggregator is not None:
                self.aggregator.note_round_done(
                    self._round_id,
                    time.perf_counter() - self._round_t0,
                    wall_t0=self._round_wall_t0,
                )
            await self._broadcast_round(P.Done(round_id=self._round_id))
            self._debug("round %s done", self._round_id)
            await self._maybe_start_round()

    async def _broadcast(self, msg) -> None:
        for token, stream in list(self._control.items()):
            try:
                await stream.send(msg)
            except (ConnectionError, OSError):
                self._debug("broadcast to %s failed", token)

    async def _broadcast_round(self, msg) -> None:
        """Round-lifecycle broadcast: participants only — an agent
        dropped from the round must not mistake its notifications/Done
        for a round it will join later."""
        for token in sorted(self._round_participants):
            stream = self._control.get(token)
            if stream is None:
                continue
            try:
                await stream.send(msg)
            except (ConnectionError, OSError):
                self._debug("round broadcast to %s failed", token)

    # ------------------------------------------------------------------ #
    async def shutdown(self, reason: str = "") -> None:
        """Broadcast shutdown and stop (parity: master.py:48-61).  A
        shutdown WITH a reason is a fault path — it ships its black
        box."""
        self._cancel_deadline()
        if reason:
            self._flight_dump("shutdown", detail=reason)
        await self._broadcast(P.Shutdown(reason=reason))
        if self._serve_task is not None:
            self._serve_task.cancel()
            try:
                await self._serve_task
            except asyncio.CancelledError:
                pass
        self._mux.close()
        # Close accepted control streams BEFORE wait_closed: since 3.12,
        # Server.wait_closed also waits for accepted connections to drop.
        for stream in self._control.values():
            stream.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def wait_all_registered(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._all_registered.wait(), timeout)
