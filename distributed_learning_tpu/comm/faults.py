"""Deterministic fault injection for the comm stack.

Every survival claim this repo makes — crc rejection of torn frames
(``framing.py``), validate-before-scatter (``tensor_codec.py`` /
``native/wire.cpp``), straggler-tolerant async rounds
(``async_runtime.py``), elastic membership healing (``master.py``) —
was built against failure modes that nothing in the repo could actually
*produce* on demand.  This module closes that gap: a seeded, replayable
:class:`FaultPlan` decides per frame index whether to drop, duplicate,
reorder, corrupt (two flavors — see below), delay, or byzantine-mutate
the frame, and :class:`FaultyStream` applies those decisions while
speaking the real wire format through the real transport, so the
production receive path is exercised end-to-end.

Corruption flavors map to the two rejection layers:

* ``corrupt`` (wire-level) flips body bytes AFTER the crc is stamped —
  the receiver's checksum fails:
  :class:`~distributed_learning_tpu.comm.framing.FrameError`
  (a ConnectionError: the multiplexer evicts the stream, the async
  runtime's heal path takes over).
* ``truncate`` (payload-level) removes tail bytes BEFORE the crc is
  stamped — the frame arrives checksum-clean but structurally invalid,
  driving the codec's validate-before-scatter path:
  :class:`~distributed_learning_tpu.comm.tensor_codec.CodecError`,
  counted and dropped at the multiplexer service point, stream intact
  (the length-prefixed framing stays aligned: the body was fully
  consumed before decode).

Determinism: every decision is a pure function of ``(seed, frame
index)`` (a per-index :func:`numpy.random.default_rng` stream), so the
same plan replays the identical fault schedule — the property the
breakdown and determinism tests in ``tests/test_faults.py`` pin.

Schedule composition: because delays are applied through
``asyncio.sleep`` and carry no wall-clock state, a :class:`FaultyStream`
runs unmodified under the graftsched virtual-clock explorer
(``tools/graftlint/schedsim.py``) — (fault seed, schedule seed) then
jointly replays a wire-fault storm under a chosen task interleaving in
simulated time, which is how the sched corpus composes the two
harnesses (``docs/static_analysis.md`` §Stage 7).

The reference's transport (``utils/consensus_tcp/pickled_socket.py``)
has no failure injection at all — its failure story is whatever pickle
does with a torn byte stream; this harness is the framework's addition
the ROADMAP's fleet-churn item builds on.
"""

from __future__ import annotations

import asyncio
import dataclasses
import struct
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from distributed_learning_tpu import native
from distributed_learning_tpu.comm import protocol as P
from distributed_learning_tpu.comm.framing import (
    _HEADER,
    WIRE_VERSION,
    FramedStream,
)
from distributed_learning_tpu.obs import get_registry

__all__ = [
    "FaultDecision",
    "FaultPlan",
    "FaultyStream",
    "inject_neighbor_faults",
    "lying_fields_mutator",
    "poison_value_mutator",
]

#: graftsched hot-coroutine annotation (tools/graftlint/schedsim.py):
#: ``FaultyStream.send`` is where injected delays suspend — its
#: await-point model pins under ``sched_model`` so the joint
#: (FaultPlan x schedule) exploration surface cannot drift silently.
SCHED_HOT = ("FaultyStream.send",)

#: Exclusive per-frame fault kinds, in decision priority order.
_KINDS = (
    "drop", "corrupt", "truncate", "dup", "reorder", "byzantine"
)


class FaultDecision(NamedTuple):
    """What the plan does to ONE outgoing frame: an exclusive ``kind``
    (``"none"`` or one of drop / corrupt / truncate / dup / reorder /
    byzantine / crash) plus an independent bounded ``delay_s``."""

    kind: str = "none"
    delay_s: float = 0.0


class FaultPlan:
    """Seeded, replayable per-frame fault schedule.

    Probabilities are exclusive (at most one kind per frame, chosen by
    one uniform draw against cumulative thresholds, in :data:`_KINDS`
    order); ``delay_p``/``delay_max_s`` is an independent bounded hold
    before the frame is written (straggler storms).  ``crash_at``
    overrides everything from that send index on: the transport is torn
    down abruptly (mid-round agent crash).  ``mutate`` is the byzantine
    arm's message transform (default:
    :func:`lying_fields_mutator` — protocol-field lies the async
    runtime's validation must catch).
    """

    def __init__(
        self,
        seed: int,
        *,
        drop_p: float = 0.0,
        corrupt_p: float = 0.0,
        truncate_p: float = 0.0,
        dup_p: float = 0.0,
        reorder_p: float = 0.0,
        byzantine_p: float = 0.0,
        delay_p: float = 0.0,
        delay_max_s: float = 0.0,
        crash_at: Optional[int] = None,
        mutate: Optional[Callable[[int, Any], Any]] = None,
    ):
        probs = {
            "drop": drop_p, "corrupt": corrupt_p,
            "truncate": truncate_p, "dup": dup_p,
            "reorder": reorder_p, "byzantine": byzantine_p,
        }
        for name, p in probs.items():
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name}_p must be in [0, 1], got {p}")
        if sum(probs.values()) > 1.0:
            raise ValueError(
                "fault probabilities must sum to <= 1 (kinds are "
                f"exclusive per frame), got {sum(probs.values())}"
            )
        if not 0.0 <= delay_p <= 1.0:
            raise ValueError(f"delay_p must be in [0, 1], got {delay_p}")
        self.seed = int(seed)
        self.probs = probs
        self.delay_p = float(delay_p)
        self.delay_max_s = float(delay_max_s)
        self.crash_at = None if crash_at is None else int(crash_at)
        self.mutate = mutate if mutate is not None else lying_fields_mutator

    def decide(self, index: int) -> FaultDecision:
        """The decision for frame ``index`` — a pure function of
        ``(seed, index)``, so replays are bit-identical regardless of
        timing or interleaving."""
        if self.crash_at is not None and index >= self.crash_at:
            return FaultDecision(kind="crash")
        rng = np.random.default_rng([self.seed, int(index)])
        u, v, w = rng.random(3)
        kind = "none"
        acc = 0.0
        for name in _KINDS:
            acc += self.probs[name]
            if u < acc:
                kind = name
                break
        delay = self.delay_max_s * w if v < self.delay_p else 0.0
        return FaultDecision(kind=kind, delay_s=delay)

    def schedule(self, n: int) -> List[FaultDecision]:
        """The first ``n`` decisions — the replayable schedule the
        determinism tests compare across plan instances."""
        return [self.decide(i) for i in range(n)]

    def corrupt_bytes(self, index: int, body: bytes) -> bytes:
        """Wire-level corruption: flip one deterministically-chosen byte
        (applied after the crc is stamped -> receiver FrameError)."""
        if not body:
            return body
        rng = np.random.default_rng([self.seed, int(index), 1])
        pos = int(rng.integers(0, len(body)))
        mask = int(rng.integers(1, 256))
        return body[:pos] + bytes([body[pos] ^ mask]) + body[pos + 1:]

    def truncate_bytes(self, index: int, body: bytes) -> bytes:
        """Payload-level corruption: cut a deterministic tail slice
        (applied BEFORE the crc is stamped -> checksum-clean frame whose
        decode fails structurally: CodecError, never a scatter)."""
        if len(body) <= 1:
            return body
        rng = np.random.default_rng([self.seed, int(index), 2])
        # Keep at least 1 byte, drop at least 1: always structurally
        # short for the codec's length validation.
        keep = int(rng.integers(1, len(body)))
        return body[:keep]

    def wrap(self, stream: FramedStream, *, peer: str = "",
             edge: str = "") -> "FaultyStream":
        return FaultyStream(stream, self, peer=peer, edge=edge)


def lying_fields_mutator(index: int, msg: Any) -> Any:
    """Default byzantine mutation: protocol-field lies on AsyncValue
    pushes — alternating an absurdly-far-future round claim, a
    backwards round counter, and a negative staleness — exactly the
    violations :class:`~distributed_learning_tpu.comm.async_runtime.
    AsyncGossipRunner`'s wire validation must reject."""
    if not isinstance(msg, P.AsyncValue):
        return msg
    arm = index % 3
    if arm == 0:
        return dataclasses.replace(msg, round_id=2 ** 40)
    if arm == 1:
        return dataclasses.replace(msg, round_id=-1)
    return dataclasses.replace(msg, staleness=-7)


def poison_value_mutator(
    scale: float = 1e6,
) -> Callable[[int, Any], Any]:
    """Byzantine VALUE mutation: a well-formed frame carrying a poisoned
    payload (``value * scale``) — invisible to wire validation, the case
    the robust mixing programs (``parallel/robust.py``) exist for."""

    def mutate(index: int, msg: Any) -> Any:
        if isinstance(msg, P.AsyncValue):
            return dataclasses.replace(
                msg, value=np.asarray(msg.value, np.float32) * scale
            )
        return msg

    return mutate


class FaultyStream:
    """A :class:`FramedStream` lookalike whose ``send`` routes every
    frame through a :class:`FaultPlan`.

    Speaks the real wire format onto the inner stream's transport, so
    the receiving side runs the production path end-to-end (framing crc,
    codec validation, multiplexer eviction/drop accounting).  ``recv``
    and everything else delegate to the inner stream — wrap the sender's
    side of an edge to inject into the peer's receive path.

    Visible state: ``send_index`` (frames offered so far), ``events``
    (``(index, kind)`` log, the replay-assertion surface), ``counters``
    (per-kind tallies, also mirrored into the obs registry as
    ``comm.faults.<kind>`` — plus ``comm.faults.<kind>/<edge>`` and a
    ``comm.fault`` registry event carrying (fault, peer, frame_index,
    round) when the wrapper knows its edge, so every injected decision
    is attributable in the merged run log and the per-edge profile).
    """

    def __init__(self, inner: FramedStream, plan: FaultPlan, *,
                 peer: str = "", edge: str = ""):
        self.inner = inner
        self.plan = plan
        self.peer = peer
        self.edge = edge  # directed "src->dst" label, "" when unknown
        self.send_index = 0
        self.events: List[Tuple[int, str]] = []
        self.counters: Dict[str, int] = {}
        self._held: Optional[bytes] = None  # reorder buffer (one frame)
        self._round: Optional[int] = None  # round_id of the frame in flight

    def _note(self, index: int, kind: str) -> None:
        if kind == "none":
            return
        self.events.append((index, kind))
        self.counters[kind] = self.counters.get(kind, 0) + 1
        reg = get_registry()
        reg.inc(f"comm.faults.{kind}")
        if self.edge:
            reg.inc(f"comm.faults.{kind}/{self.edge}")
        reg.event(
            "comm.fault", fault=kind, peer=self.peer,
            frame_index=index, round=self._round, edge=self.edge,
        )

    def _encode(self, msg: Any, decision: FaultDecision, index: int) -> bytes:
        code, body = P.pack_message(msg)
        if decision.kind == "truncate":
            body = self.plan.truncate_bytes(index, body)
        crc = native.crc32(body)
        if decision.kind == "corrupt":
            body = self.plan.corrupt_bytes(index, body)
        header = _HEADER.pack(len(body), WIRE_VERSION, code, 0)
        return header + body + struct.pack("<I", crc)

    async def _write(self, frame: bytes) -> None:
        async with self.inner._send_lock:
            self.inner.writer.write(frame)
            await self.inner.writer.drain()
        self.inner.bytes_sent += len(frame)
        self.inner.frames_sent += 1

    async def send(self, msg: Any) -> None:
        index = self.send_index
        self.send_index += 1
        decision = self.plan.decide(index)
        self._round = getattr(msg, "round_id", None)
        self._note(index, decision.kind)
        if decision.kind == "crash":
            # Mid-round agent crash: abrupt transport teardown — the
            # peer sees an incomplete read, the master a death sentinel.
            self.inner.close()
            raise ConnectionResetError("fault-injected crash")
        if decision.kind == "byzantine":
            msg = self.plan.mutate(index, msg)
        if decision.delay_s > 0.0:
            self._note(index, "delay")
            await asyncio.sleep(decision.delay_s)
        if decision.kind == "drop":
            return
        frame = self._encode(msg, decision, index)
        if decision.kind == "reorder" and self._held is None:
            # Swap-with-next: held until the next frame is written.  (A
            # trailing reorder on a stream that then goes quiet stays
            # held — inherent to swapping with a frame that never comes.)
            self._held = frame
            return
        await self._write(frame)
        if self._held is not None:
            held, self._held = self._held, None
            await self._write(held)
        if decision.kind == "dup":
            await self._write(frame)

    async def recv(self, timeout: Optional[float] = None) -> Any:
        return await self.inner.recv(timeout)

    def close(self) -> None:
        self._held = None
        self.inner.close()

    async def wait_closed(self) -> None:
        await self.inner.wait_closed()

    def __getattr__(self, name: str) -> Any:
        # Counter/introspection passthrough (bytes_sent, peername, ...):
        # the wrapper must be drop-in wherever a FramedStream is held.
        return getattr(self.inner, name)


def inject_neighbor_faults(
    agent: Any, token: str, plan: FaultPlan
) -> FaultyStream:
    """Wrap ``agent``'s installed stream to ``token`` so every frame the
    agent pushes to that neighbor routes through ``plan`` — the
    one-liner the breakdown tests use to turn a healthy in-process
    deployment into a byzantine one.  Returns the wrapper (its
    ``events``/``counters`` are the assertion surface)."""
    stream = agent._neighbors[token]
    wrapped = plan.wrap(
        stream, peer=token, edge=f"{agent.token}->{token}"
    )
    agent._neighbors[token] = wrapped
    return wrapped
