"""Model-pytree <-> flat wire vector adapter for the comm backend.

The reference gossips *flattened* torch parameters — ``Mixer`` round-trips
every model through ``_get_flatten_model_params`` / ``_load_flatten_params``
(``utils/consensus_simple/mixer.py:68-76``).  The TCP data plane here
(:mod:`~distributed_learning_tpu.comm.agent`) likewise moves one flat f32
vector per agent.  This module is the structured boundary: a model pytree
crosses the wire as ``(flat f32 vector, TreeSpec)``, where the spec
(treedef + per-leaf shapes/dtypes) is construction-time static and
identical on every agent — only the vector ever touches the network, so
the existing ``run_once``/``run_round`` protocol carries whole models
unchanged (bf16 wire narrowing included, ``tensor_codec.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import numpy as np

__all__ = ["TreeSpec", "tree_to_flat", "flat_to_tree"]

Pytree = Any


def _is_float_dtype(dt: np.dtype) -> bool:
    if np.issubdtype(dt, np.floating):
        return True
    try:  # extension float types (bfloat16 & friends) register in ml_dtypes
        import ml_dtypes

        return np.issubdtype(dt, ml_dtypes.bfloat16) or dt in (
            np.dtype(ml_dtypes.bfloat16),
            np.dtype(ml_dtypes.float8_e4m3fn),
            np.dtype(ml_dtypes.float8_e5m2),
        )
    except Exception:
        return False


@dataclasses.dataclass(frozen=True)
class TreeSpec:
    """Static description of a flattened pytree: enough to rebuild the
    tree from the wire vector.  Equal specs on every agent are the
    deployment invariant (same model class + config => same spec)."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[np.dtype, ...]

    @property
    def sizes(self) -> Tuple[int, ...]:
        return tuple(int(np.prod(s, dtype=np.int64)) for s in self.shapes)

    @property
    def total(self) -> int:
        return int(sum(self.sizes))

    def dtype_buckets(self) -> Tuple[Tuple[str, Tuple[Tuple[int, int], ...]], ...]:
        """Leaf spans of the wire ravel grouped by ORIGINAL storage dtype.

        ``((dtype_name, ((offset, size), ...)), ...)``, dtype names
        sorted, offsets ascending flat positions into the f32 wire
        vector.  This is the wire-side twin of
        ``ops.mixing.FusedLayout.bucket_spans``: the fused sparse frame
        (``tensor_codec.encode_fused_sparse``) ships one
        ``indices|values`` payload per bucket, so bf16-origin leaves
        ride a bf16 value section while f32 leaves keep full precision
        — per-leaf framing collapses to one frame with per-bucket value
        encodings."""
        by_dtype: dict = {}
        off = 0
        for dt, size in zip(self.dtypes, self.sizes):
            by_dtype.setdefault(str(np.dtype(dt)), []).append((off, size))
            off += size
        return tuple(
            (name, tuple(spans)) for name, spans in sorted(by_dtype.items())
        )


def tree_to_flat(tree: Pytree) -> Tuple[np.ndarray, TreeSpec]:
    """Flatten a float pytree into one f32 wire vector plus its spec.

    Non-float leaves are rejected: gossip averages values, which is
    meaningless for integer state (step counters etc.) — mix parameters,
    keep such state local (the reference averages only
    ``model.parameters()``, same boundary).
    """
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.asarray(leaf) for leaf in leaves]
    for a in arrs:
        if not _is_float_dtype(a.dtype):
            raise TypeError(
                f"cannot gossip non-float leaf of dtype {a.dtype}; flatten "
                "only the model parameters"
            )
    spec = TreeSpec(
        treedef=treedef,
        shapes=tuple(a.shape for a in arrs),
        dtypes=tuple(np.dtype(a.dtype) for a in arrs),
    )
    if not arrs:
        return np.zeros(0, np.float32), spec
    flat = np.concatenate([a.astype(np.float32).ravel() for a in arrs])
    return flat, spec


def flat_to_tree(flat: np.ndarray, spec: TreeSpec) -> Pytree:
    """Rebuild the pytree from a wire vector (leaves restored to their
    original shapes and dtypes)."""
    import jax

    flat = np.asarray(flat, dtype=np.float32).ravel()
    if flat.size != spec.total:
        raise ValueError(
            f"wire vector has {flat.size} elements, spec expects {spec.total}"
        )
    leaves = []
    off = 0
    for shape, dtype, size in zip(spec.shapes, spec.dtypes, spec.sizes):
        leaves.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(spec.treedef, leaves)
