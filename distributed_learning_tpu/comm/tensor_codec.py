"""Binary tensor wire format for the TCP comm backend.

Replaces the reference's pickled-numpy payloads
(``utils/consensus_tcp/pickled_socket.py:12,23`` — arbitrary code execution
from any peer, and f64-sized frames) with a fixed, safe layout:

    u8 dtype_code | u8 flags | u8 ndim | u8 reserved |
    u32 dim[ndim] | raw little-endian data

``flags`` bit 0 marks a float32 tensor narrowed to bfloat16 on the wire
(half the bytes; round-to-nearest-even via the native codec) — the TPU
wire format for gossip values.  Integrity is checked one level up by the
frame crc32 (``framing.py``).
"""

from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

from distributed_learning_tpu import native

__all__ = ["encode_tensor", "decode_tensor", "FLAG_BF16_COMPRESSED"]

FLAG_BF16_COMPRESSED = 0x01

_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
    np.dtype(np.uint16): 5,  # raw bfloat16 bit patterns
    np.dtype(np.bool_): 6,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}
_MAX_NDIM = 16


def encode_tensor(x: np.ndarray, *, bf16_wire: bool = False) -> bytes:
    """Serialize an array; ``bf16_wire=True`` narrows f32 payloads to bf16."""
    x = np.asarray(x)
    if not x.flags["C_CONTIGUOUS"]:
        # (ascontiguousarray unconditionally promotes 0-d arrays to 1-d,
        # so only reorder when actually needed.)
        x = np.ascontiguousarray(x)
    if x.dtype not in _DTYPE_CODES:
        raise TypeError(f"unsupported wire dtype {x.dtype}")
    if x.ndim > _MAX_NDIM:
        raise ValueError(f"ndim {x.ndim} exceeds wire limit {_MAX_NDIM}")
    flags = 0
    payload = x
    if bf16_wire and x.dtype == np.float32:
        payload = native.f32_to_bf16(x)
        flags |= FLAG_BF16_COMPRESSED
    header = struct.pack(
        f"<BBBB{x.ndim}I",
        _DTYPE_CODES[np.dtype(payload.dtype)],
        flags,
        x.ndim,
        0,
        *x.shape,
    )
    return header + payload.tobytes()


def decode_tensor(buf: bytes) -> np.ndarray:
    """Inverse of :func:`encode_tensor` (bf16 wire data returns as f32)."""
    if len(buf) < 4:
        raise ValueError("tensor frame too short")
    code, flags, ndim, _ = struct.unpack_from("<BBBB", buf, 0)
    if code not in _CODE_DTYPES:
        raise ValueError(f"unknown wire dtype code {code}")
    if ndim > _MAX_NDIM:
        raise ValueError(f"ndim {ndim} exceeds wire limit {_MAX_NDIM}")
    dims: Tuple[int, ...] = struct.unpack_from(f"<{ndim}I", buf, 4)
    offset = 4 + 4 * ndim
    dtype = _CODE_DTYPES[code]
    count = int(np.prod(dims, dtype=np.int64)) if ndim else 1
    expect = count * dtype.itemsize
    data = buf[offset : offset + expect]
    if len(data) != expect:
        raise ValueError(
            f"tensor frame truncated: want {expect} payload bytes, "
            f"have {len(data)}"
        )
    x = np.frombuffer(data, dtype=dtype).reshape(dims)
    if flags & FLAG_BF16_COMPRESSED:
        x = native.bf16_to_f32(x)
    return x
