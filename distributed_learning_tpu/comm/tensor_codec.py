"""Binary tensor wire format for the TCP comm backend.

Replaces the reference's pickled-numpy payloads
(``utils/consensus_tcp/pickled_socket.py:12,23`` — arbitrary code execution
from any peer, and f64-sized frames) with a fixed, safe layout:

    u8 dtype_code | u8 flags | u8 ndim | u8 reserved |
    u32 dim[ndim] | raw little-endian data

``flags`` bit 0 marks a float32 tensor narrowed to bfloat16 on the wire
(half the bytes; round-to-nearest-even via the native codec) — the TPU
wire format for gossip values.  ``flags`` bit 1 marks symmetric int8
quantization (quarter bytes: one f32 scale = max|x|/127 ahead of the
int8 payload) — the CHOCO-wire option whose quantization error the
error-feedback loop absorbs.  Integrity is checked one level up by the
frame crc32 (``framing.py``); the fused sparse frame additionally
carries its OWN trailing crc32 (see ``encode_fused_sparse``) so its
decoder can reject corruption before the first scatter into the ravel.

Native wire engine (ISSUE 9): the dense frame path and the fused sparse
frame path route through ``native/wire.cpp`` when it builds — whole
frames encoded/decoded in one call, the u32 gather/scatter fused with
the bf16/int8 conversion, a slicing-by-8 crc over the assembled frame.
THIS module's pure-Python implementation stays the byte-for-byte
authoritative oracle and the ``DLT_NO_NATIVE=1`` fallback; the native
path must produce identical bytes (pinned by ``tests/test_wire.py``).
Every encode/decode records which path served on the ``comm.wire.native``
gauge so run reports say which engine a measurement ran on.
"""

from __future__ import annotations

import os
import struct
from typing import Tuple

import numpy as np

from distributed_learning_tpu import native
from distributed_learning_tpu.native import wire as native_wire

__all__ = [
    "CodecError",
    "encode_tensor",
    "decode_tensor",
    "encode_sparse",
    "decode_sparse",
    "encode_fused_sparse",
    "decode_fused_sparse",
    "decode_fused_apply",
    "FusedFrame",
    "DenseFrame",
    "SparseFrame",
    "top_k_sparse",
    "FLAG_BF16_COMPRESSED",
    "FLAG_INT8_COMPRESSED",
]


class CodecError(ValueError):
    """Corrupt or protocol-violating tensor frame.

    Subclasses ``ValueError`` so pre-existing callers (and tests) that
    catch the broad class keep working; raised by the wire-engine paths
    for every corruption class — truncation, checksum mismatch, section
    lengths/offsets out of bounds, scatter indices outside the ravel —
    and NEVER accompanied by a partial write (decode validates before it
    scatters, both native and Python)."""

FLAG_BF16_COMPRESSED = 0x01
FLAG_INT8_COMPRESSED = 0x02

_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
    np.dtype(np.uint16): 5,  # raw bfloat16 bit patterns
    np.dtype(np.bool_): 6,
    np.dtype(np.int8): 7,  # int8-quantized wire payloads
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}
_MAX_NDIM = 16
# Densification cap for sparse frames: 2^28 f32 elements = 1 GiB, matching
# the largest single gossip tensor the backend is sized for (MAX_FRAME in
# framing.py bounds dense frames the same way).
_MAX_SPARSE_DENSE_ELEMS = 1 << 28


def _wire_engine():
    """The native wire engine module, or None when unavailable or
    disabled (``DLT_NO_NATIVE=1``, honored per call so the fallback can
    be forced without restarting).  Records the serving path on the
    ``comm.wire.native`` gauge — one dict write per FRAME, so run
    reports (and bench records) can say which engine ran."""
    eng = native_wire if native_wire.available() else None
    try:  # lazy: obs is optional at this layer and must not cycle imports
        from distributed_learning_tpu.obs import get_registry

        get_registry().gauge("comm.wire.native", 1.0 if eng else 0.0)
    except Exception:
        pass
    return eng


def encode_tensor(x: np.ndarray, *, bf16_wire: bool = False,
                  int8_wire: bool = False) -> bytes:
    """Serialize an array.

    For f32 payloads ``bf16_wire=True`` halves the bytes (RNE) and
    ``int8_wire=True`` quarters them (symmetric quantization, per-tensor
    f32 scale stored ahead of the int8 data).  Mutually exclusive.
    """
    x = np.asarray(x)
    if bf16_wire and int8_wire:
        raise ValueError("bf16_wire and int8_wire are mutually exclusive")
    if not x.flags["C_CONTIGUOUS"]:
        # (ascontiguousarray unconditionally promotes 0-d arrays to 1-d,
        # so only reorder when actually needed.)
        x = np.ascontiguousarray(x)
    if x.dtype not in _DTYPE_CODES:
        raise TypeError(f"unsupported wire dtype {x.dtype}")
    if x.ndim > _MAX_NDIM:
        raise ValueError(f"ndim {x.ndim} exceeds wire limit {_MAX_NDIM}")
    if x.dtype == np.dtype(np.float32):
        # Native whole-frame path: header + converted payload written
        # into one preallocated buffer (wire.cpp), byte-identical to the
        # Python assembly below.
        eng = _wire_engine()
        if eng is not None:
            mode = (
                native_wire.MODE_BF16 if bf16_wire
                else native_wire.MODE_I8 if int8_wire
                else native_wire.MODE_F32
            )
            try:
                frame = eng.encode_dense(x, mode)
            except ValueError as exc:
                raise CodecError(str(exc)) from None
            if frame is not None:
                return frame
    flags = 0
    payload = x
    prefix = b""
    if bf16_wire and x.dtype == np.float32:
        payload = native.f32_to_bf16(x)
        flags |= FLAG_BF16_COMPRESSED
    elif int8_wire and x.dtype == np.float32:
        scale = float(np.max(np.abs(x)) / 127.0) if x.size else 0.0
        if not np.isfinite(scale):
            # A NaN/Inf anywhere poisons max|x| (and would quantize the
            # whole tensor to garbage, platform-dependently).  Loud, not
            # dropped — same stance as top_k_sparse.  CodecError (a
            # ValueError) so both wire-engine paths raise the same class.
            raise CodecError(
                "int8 wire requires finite values (scale came out "
                f"{scale}); refusing to quantize a poisoned tensor"
            )
        payload = native.f32_to_i8(x, scale)
        flags |= FLAG_INT8_COMPRESSED
        prefix = struct.pack("<f", scale)
    header = struct.pack(
        f"<BBBB{x.ndim}I",
        _DTYPE_CODES[np.dtype(payload.dtype)],
        flags,
        x.ndim,
        0,
        *x.shape,
    )
    return header + prefix + payload.tobytes()


def _check_out(out: np.ndarray, count: int) -> None:
    """Validate a caller-supplied scratch ravel for the ``out=`` decode
    contract: C-contiguous writable f32 of exactly ``count`` elements.
    A mismatch is a caller bug (ValueError), never a wire error."""
    if not isinstance(out, np.ndarray):
        raise ValueError("out= must be a numpy ndarray")
    if out.dtype != np.dtype(np.float32):
        raise ValueError(f"out= must be float32, got {out.dtype}")
    if not out.flags["C_CONTIGUOUS"] or not out.flags["WRITEABLE"]:
        raise ValueError("out= must be C-contiguous and writable")
    if out.size != count:
        raise ValueError(
            f"out= holds {out.size} elements, frame decodes {count}"
        )


def _parse_tensor(buf: bytes):
    """Header parse + full length validation of a dense tensor frame —
    the O(1) half of :func:`decode_tensor`, shared with the lazy
    :class:`DenseFrame` payload.  Returns ``(code, flags, dims, dtype,
    scale, payload_offset, count, data)``."""
    if len(buf) < 4:
        raise ValueError("tensor frame too short")
    code, flags, ndim, _ = struct.unpack_from("<BBBB", buf, 0)
    if code not in _CODE_DTYPES:
        raise ValueError(f"unknown wire dtype code {code}")
    if ndim > _MAX_NDIM:
        raise ValueError(f"ndim {ndim} exceeds wire limit {_MAX_NDIM}")
    dims: Tuple[int, ...] = struct.unpack_from(f"<{ndim}I", buf, 4)
    offset = 4 + 4 * ndim
    dtype = _CODE_DTYPES[code]
    scale = None
    if flags & FLAG_INT8_COMPRESSED:
        if dtype != np.dtype(np.int8):
            raise ValueError("int8 flag on a non-int8 payload")
        (scale,) = struct.unpack_from("<f", buf, offset)
        offset += 4
    count = int(np.prod(dims, dtype=np.int64)) if ndim else 1
    expect = count * dtype.itemsize
    data = buf[offset : offset + expect]
    if len(data) != expect:
        raise ValueError(
            f"tensor frame truncated: want {expect} payload bytes, "
            f"have {len(data)}"
        )
    return code, flags, dims, dtype, scale, offset, count, data


def decode_tensor(buf: bytes, *, out: "np.ndarray" = None) -> np.ndarray:
    """Inverse of :func:`encode_tensor` (bf16 wire data returns as f32).

    ``out=`` (optional) is a reusable f32 scratch ravel of exactly the
    frame's element count: the decode writes into it (every element —
    prior contents never leak) and returns it reshaped, skipping the
    per-frame allocation.  Bytes are identical to the allocating path.
    """
    code, flags, dims, dtype, scale, offset, count, data = \
        _parse_tensor(buf)
    if out is not None:
        _check_out(out, count)
    if (
        flags & (FLAG_BF16_COMPRESSED | FLAG_INT8_COMPRESSED)
        and len(buf) == offset + len(data)
        and code in (5, 7)
    ):
        # Native whole-frame decode for the converting layouts (bf16 and
        # int8 payloads): parse + convert in one call.  Raw frames keep
        # the zero-copy numpy view below; a buffer with trailing slack
        # (tolerated here) also stays on the Python path.
        eng = _wire_engine()
        if eng is not None:
            target = out.reshape(dims) if out is not None \
                else np.empty(dims, np.float32)
            if eng.decode_dense(buf, target) == 0:
                return target
    x = np.frombuffer(data, dtype=dtype).reshape(dims)
    if flags & FLAG_BF16_COMPRESSED:
        # The converters ravel: reshape back so the 0-d/N-d frame shape
        # survives the fallback path exactly as it does in-engine.
        x = native.bf16_to_f32(x).reshape(dims)
    elif flags & FLAG_INT8_COMPRESSED:
        x = native.i8_to_f32(x, scale).reshape(dims)
    if out is not None:
        ret = out.reshape(dims)
        np.copyto(ret, x, casting="unsafe")
        return ret
    return x


# --------------------------------------------------------------------- #
# Sparse wire format (compressed-gossip corrections)                    #
# --------------------------------------------------------------------- #
def encode_sparse(x: np.ndarray, *, bf16_wire: bool = False,
                  int8_wire: bool = False) -> bytes:
    """Serialize only the non-zero entries of a (dense) array.

    The wire for CHOCO-style corrections
    (:mod:`distributed_learning_tpu.parallel.compression`): a top-k
    compressed correction is dense in memory but k-sparse in content, so
    the payload is ``shape | u32 indices[k] | values[k]`` — ``O(k)`` bytes
    instead of ``O(d)``.  Values ride :func:`encode_tensor` (so
    ``bf16_wire`` composes), indices are flat positions into the C-order
    ravel.  Per entry the sparse wire costs 4 (index) + 2 (bf16 value)
    bytes vs 2 dense, so it wins below ~1/3 density (f32: 8 vs 4, below
    ~1/2; int8: 5 vs 1, below ~1/5) — at CHOCO's typical 1-10% top-k
    fractions a 3-33x (bf16) / 5-50x (f32) byte reduction; measured 6.6x
    at 5% top-k, bf16.  ``int8_wire`` quantizes the value payload
    (scale from the non-zero values only, so sparsity does not waste
    quantization range).
    """
    x = np.asarray(x)
    flat = x.ravel()  # C-order view (copy when non-contiguous)
    if flat.size > _MAX_SPARSE_DENSE_ELEMS:
        # Mirror decode_sparse's densification cap: failing here is a clear
        # local error instead of an opaque decode failure on every peer.
        raise ValueError(
            f"sparse wire limited to {_MAX_SPARSE_DENSE_ELEMS} dense "
            f"elements, got {flat.size}"
        )
    if x.ndim > _MAX_NDIM:
        # Same clear-local-error policy: decode_sparse rejects ndim >
        # _MAX_NDIM, so encoding it would fail on every peer instead.
        raise ValueError(f"ndim {x.ndim} exceeds wire limit {_MAX_NDIM}")
    idx = np.flatnonzero(flat).astype(np.uint32)
    vals = flat[idx]
    header = struct.pack(f"<BBBB{x.ndim}I", 0xFF, 0, x.ndim, 0, *x.shape)
    return (
        header
        + struct.pack("<I", idx.size)
        + idx.tobytes()
        + encode_tensor(vals, bf16_wire=bf16_wire, int8_wire=int8_wire)
    )


def _parse_sparse(buf: bytes):
    """The O(k) half of :func:`decode_sparse`: full validation + value
    decode, NO densification.  Returns ``(dims, count, idx, vals)``."""
    if len(buf) < 4:
        raise ValueError("sparse frame too short")
    magic, _flags, ndim, _ = struct.unpack_from("<BBBB", buf, 0)
    if magic != 0xFF:
        raise ValueError(f"not a sparse tensor frame (magic {magic:#x})")
    if ndim > _MAX_NDIM:
        raise ValueError(f"ndim {ndim} exceeds wire limit {_MAX_NDIM}")
    if len(buf) < 4 + 4 * ndim + 4:
        raise ValueError("sparse frame truncated in header")
    dims: Tuple[int, ...] = struct.unpack_from(f"<{ndim}I", buf, 4)
    offset = 4 + 4 * ndim
    (k,) = struct.unpack_from("<I", buf, offset)
    offset += 4
    count = int(np.prod(dims, dtype=np.int64)) if ndim else 1
    if count > _MAX_SPARSE_DENSE_ELEMS:
        # The dense target is allocated from the (untrusted) shape header
        # alone — unlike dense frames, the payload length scales with k,
        # not count, so a tiny frame could otherwise demand an unbounded
        # allocation.
        raise ValueError(
            f"sparse frame densifies to {count} elements "
            f"(limit {_MAX_SPARSE_DENSE_ELEMS})"
        )
    if k > count:
        raise ValueError(f"sparse frame claims {k} entries in {count} slots")
    idx_bytes = buf[offset : offset + 4 * k]
    if len(idx_bytes) != 4 * k:
        raise ValueError("sparse frame truncated in indices")
    idx = np.frombuffer(idx_bytes, dtype=np.uint32)
    offset += 4 * k
    if k and int(idx.max()) >= count:
        raise ValueError("sparse index out of range")
    vals = decode_tensor(buf[offset:])
    if vals.shape != (k,):
        raise ValueError(f"sparse frame value count {vals.shape} != {k}")
    return dims, count, idx, vals


def decode_sparse(buf: bytes, *, out: "np.ndarray" = None) -> np.ndarray:
    """Inverse of :func:`encode_sparse`; returns the densified array.

    ``out=`` (optional) is a reusable f32 scratch ravel of the frame's
    dense element count: the decode zero-fills it, scatters into it,
    and returns it reshaped — prior (dirty) contents never leak.  The
    result dtype is then f32 regardless of the value section's dtype
    (the scatter casts on assignment, same values as the allocating
    path for the f32-sourced wire modes)."""
    dims, count, idx, vals = _parse_sparse(buf)
    if out is not None:
        _check_out(out, count)
        out.fill(0.0)
        out[idx] = vals
        return out.reshape(dims)
    dense = np.zeros(count, dtype=vals.dtype)
    dense[idx] = vals
    return dense.reshape(dims)


# --------------------------------------------------------------------- #
# Fused sparse wire format (one frame per gossip round)                 #
# --------------------------------------------------------------------- #
_FUSED_MAGIC = 0xFE
#: Fused frame version.  v1 (ISSUE 9) added the version byte itself and
#: the trailing frame crc32, so the decoder — whose scatter writes into a
#: freshly allocated ravel — rejects corruption before touching it.
_FUSED_VERSION = 1
#: bf16-precision storage dtypes: their value sections always narrow to
#: bf16 on the wire (that IS their information content).
_BF16_ORIGIN = ("bfloat16", "float16")


def _bucket_modes(buckets, bf16_wire: bool, int8_wire: bool):
    """Per-bucket wire mode (native_wire.MODE_*): bf16-origin buckets
    always ride bf16 values, f32 buckets honor ``bf16_wire``, and
    ``int8_wire`` quantizes every section."""
    modes = []
    for name, _spans in buckets:
        if int8_wire:
            modes.append(native_wire.MODE_I8)
        elif bf16_wire or name in _BF16_ORIGIN:
            modes.append(native_wire.MODE_BF16)
        else:
            modes.append(native_wire.MODE_F32)
    return tuple(modes)


def encode_fused_sparse(
    x: np.ndarray,
    buckets,
    *,
    bf16_wire: bool = False,
    int8_wire: bool = False,
) -> bytes:
    """Serialize a k-sparse wire vector as ONE frame with one
    ``indices|values`` payload per dtype bucket.

    ``x`` is the dense flat f32 wire vector of a whole model
    (``pytree_codec.tree_to_flat``); ``buckets`` is
    ``TreeSpec.dtype_buckets()`` — leaf spans grouped by ORIGINAL
    storage dtype.  Where per-leaf gossip ships one sparse frame per
    leaf (leaf_count x framing/CRC/headers per neighbor per round), this
    format collapses a round's whole correction to one frame: indices
    are u32 flat positions into the TreeSpec ravel, and each bucket's
    value section is encoded at that bucket's precision — bf16-origin
    leaves ride bf16 values regardless of ``bf16_wire``, f32 buckets
    honor ``bf16_wire``; ``int8_wire`` quantizes every section (the
    CHOCO error-feedback loop absorbs the noise).

    Layout (v1)::

        u8 0xFE | u8 version=1 | u8 nbuckets | u8 0 | u32 total_dim |
        per bucket: u32 k | u32 idx[k] | u32 vlen | encode_tensor(vals) |
        u32 crc32(all preceding bytes)

    The trailing crc is the frame's own integrity check (on top of the
    transport-level one in ``framing.py``): the decoder verifies it — and
    bounds-checks every section header — BEFORE the first scatter into
    the ravel, so corruption raises :class:`CodecError` and never writes.

    When the native wire engine is up, the whole frame is assembled by
    ONE call into ``wire.cpp`` (gather + conversion + crc fused, two
    linear passes over the ravel); the Python loop below is the
    byte-for-byte oracle and the ``DLT_NO_NATIVE=1`` fallback.
    """
    if bf16_wire and int8_wire:
        raise ValueError("bf16_wire and int8_wire are mutually exclusive")
    flat = np.ascontiguousarray(x, dtype=np.float32).ravel()
    if flat.size > _MAX_SPARSE_DENSE_ELEMS:
        raise ValueError(
            f"sparse wire limited to {_MAX_SPARSE_DENSE_ELEMS} dense "
            f"elements, got {flat.size}"
        )
    buckets = tuple(buckets)
    if len(buckets) > 0xFF:
        raise ValueError(f"{len(buckets)} dtype buckets exceed wire limit 255")
    covered = 0
    for _name, spans in buckets:
        for off, size in spans:
            if off < 0 or size < 0 or off + size > flat.size:
                raise ValueError(
                    f"bucket span ({off}, {size}) outside the wire vector "
                    f"of {flat.size} elements"
                )
            covered += size
    if covered != flat.size:
        raise ValueError(
            f"bucket spans cover {covered} of {flat.size} wire elements — "
            "buckets must tile the TreeSpec ravel exactly"
        )
    modes = _bucket_modes(buckets, bf16_wire, int8_wire)
    eng = _wire_engine()
    if eng is not None:
        try:
            frame = eng.encode_fused(
                flat,
                tuple(
                    (mode, spans)
                    for mode, (_name, spans) in zip(modes, buckets)
                ),
            )
        except ValueError as exc:
            raise CodecError(str(exc)) from None
        if frame is not None:
            return frame
    return _encode_fused_sparse_py(flat, buckets, modes)


def _encode_fused_sparse_py(flat: np.ndarray, buckets, modes) -> bytes:
    """The authoritative Python assembly of a fused sparse frame (inputs
    pre-validated by :func:`encode_fused_sparse`)."""
    out = [
        struct.pack(
            "<BBBBI", _FUSED_MAGIC, _FUSED_VERSION, len(buckets), 0,
            flat.size,
        )
    ]
    for (_name, spans), mode in zip(buckets, modes):
        pos = np.concatenate(
            [np.arange(off, off + size, dtype=np.uint32)
             for off, size in spans]
        ) if spans else np.empty(0, np.uint32)
        sub = flat[pos]
        nz = np.flatnonzero(sub)
        idx = pos[nz]
        vals = encode_tensor(
            sub[nz],
            bf16_wire=mode == native_wire.MODE_BF16,
            int8_wire=mode == native_wire.MODE_I8,
        )
        out.append(struct.pack("<I", idx.size))
        out.append(idx.tobytes())
        out.append(struct.pack("<I", len(vals)))
        out.append(vals)
    body = b"".join(out)
    return body + struct.pack("<I", native.crc32(body))


def _parse_fused_header(buf: bytes) -> Tuple[int, int]:
    """Shared header prelude of the fused read paths: returns
    ``(nbuckets, total)`` or raises :class:`CodecError`."""
    if len(buf) < 12:
        raise CodecError("fused sparse frame too short")
    magic, version, nbuckets, _r, total = struct.unpack_from(
        "<BBBBI", buf, 0
    )
    if magic != _FUSED_MAGIC:
        raise CodecError(f"not a fused sparse frame (magic {magic:#x})")
    if total > _MAX_SPARSE_DENSE_ELEMS:
        raise CodecError(
            f"fused sparse frame densifies to {total} elements "
            f"(limit {_MAX_SPARSE_DENSE_ELEMS})"
        )
    if version != _FUSED_VERSION:
        raise CodecError(
            f"unsupported fused sparse frame version {version}"
        )
    return nbuckets, total


def decode_fused_sparse(buf: bytes, *, out: "np.ndarray" = None) -> np.ndarray:
    """Inverse of :func:`encode_fused_sparse`; returns the densified flat
    f32 wire vector (the receiver rebuilds the pytree via its own
    ``TreeSpec`` — the deployment invariant: same model, same spec).

    ``out=`` (optional) is a reusable f32 scratch ravel of ``total``
    elements (the zero-copy receive path): the decode zero-fills it
    between validation and scatter, so dirty scratch never leaks into
    untouched positions, and returns it instead of allocating.

    Corruption discipline (native and Python paths alike): the frame crc
    is verified and every section header bounds-checked BEFORE the first
    scatter write into a freshly-allocated ravel; violations raise
    :class:`CodecError`.  With ``out=``, a frame the ORACLE path rejects
    mid-walk may leave earlier buckets' writes in the scratch — the
    scratch contract is that a failed decode leaves ``out`` unspecified
    (the caller drops the frame and the next decode zero-fills)."""
    nbuckets, total = _parse_fused_header(buf)
    if out is not None:
        _check_out(out, total)
    eng = _wire_engine()
    if eng is not None:
        # The native decode zero-fills the ravel itself (between its
        # validation walk and the scatter), so np.empty — not np.zeros —
        # is correct here: the O(total) clear happens once, page-fault
        # batched, inside the engine.
        target = out if out is not None else np.empty(total, np.float32)
        status = eng.decode_fused(buf, target)
        if status == 0:
            return target
        if status != native_wire.ERR_UNSUPPORTED:
            raise CodecError(
                native_wire.CORRUPT_MESSAGES.get(
                    status, f"wire status {status}"
                )
            )
        # A valid frame with a value dtype the native engine does not
        # speak: the Python oracle below decodes it.
    return _decode_fused_sparse_py(buf, nbuckets, total, out=out)


def _iter_fused_sections(buf: bytes, nbuckets: int, total: int):
    """Walk a fused frame's sections with full validation (crc checked
    FIRST, then per-section bounds/range/shape), yielding
    ``(idx: uint32[k], vals: ndarray[k])`` per bucket — the shared core
    of the Python decode/apply/validate paths."""
    body_end = len(buf) - 4
    (crc,) = struct.unpack_from("<I", buf, body_end)
    if native.crc32(buf[:body_end]) != crc:
        raise CodecError("fused sparse frame checksum mismatch")
    off = 8
    for _ in range(nbuckets):
        if body_end < off + 4:
            raise CodecError("fused sparse frame truncated at bucket header")
        (k,) = struct.unpack_from("<I", buf, off)
        off += 4
        if k > total:
            raise CodecError(
                f"fused sparse bucket claims {k} entries in {total} slots"
            )
        idx_bytes = buf[off : off + min(4 * k, body_end - off)]
        if len(idx_bytes) != 4 * k:
            raise CodecError("fused sparse frame truncated in indices")
        idx = np.frombuffer(idx_bytes, dtype=np.uint32)
        off += 4 * k
        if k and int(idx.max()) >= total:
            raise CodecError("fused sparse index out of range")
        if body_end < off + 4:
            raise CodecError("fused sparse frame truncated at value header")
        (vlen,) = struct.unpack_from("<I", buf, off)
        off += 4
        if off + vlen > body_end:
            raise CodecError("fused sparse frame truncated in values")
        try:
            vals = decode_tensor(buf[off : off + vlen])
        except (ValueError, struct.error) as exc:
            raise CodecError(str(exc)) from None
        off += vlen
        if vals.shape != (k,):
            raise CodecError(
                f"fused sparse value count {vals.shape} != {k}"
            )
        yield idx, vals
    if off != body_end:
        raise CodecError("fused sparse frame section out of bounds")


def _decode_fused_sparse_py(buf: bytes, nbuckets: int, total: int,
                            out: "np.ndarray" = None) -> np.ndarray:
    """The authoritative Python decode (header pre-parsed): crc first,
    then per-section bounds checks, then the scatter.  ``out`` (when
    given) is zero-filled first so dirty scratch never leaks."""
    if out is None:
        out = np.zeros(total, np.float32)
    else:
        out.fill(0.0)
    for idx, vals in _iter_fused_sections(buf, nbuckets, total):
        out[idx] = vals.astype(np.float32)
    return out


def decode_fused_apply(buf: bytes, target: np.ndarray, *,
                       scale: float = 1.0) -> np.ndarray:
    """Scatter-ADD a fused sparse frame straight into a live f32 ravel
    (``target[idx] += scale * vals``) with NO dense intermediate — the
    fused consume primitive for CHOCO hat updates.

    For the duplicate-free frames the encoder produces the result is
    ulp-identical to ``target += scale * decode_fused_sparse(buf)``
    (untouched positions keep their exact bytes, which the dense form
    only perturbs at ``-0.0``).  Corruption discipline is strict on BOTH
    paths here: the whole frame is validated before the first add, so a
    :class:`CodecError` guarantees ``target`` is untouched — required,
    since the target is live state, not scratch.  Returns ``target``."""
    nbuckets, total = _parse_fused_header(buf)
    _check_out(target, total)
    scale = float(scale)
    eng = _wire_engine()
    if eng is not None:
        status = eng.decode_apply(buf, target, scale)
        if status == 0:
            return target
        if status != native_wire.ERR_UNSUPPORTED:
            raise CodecError(
                native_wire.CORRUPT_MESSAGES.get(
                    status, f"wire status {status}"
                )
            )
    # Python oracle: materialize (and thereby validate) EVERY section
    # before the first add — a corrupt later bucket must not leave a
    # half-applied update in live state.
    sections = list(_iter_fused_sections(buf, nbuckets, total))
    s = np.float32(scale)
    for idx, vals in sections:
        np.add.at(target, idx, s * vals.astype(np.float32))
    return target


# --------------------------------------------------------------------- #
# Lazy receive payloads (zero-copy wire path)                            #
#                                                                        #
# The comm layer unpacks message bodies on the mux task, but the scratch #
# ravel a frame should decode into is owned by the ROUND task (the       #
# runner's per-edge scratch pool).  These wrappers split the pipeline:   #
# construction VALIDATES the frame (corruption still raises CodecError   #
# at unpack time, preserving the mux drop discipline) but defers the     #
# O(total) densify/apply to the consumer, which passes its own ``out=``  #
# scratch or applies the frame in place.                                 #
# --------------------------------------------------------------------- #
class DenseFrame:
    """A validated, not-yet-decoded dense tensor frame.

    Construction is O(1) (header + length checks); :meth:`densify` runs
    the conversion, into ``out=`` scratch when given."""

    __slots__ = ("buf", "shape", "size")

    def __init__(self, buf: bytes):
        _code, _flags, dims, _dtype, _scale, _off, count, _data = \
            _parse_tensor(buf)
        self.buf = buf
        self.shape = tuple(dims)
        self.size = count

    def densify(self, out: "np.ndarray" = None) -> np.ndarray:
        return decode_tensor(self.buf, out=out)

    def __array__(self, dtype=None, copy=None):
        dense = self.densify()
        return dense if dtype is None else dense.astype(dtype)


class SparseFrame:
    """A validated sparse frame whose O(k) parse (indices + values) ran
    at construction; only the O(total) densification is deferred."""

    __slots__ = ("shape", "size", "idx", "vals")

    def __init__(self, buf: bytes):
        dims, count, idx, vals = _parse_sparse(buf)
        self.shape = tuple(dims)
        self.size = count
        self.idx = idx
        self.vals = vals

    def densify(self, out: "np.ndarray" = None) -> np.ndarray:
        if out is not None:
            _check_out(out, self.size)
            out.fill(0.0)
            out[self.idx] = self.vals
            return out.reshape(self.shape)
        dense = np.zeros(self.size, dtype=self.vals.dtype)
        dense[self.idx] = self.vals
        return dense.reshape(self.shape)

    def __array__(self, dtype=None, copy=None):
        dense = self.densify()
        return dense if dtype is None else dense.astype(dtype)


class FusedFrame:
    """A validated, not-yet-densified fused sparse frame.

    Construction runs the full decode-side validation walk (crc +
    section geometry + dtype support + index range — native
    ``dlt_wire_fused_validate`` when available, the Python walk
    otherwise) so a corrupt frame raises :class:`CodecError` at unpack
    time and the transport drops it; the frame then densifies into
    caller scratch (:meth:`densify`) or scatter-adds straight into live
    state (:meth:`apply_into`) with no dense intermediate."""

    __slots__ = ("buf", "nbuckets", "size")

    def __init__(self, buf: bytes):
        self.nbuckets, self.size = _parse_fused_header(buf)
        eng = _wire_engine()
        status = (
            eng.validate_fused(buf, self.size)
            if eng is not None else native_wire.ERR_UNSUPPORTED
        )
        if status not in (0, native_wire.ERR_UNSUPPORTED):
            raise CodecError(
                native_wire.CORRUPT_MESSAGES.get(
                    status, f"wire status {status}"
                )
            )
        if status != 0:
            # No native engine (or a value dtype it does not speak):
            # the Python walk is the validating authority.
            for _idx, _vals in _iter_fused_sections(
                buf, self.nbuckets, self.size
            ):
                pass
        self.buf = buf

    @property
    def shape(self):
        return (self.size,)

    def densify(self, out: "np.ndarray" = None) -> np.ndarray:
        return decode_fused_sparse(self.buf, out=out)

    def apply_into(self, target: np.ndarray, *,
                   scale: float = 1.0) -> np.ndarray:
        return decode_fused_apply(self.buf, target, scale=scale)

    def __array__(self, dtype=None, copy=None):
        dense = self.densify()
        return dense if dtype is None else dense.astype(dtype)


def top_k_sparse(v: "np.ndarray", k: int):
    """Indices (ascending, uint32) and values of the k largest-|v| entries
    — the host-side selection for sparse-wire corrections.

    Deterministic: magnitude ties at the k-th boundary go to the LOWEST
    indices; NaN magnitudes count as above-threshold (a NaN-poisoned
    correction should be loud, not dropped).  Implementation is numpy
    introselect (``argpartition``) + a threshold sweep; a g++ -O3
    ``nth_element`` version was measured 2.3x SLOWER at n=36M (numpy's
    partition is simply better optimized), so unlike bf16/crc32 this op
    intentionally has no native-codec path.
    """
    v = np.ascontiguousarray(v, dtype=np.float32).ravel()
    k = int(k)
    if k <= 0 or v.size == 0:
        return np.empty(0, np.uint32), np.empty(0, np.float32)
    k = min(k, v.size)
    mag = np.abs(v)
    part = np.argpartition(mag, v.size - k)
    thresh = mag[part[v.size - k]]
    above = np.flatnonzero((mag > thresh) | np.isnan(mag))
    if above.size >= k:
        sel = above[:k]
    else:
        ties = np.flatnonzero(mag == thresh)
        sel = np.concatenate([above, ties[: k - above.size]])
        sel.sort()
    sel = sel.astype(np.uint32)
    return sel, v[sel]
