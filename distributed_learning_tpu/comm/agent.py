"""TCP consensus agent: gossip worker for multi-process deployments.

Parity: ``utils/consensus_tcp/agent.py:11-236`` (``ConsensusAgent``) — the
status state machine (:12-22), dual server/client handshake with master
and neighbors (:53-153), single-shot ``run_once`` gossip iteration
(:158-212, update x <- (1 - sum w) x + sum w_j x_j at :204-207), telemetry
(:214-218) — plus a **working ``run_round``**: the reference's TCP
``run_round`` is an unimplemented stub (:155-156, a recorded defect); the
converge-until-eps protocol it was meant to have exists only in the
asyncio backend (``consensus_asyncio.py:209-312``).  This agent implements
it over TCP: weighted lift ``y = x * w / mean_w`` (:231), iterative
neighbor exchange with round/iteration tagging to drop stale messages
(:276-278), two-sided residual check (fixing the one-sided ``(y - v) <=
eps`` defect at :297), CONVERGED/NOT_CONVERGED signaling, master DONE
broadcast.

Values travel agent<->agent only (data plane); the master only coordinates
rounds (control plane).  ``bf16_wire=True`` narrows f32 values to bfloat16
on the wire through the native codec — the TPU wire format, halving gossip
bandwidth.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import logging
import time
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

import numpy as np

from distributed_learning_tpu.comm.framing import FramedStream, open_framed_connection
from distributed_learning_tpu.comm.multiplexer import StreamMultiplexer
from distributed_learning_tpu.comm import protocol as P
from distributed_learning_tpu.obs import (
    MetricsRegistry,
    ObsDeltaSource,
    emit_flow,
    get_registry,
    trace_keep,
)

__all__ = [
    "ConsensusAgent",
    "AgentStatus",
    "ShutdownError",
    "RoundAbortedError",
]

#: graftproto role annotation (tools/graftlint/proto_extract.py): the
#: protocol state-machine extractor walks this module's isinstance
#: dispatch branches and message-constructor send sites under this role
#: name and cross-checks the recovered send/handle sets against
#: protocol.py's _REGISTRY.  Dispatch must stay extractable: construct
#: messages with explicit ``P.<Class>(...)`` calls, never through a
#: class held in a variable.
PROTO_ROLE = "agent"

#: graftsched hot-coroutine annotation (tools/graftlint/schedsim.py):
#: the await-point model of these coroutines pins under ``sched_model``
#: — they are the agent-side suspension points the schedule explorer
#: permutes (membership realignment and the detached telemetry path the
#: async runner's quarantine reporting rides).
SCHED_HOT = (
    "_apply_neighborhood",
    "send_telemetry",
    "_recv_any",
)

# Collective-op tag space: op_id = round_id * _OPS_PER_ROUND + seq, where
# round_id is the master's (global, strictly increasing) round counter and
# seq counts collective ops since that round (the round itself is seq 0,
# interleaved run_once calls advance seq).  Entering a master round
# therefore re-derives the SAME op id on every agent from the broadcast
# round id alone — including an agent that just rejoined with fresh local
# state — while tags stay strictly increasing and collision-free for up to
# _OPS_PER_ROUND-1 run_once calls between consecutive rounds.
_OPS_PER_ROUND = 1 << 20


class ShutdownError(RuntimeError):
    """Master broadcast Shutdown while an operation was in flight."""


class RoundAbortedError(ConnectionError):
    """The elastic master aborted the round (an agent died mid-round); the
    caller's value was NOT mixed to consensus.  Subclasses ConnectionError
    so the standard heal-and-retry pattern (catch, ``wait_neighbors()``,
    retry the round) covers aborts too."""


class AgentStatus(enum.Enum):
    """Lifecycle (parity: the ``Status`` enum, agent.py:12-22)."""

    NEW = "new"
    REGISTERED = "registered"
    READY = "ready"  # neighborhood received, peers connected
    IN_ROUND = "in_round"
    SHUTDOWN = "shutdown"


class ConsensusAgent:
    def __init__(
        self,
        token: Hashable,
        master_host: str,
        master_port: int,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        bf16_wire: bool = False,
        int8_wire: bool = False,
        sparse_wire: bool = False,
        rejoin: bool = False,
        debug: bool = False,
        obs: Optional[MetricsRegistry] = None,
        trace: bool = False,
        trace_run_id: int = 0,
        trace_sample: float = 1.0,
    ):
        if bf16_wire and int8_wire:
            raise ValueError("bf16_wire and int8_wire are mutually exclusive")
        self.token = str(token)
        self.master_addr = (master_host, master_port)
        self.host, self.port = host, port
        self.bf16_wire = bf16_wire
        # int8 wire: quarter-size value payloads via symmetric per-tensor
        # quantization (tensor_codec FLAG_INT8_COMPRESSED).  Applied ONLY
        # inside run_choco_once's exchange: there the error-feedback loop
        # folds quantization noise into the next correction.  Plain
        # run_once/run_round values have no such feedback — int8 noise
        # (up to max|x|/254 per hop) would put a floor under the
        # convergence residual and spin eps-rounds to max_iterations —
        # so those paths keep full precision.
        self.int8_wire = int8_wire
        self._int8_active = False
        # Sparse wire: value responses ship non-zeros as k values + indices
        # (tensor_codec.encode_sparse) — for k-sparse payloads such as
        # CHOCO compressed-gossip corrections (run_choco_once).  Deploy
        # uniformly: every agent must understand both response kinds (they
        # do), but only sparse senders realize the byte saving.
        self.sparse_wire = sparse_wire
        # Rejoin mode (elastic master required): this process replaces a
        # dead agent with the same token.  It initiates connections to ALL
        # its neighbors (the usual smaller-token-accepts rule assumes
        # everyone handshakes at once); its first collective op must be a
        # master round (round tags re-align it with the survivors).
        self.rejoin = bool(rejoin)
        # A rejoiner's local op counter starts fresh while survivors' are
        # far ahead; until a master round re-derives the shared tag, any
        # MASTERLESS collective would deadlock (its requests look stale to
        # everyone).  Tracked so those calls fail loudly instead.
        self._tag_realigned = not self.rejoin
        self._ever_connected: set = set()
        self._in_master_round = False
        # Membership generation (docs/async_runtime.md): the version of
        # the (topology, W) epoch this agent's weight table reflects.  A
        # regenerating elastic master bumps it on every death/(re)join
        # and broadcasts fresh NeighborhoodData; _apply_neighborhood
        # realigns the weight/stream sets to it mid-run — the
        # _require_realigned machinery generalized from a static graph
        # to a counter.
        self._generation = 0
        # Tokens a deadline-enforcing master dropped from the CURRENT
        # round (NewRoundNotification.dropped): their edges get zero
        # weight this round, the mass stays on self.
        self._round_excluded: set = set()
        # Wire-level resilience (FramedStream): transient socket errors
        # on send retry with bounded exponential backoff instead of
        # aborting the round; every retry counts as comm.agent.retries.
        self._send_retries = 3
        self.debug = debug
        self.status = AgentStatus.NEW

        self._server: Optional[asyncio.AbstractServer] = None
        self._master: Optional[FramedStream] = None
        self._neighbors: Dict[str, FramedStream] = {}
        self._weights: Dict[str, float] = {}
        self.self_weight = 0.0
        self.convergence_eps = 1e-4
        self._expected_peers: set = set()
        self._peers_ready = asyncio.Event()
        self._nbhd_ready = asyncio.Event()
        self._mux = StreamMultiplexer()

        # Gossip state.  Wire tags are (op_id, iteration): op_id counts
        # collective operations (each run_once call, each run_round) and
        # stays aligned across agents because collective calls happen in
        # the same order everywhere; iteration counts gossip steps within
        # the op.  Requests for a future tag are deferred until we get
        # there (the reference asyncio agent stores future-round messages
        # the same way, consensus_asyncio.py:276-278); master round ids
        # are a separate, master-assigned counter used only on the control
        # channel.
        self._op_id = -1
        self._round_id = -1
        self._iteration = -1
        self._iter_value: Optional[np.ndarray] = None
        self._prev_value: Optional[np.ndarray] = None
        # Exact wire tags of the two held values.  Answering by TAG
        # (not by "same op, one iteration back" arithmetic) keeps the
        # exchange live across an OP boundary too: a neighbor that
        # finished op k off our deferred answer and entered k+1 may ask
        # for our op-k value after we also moved on — _prev_value IS
        # that value, and dropping the request as stale would deadlock
        # un-barriered masterless sequences (skew is bounded by 1: a
        # neighbor cannot finish op k+1 before we reach it).
        self._iter_key: Tuple[int, int] = (-1, -1)
        self._prev_key: Tuple[int, int] = (-2, -1)
        # Two-slot (array, sparse-beats-dense) memo for _sparse_wins.
        self._sparse_cache: list = [(None, False), (None, False)]
        # Fused tree gossip (run_choco_tree): the TreeSpec of the gossiped
        # model (a deployment invariant — every agent has the same model)
        # and its dtype-bucket spans; _fused_spans is non-None exactly
        # while a fused tree op is in flight, switching _make_response to
        # the one-frame-per-round fused sparse encoding.
        self._tree_spec = None
        self._tree_buckets = None
        self._fused_spans = None
        self._deferred: Dict[Tuple[int, int], list] = {}
        # Persistent read tasks: a FramedStream.recv interrupted mid-frame
        # would corrupt the stream, so reads are never cancelled — a
        # pending task survives across calls and its result is consumed on
        # a later call (the multiplexer uses the same pattern internally).
        self._master_task: Optional[asyncio.Task] = None
        self._mux_task: Optional[asyncio.Task] = None
        # CHOCO state (run_choco_once): public estimates of self and of
        # each neighbor, lazily initialized to zeros on first use.
        self._choco_hat_self: Optional[np.ndarray] = None
        self._choco_hat_nbrs: Dict[str, np.ndarray] = {}
        self._choco_invalidated_by: Optional[str] = None
        # Observability: named logger (obs and logs share one switch —
        # `logging.getLogger("dlt").setLevel(DEBUG)`; the legacy
        # debug=True flag wires a handler via enable_debug_logging) and
        # per-agent gossip counters mirrored into the default registry.
        self._log = logging.getLogger(f"dlt.comm.agent.{self.token}")
        if debug:
            from distributed_learning_tpu.utils.profiling import (
                enable_debug_logging,
            )

            enable_debug_logging()
        self.counters: Dict[str, float] = {}
        # Run-wide plane (docs/observability.md §Run-wide plane): an
        # optional PER-AGENT registry.  With several agents in one
        # process (tests, simulators) the process-wide default registry
        # mixes their streams; `obs=` keeps this agent's metrics
        # separable so its deltas attribute cleanly at the master.
        self._obs = obs
        # Eager bind for a dedicated registry: its event stream is this
        # agent's by construction, so deltas should cover it from the
        # first event (the default registry binds lazily — a process
        # may host several agents and non-comm producers).
        self._obs_source: Optional[ObsDeltaSource] = (
            ObsDeltaSource(obs) if obs is not None else None
        )
        self._obs_task: Optional[asyncio.Task] = None
        self._obs_period = 1.0
        # Wire trace plane (docs/observability.md §Trace plane): when on,
        # every outgoing value response carries a protocol.TraceContext
        # (run_id, origin=token, seq, t_wall) and both ends of the edge
        # emit paired ``trace.flow`` events — encode/send here,
        # recv/decode/mix at the receiver — so the merged Perfetto trace
        # arrow-links each frame's causal chain across process tracks.
        # Off (the default) the trace trailer is absent on the wire and
        # no flow events are emitted: the <=5% rounds/sec overhead gate
        # (benchmarks/bench_async_gossip.py) measures exactly this flag.
        self.trace = bool(trace)
        self._trace_run_id = int(trace_run_id)
        # Consistent flow sampling (docs/observability.md §Fleet-scale
        # plane): keep/drop is a pure function of the frame's
        # wire-carried (run_id, origin, seq) identity (spans.trace_keep),
        # so every hop of a flow agrees without coordination and chains
        # are never half-sampled.  1.0 (the default) short-circuits
        # before hashing — bit-identical to unsampled tracing; dropped
        # hops count as ``obs.sampled_out``, never vanish silently.
        self.trace_sample = float(trace_sample)
        # One per-agent frame counter: (run_id, origin, seq) is then
        # fleet-unique without per-edge bookkeeping.
        self._trace_seq = 0
        # Traces of the responses accepted by the exchange in flight,
        # held until the mix step consumes them (the "mix" hop closes
        # the frame's flow chain).
        self._recv_traces: Dict[str, P.TraceContext] = {}

    # ------------------------------------------------------------------ #
    def _debug(self, msg: str, *args):
        """Lazy-formatted debug line on the agent's named logger."""
        self._log.debug(msg, *args)

    def _count(self, name: str, value: float = 1) -> None:
        """Bump a per-agent counter and its ``comm.agent.*`` aggregate
        in the default registry (and the per-agent ``obs=`` registry
        when one is attached)."""
        self.counters[name] = self.counters.get(name, 0) + value
        get_registry().inc(f"comm.agent.{name}", value)
        if self._obs is not None and self._obs is not get_registry():
            self._obs.inc(f"comm.agent.{name}", value)

    def _observe(self, name: str, value: float, step=None) -> None:
        """Series point into the default registry (and the per-agent
        ``obs=`` registry) — the staleness histogram channel."""
        get_registry().observe(name, value, step=step)
        if self._obs is not None and self._obs is not get_registry():
            self._obs.observe(name, value, step=step)

    def _count_wire(self, name: str, value: float = 1) -> None:
        """Bump a ``comm.wire.*`` counter (decode scratch-pool and
        zero-copy receive-path accounting, shared with the async
        runner) with the same dual-registry mirror as :meth:`_count` —
        but no per-agent ``counters`` entry and no ``comm.agent.``
        prefix: these count wire-path mechanics, not agent behavior."""
        get_registry().inc(f"comm.wire.{name}", value)
        if self._obs is not None and self._obs is not get_registry():
            self._obs.inc(f"comm.wire.{name}", value)

    def _apply_fused(self, frame, target: np.ndarray, *,
                     scale: float = 1.0) -> np.ndarray:
        """Scatter-add a validated lazy fused frame straight onto live
        state (``tensor_codec.FusedFrame.apply_into`` — the zero-copy
        consume primitive), timed as a ``comm.wire.decode.apply`` span
        in both registries."""
        wall_t0 = time.time()
        t0 = time.perf_counter()
        out = frame.apply_into(target, scale=scale)
        dur_s = time.perf_counter() - t0
        regs = [get_registry()]
        if self._obs is not None and self._obs is not regs[0]:
            regs.append(self._obs)
        for reg in regs:
            reg.record_span("comm.wire.decode.apply", dur_s, t0=wall_t0)
        return out

    def _on_stream_retry(self) -> None:
        """FramedStream retry hook: a transient socket error was retried
        instead of aborting the round."""
        self._count("retries")

    # ------------------------------------------------------------------ #
    # Wire trace plane (docs/observability.md §Trace plane)              #
    # ------------------------------------------------------------------ #
    def _emit_flow(self, phase: str, tc: "P.TraceContext", edge: str,
                   **fields) -> None:
        """One frame-lifecycle hop into the default registry (and the
        per-agent ``obs=`` registry) — the same dual-mirror discipline
        as :meth:`_count`.

        Sampling gate: ``trace_sample < 1.0`` keeps or drops the WHOLE
        flow by its wire identity (every hop of a frame — here and at
        the peer — computes the same decision from the same trailer),
        bounding trace volume at fleet scale; suppressed hops count as
        ``obs.sampled_out``."""
        if not trace_keep(tc.run_id, tc.origin, tc.seq,
                          self.trace_sample):
            get_registry().inc("obs.sampled_out")
            if self._obs is not None and self._obs is not get_registry():
                self._obs.inc("obs.sampled_out")
            return
        emit_flow(
            get_registry(), phase, origin=tc.origin, seq=tc.seq,
            run_id=tc.run_id, edge=edge, **fields,
        )
        if self._obs is not None and self._obs is not get_registry():
            emit_flow(
                self._obs, phase, origin=tc.origin, seq=tc.seq,
                run_id=tc.run_id, edge=edge, **fields,
            )

    def _stamp_trace(self, msg, dest: str):
        """Attach a fresh :class:`~distributed_learning_tpu.comm.protocol.
        TraceContext` to an outgoing value response and emit its
        "encode" hop.  No-op when tracing is off (the trailer stays
        absent on the wire — one sentinel byte)."""
        if not self.trace:
            return msg
        self._trace_seq += 1
        tc = P.TraceContext(
            run_id=self._trace_run_id, origin=self.token,
            seq=self._trace_seq, t_wall=time.time(),
        )
        msg = dataclasses.replace(msg, trace=tc)
        self._emit_flow("encode", tc, f"{self.token}->{dest}")
        return msg

    def _note_recv_trace(self, token: str, tc: "P.TraceContext") -> None:
        """Receiver half of a traced frame: emit the "recv" and "decode"
        hops with the SENDER's trace fields (both ends must replay the
        same (run_id, origin, seq) or the chain breaks) and observe the
        edge's wall-clock transit latency into ``comm.edge.latency_s``."""
        edge = f"{token}->{self.token}"
        self._recv_traces[token] = tc
        self._emit_flow("recv", tc, edge)
        self._emit_flow("decode", tc, edge)
        if tc.t_wall:
            # graftlint: disable=wallclock-duration -- cross-process edge latency: t_wall is the SENDER's wall-clock send stamp; monotonic clocks cannot compare across processes
            self._observe(f"comm.edge.latency_s/{edge}", time.time() - tc.t_wall)

    def _emit_mix(self, tokens) -> None:
        """Emit the "mix" hop for each traced frame this mix step
        consumed — closing those frames' flow chains."""
        if not self.trace:
            return
        for t in tokens:
            tc = self._recv_traces.pop(t, None)
            if tc is not None:
                self._emit_flow("mix", tc, f"{t}->{self.token}")

    @property
    def generation(self) -> int:
        """Membership generation this agent's weight table reflects."""
        return self._generation

    def wire_stats(self) -> Dict[str, int]:
        """Whole-frame byte/frame totals over this agent's live streams
        (master + neighbors) — the per-process "bytes framed" view of
        the registry's global ``comm.bytes_framed_*`` counters."""
        streams = list(self._neighbors.values())
        if self._master is not None:
            streams.append(self._master)
        return {
            "bytes_sent": sum(s.bytes_sent for s in streams),
            "bytes_received": sum(s.bytes_received for s in streams),
            "frames_sent": sum(s.frames_sent for s in streams),
            "frames_received": sum(s.frames_received for s in streams),
        }

    @property
    def neighbor_tokens(self) -> Tuple[str, ...]:
        return tuple(self._neighbors)

    async def start(self, timeout: float = 30.0) -> None:
        """Full handshake: serve, register with master, receive the
        neighborhood, connect peers (parity: ``_do_handshake`` +
        ``serve_forever``, agent.py:53-153)."""
        self._server = await asyncio.start_server(
            self._handle_peer, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            self._master = await open_framed_connection(
                *self.master_addr,
                send_retries=self._send_retries,
                on_retry=self._on_stream_retry,
            )
            await self._master.send(
                P.Register(token=self.token, host=self.host, port=self.port)
            )
            msg = await asyncio.wait_for(self._master.recv(), timeout)
            if isinstance(msg, P.Ok):
                break
            if (
                self.rejoin
                and isinstance(msg, P.ErrorException)
                and "already registered" in msg.message
                and asyncio.get_event_loop().time() < deadline
            ):
                # Rejoin raced the master's death detection: our
                # predecessor's control stream still looks registered.
                # Back off until the master observes the death.
                self._count("register_retries")
                self._master.close()
                await asyncio.sleep(0.05)
                continue
            if isinstance(msg, P.ErrorException):
                raise ConnectionError(
                    f"master rejected registration: {msg.message}"
                )
            raise ConnectionError(f"unexpected registration reply {msg}")
        self.status = AgentStatus.REGISTERED

        msg = await asyncio.wait_for(self._master.recv(), timeout)
        if isinstance(msg, P.Shutdown):
            raise ShutdownError(msg.reason)
        if not isinstance(msg, P.NeighborhoodData):
            raise ConnectionError(f"expected NeighborhoodData, got {msg}")
        await self._apply_neighborhood(msg, timeout=timeout)
        if self._expected_peers:
            await asyncio.wait_for(self._peers_ready.wait(), timeout)
        self.status = AgentStatus.READY
        self._debug("ready; neighbors=%s", sorted(self._neighbors))

    async def _apply_neighborhood(
        self, msg: P.NeighborhoodData, *, timeout: float = 30.0
    ) -> None:
        """Install a neighborhood: the initial handshake AND mid-run
        membership-generation broadcasts (a regenerating elastic master
        re-forms the topology and re-solves W on every death/(re)join).

        Weight table, eps, and generation counter are replaced; streams
        of removed edges close; NEW edges handshake by the usual rule —
        the lexicographically smaller token accepts, the larger connects
        (the reference uses registration order for the same purpose,
        agent.py:137-150); a rejoiner's initial apply dials everyone.
        A mid-run generation change also suspends masterless collectives
        until the next master round re-derives the shared op tag."""
        initial = not self._nbhd_ready.is_set()
        old_gen = self._generation
        self.self_weight = msg.self_weight
        self.convergence_eps = msg.convergence_eps
        self._generation = msg.generation
        new_weights = {nb.token: nb.weight for nb in msg.neighbors}
        removed = set(self._weights) - set(new_weights)
        self._weights = new_weights
        if initial:
            self._expected_peers = (
                set()
                if self.rejoin
                else {
                    nb.token for nb in msg.neighbors
                    if nb.token < self.token
                }
            )
            self._nbhd_ready.set()
        elif msg.generation != old_gen:
            self._count("generation_updates")
            # Op counters across the membership change no longer agree;
            # the next master round re-derives the tag for everyone.
            self._tag_realigned = False
            self._debug(
                "membership generation %s -> %s; neighbors now %s",
                old_gen, msg.generation, sorted(new_weights),
            )
        for token in removed:
            dead = self._neighbors.pop(token, None)
            if dead is not None:
                self._mux.remove(token)
                dead.close()
        for nb in msg.neighbors:
            if nb.port == 0 or nb.token in self._neighbors:
                # port 0: the master flags a peer that will dial IN (a
                # down agent's stale address, or this generation's fresh
                # (re)joiner) — never dial it.
                continue
            dial = (
                (self.rejoin or nb.token > self.token)
                if initial
                else nb.token > self.token
            )
            if dial:
                await self._dial_peer(nb, timeout)

    async def _dial_peer(self, nb: P.Neighbor, timeout: float) -> None:
        """Open + handshake one peer stream, retrying a bounded number of
        rejections — a peer reached before ITS copy of the (new)
        neighborhood arrived legitimately answers "unexpected peer"."""
        last = None
        for _ in range(20):
            stream = await open_framed_connection(
                nb.host, nb.port,
                send_retries=self._send_retries,
                on_retry=self._on_stream_retry,
            )
            await stream.send(
                P.Register(token=self.token, host=self.host, port=self.port)
            )
            try:
                reply = await asyncio.wait_for(stream.recv(), timeout)
            except (ConnectionError, asyncio.IncompleteReadError) as e:
                stream.close()
                last = e
                await asyncio.sleep(0.05)
                continue
            if isinstance(reply, P.Ok):
                self._add_neighbor(nb.token, stream)
                return
            stream.close()
            last = reply
            await asyncio.sleep(0.05)
        raise ConnectionError(
            f"peer {nb.token} kept rejecting the handshake: {last}"
        )

    async def _handle_peer(self, reader, writer):
        stream = FramedStream(
            reader, writer,
            send_retries=self._send_retries,
            on_retry=self._on_stream_retry,
        )
        try:
            msg = await stream.recv()
            # A legitimate neighbor may dial in before OUR copy of the
            # NeighborhoodData has arrived (delivery order across agents
            # is unconstrained): wait for it before validating the token.
            try:
                await asyncio.wait_for(self._nbhd_ready.wait(), 30.0)
            except asyncio.TimeoutError:
                pass
        except (ConnectionError, asyncio.IncompleteReadError):
            stream.close()
            return
        if not isinstance(msg, P.Register) or msg.token not in self._weights:
            await stream.send(P.ErrorException(message="unexpected peer"))
            stream.close()
            return
        await stream.send(P.Ok(info="peer"))
        self._add_neighbor(msg.token, stream)
        self._expected_peers.discard(msg.token)
        if not self._expected_peers:
            self._peers_ready.set()

    def _add_neighbor(self, token: str, stream: FramedStream) -> None:
        old = self._neighbors.get(token)
        if old is not None:
            # A rejoined peer replaces its dead stream: cancel the pending
            # read on the corpse first or the multiplexer would keep
            # watching it under the same token.
            self._mux.remove(token)
            old.close()
        if self._choco_hat_self is not None:
            # CHOCO estimates are REPLICATED state (every holder of
            # x̂_j applies identical corrections).  A replacement process
            # starts with zero estimates while ours are non-zero, so the
            # copies have permanently diverged — run_choco_once must not
            # continue silently.  Flag it; the caller resets via
            # reset_choco() on every agent (a coordinated restart of the
            # compressed stream; plain run_once/run_round are unaffected).
            self._choco_invalidated_by = token
        if token in self._ever_connected:
            # The replacement's op counter is behind ours: a masterless
            # collective would deadlock on both sides (its requests look
            # stale to us, ours look future to it and get dropped when its
            # first master round jumps the tag).  Suspend masterless ops
            # until a master round re-aligns everyone — symmetric to the
            # rejoiner's own guard.
            self._tag_realigned = False
            self._count("reconnects")
        self._ever_connected.add(token)
        # Edge observatory: label the stream with its directed edge so
        # framing attributes bytes/frames/retries to ``comm.edge.*``
        # per-edge counters (docs/observability.md §Per-edge observatory).
        stream.edge = (self.token, token)
        stream.obs = self._obs
        self._neighbors[token] = stream
        self._mux.add(token, stream)

    # ------------------------------------------------------------------ #
    # Gossip iterations                                                  #
    # ------------------------------------------------------------------ #
    async def _answer(self, token: str, req: P.ValueRequest) -> None:
        """Answer a neighbor's value request — now if it targets one of
        the two held values (current, or the previous iteration/op the
        neighbor is still mixing against), later (deferred) if it's
        ahead, never if it is older than both (round/iteration tagging,
        consensus_asyncio.py:276-278)."""
        key = (req.round_id, req.iteration)  # wire round_id carries op_id
        if key == self._iter_key:
            value = self._iter_value
        elif key == self._prev_key:
            # A neighbor one step behind (lockstep skew across an edge —
            # within an op, or across an op boundary it crossed off our
            # deferred answer — is at most 1): answer with the value it
            # is mixing against.  Counted separately: the graftproto
            # conformance replay asserts this liveness-critical path
            # actually engaged under an injected skew-1 schedule.
            self._count("prev_tag_answers")
            value = self._prev_value
        elif key > self._iter_key:
            self._count("requests_deferred")
            self._deferred.setdefault(key, []).append(token)
            return
        else:
            self._count("stale_requests_dropped")
            return  # stale (finished op/iteration): drop
        self._count("responses_sent")
        resp = self._stamp_trace(
            self._make_response(req.round_id, req.iteration, value), token
        )
        await self._neighbors[token].send(resp)
        if resp.trace is not None:
            self._emit_flow("send", resp.trace, f"{self.token}->{token}")

    def _sparse_wins(self, value) -> bool:
        """Whether the sparse wire beats dense for this value: its density
        must be below the sparse format's breakeven (~1/3 with bf16
        values, ~1/2 f32 — see ``encode_sparse``).  The O(d) nonzero scan
        is memoized per array object: the same iteration value is
        answered once per neighbor plus every deferred resend, and it is
        never mutated in place (``_exchange_values`` rebinds, mixing
        allocates new arrays).  Two slots, because answers alternate
        between ``_iter_value`` and ``_prev_value`` when neighbors run
        one iteration behind — a single slot would thrash exactly then."""
        for ref, verdict in self._sparse_cache:
            if ref is value:
                return verdict
        per_dense = 1 if self._int8_active else 2 if self.bf16_wire else 4
        breakeven = value.size * per_dense / (4 + per_dense)
        verdict = bool(np.count_nonzero(value) < breakeven)
        self._sparse_cache = [(value, verdict), self._sparse_cache[0]]
        return verdict

    def _make_response(self, round_id: int, iteration: int, value):
        """Pick the wire encoding per message: sparse only when it
        actually saves bytes (a dense value on a ``sparse_wire`` agent
        would otherwise cost ~2-3x the dense wire); during a fused tree
        op (``run_choco_tree``) a sparse win ships as ONE fused frame
        with per-dtype-bucket value sections.  Counts the choice as
        ``sparse_frames``/``dense_frames`` (fused additionally as
        ``fused_frames``)."""
        if self._fused_spans is not None and value is not None:
            # Fused tree op: the fused frame IS this round's value
            # contract — the sender's own estimate was updated with the
            # fused-rounded bytes (per-bucket value narrowing), so a
            # per-message dense fallback here would hand neighbors
            # different bytes and permanently diverge the replicated
            # estimates.
            self._count("sparse_frames")
            self._count("fused_frames")
            return P.ValueResponseFusedSparse(
                round_id=round_id, iteration=iteration, value=value,
                buckets=self._fused_spans,
                bf16_wire=self.bf16_wire, int8_wire=self._int8_active,
            )
        if self.sparse_wire and value is not None and self._sparse_wins(value):
            self._count("sparse_frames")
            return P.ValueResponseSparse(
                round_id=round_id, iteration=iteration, value=value,
                bf16_wire=self.bf16_wire, int8_wire=self._int8_active,
            )
        self._count("dense_frames")
        return P.ValueResponse(
            round_id=round_id, iteration=iteration, value=value,
            bf16_wire=self.bf16_wire, int8_wire=self._int8_active,
        )

    async def _flush_deferred(self) -> None:
        key = (self._op_id, self._iteration)
        for token in self._deferred.pop(key, []):
            stream = self._neighbors.get(token)
            if stream is None:
                continue  # edge removed by a membership generation
            self._count("responses_sent")
            resp = self._stamp_trace(
                self._make_response(
                    self._op_id, self._iteration, self._iter_value
                ),
                token,
            )
            await stream.send(resp)
            if resp.trace is not None:
                self._emit_flow("send", resp.trace, f"{self.token}->{token}")
        # Drop stale deferral keys from finished ops/iterations.
        for k in [k for k in self._deferred if k < key]:
            del self._deferred[k]

    def _active_tokens(self) -> list:
        """Neighbors participating in the current exchange: weighted,
        connected, and not dropped from this round by a deadline-
        enforcing master.  Sorted — mixing accumulates in this order on
        every agent, so results are reproducible across runs (and the
        async runtime's lock-step oracle can be bit-exact)."""
        return sorted(
            t for t in self._weights
            if t in self._neighbors and t not in self._round_excluded
        )

    async def _gossip_iteration(self, y: np.ndarray) -> Optional[np.ndarray]:
        """One symmetric exchange + mix:
        ``y <- (1 - sum_j w_j) y + sum_j w_j y_j`` (parity: run_once's
        update, agent.py:204-207), accumulated in sorted-token order.
        Neighbors a deadline-enforcing master dropped from this round
        keep their edge weight on OUR value instead (``w_j * y``) — the
        wire-level mirror of
        :func:`~distributed_learning_tpu.ops.mixing.presence_weight_matrix`:
        the row still sums to one.  Returns None if Done/Shutdown arrived
        mid-iteration (round aborted by the master)."""
        self._count("gossip_iterations")
        active = self._active_tokens()
        values = await self._exchange_values(y, active)
        if values is None:
            return None
        total_w = sum(self._weights.values())
        out = (1.0 - total_w) * y
        for token in sorted(values):
            out = out + self._weights[token] * values[token]
        for token in sorted(set(self._weights) - set(values)):
            # Dropped-from-round neighbor: its mass renormalizes to self.
            out = out + self._weights[token] * y
        self._emit_mix(sorted(values))
        return out

    async def _exchange_values(
        self, y: np.ndarray, active: Optional[list] = None
    ) -> Optional[Dict[str, np.ndarray]]:
        """Symmetric per-iteration exchange: publish ``y`` as this
        iteration's value, collect every active neighbor's.  Returns None
        if a master Done ended the round mid-exchange."""
        if active is None:
            active = self._active_tokens()
        self._recv_traces = {}
        self._prev_value = self._iter_value
        self._prev_key = self._iter_key
        self._iter_value = y
        self._iter_key = (self._op_id, self._iteration)
        await self._flush_deferred()
        req = P.ValueRequest(round_id=self._op_id, iteration=self._iteration)
        for token in active:
            await self._neighbors[token].send(req)

        values: Dict[str, np.ndarray] = {}
        done_seen = False
        while len(values) < len(active):
            token, msg, src = await self._recv_any()
            if msg is None and token not in self._weights:
                # A stream an old membership generation removed died:
                # nobody mixes with it any more — old news, keep going.
                continue
            if msg is None:
                # Multiplexer sentinel: a neighbor connection died.  It can
                # be STALE: produced (inside the persistent _recv_any read)
                # before a rejoined replacement dialed back in.  Stream
                # identity decides: if the current stream for that token is
                # not the one that died, the death is old news — resend this
                # iteration's request on the fresh stream and keep going.
                cur = self._neighbors.get(token)
                if cur is not None and cur is not src:
                    if self._in_master_round:
                        # Round tags re-derive from the master broadcast,
                        # so the replacement WILL reach this tag: resend.
                        if token not in values:
                            await cur.send(req)
                        continue
                    # Masterless op: the replacement cannot reach this tag
                    # until a master round (which cannot happen while we
                    # block here) — fail loudly, keep the live stream.
                    raise ConnectionError(
                        f"neighbor {token} was replaced mid-op; run a "
                        "master run_round to re-align, then retry"
                    )
                # Genuine death: drop the corpse (a rejoined replacement
                # re-registers through _handle_peer; see wait_neighbors)
                # and fail the current op loudly rather than wait forever —
                # recovery happens between rounds, not inside one.
                # (CHOCO note: no invalidation needed here — the only
                # path back into run_choco_once is via the replacement
                # dialing in, and _add_neighbor flags it then.)
                self._neighbors.pop(token, None)
                raise ConnectionError(f"neighbor {token} disconnected mid-gossip")
            if isinstance(msg, P.ValueRequest):
                await self._answer(token, msg)
            elif isinstance(
                msg,
                (
                    P.ValueResponse,
                    P.ValueResponseSparse,
                    P.ValueResponseFusedSparse,
                ),
            ):
                if token in active and (msg.round_id, msg.iteration) == (
                    self._op_id,
                    self._iteration,
                ):
                    values[token] = msg.value
                    if self.trace and msg.trace is not None:
                        self._note_recv_trace(token, msg.trace)
                # else stale response from an aborted iteration: drop.
            elif isinstance(msg, P.Done) and msg.round_id == self._round_id:
                if msg.aborted:
                    # Elastic abort: the value is mid-mix (and still weight
                    # lifted in run_round) — it must NOT be returned as a
                    # consensus result.
                    self._count("rounds_aborted")
                    raise RoundAbortedError(
                        f"round {self._round_id} aborted by the master"
                    )
                done_seen = True
                break
            elif isinstance(msg, P.Shutdown):
                self.status = AgentStatus.SHUTDOWN
                raise ShutdownError(msg.reason)
            elif isinstance(msg, P.NewRoundNotification):
                # Can't happen mid-round with a correct master; ignore.
                self._debug("unexpected %s mid-round", msg)
        if done_seen:
            return None
        return values

    @staticmethod
    def _silence(task: asyncio.Task) -> None:
        """Mark a task's exception retrieved (tasks outliving their waiter
        — e.g. a pending master read at close — must not warn)."""
        if not task.cancelled():
            task.exception()

    async def _recv_any(self):
        """Next message from the master or any neighbor, without ever
        cancelling an in-flight frame read."""
        if self._master_task is None:
            self._master_task = asyncio.ensure_future(self._master.recv())
            self._master_task.add_done_callback(self._silence)
        if self._mux_task is None:
            self._mux_task = asyncio.ensure_future(self._mux.__anext__())
        done, _ = await asyncio.wait(
            {self._master_task, self._mux_task},
            return_when=asyncio.FIRST_COMPLETED,
        )
        if self._master_task in done:
            msg = self._master_task.result()
            self._master_task = None
            return "<master>", msg, self._master
        token, msg, stream = self._mux_task.result()
        self._mux_task = None
        return token, msg, stream

    async def _master_recv(self):
        """Master-stream read through the same persistent-task discipline."""
        if self._master_task is None:
            self._master_task = asyncio.ensure_future(self._master.recv())
            self._master_task.add_done_callback(self._silence)
        msg = await self._master_task
        self._master_task = None
        return msg

    async def _drain_membership_updates(self, timeout: float = 0.0) -> None:
        """Apply already-delivered master messages between rounds —
        membership-generation NeighborhoodData broadcasts land here;
        stale Done/notification frames are dropped.  Bounded by
        ``timeout`` seconds of waiting for a first/next frame."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while self._master is not None:
            if self._master_task is None:
                self._master_task = asyncio.ensure_future(self._master.recv())
                self._master_task.add_done_callback(self._silence)
            remaining = deadline - loop.time()
            done, _ = await asyncio.wait(
                {self._master_task}, timeout=max(0.0, remaining)
            )
            if not done:
                return
            task, self._master_task = self._master_task, None
            msg = task.result()
            if isinstance(msg, P.NeighborhoodData):
                await self._apply_neighborhood(msg)
            elif isinstance(msg, P.Shutdown):
                self.status = AgentStatus.SHUTDOWN
                raise ShutdownError(msg.reason)
            # else: stale Done / notification from a finished round.

    # ------------------------------------------------------------------ #
    def _require_realigned(self) -> None:
        if not self._tag_realigned:
            raise RuntimeError(
                "gossip tags are not aligned (this agent rejoined, a "
                "neighbor reconnected with fresh state, or the membership "
                "generation changed): one master run_round re-aligns "
                "every agent; a masterless collective now would deadlock"
            )

    async def run_once(self, value: np.ndarray) -> np.ndarray:
        """One masterless gossip iteration (parity: ``run_once``,
        agent.py:158-212).  All agents must call it concurrently."""
        if self.status not in (AgentStatus.READY, AgentStatus.IN_ROUND):
            raise RuntimeError(f"agent not ready (status={self.status})")
        self._require_neighbors()
        self._require_realigned()
        y = np.asarray(value, dtype=np.float32).ravel()
        # New collective op: op ids advance identically on every agent
        # (collective calls happen in the same order everywhere), which
        # re-synchronizes tags even when a prior run_round ended with
        # agents at different iteration counts.
        self._op_id += 1
        self._iteration = 0
        self._count("run_once")
        out = await self._gossip_iteration(y)
        assert out is not None  # no master Done in masterless mode
        return out

    async def run_choco_once(
        self,
        value: np.ndarray,
        compressor: Callable[[np.ndarray], np.ndarray],
        *,
        gamma: float = 0.3,
    ) -> np.ndarray:
        """One CHOCO-GOSSIP iteration over the real wire
        (``parallel/compression.py`` is the on-device engine; this is the
        multi-process analogue).  Only the compressed correction
        ``q = C(x - xhat_self)`` crosses the network — construct the agent
        with ``sparse_wire=True`` so a top-k correction ships as k values +
        indices (``tensor_codec.encode_sparse``) instead of the dense
        vector.  All agents must call it concurrently with the same
        ``gamma`` and compressor family; estimates persist across calls
        and start at zero (the standard CHOCO initialization).

        Elastic deployments: an agent rejoin invalidates the replicated
        estimates (the replacement starts at zero; survivors' copies do
        not) — the next call raises, and recovery is ``reset_choco()`` on
        every agent followed by one master ``run_round`` (tag re-align),
        then the compressed stream resumes.
        """
        x = self._choco_begin(value)
        q = np.asarray(compressor(x - self._choco_hat_self), np.float32).ravel()
        q = self._wire_round(q)
        self._op_id += 1
        self._iteration = 0
        self._count("choco_iterations")
        self._int8_active = self.int8_wire  # int8 only for this exchange
        try:
            neighbor_qs = await self._exchange_values(q)
        finally:
            self._int8_active = False
        assert neighbor_qs is not None  # no master Done in masterless mode
        return self._choco_finish(x, q, neighbor_qs, gamma)

    def _choco_begin(
        self, value: np.ndarray, *, require_aligned: bool = True
    ) -> np.ndarray:
        """Shared CHOCO preamble: readiness/realignment/invalidation
        guards, flatten to the f32 wire vector, lazy zero-init of the
        replicated estimates.  ``require_aligned=False`` is the async
        runtime's entry: its correction streams are per-neighbor FIFOs
        applied in arrival order, so op-tag alignment is not part of
        their contract (generation tags on the frames gate membership
        epochs instead)."""
        if self.status not in (AgentStatus.READY, AgentStatus.IN_ROUND):
            raise RuntimeError(f"agent not ready (status={self.status})")
        self._require_neighbors()
        if require_aligned:
            self._require_realigned()
        if self._choco_invalidated_by is not None:
            raise RuntimeError(
                f"CHOCO estimates invalidated: neighbor "
                f"{self._choco_invalidated_by!r} reconnected with fresh "
                "(zero) estimates while ours are non-zero — the replicated "
                "copies have diverged.  Call reset_choco() on EVERY agent "
                "(same collective position), then rerun."
            )
        x = np.asarray(value, dtype=np.float32).ravel()
        if self._choco_hat_self is None:
            self._choco_hat_self = np.zeros_like(x)
        if self._choco_hat_self.shape != x.shape:
            raise ValueError(
                f"value shape {x.shape} does not match existing CHOCO "
                f"estimates {self._choco_hat_self.shape}"
            )
        for t in self._neighbors:
            self._choco_hat_nbrs.setdefault(t, np.zeros_like(x))
        return x

    def _wire_round(self, q: np.ndarray) -> np.ndarray:
        """Round a correction through this agent's own wire encoding.

        CRITICAL: every holder of an estimate must apply the SAME bytes.
        Neighbors receive q after the wire round-trip (bf16 narrowing,
        sparse re-densification); the sender must update its own hat with
        that wire-rounded q, not the exact one, or the replicated
        estimates permanently diverge and consensus stalls (measured:
        0.167 residual floor with bf16_wire and the exact-q update)."""
        from distributed_learning_tpu.comm.tensor_codec import (
            decode_fused_sparse,
            decode_sparse,
            decode_tensor,
            encode_fused_sparse,
            encode_sparse,
            encode_tensor,
        )

        if self._fused_spans is not None:
            return decode_fused_sparse(encode_fused_sparse(
                q, self._fused_spans,
                bf16_wire=self.bf16_wire, int8_wire=self.int8_wire,
            ))
        if self.sparse_wire:
            return decode_sparse(encode_sparse(
                q, bf16_wire=self.bf16_wire, int8_wire=self.int8_wire
            ))
        if self.bf16_wire or self.int8_wire:
            return decode_tensor(encode_tensor(
                q, bf16_wire=self.bf16_wire, int8_wire=self.int8_wire
            ))
        return q

    def _choco_finish(
        self, x: np.ndarray, q: np.ndarray, neighbor_qs, gamma: float
    ) -> np.ndarray:
        """Shared CHOCO epilogue: apply the exchanged corrections to the
        replicated estimates and step the iterate — in sorted-token
        order, so the recurrence is reproducible across runs and the
        async runtime's tau=0 oracle can be bit-exact."""
        from distributed_learning_tpu.comm.tensor_codec import FusedFrame

        self._choco_hat_self = self._choco_hat_self + q
        out = x.copy()
        for t in sorted(neighbor_qs):
            qn = neighbor_qs[t]
            if isinstance(qn, FusedFrame):
                # Zero-copy consume (lazy fused receive): the frame's
                # sections scatter-add straight onto the replicated
                # estimate — no densified intermediate.  Ulp-identical
                # to the dense add for the duplicate-free frames the
                # encoder produces (see decode_fused_apply).
                self._apply_fused(qn, self._choco_hat_nbrs[t])
            else:
                self._choco_hat_nbrs[t] = self._choco_hat_nbrs[
                    t
                ] + np.asarray(qn, np.float32).ravel()
            out += gamma * self._weights[t] * (
                self._choco_hat_nbrs[t] - self._choco_hat_self
            )
        # Self term of sum_j W_ij (xhat_j - xhat_i): j = i contributes 0.
        self._emit_mix(sorted(neighbor_qs))
        return out

    async def run_choco_tree(
        self,
        tree: Any,
        compressor: Callable[[np.ndarray], np.ndarray],
        *,
        gamma: float = 0.3,
        budget: str = "per-leaf",
        fused: bool = True,
    ) -> Any:
        """One CHOCO-GOSSIP iteration over a whole model pytree.

        The tree crosses the wire as its ``pytree_codec.TreeSpec`` ravel
        (the spec is a deployment invariant — same model class + config
        on every agent).  ``budget`` scopes the compressor exactly like
        the on-device engine (``parallel/compression.py``):
        ``"per-leaf"`` applies it to each leaf span of the ravel (a
        top-k fraction stays a per-tensor contract), ``"global"`` once
        to the whole ravel (one k budget across the model).

        ``fused=True`` (default) runs ONE collective exchange per round
        and — under ``sparse_wire`` — ships the correction as ONE fused
        sparse frame with one ``indices|values`` section per dtype
        bucket (``ValueResponseFusedSparse``), collapsing per-leaf
        framing/CRC/header overhead.  ``fused=False`` is the per-leaf
        baseline it replaces: one exchange (one frame per neighbor and
        direction) PER LEAF per round — kept as the wire-level oracle;
        the frame-count loopback test pins the >= 2x frame reduction.

        All agents must call it concurrently with the same tree
        structure, compressor family, ``budget``, ``gamma``, and
        ``fused`` flag; estimates persist across calls (and are shared
        with :meth:`run_choco_once` — one estimate stream per agent).
        """
        from distributed_learning_tpu.comm.pytree_codec import (
            flat_to_tree,
            tree_to_flat,
        )

        if budget not in ("per-leaf", "global"):
            raise ValueError(
                f"unknown compression budget {budget!r} (want 'per-leaf' "
                "or 'global')"
            )
        flat, spec = tree_to_flat(tree)
        if self._tree_spec is None:
            self._tree_spec = spec
            self._tree_buckets = spec.dtype_buckets()
        elif spec != self._tree_spec:
            raise ValueError(
                "tree structure changed across run_choco_tree calls; the "
                "TreeSpec is a deployment invariant (reset_choco() and "
                "restart the stream to change models)"
            )
        x = self._choco_begin(flat)
        delta = x - self._choco_hat_self
        if budget == "global":
            q = np.asarray(compressor(delta), np.float32).ravel()
        else:
            q = np.empty_like(delta)
            off = 0
            for size in spec.sizes:
                q[off : off + size] = np.asarray(
                    compressor(delta[off : off + size]), np.float32
                ).ravel()
                off += size

        if fused:
            # The fused sparse frame engages under sparse_wire (CHOCO
            # corrections are k-sparse by construction); without it the
            # round still fuses to ONE exchange with the plain dense
            # wire-rounding — the framing win, minus the sparse payload.
            self._fused_spans = (
                self._tree_buckets if self.sparse_wire else None
            )
            try:
                q = self._wire_round(q)
                self._op_id += 1
                self._iteration = 0
                self._count("choco_tree_rounds")
                self._int8_active = self.int8_wire
                neighbor_qs = await self._exchange_values(q)
            finally:
                self._int8_active = False
                self._fused_spans = None
            assert neighbor_qs is not None
        else:
            # Per-leaf baseline: one collective exchange per leaf span,
            # each wire-rounded exactly as a standalone correction.
            parts: Dict[str, list] = {t: [] for t in self._neighbors}
            rounded = []
            off = 0
            for size in spec.sizes:
                piece = self._wire_round(
                    np.ascontiguousarray(q[off : off + size])
                )
                rounded.append(piece)
                self._op_id += 1
                self._iteration = 0
                self._count("choco_tree_leaf_rounds")
                self._int8_active = self.int8_wire
                try:
                    vals = await self._exchange_values(piece)
                finally:
                    self._int8_active = False
                assert vals is not None
                for t, v in vals.items():
                    parts[t].append(np.asarray(v, np.float32).ravel())
                off += size
            q = (
                np.concatenate(rounded)
                if rounded else np.zeros(0, np.float32)
            )
            neighbor_qs = {
                t: np.concatenate(ps) for t, ps in parts.items()
            }
        out = self._choco_finish(x, q, neighbor_qs, gamma)
        return flat_to_tree(out, spec)

    def reset_choco(self) -> None:
        """Restart the compressed-gossip stream: drop all public estimates.

        Must run on EVERY agent at the same collective position (e.g.
        after an elastic rejoin, before the next ``run_choco_once``) — the
        estimates are replicated state, so a one-sided reset would itself
        diverge the copies.  Error feedback re-converges from zero."""
        self._choco_hat_self = None
        self._choco_hat_nbrs.clear()
        self._choco_invalidated_by = None

    async def run_round(
        self,
        value: np.ndarray,
        weight: float = 1.0,
        *,
        max_iterations: int = 10_000,
    ) -> np.ndarray:
        """Weighted consensus round to eps-convergence — the protocol the
        reference left as a stub over TCP (agent.py:155-156); semantics
        follow the asyncio implementation (consensus_asyncio.py:209-312).
        """
        if self.status is not AgentStatus.READY:
            raise RuntimeError(f"agent not ready (status={self.status})")
        try:
            self._require_neighbors()
        except ConnectionError:
            # The weight table may be ahead of the stream set because a
            # membership-generation broadcast is still queued on the
            # master stream (a regenerating master re-formed the
            # topology): apply what already arrived, then re-check.
            await self._drain_membership_updates(0.2)
            self._require_neighbors()
        self.status = AgentStatus.IN_ROUND
        # Round latency: duration on the monotonic clock (graftlint
        # wallclock-duration), start anchored to the wall clock so the
        # span merges onto the run-wide timeline.
        wall_t0 = time.time()
        t0 = time.perf_counter()
        try:
            await self._master.send(P.NewRoundRequest(weight=float(weight)))
            while True:
                msg = await self._master_recv()
                if isinstance(msg, P.NewRoundNotification):
                    break
                if isinstance(msg, P.NeighborhoodData):
                    # Membership generation broadcast (the master sends
                    # it BEFORE the round it applies to, on this ordered
                    # stream): realign, keep waiting for the round.
                    await self._apply_neighborhood(msg)
                    continue
                if isinstance(msg, P.Shutdown):
                    raise ShutdownError(msg.reason)
                if isinstance(msg, P.ErrorException):
                    raise RuntimeError(f"master: {msg.message}")
                # Anything else (e.g. a stale Done) is dropped.
            if msg.generation != self._generation:
                raise ConnectionError(
                    f"round {msg.round_id} is for membership generation "
                    f"{msg.generation}, this agent is at "
                    f"{self._generation}; retry the round"
                )
            self._round_excluded = set(msg.dropped)
            if msg.dropped:
                self._count("round_neighbors_dropped", len(
                    set(msg.dropped) & set(self._weights)
                ))
            self._round_id = msg.round_id
            # Master rounds re-derive the op tag from the broadcast round
            # id (see _OPS_PER_ROUND): every agent — including one that
            # just rejoined with fresh local state — lands on the same tag
            # regardless of how many run_once calls it has or hasn't seen.
            self._op_id = msg.round_id * _OPS_PER_ROUND
            self._tag_realigned = True
            self._in_master_round = True
            self._iteration = -1
            # Weighted lift: y = x * w / mean(w) (consensus_asyncio.py:231).
            y = np.asarray(value, dtype=np.float32).ravel() * (
                float(weight) / msg.mean_weight
            )
            for _ in range(max_iterations):
                self._iteration += 1
                y_new = await self._gossip_iteration(y)
                if y_new is None:  # Done broadcast mid-iteration
                    self._count("rounds_run")
                    self._observe_round(time.perf_counter() - t0, wall_t0)
                    return y
                # Two-sided residual (the reference's one-sided check at
                # consensus_asyncio.py:297 is a recorded defect).
                residual = float(np.max(np.abs(y_new - y))) if y.size else 0.0
                y = y_new
                # Explicit per-class constructions (not a class held in a
                # variable): graftproto extracts the send sites by AST.
                if residual <= self.convergence_eps:
                    status = P.Converged(
                        round_id=self._round_id, iteration=self._iteration
                    )
                else:
                    status = P.NotConverged(
                        round_id=self._round_id, iteration=self._iteration
                    )
                await self._master.send(status)
            self._count("rounds_run")
            self._observe_round(time.perf_counter() - t0, wall_t0)
            return y
        finally:
            self._in_master_round = False
            self._round_excluded = set()
            if self.status is not AgentStatus.SHUTDOWN:
                self.status = AgentStatus.READY

    def _observe_round(self, dur_s: float, wall_t0: float) -> None:
        """Per-round latency into the registries: a ``round_s`` series
        point keyed by the master's round id and a wall-anchored span
        (one track per agent in the merged run trace)."""
        regs = [get_registry()]
        if self._obs is not None and self._obs is not regs[0]:
            regs.append(self._obs)
        for reg in regs:
            reg.observe("comm.agent.round_s", dur_s, step=self._round_id)
            reg.record_span("comm.agent.round", dur_s, t0=wall_t0)

    async def send_telemetry(self, payload: Dict[str, Any]) -> None:
        """Parity: ``send_telemetry``, agent.py:214-218."""
        self._count("telemetry_sent")
        await self._master.send(P.Telemetry(token=self.token, payload=payload))

    # ------------------------------------------------------------------ #
    # Run-wide observability plane (docs/observability.md)               #
    # ------------------------------------------------------------------ #
    def _ensure_obs_source(self) -> ObsDeltaSource:
        if self._obs_source is None:
            self._obs_source = ObsDeltaSource(
                self._obs if self._obs is not None else get_registry()
            )
        return self._obs_source

    def obs_delta(self) -> Dict[str, Any]:
        """Pack this agent's registry growth since the last pack into an
        ``obs.delta`` Telemetry payload (``protocol.OBS_PAYLOAD_KIND``).
        Uses the per-agent ``obs=`` registry when one was attached, else
        the process-wide default (the right source for one-agent-per-
        process deployments)."""
        return self._ensure_obs_source().pack()

    async def send_obs_delta(self) -> None:
        """Ship one registry delta to the master's RunAggregator over
        the existing Telemetry message — no new wire message, no new
        connection."""
        self._count("obs_deltas_sent")
        await self.send_telemetry(self.obs_delta())

    def start_obs_stream(self, period_s: float = 1.0) -> None:
        """Start the periodic delta stream (an asyncio task; frame sends
        interleave safely with round traffic — FramedStream serializes
        writers).  Idempotent; stopped by :meth:`close`."""
        if self._obs_task is not None:
            return
        self._obs_period = float(period_s)
        self._ensure_obs_source()  # events from here on are buffered
        self._obs_task = asyncio.ensure_future(self._obs_stream_loop())

    async def _obs_stream_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self._obs_period)
                await self.send_obs_delta()
        except (asyncio.CancelledError, ConnectionError, OSError):
            # Stream teardown/cancel ends the telemetry stream quietly:
            # observability must never take an agent down.
            pass

    def _require_neighbors(self) -> None:
        """A collective op with missing neighbor streams would silently
        mix with the dead peer's mass dropped (the weight row no longer
        sums to 1): refuse instead, pointing at the heal path."""
        missing = set(self._weights) - set(self._neighbors)
        if missing:
            raise ConnectionError(
                f"neighbors not connected: {sorted(missing)}; await "
                "wait_neighbors() for their replacements to dial in"
            )

    async def wait_neighbors(self, timeout: float = 30.0) -> None:
        """Block until every neighbor in the weight table has a live
        stream — the heal step after a peer death under an elastic master:
        catch the ConnectionError from the failed op, ``await
        agent.wait_neighbors()`` (the rejoined replacement dials back in),
        then retry the round.  Under a regenerating master the weight
        table itself may be about to change: queued membership-generation
        broadcasts are applied while waiting."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            # Drain FIRST: the weight table itself may be about to
            # change (a queued membership-generation broadcast), and a
            # rejoiner may be dialing in right now.
            await self._drain_membership_updates(0.02)
            if not (set(self._weights) - set(self._neighbors)):
                return
            if asyncio.get_event_loop().time() > deadline:
                missing = sorted(set(self._weights) - set(self._neighbors))
                raise TimeoutError(f"neighbors never rejoined: {missing}")

    # ------------------------------------------------------------------ #
    async def close(self, *, drain: float = 0.5) -> None:
        """Tear down, after answering straggler neighbor requests.

        The exchange protocol is pull-based: a peer's request is answered
        only while this agent is awaiting inside an exchange, and round
        completion skews up to one iteration across an edge — so a fast
        agent closing immediately after its last round can strand a
        slower neighbor mid-exchange.  Before tearing down, keep serving
        ``ValueRequest``s until the fabric has been quiet for 100 ms (or
        ``drain`` seconds total, whichever comes first).  ``drain=0``
        skips the grace period (used for tests that simulate dying
        agents).
        """
        if self._obs_task is not None:
            # Stop the periodic delta stream first: a send racing the
            # teardown below would observe half-closed streams.
            self._obs_task.cancel()
            self._obs_task = None
        if self._obs_source is not None:
            self._obs_source.close()
        deadline = asyncio.get_event_loop().time() + drain
        # Once the master stream yields anything during close — a message
        # we no longer care about, or EOF from a master that exited first
        # — stop listening to it: respawning recv() on an EOF'd stream
        # completes instantly and would busy-spin the drain loop, starving
        # the neighbor mux it exists to serve.
        master_live = self._master is not None
        while drain > 0:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                break
            if master_live and self._master_task is None:
                self._master_task = asyncio.ensure_future(self._master.recv())
                self._master_task.add_done_callback(self._silence)
            if self._mux_task is None:
                self._mux_task = asyncio.ensure_future(self._mux.__anext__())
            tasks = {
                t for t in (self._master_task, self._mux_task) if t is not None
            }
            if not tasks:
                break
            done, _ = await asyncio.wait(
                tasks,
                timeout=min(0.1, remaining),
                return_when=asyncio.FIRST_COMPLETED,
            )
            if not done:
                break  # quiet: no straggler left waiting on us
            if self._master_task is not None and self._master_task in done:
                self._master_task = None
                master_live = False
            if self._mux_task is not None and self._mux_task in done:
                try:
                    token, msg, _stream = self._mux_task.result()
                    self._mux_task = None
                    if isinstance(msg, P.ValueRequest):
                        await self._answer(token, msg)
                except Exception:
                    break  # a dying fabric must not block teardown
        self._mux.close()
        for task in (self._master_task, self._mux_task):
            if task is not None:
                task.cancel()
        # Streams (including ones our server accepted) must close before
        # wait_closed: since 3.12 it also waits for accepted connections.
        for stream in self._neighbors.values():
            stream.close()
        if self._master is not None:
            self._master.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self.status = AgentStatus.SHUTDOWN
