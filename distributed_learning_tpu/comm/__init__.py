"""Multi-process communication backend (control plane + CPU data plane).

The reference ships three backends (SURVEY.md §2): shared-memory
(``consensus_simple``), asyncio queues (``consensus_asyncio``), and
TCP+pickle (``consensus_tcp``).  In this framework the first two collapse
into the compiled SPMD engine (``parallel/consensus.py``: dense mode is
the shared-memory analogue, the CPU virtual mesh is the simulator).  This
package is the third: a genuinely multi-process master/agent deployment
over TCP for hosts that are *not* members of one jax.distributed mesh —
with typed binary framing (no pickle), crc32 integrity, bf16 wire
compression through the native codec, and the round protocol the
reference's TCP backend left broken (stub ``run_round``, uninitialized
master round state).

For TPU pods, prefer ``parallel/multihost.py`` (XLA collectives over
ICI/DCN); this backend is the interoperability / heterogeneous-cluster
path.
"""

import importlib

# PEP 562 lazy re-exports: ``master`` imports the jax-backed weight
# solvers (``parallel.topology`` / ``parallel.fast_averaging``), so an
# eager import here would make *every* comm submodule import pull jax.
# The graftlint sched stage drives the real agent/runner coroutines on
# a jax-free box (docs/static_analysis.md §Stage 7) and relies on
# ``comm.agent`` / ``comm.async_runtime`` / ``comm.faults`` importing
# bare; resolve the public names on first attribute access instead.
_LAZY = {
    "AgentStatus": ("agent", "AgentStatus"),
    "ConsensusAgent": ("agent", "ConsensusAgent"),
    "RoundAbortedError": ("agent", "RoundAbortedError"),
    "ShutdownError": ("agent", "ShutdownError"),
    "AsyncGossipRunner": ("async_runtime", "AsyncGossipRunner"),
    "AsyncRoundStats": ("async_runtime", "AsyncRoundStats"),
    "QUARANTINE_PAYLOAD_KIND": (
        "async_runtime", "QUARANTINE_PAYLOAD_KIND"
    ),
    "FaultPlan": ("faults", "FaultPlan"),
    "FaultyStream": ("faults", "FaultyStream"),
    "inject_neighbor_faults": ("faults", "inject_neighbor_faults"),
    "lying_fields_mutator": ("faults", "lying_fields_mutator"),
    "poison_value_mutator": ("faults", "poison_value_mutator"),
    "FramedStream": ("framing", "FramedStream"),
    "FrameError": ("framing", "FrameError"),
    "open_framed_connection": ("framing", "open_framed_connection"),
    "ConsensusMaster": ("master", "ConsensusMaster"),
    "StreamMultiplexer": ("multiplexer", "StreamMultiplexer"),
    "decode_fused_sparse": ("tensor_codec", "decode_fused_sparse"),
    "decode_sparse": ("tensor_codec", "decode_sparse"),
    "decode_tensor": ("tensor_codec", "decode_tensor"),
    "encode_fused_sparse": ("tensor_codec", "encode_fused_sparse"),
    "encode_sparse": ("tensor_codec", "encode_sparse"),
    "encode_tensor": ("tensor_codec", "encode_tensor"),
    "top_k_sparse": ("tensor_codec", "top_k_sparse"),
}


def __getattr__(name):
    try:
        submodule, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    module = importlib.import_module(
        f"distributed_learning_tpu.comm.{submodule}"
    )
    value = getattr(module, attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))


def top_k_compressor(fraction: float):
    """Host-side top-k compressor for :meth:`ConsensusAgent.run_choco_once`
    (densified k-sparse output).  Selection is numpy introselect
    (``tensor_codec.top_k_sparse``, 285 ms at n=36M, k=1%).  The dense
    output is not waste: the CHOCO recurrence updates the full public
    estimate with q either way, so densification happens exactly once
    here; only ``encode_sparse``'s flatnonzero re-scan (~1 extra pass)
    is redundant with the selection."""
    import numpy as np

    from distributed_learning_tpu.comm.tensor_codec import top_k_sparse

    if not 0.0 < fraction <= 1.0:
        raise ValueError(f"fraction must be in (0, 1], got {fraction}")

    def compress(v: "np.ndarray") -> "np.ndarray":
        flat = np.asarray(v, np.float32).ravel()
        k = max(1, int(round(fraction * flat.size)))
        idx, vals = top_k_sparse(flat, k)
        out = np.zeros_like(flat)
        out[idx] = vals
        return out.reshape(np.shape(v))

    return compress

__all__ = [
    "AgentStatus",
    "AsyncGossipRunner",
    "AsyncRoundStats",
    "ConsensusAgent",
    "ConsensusMaster",
    "FaultPlan",
    "FaultyStream",
    "FramedStream",
    "FrameError",
    "QUARANTINE_PAYLOAD_KIND",
    "inject_neighbor_faults",
    "lying_fields_mutator",
    "poison_value_mutator",
    "RoundAbortedError",
    "ShutdownError",
    "StreamMultiplexer",
    "open_framed_connection",
    "encode_tensor",
    "decode_tensor",
    "encode_sparse",
    "decode_sparse",
    "encode_fused_sparse",
    "decode_fused_sparse",
    "top_k_compressor",
]
