"""Multi-process communication backend (control plane + CPU data plane).

The reference ships three backends (SURVEY.md §2): shared-memory
(``consensus_simple``), asyncio queues (``consensus_asyncio``), and
TCP+pickle (``consensus_tcp``).  In this framework the first two collapse
into the compiled SPMD engine (``parallel/consensus.py``: dense mode is
the shared-memory analogue, the CPU virtual mesh is the simulator).  This
package is the third: a genuinely multi-process master/agent deployment
over TCP for hosts that are *not* members of one jax.distributed mesh —
with typed binary framing (no pickle), crc32 integrity, bf16 wire
compression through the native codec, and the round protocol the
reference's TCP backend left broken (stub ``run_round``, uninitialized
master round state).

For TPU pods, prefer ``parallel/multihost.py`` (XLA collectives over
ICI/DCN); this backend is the interoperability / heterogeneous-cluster
path.
"""

from distributed_learning_tpu.comm.agent import (
    AgentStatus,
    ConsensusAgent,
    RoundAbortedError,
    ShutdownError,
)
from distributed_learning_tpu.comm.framing import FramedStream, FrameError, open_framed_connection
from distributed_learning_tpu.comm.master import ConsensusMaster
from distributed_learning_tpu.comm.multiplexer import StreamMultiplexer
from distributed_learning_tpu.comm.tensor_codec import (
    decode_sparse,
    decode_tensor,
    encode_sparse,
    encode_tensor,
)

__all__ = [
    "AgentStatus",
    "ConsensusAgent",
    "ConsensusMaster",
    "FramedStream",
    "FrameError",
    "RoundAbortedError",
    "ShutdownError",
    "StreamMultiplexer",
    "open_framed_connection",
    "encode_tensor",
    "decode_tensor",
    "encode_sparse",
    "decode_sparse",
]
