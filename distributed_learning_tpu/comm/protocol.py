"""Typed message protocol for the multi-process comm backend.

Parity: ``utils/consensus_tcp/protocol.py:4-84`` — the same message set and
invariants ("every request gets exactly one response"; agents talk to the
master for control and to each other for data), but messages serialize to a
fixed binary layout instead of pickle (see the reference's
``ProtoErrorException``/dataclass definitions at :15-84 and the security
note in SURVEY.md §2: pickle-over-TCP must not survive into the new
design).

Every message is a dataclass with a one-byte type code and explicit
``_pack``/``_unpack`` methods; tensors go through
:mod:`~distributed_learning_tpu.comm.tensor_codec`.
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import Any, ClassVar, Dict, List, Optional, Tuple, Type

import numpy as np

from distributed_learning_tpu.comm.tensor_codec import (
    CodecError,
    decode_tensor,
    encode_tensor,
)
# The run-wide observability plane's structured Telemetry payload: a
# per-agent registry delta, marked by payload["kind"] ==
# OBS_PAYLOAD_KIND and versioned by payload["v"] == OBS_PAYLOAD_VERSION.
# The schema lives with its producer/consumer (obs/aggregate.py:
# ObsDeltaSource.pack / RunAggregator.process) and is re-exported here
# because it IS wire surface: any payload claiming the kind must follow
# the versioned layout, exactly like a message's binary fields.
from distributed_learning_tpu.obs.aggregate import (  # noqa: F401
    OBS_PAYLOAD_KIND,
    OBS_PAYLOAD_SECTIONS,
    OBS_PAYLOAD_VERSION,
    is_obs_payload,
)

__all__ = [
    "Message",
    "Register",
    "Ok",
    "ErrorException",
    "Neighbor",
    "NeighborhoodData",
    "NewRoundRequest",
    "NewRoundNotification",
    "ValueRequest",
    "ValueResponse",
    "Converged",
    "NotConverged",
    "Done",
    "Shutdown",
    "Telemetry",
    "ValueResponseSparse",
    "ValueResponseFusedSparse",
    "AsyncValue",
    "AsyncPoke",
    "TraceContext",
    "TRACE_CTX_VERSION",
    "pack_message",
    "unpack_message",
    "OBS_PAYLOAD_KIND",
    "OBS_PAYLOAD_SECTIONS",
    "OBS_PAYLOAD_VERSION",
    "is_obs_payload",
]

#: Version of the trace-context trailer carried by the value-bearing
#: frames (ValueResponse*/AsyncValue/AsyncPoke).  Wire surface: the
#: layout below is cross-checked against ``native/wire.cpp``'s
#: ``kTraceCtxVersion`` and ``dlt_abi.h``'s ``DLT_TRACE_CTX_VERSION``
#: by graftlint's wire-contract stage — bump all three together.
TRACE_CTX_VERSION = 1


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Compact per-frame trace identity carried on the gossip wire.

    ``(run_id, origin, seq)`` names one frame fleet-uniquely: ``origin``
    is the sending agent's token and ``seq`` its per-edge frame counter,
    so the obs plane can flow-link the sender's encode/send events to
    the receiver's recv/decode/mix events for the same frame
    (``obs/spans.py`` flow events -> ``RunAggregator.to_chrome_trace``
    arrows).  ``t_wall`` is the sender's wall-clock send stamp, used by
    the receiver for per-edge wire latency (wall clock on purpose: it is
    the only clock two processes share).  Generation and round already
    travel in the host messages (``AsyncValue.round_id/generation``,
    ``ValueResponse.round_id/iteration``), so they are not duplicated
    here.

    Trailer layout (appended at the END of the host frame's body):
    ``u8 present | u32 run_id | i64 seq | f64 t_wall | str origin``.
    An absent context packs as the single byte 0, and a body with no
    trailer at all unpacks as ``trace=None`` — both directions
    round-trip ``None`` exactly.
    """

    run_id: int = 0
    origin: str = ""
    seq: int = 0
    t_wall: float = 0.0


_TRACE_FIXED = struct.Struct("<Iqd")


def _pack_trace(tc: Optional[TraceContext]) -> bytes:
    if tc is None:
        return b"\x00"
    return (
        b"\x01"
        + _TRACE_FIXED.pack(tc.run_id, tc.seq, tc.t_wall)
        + _pack_str(tc.origin)
    )


def _unpack_trace(buf: bytes, off: int) -> Optional[TraceContext]:
    if off >= len(buf) or buf[off] == 0:
        return None
    run_id, seq, t_wall = _TRACE_FIXED.unpack_from(buf, off + 1)
    origin, _ = _unpack_str(buf, off + 1 + _TRACE_FIXED.size)
    return TraceContext(
        run_id=run_id, origin=origin, seq=seq, t_wall=t_wall
    )


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise ValueError("string field exceeds 64KiB")
    return struct.pack("<H", len(b)) + b


def _unpack_str(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    return buf[off : off + n].decode("utf-8"), off + n


def _pack_tensor(x: np.ndarray, bf16_wire: bool,
                 int8_wire: bool = False) -> bytes:
    t = encode_tensor(x, bf16_wire=bf16_wire, int8_wire=int8_wire)
    return struct.pack("<I", len(t)) + t


def _unpack_tensor(buf: bytes, off: int) -> Tuple[np.ndarray, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return decode_tensor(buf[off : off + n]), off + n


@dataclasses.dataclass
class Message:
    """Base: subclasses set ``TYPE_CODE`` and implement pack/unpack."""

    TYPE_CODE: ClassVar[int] = -1

    def _pack(self) -> bytes:  # pragma: no cover - overridden
        return b""

    @classmethod
    def _unpack(cls, buf: bytes) -> "Message":  # pragma: no cover
        return cls()


@dataclasses.dataclass
class Register(Message):
    """Agent -> master (and agent -> peer) identification handshake
    (parity: ``ProtoRegister``, protocol.py:23-27 — token + listen address
    so the master/peer can route back-connections)."""

    TYPE_CODE: ClassVar[int] = 1
    token: str = ""
    host: str = ""
    port: int = 0

    def _pack(self) -> bytes:
        return _pack_str(self.token) + _pack_str(self.host) + struct.pack("<I", self.port)

    @classmethod
    def _unpack(cls, buf: bytes) -> "Register":
        token, off = _unpack_str(buf, 0)
        host, off = _unpack_str(buf, off)
        (port,) = struct.unpack_from("<I", buf, off)
        return cls(token=token, host=host, port=port)


@dataclasses.dataclass
class Ok(Message):
    """Positive acknowledgement (parity: ``ProtoOk``, protocol.py:30-32)."""

    TYPE_CODE: ClassVar[int] = 2
    info: str = ""

    def _pack(self) -> bytes:
        return _pack_str(self.info)

    @classmethod
    def _unpack(cls, buf: bytes) -> "Ok":
        info, _ = _unpack_str(buf, 0)
        return cls(info=info)


@dataclasses.dataclass
class ErrorException(Message):
    """Error report (parity: ``ProtoErrorException``, protocol.py:15-20)."""

    TYPE_CODE: ClassVar[int] = 3
    message: str = ""

    def _pack(self) -> bytes:
        return _pack_str(self.message)

    @classmethod
    def _unpack(cls, buf: bytes) -> "ErrorException":
        message, _ = _unpack_str(buf, 0)
        return cls(message=message)


@dataclasses.dataclass
class Neighbor:
    token: str
    host: str
    port: int
    weight: float


@dataclasses.dataclass
class NeighborhoodData(Message):
    """Master -> agent: neighbor addresses + per-edge mixing weights +
    self-weight + convergence eps (parity: ``ProtoNeighborhoodData``,
    protocol.py:35-39, with the SDP weights the master solves at
    ``master.py:262-266``).

    ``generation`` (this framework's addition) versions the membership
    epoch: an elastic master that re-forms the topology and re-solves W
    after a death/(re)join broadcasts a fresh NeighborhoodData with the
    counter bumped, and agents realign their weight tables to it
    mid-run (docs/async_runtime.md §Membership generations)."""

    TYPE_CODE: ClassVar[int] = 4
    self_weight: float = 0.0
    convergence_eps: float = 1e-4
    neighbors: List[Neighbor] = dataclasses.field(default_factory=list)
    generation: int = 0

    def _pack(self) -> bytes:
        out = [struct.pack("<ddH", self.self_weight, self.convergence_eps, len(self.neighbors))]
        for nb in self.neighbors:
            out.append(_pack_str(nb.token) + _pack_str(nb.host))
            out.append(struct.pack("<Id", nb.port, nb.weight))
        out.append(struct.pack("<q", self.generation))
        return b"".join(out)

    @classmethod
    def _unpack(cls, buf: bytes) -> "NeighborhoodData":
        self_w, eps, count = struct.unpack_from("<ddH", buf, 0)
        off = 18
        nbs = []
        for _ in range(count):
            token, off = _unpack_str(buf, off)
            host, off = _unpack_str(buf, off)
            port, weight = struct.unpack_from("<Id", buf, off)
            off += 12
            nbs.append(Neighbor(token=token, host=host, port=port, weight=weight))
        (gen,) = struct.unpack_from("<q", buf, off)
        return cls(
            self_weight=self_w, convergence_eps=eps, neighbors=nbs,
            generation=gen,
        )


@dataclasses.dataclass
class NewRoundRequest(Message):
    """Agent -> master: ready for a weighted consensus round with this
    sample weight (parity: ``ProtoNewRoundRequest``, protocol.py:52-55)."""

    TYPE_CODE: ClassVar[int] = 5
    weight: float = 1.0

    def _pack(self) -> bytes:
        return struct.pack("<d", self.weight)

    @classmethod
    def _unpack(cls, buf: bytes) -> "NewRoundRequest":
        (w,) = struct.unpack_from("<d", buf, 0)
        return cls(weight=w)


@dataclasses.dataclass
class NewRoundNotification(Message):
    """Master -> agents: round starts; carries the mean sample weight for
    the weighted-lift trick (parity: ``ProtoNewRoundNotification``,
    protocol.py:56-59, mean weight computed at ``master.py:145-146,165``)."""

    TYPE_CODE: ClassVar[int] = 6
    round_id: int = 0
    mean_weight: float = 1.0
    #: membership epoch this round runs under (must match the agent's).
    generation: int = 0
    #: tokens dropped from this round by a deadline-enforcing master
    #: (their edges get zero weight, mass renormalized onto self).
    dropped: List[str] = dataclasses.field(default_factory=list)

    def _pack(self) -> bytes:
        out = [
            struct.pack(
                "<qdqH",
                self.round_id, self.mean_weight, self.generation,
                len(self.dropped),
            )
        ]
        for tok in self.dropped:
            out.append(_pack_str(tok))
        return b"".join(out)

    @classmethod
    def _unpack(cls, buf: bytes) -> "NewRoundNotification":
        r, w, gen, count = struct.unpack_from("<qdqH", buf, 0)
        off = 26
        dropped = []
        for _ in range(count):
            tok, off = _unpack_str(buf, off)
            dropped.append(tok)
        return cls(
            round_id=r, mean_weight=w, generation=gen, dropped=dropped
        )


@dataclasses.dataclass
class ValueRequest(Message):
    """Agent -> neighbor: your value for (round, iteration), please
    (parity: ``ProtoRunOnceValueRequest``, protocol.py:62-65)."""

    TYPE_CODE: ClassVar[int] = 7
    round_id: int = 0
    iteration: int = 0

    def _pack(self) -> bytes:
        return struct.pack("<qq", self.round_id, self.iteration)

    @classmethod
    def _unpack(cls, buf: bytes) -> "ValueRequest":
        r, i = struct.unpack_from("<qq", buf, 0)
        return cls(round_id=r, iteration=i)


@dataclasses.dataclass
class ValueResponse(Message):
    """Neighbor -> agent: flattened value tensor for (round, iteration)
    (parity: ``ProtoRunOnceValueResponse``, protocol.py:66-69; bf16 wire
    narrowing is this framework's addition)."""

    TYPE_CODE: ClassVar[int] = 8
    round_id: int = 0
    iteration: int = 0
    value: Optional[np.ndarray] = None
    bf16_wire: bool = False
    int8_wire: bool = False
    trace: Optional[TraceContext] = None

    def _pack(self) -> bytes:
        v = self.value if self.value is not None else np.zeros(0, np.float32)
        return (
            struct.pack("<qq", self.round_id, self.iteration)
            + _pack_tensor(np.asarray(v), self.bf16_wire, self.int8_wire)
            + _pack_trace(self.trace)
        )

    @classmethod
    def _unpack(cls, buf: bytes) -> "ValueResponse":
        r, i = struct.unpack_from("<qq", buf, 0)
        value, off = _unpack_tensor(buf, 16)
        return cls(
            round_id=r, iteration=i, value=value,
            trace=_unpack_trace(buf, off),
        )


@dataclasses.dataclass
class Converged(Message):
    """Agent -> master (parity: ``ProtoConverged``, protocol.py:42-45)."""

    TYPE_CODE: ClassVar[int] = 9
    round_id: int = 0
    iteration: int = 0

    def _pack(self) -> bytes:
        return struct.pack("<qq", self.round_id, self.iteration)

    @classmethod
    def _unpack(cls, buf: bytes) -> "Converged":
        r, i = struct.unpack_from("<qq", buf, 0)
        return cls(round_id=r, iteration=i)


@dataclasses.dataclass
class NotConverged(Message):
    """Agent -> master (parity: ``ProtoNotConverged``, protocol.py:46-49)."""

    TYPE_CODE: ClassVar[int] = 10
    round_id: int = 0
    iteration: int = 0

    def _pack(self) -> bytes:
        return struct.pack("<qq", self.round_id, self.iteration)

    @classmethod
    def _unpack(cls, buf: bytes) -> "NotConverged":
        r, i = struct.unpack_from("<qq", buf, 0)
        return cls(round_id=r, iteration=i)


@dataclasses.dataclass
class Done(Message):
    """Master -> agents: round ended (parity: ``ProtoDone``,
    protocol.py:72-74).  ``aborted`` (this framework's addition) marks an
    elastic-mode abort — an agent died mid-round, values are NOT a
    consensus — as opposed to global convergence."""

    TYPE_CODE: ClassVar[int] = 11
    round_id: int = 0
    aborted: bool = False
    #: round was cut by an enforced round deadline — agents return their
    #: current (partially converged) values rather than wait any longer.
    deadline: bool = False

    def _pack(self) -> bytes:
        flags = int(self.aborted) | (int(self.deadline) << 1)
        return struct.pack("<qB", self.round_id, flags)

    @classmethod
    def _unpack(cls, buf: bytes) -> "Done":
        r, flags = struct.unpack_from("<qB", buf, 0)
        return cls(
            round_id=r, aborted=bool(flags & 1), deadline=bool(flags & 2)
        )


@dataclasses.dataclass
class Shutdown(Message):
    """Master -> agents broadcast (parity: ``ProtoShutdown``,
    protocol.py:77-79, broadcast at ``master.py:48-61``)."""

    TYPE_CODE: ClassVar[int] = 12
    reason: str = ""

    def _pack(self) -> bytes:
        return _pack_str(self.reason)

    @classmethod
    def _unpack(cls, buf: bytes) -> "Shutdown":
        reason, _ = _unpack_str(buf, 0)
        return cls(reason=reason)


@dataclasses.dataclass
class Telemetry(Message):
    """Agent -> master metrics payload, dispatched to a
    ``TelemetryProcessor`` (parity: ``ProtoTelemetry``, protocol.py:82-84,
    dispatch at ``master.py:192-199``).  The payload is JSON, not pickle."""

    TYPE_CODE: ClassVar[int] = 13
    token: str = ""
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def _pack(self) -> bytes:
        return _pack_str(self.token) + _pack_str(json.dumps(self.payload))

    @classmethod
    def _unpack(cls, buf: bytes) -> "Telemetry":
        token, off = _unpack_str(buf, 0)
        payload, _ = _unpack_str(buf, off)
        return cls(token=token, payload=json.loads(payload))


@dataclasses.dataclass
class ValueResponseSparse(Message):
    """Neighbor -> agent: a k-sparse value (e.g. a CHOCO compressed-gossip
    correction, ``parallel/compression.py``) shipped as k values + indices
    via :func:`~distributed_learning_tpu.comm.tensor_codec.encode_sparse`
    instead of the dense vector.  This framework's addition — the
    reference's wire is always dense pickled numpy
    (``pickled_socket.py:12``)."""

    TYPE_CODE: ClassVar[int] = 14
    round_id: int = 0
    iteration: int = 0
    value: Optional[np.ndarray] = None
    bf16_wire: bool = False
    int8_wire: bool = False
    trace: Optional[TraceContext] = None

    def _pack(self) -> bytes:
        from distributed_learning_tpu.comm.tensor_codec import encode_sparse

        v = self.value if self.value is not None else np.zeros(0, np.float32)
        t = encode_sparse(np.asarray(v), bf16_wire=self.bf16_wire,
                          int8_wire=self.int8_wire)
        return (
            struct.pack("<qqI", self.round_id, self.iteration, len(t))
            + t
            + _pack_trace(self.trace)
        )

    @classmethod
    def _unpack(cls, buf: bytes) -> "ValueResponseSparse":
        from distributed_learning_tpu.comm.tensor_codec import decode_sparse

        r, i, n = struct.unpack_from("<qqI", buf, 0)
        return cls(
            round_id=r, iteration=i, value=decode_sparse(buf[20 : 20 + n]),
            trace=_unpack_trace(buf, 20 + n),
        )


@dataclasses.dataclass
class ValueResponseFusedSparse(Message):
    """Neighbor -> agent: a whole model-tree correction as ONE fused
    sparse frame — one ``indices|values`` payload per dtype bucket, flat
    positions into the ``pytree_codec.TreeSpec`` ravel
    (:func:`~distributed_learning_tpu.comm.tensor_codec.encode_fused_sparse`).
    Collapses the per-leaf framing/CRC/header overhead of gossiping a
    tree leaf by leaf to one frame per round.  ``buckets`` (the
    ``TreeSpec.dtype_buckets()`` spans) is encode-side only: the frame
    is self-describing on decode.  Receive side, ``value`` is a lazy
    (but fully validated) ``tensor_codec.FusedFrame``: densify with
    ``np.asarray`` / ``densify(out=scratch)``, or skip the dense
    intermediate entirely with ``apply_into(target, scale=...)``."""

    TYPE_CODE: ClassVar[int] = 15
    round_id: int = 0
    iteration: int = 0
    value: Optional[np.ndarray] = None
    buckets: Optional[Tuple] = None
    bf16_wire: bool = False
    int8_wire: bool = False
    trace: Optional[TraceContext] = None

    def _pack(self) -> bytes:
        from distributed_learning_tpu.comm.tensor_codec import (
            encode_fused_sparse,
        )

        v = self.value if self.value is not None else np.zeros(0, np.float32)
        buckets = self.buckets
        if buckets is None:
            # Degenerate single-bucket framing for spec-less callers.
            buckets = (("float32", ((0, int(np.asarray(v).size)),)),)
        t = encode_fused_sparse(
            np.asarray(v), buckets,
            bf16_wire=self.bf16_wire, int8_wire=self.int8_wire,
        )
        return (
            struct.pack("<qqI", self.round_id, self.iteration, len(t))
            + t
            + _pack_trace(self.trace)
        )

    @classmethod
    def _unpack(cls, buf: bytes) -> "ValueResponseFusedSparse":
        from distributed_learning_tpu.comm.tensor_codec import FusedFrame

        r, i, n = struct.unpack_from("<qqI", buf, 0)
        # Lazy receive (zero-copy wire path): the frame is VALIDATED
        # here — CRC, section walk, bounds — so the CodecError drop
        # discipline is unchanged, but densify/scatter is deferred to
        # the consumer, which can decode into its own scratch ravel or
        # apply the sections straight onto a live target
        # (FusedFrame.apply_into).  ``np.asarray(msg.value)`` densifies
        # on demand for spec-less callers.
        return cls(
            round_id=r, iteration=i,
            value=FusedFrame(buf[20 : 20 + n]),
            trace=_unpack_trace(buf, 20 + n),
        )


#: payload encodings of an :class:`AsyncValue` frame.
_ASYNC_DENSE, _ASYNC_SPARSE, _ASYNC_FUSED = 0, 1, 2


@dataclasses.dataclass
class AsyncValue(Message):
    """Agent -> neighbor PUSH of the async gossip runtime
    (``comm/async_runtime.py``): unsolicited "here is my latest state",
    no request/response pairing.  This framework's addition — the
    reference has no asynchronous wire at all (its asyncio backend is
    still lock-step request/response, ``consensus_asyncio.py:209-312``).

    ``round_id`` is the *sender's* async round counter (receivers anchor
    staleness to their own arrival clock, so counters need no cross-agent
    alignment); ``generation`` is the membership epoch the value belongs
    to (frames from another generation are dropped); ``staleness`` stamps
    how many sender rounds old the payload already was when shipped
    (0 for a fresh push; >0 when a poke re-sends the standing published
    buffer).  ``kind`` picks the payload codec: dense
    (``encode_tensor``), k-sparse (``encode_sparse``), or fused sparse
    with per-dtype-bucket sections (``encode_fused_sparse``)."""

    TYPE_CODE: ClassVar[int] = 16
    round_id: int = 0
    generation: int = 0
    staleness: int = 0
    value: Optional[np.ndarray] = None
    kind: int = _ASYNC_DENSE
    buckets: Optional[Tuple] = None  # encode-side, fused kind only
    bf16_wire: bool = False
    int8_wire: bool = False
    trace: Optional[TraceContext] = None

    def _pack(self) -> bytes:
        from distributed_learning_tpu.comm.tensor_codec import (
            encode_fused_sparse,
            encode_sparse,
        )

        v = np.asarray(
            self.value if self.value is not None else np.zeros(0, np.float32)
        )
        if self.kind == _ASYNC_SPARSE:
            t = encode_sparse(
                v, bf16_wire=self.bf16_wire, int8_wire=self.int8_wire
            )
        elif self.kind == _ASYNC_FUSED:
            buckets = self.buckets
            if buckets is None:
                buckets = (("float32", ((0, int(v.size)),)),)
            t = encode_fused_sparse(
                v, buckets,
                bf16_wire=self.bf16_wire, int8_wire=self.int8_wire,
            )
        else:
            t = encode_tensor(
                v, bf16_wire=self.bf16_wire, int8_wire=self.int8_wire
            )
        return struct.pack(
            "<qqqBI",
            self.round_id, self.generation, self.staleness,
            self.kind, len(t),
        ) + t + _pack_trace(self.trace)

    @classmethod
    def _unpack(cls, buf: bytes) -> "AsyncValue":
        from distributed_learning_tpu.comm.tensor_codec import (
            DenseFrame,
            FusedFrame,
            SparseFrame,
        )

        r, gen, stale, kind, n = struct.unpack_from("<qqqBI", buf, 0)
        body = buf[29 : 29 + n]
        # Lazy receive (zero-copy wire path): construction VALIDATES
        # the payload (so unpack_message's CodecError drop discipline
        # is unchanged — a corrupt frame still dies here, on the mux
        # task, before any consumer sees it), but the densify is
        # deferred: the async runner decodes dense/sparse payloads into
        # its per-peer scratch ravel at dispatch, and fused payloads
        # scatter straight onto the CHOCO target (apply_into) with no
        # dense intermediate at all.
        if kind == _ASYNC_SPARSE:
            value = SparseFrame(body)
        elif kind == _ASYNC_FUSED:
            value = FusedFrame(body)
        else:
            value = DenseFrame(body)
        return cls(
            round_id=r, generation=gen, staleness=stale,
            value=value, kind=kind, trace=_unpack_trace(buf, 29 + n),
        )


@dataclasses.dataclass
class AsyncPoke(Message):
    """Agent -> neighbor of the async runtime: "your last value aged past
    my staleness bound — push me a fresh one when you can".  The
    re-request half of drop-and-re-request: the poked agent answers with
    an :class:`AsyncValue` at its next dispatch-loop service point
    (best-effort; a peer wedged in compute answers late by design)."""

    TYPE_CODE: ClassVar[int] = 17
    round_id: int = 0
    generation: int = 0
    trace: Optional[TraceContext] = None

    def _pack(self) -> bytes:
        return (
            struct.pack("<qq", self.round_id, self.generation)
            + _pack_trace(self.trace)
        )

    @classmethod
    def _unpack(cls, buf: bytes) -> "AsyncPoke":
        r, gen = struct.unpack_from("<qq", buf, 0)
        return cls(
            round_id=r, generation=gen, trace=_unpack_trace(buf, 16)
        )


_REGISTRY: Dict[int, Type[Message]] = {
    cls.TYPE_CODE: cls
    for cls in (
        Register, Ok, ErrorException, NeighborhoodData, NewRoundRequest,
        NewRoundNotification, ValueRequest, ValueResponse, Converged,
        NotConverged, Done, Shutdown, Telemetry, ValueResponseSparse,
        ValueResponseFusedSparse, AsyncValue, AsyncPoke,
    )
}


def pack_message(msg: Message) -> Tuple[int, bytes]:
    """-> (type_code, body) for the framing layer."""
    if type(msg).TYPE_CODE not in _REGISTRY:
        raise TypeError(f"unregistered message type {type(msg).__name__}")
    return type(msg).TYPE_CODE, msg._pack()


def unpack_message(type_code: int, body: bytes) -> Message:
    cls = _REGISTRY.get(type_code)
    if cls is None:
        raise ValueError(f"unknown message type code {type_code}")
    try:
        return cls._unpack(body)
    except CodecError:
        raise
    except (struct.error, ValueError, IndexError) as exc:
        # A checksum-clean frame whose body fails structural unpack
        # (e.g. truncated inside a fixed prefix) is the same class of
        # fault as a corrupt tensor section: surface it uniformly as
        # CodecError so receive paths drop-and-count instead of
        # crashing on a struct.error (validate-before-scatter is a
        # whole-body contract, not just the tensor payload's).
        raise CodecError(
            f"malformed {cls.__name__} body: {exc}"
        ) from None
