"""Async select() over many framed sockets.

Parity: ``utils/consensus_tcp/psocket_multiplexer.py:7-36``
(``PSocketMultiplexer``): an async iterator yielding
``(token, message, stream)`` from whichever registered socket produces a
frame first, built on ``asyncio.wait(FIRST_COMPLETED)`` with pending reads
carried between iterations (:19-31).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, Hashable, Optional, Tuple

from distributed_learning_tpu.comm.framing import FramedStream
from distributed_learning_tpu.comm.protocol import Message
from distributed_learning_tpu.comm.tensor_codec import CodecError
from distributed_learning_tpu.obs import get_registry

__all__ = ["StreamMultiplexer"]

#: graftproto role annotation (tools/graftlint/proto_extract.py).  The
#: multiplexer is pure transport: it yields whatever unpacks and never
#: dispatches on a message type, so its send/handle sets are empty — the
#: extractor still walks it so any future per-type dispatch added here
#: lands in the pinned protocol model instead of drifting silently.
PROTO_ROLE = "transport"

#: graftsched hot-coroutine annotation (tools/graftlint/schedsim.py):
#: ``__anext__`` is the single dispatch service point every peer frame
#: funnels through — its await-point model (arm pending reads, wait
#: FIRST_COMPLETED with the wake event) pins under ``sched_model``.
SCHED_HOT = ("__anext__",)


class StreamMultiplexer:
    """``async for token, msg, stream in mux:`` over a dynamic socket set."""

    def __init__(self, streams: Optional[Dict[Hashable, FramedStream]] = None):
        self._streams: Dict[Hashable, FramedStream] = dict(streams or {})
        # token -> (read task, the stream that task reads).  Tracking the
        # stream alongside the task keeps replacement safe: an error from a
        # read on a since-replaced stream must not evict the replacement.
        self._pending: Dict[Hashable, tuple] = {}
        self._closed = False
        # Set by add(): wakes a parked __anext__ so a stream registered
        # mid-wait (e.g. an elastic rejoin) gets its read armed immediately
        # instead of after the next unrelated frame.
        self._wake: asyncio.Event = asyncio.Event()

    def add(self, token: Hashable, stream: FramedStream) -> None:
        self._streams[token] = stream
        self._wake.set()

    def remove(self, token: Hashable) -> None:
        self._streams.pop(token, None)
        entry = self._pending.pop(token, None)
        if entry is not None:
            entry[0].cancel()

    def tokens(self):
        return tuple(self._streams)

    def close(self) -> None:
        self._closed = True
        for task, _ in self._pending.values():
            task.cancel()
        self._pending.clear()
        self._wake.set()  # unpark a waiter blocked on an empty stream set

    def __aiter__(self) -> AsyncIterator[Tuple[Hashable, Optional[Message], Optional[FramedStream]]]:
        return self

    async def __anext__(self):
        """Yields ``(token, msg, stream)``; a dead peer yields
        ``(token, None, dead_stream)`` exactly once so the caller can decide
        how to handle the loss (silently shrinking the set would leave
        callers waiting on a response count that can never be reached; the
        dead stream's identity lets the caller tell a stale death notice
        from the current stream's — e.g. after an elastic rejoin replaced
        it)."""
        while True:
            if self._closed:
                raise StopAsyncIteration
            for token, stream in self._streams.items():
                if (
                    token not in self._pending
                    or self._pending[token][1] is not stream
                ):
                    stale = self._pending.pop(token, None)
                    if stale is not None:
                        stale[0].cancel()
                    task = asyncio.ensure_future(stream.recv())
                    # Retrieve exceptions even if this task outlives every
                    # __anext__ call (e.g. connection dies after close()).
                    task.add_done_callback(
                        lambda t: t.exception() if not t.cancelled() else None
                    )
                    self._pending[token] = (task, stream)
            self._wake.clear()
            wake = asyncio.ensure_future(self._wake.wait())
            try:
                # Waiting on wake alongside the reads means an empty set
                # parks (streams may be added later — e.g. before agents
                # register, or awaiting an elastic rejoin) instead of
                # stopping, and a mid-wait add() re-arms immediately.
                done, _ = await asyncio.wait(
                    [t for t, _ in self._pending.values()] + [wake],
                    return_when=asyncio.FIRST_COMPLETED,
                )
            finally:
                wake.cancel()
            for token in list(self._pending):
                task, src = self._pending[token]
                if task in done:
                    del self._pending[token]
                    try:
                        msg = task.result()
                    except CodecError:
                        # Checksum-clean frame whose body failed the
                        # codec's validate-before-scatter checks: the
                        # framing consumed the whole frame before decode,
                        # so the stream is still aligned — drop the frame
                        # with a counter and keep the peer (its next push
                        # is independently validated).  Torn/corrupt
                        # frames (crc, version) raise FrameError instead,
                        # a ConnectionError: eviction below.
                        get_registry().inc("comm.frames_rejected")
                        continue
                    except (asyncio.IncompleteReadError, ConnectionError, OSError):
                        # Evict only if the erroring stream is still the
                        # registered one (not an already-replaced corpse).
                        if self._streams.get(token) is src:
                            self._streams.pop(token, None)
                        return token, None, src
                    return token, msg, self._streams.get(token, src)
