"""Asynchronous straggler-tolerant gossip runtime over the TCP backend.

Every other backend in this repo runs LOCK-STEP rounds: the protocol the
reference gestures at in its asyncio backend (``consensus_asyncio.py:
209-312``) still pairs every request with a response, so the slowest of
N agents sets the pace of all of them.  This module is the asynchronous
round engine the ROADMAP names: gossip overlaps local compute, stale
neighbor state is mixed at decayed weight instead of waited for, and a
wedged straggler costs its own progress — not the fleet's.

Model (grounded in *Improving Efficiency in Large-Scale Decentralized
Distributed Training*, arXiv:2002.01119, for stale-tolerant mixing, and
*Local SGD with Periodic Averaging*, arXiv:1910.13598, for when it is
safe to communicate less):

* **Push, don't pull.**  Each round an agent PUSHES its current value to
  every neighbor as an :class:`~distributed_learning_tpu.comm.protocol.
  AsyncValue` frame (round- and generation-tagged) and mixes against
  whatever sits in its per-neighbor inbox — the **double buffer**:
  buffer A is the live value local compute runs on, buffer B is the last
  *received* neighbor state the wire keeps filling.
* **Arrival-anchored staleness.**  A neighbor's staleness is how many of
  MY rounds already mixed its standing value (0 = fresh this round), so
  round counters never need cross-agent alignment — a rejoiner's frames
  are immediately usable.  Stale values mix at weight ``w/(1+s)``; the
  decayed/dropped mass stays on the self edge so the mixing row still
  sums to one (mirroring
  :func:`~distributed_learning_tpu.ops.mixing.stale_weight_matrix`, the
  device-side program of the same model).
* **Hard staleness bound tau.**  Beyond ``tau`` the contribution is
  DROPPED (zero weight this round) and the neighbor is POKED — the
  re-request half of drop-and-re-request.  ``tau=0`` means synchronous:
  block until every neighbor delivered a value newer than the last round
  — the runtime degenerates to the lock-step protocol and is
  bit-identical to ``run_once``/``run_choco_once`` sequences.
* **Deadline-bounded waits.**  ``deadline_s`` caps any blocking wait; on
  expiry the missing neighbors are dropped for this round (sticky until
  their next frame arrives, so a dead peer is paid for once, not every
  round).

CHOCO-compressed rounds ride the same runtime with one twist: the
replicated public estimates (``x̂``) ARE the double buffer, and
corrections are deltas, so they must be applied **exactly once, in
order** — the inbox keeps a per-neighbor FIFO and a straggler's backlog
is drained in one catch-up batch (``tau=0`` applies exactly one per
round: the lock-step recurrence).  A round that got no correction from a
neighbor simply mixes against the standing estimates, which is why CHOCO
tolerates asynchrony so naturally.
"""

from __future__ import annotations

import asyncio
import dataclasses
import functools
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from distributed_learning_tpu.comm import protocol as P
from distributed_learning_tpu.comm.agent import (
    AgentStatus,
    ConsensusAgent,
    ShutdownError,
)
from distributed_learning_tpu.comm.tensor_codec import (
    DenseFrame,
    FusedFrame,
    SparseFrame,
)

__all__ = [
    "AsyncGossipRunner",
    "AsyncRoundStats",
    "QUARANTINE_PAYLOAD_KIND",
]

#: graftproto role annotation (tools/graftlint/proto_extract.py): the
#: protocol extractor recovers this module's send/handle message sets
#: (isinstance dispatch + ``P.<Class>(...)`` constructions) under this
#: role and cross-checks them against protocol.py's _REGISTRY.
PROTO_ROLE = "async_runner"

#: graftsched hot-coroutine annotation (tools/graftlint/schedsim.py):
#: the schedule explorer extracts the ordered await points of these
#: coroutines into the ``sched_model`` pin and permutes wakeup order at
#: each of them.  Every coroutine here must keep its timing loop-derived
#: (``asyncio.get_event_loop().time()``/``asyncio.sleep``) so the
#: virtual clock can drive ``deadline_s`` paths in simulated time.
SCHED_HOT = (
    "_push",
    "_answer_poke",
    "_poke",
    "_recv_step",
    "_handle_master",
    "_drain_ready",
    "_collect",
    "begin_round",
    "finish_round",
    "_mix_pipelined",
    "run_async_round",
    "_collect_choco",
    "run_async_choco",
)

#: ``payload["kind"]`` marking a Telemetry payload as a quarantine report
#: (runner -> master): ``{"kind": ..., "accused": token, "violations": n,
#: "round": r, "generation": g}``.  The master accumulates accusers per
#: accused token and, at quorum, evicts the peer and (with
#: ``regenerate=True``) excludes it from the next membership generation
#: (docs/robustness.md §Quarantine).
QUARANTINE_PAYLOAD_KIND = "robust.quarantine"


@dataclasses.dataclass
class AsyncRoundStats:
    """What one async round actually mixed (``runner.last_stats``)."""

    round: int = 0
    #: token -> staleness of the contribution mixed (0 = fresh).
    mixed: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: tokens whose contribution was dropped this round (staleness > tau
    #: or deadline expiry); their edge weight stayed on self.
    dropped: List[str] = dataclasses.field(default_factory=list)
    #: queued frames skipped: latest-wins consumption (plain rounds,
    #: tau > 0) or replayed corrections deduplicated by the exactly-once
    #: watermark (CHOCO rounds).
    skipped: int = 0
    #: corrections applied this round (CHOCO rounds), token -> count.
    applied: Dict[str, int] = dataclasses.field(default_factory=dict)


class _Inbox:
    """Per-neighbor receive state: the FIFO of unconsumed frames plus
    the standing (last mixed) value and its reuse count."""

    __slots__ = (
        "queue", "last", "times_mixed", "dropped", "choco_lag",
        "violations", "seen_gen", "seen_round", "seen_stale",
        "last_trace", "choco_applied_gen", "choco_applied_round",
    )

    def __init__(self):
        self.queue: deque = deque()  # (value, sender_round, staleness, trace)
        self.last: Optional[np.ndarray] = None
        # TraceContext of the frame `last` came from (None untraced):
        # consumed by the first mix of that frame — the "mix" hop that
        # closes its flow chain in the merged trace.
        self.last_trace = None
        self.times_mixed = 0  # rounds `last` was already mixed
        self.dropped = False  # sticky: dropped until a fresh arrival
        self.choco_lag = 0  # consecutive rounds without a correction
        # Wire-field validation state (docs/robustness.md §Validation):
        # violation tally + the last accepted (generation, round,
        # staleness) — round ids must be monotone per neighbor within a
        # generation, staleness monotone within a round (re-pushes age).
        self.violations = 0
        self.seen_gen: Optional[int] = None
        self.seen_round = -1
        self.seen_stale = -1
        # Exactly-once CHOCO accounting: the newest sender round whose
        # correction was APPLIED (within choco_applied_gen).  A replayed
        # frame — a dup, or a poke-triggered re-push of a round that
        # already landed through the normal path — carries a round id at
        # or below this watermark and must be counted, never re-applied:
        # corrections are deltas on the replicated estimate, so a second
        # apply corrupts x̂ for every subsequent round (the
        # ``choco-replay-apply`` spec mutation in
        # tools/graftlint/proto_spec.py models exactly this bug).
        self.choco_applied_gen: Optional[int] = None
        self.choco_applied_round = -1


class AsyncGossipRunner:
    """Drives asynchronous gossip rounds over a started
    :class:`~distributed_learning_tpu.comm.agent.ConsensusAgent`.

    Parameters
    ----------
    agent:
        A READY agent (handshake complete).  The runner owns the
        agent's receive path while its rounds run; do not interleave
        lock-step collectives (``run_once``/``run_round``) with async
        rounds without a quiescent point in between.
    staleness_bound:
        tau.  0 = synchronous (bit-identical to the lock-step path);
        k >= 1 mixes values up to k rounds old at ``w/(1+s)`` weight and
        drops older ones.
    deadline_s:
        Cap on any blocking wait for a required-fresh neighbor; expiry
        drops it for this round (sticky) and pokes it.  None = wait
        forever (pure bounded-staleness mode).
    validate_wire:
        Validate the protocol fields of every incoming
        :class:`~distributed_learning_tpu.comm.protocol.AsyncValue`
        (round ids monotone per neighbor within a generation, staleness
        monotone within a round, both non-negative and within
        ``round_slack`` of this runner's own round).  An honest runtime
        never trips these, so the default is on; a violating frame is
        dropped unmixed and the peer poked for a well-formed push.
    quarantine_after:
        Violations (per neighbor) before the peer is QUARANTINED: its
        stream is evicted, its edge weight renormalizes to self, and the
        master is notified via a :data:`QUARANTINE_PAYLOAD_KIND`
        telemetry payload so regeneration can exclude it.
    round_slack:
        Bound on how far ahead of this runner's own round counter a
        claimed ``round_id``/``staleness`` may run.  Generous on purpose
        — honest peers legitimately run ahead in bounded-staleness mode;
        the bound only has to catch absurd claims (a lying peer
        advertising round 10**18 to poison staleness accounting).
    overlap:
        Decode/compute overlap (zero-copy wire path, docs/wire.md
        §Zero-copy receive path).  Off (default): the dispatch loop
        densifies each arriving frame into the edge's scratch ravel at
        its service point.  On: frames stay lazy in the inbox and
        :meth:`finish_round` pipelines them — the NEXT neighbor's frame
        densifies on a worker thread (ctypes/numpy release the GIL)
        while the round task numpy-mixes the PREVIOUS one.  Mixing
        order and arithmetic are identical either way.
    """

    def __init__(
        self,
        agent: ConsensusAgent,
        *,
        staleness_bound: int = 0,
        deadline_s: Optional[float] = None,
        validate_wire: bool = True,
        quarantine_after: int = 3,
        round_slack: int = 100_000,
        overlap: bool = False,
    ):
        if staleness_bound < 0:
            raise ValueError(
                f"staleness_bound must be >= 0, got {staleness_bound}"
            )
        if quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got {quarantine_after}"
            )
        self.agent = agent
        self.tau = int(staleness_bound)
        self.deadline_s = (
            None if deadline_s is None else float(deadline_s)
        )
        self.validate_wire = bool(validate_wire)
        self.quarantine_after = int(quarantine_after)
        self.round_slack = int(round_slack)
        self.overlap = bool(overlap)
        self._round = 0
        self._inbox: Dict[str, _Inbox] = {}
        self._pub_value: Optional[np.ndarray] = None
        self._pub_round = 0
        self._poked: set = set()
        self._quarantined: set = set()
        # Per-edge decode scratch pool (zero-copy receive path): token ->
        # ONE idle f32 ravel awaiting the edge's next frame.  A buffer
        # leaves the pool at the dispatch service point (decode target),
        # rides the inbox as the decoded value, and re-enters the pool —
        # adopt-on-supersede — when the round task replaces it as the
        # standing value (or applies it, for CHOCO corrections).  All
        # hand-offs run on the round task's turns, which is exactly the
        # claim the two task-shared-mutation suppressions below carry
        # and graftlint --sched verifies on every explored schedule.
        # Evicted wholesale on membership realignment and per-edge on
        # quarantine: a stale-sized buffer must miss, never corrupt.
        self._scratch: Dict[str, np.ndarray] = {}
        self._decode_pool = None  # 1-thread executor, built on first use
        # In-flight detached value sends (_send_detached): tracked so a
        # late failure is still silenced/observed, bounded by the round
        # structure itself (a round cannot finish without the neighbors
        # it pushed to making progress of their own).
        self._send_tasks: set = set()
        self.last_stats = AsyncRoundStats()

    # ------------------------------------------------------------------ #
    @property
    def round(self) -> int:
        """Completed async rounds."""
        return self._round

    def _box(self, token: str) -> _Inbox:
        box = self._inbox.get(token)
        if box is None:
            box = self._inbox[token] = _Inbox()
        return box

    @property
    def quarantined(self) -> frozenset:
        """Tokens this runner has quarantined (their edges renormalize
        to self until the master regenerates the topology without them)."""
        return frozenset(self._quarantined)

    def _active(self) -> List[str]:
        """Weighted neighbors with a live stream, sorted (mixing
        accumulates in this order on every agent — deterministic, and
        the tau=0 oracle against the lock-step path can be bit-exact).
        Quarantined peers are excluded even if a replacement stream
        reappears: only a membership regeneration can readmit them."""
        a = self.agent
        return sorted(
            t for t in a._weights
            if t in a._neighbors and t not in self._quarantined
        )

    # ------------------------------------------------------------------ #
    # Decode scratch pool (docs/wire.md §Zero-copy receive path)         #
    # ------------------------------------------------------------------ #
    def _scratch_buf(
        self, token: str, buf: Optional[np.ndarray], size: int
    ) -> np.ndarray:
        """Account and return a decode target for ``token``'s next
        frame: the pool buffer the caller popped when it fits
        (``comm.wire.scratch_hits``), else a fresh ravel (misses — the
        first two frames of an edge, and any size change).  Each bump
        lands twice: the bare run total and a per-edge labeled copy
        under the frame's inbound direction (``<peer>-><self>``, the
        same convention as ``comm.edge.*``) so the ``obs-report
        --merge`` edge table can attribute pool behavior per link."""
        a = self.agent
        edge = f"{token}->{a.token}"
        if buf is not None and buf.size == size:
            a._count_wire("scratch_hits")
            a._count_wire(f"scratch_hits/{edge}")
        else:
            buf = np.empty(size, np.float32)
            a._count_wire("scratch_misses")
            a._count_wire(f"scratch_misses/{edge}")
        a._count_wire("scratch_bytes", 4 * size)
        a._count_wire(f"scratch_bytes/{edge}", 4 * size)
        return buf

    def _densify_dispatch(
        self, token: str, value: Any, buf: Optional[np.ndarray]
    ) -> np.ndarray:
        """Serial-mode dispatch decode: densify an arriving dense/sparse
        frame into the edge's scratch ravel.  Direct-injected ndarrays
        (tests drive ``_handle_peer_msg`` without the wire) are copied
        into a runner-owned buffer too, so adopt-on-supersede can never
        recycle caller memory into the pool."""
        if isinstance(value, np.ndarray):
            v = np.ascontiguousarray(value, np.float32).ravel()
            out = self._scratch_buf(token, buf, v.size)
            np.copyto(out, v)
            return out
        return value.densify(out=self._scratch_buf(token, buf, value.size))

    def _recycle(self, token: str, old: Any, new: Any) -> None:
        """Adopt a superseded decode buffer back into the pool (single
        idle slot per edge; ``setdefault`` keeps an existing idle buffer
        and simply drops the extra)."""
        if (
            old is not None
            and old is not new
            and isinstance(old, np.ndarray)
            and old.ndim == 1
            and old.dtype == np.float32
            and old.flags.c_contiguous
            and old.flags.writeable
        ):
            self._scratch.setdefault(token, old)

    def _decode_executor(self):
        """The overlap mode's single decode worker, built lazily (a
        serial runner never spawns a thread)."""
        if self._decode_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._decode_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dlt-decode"
            )
        return self._decode_pool

    # ------------------------------------------------------------------ #
    # Wire-field validation + quarantine (docs/robustness.md)            #
    # ------------------------------------------------------------------ #
    def _validate_async_fields(self, token: str, msg: Any) -> bool:
        """Check an AsyncValue's protocol fields against the per-neighbor
        history: non-negative, round ids monotone within a generation
        (an honest peer's counter never runs backwards; a rejoin resets
        it WITH a generation bump), staleness monotone for re-pushes of
        the same round, and both within ``round_slack`` of our own round
        (arrival-anchored staleness never needs alignment, so the bound
        only rejects absurd claims).  Accepting updates the history."""
        box = self._box(token)
        if box.seen_gen != msg.generation:
            # New membership generation: the peer's counter legitimately
            # restarts (rejoin/replacement); reset the monotonicity base.
            box.seen_gen = msg.generation
            box.seen_round = -1
            box.seen_stale = -1
        bound = self._round + self.round_slack
        ok = (
            msg.round_id >= 0
            and msg.staleness >= 0
            and msg.round_id >= box.seen_round
            and not (
                msg.round_id == box.seen_round
                and msg.staleness < box.seen_stale
            )
            and msg.round_id <= bound
            and msg.staleness <= bound
        )
        if ok:
            box.seen_round = msg.round_id
            box.seen_stale = msg.staleness
        return ok

    def _on_violation(self, token: str) -> None:
        """One protocol violation from ``token``: the frame was already
        dropped unmixed; tally it, poke for a well-formed push
        (drop-and-poke), and quarantine at the threshold."""
        a = self.agent
        box = self._box(token)
        box.violations += 1
        a._count("async_field_violations")
        if box.violations >= self.quarantine_after:
            self._quarantine(token)
        else:
            task = asyncio.ensure_future(self._poke(token))
            task.add_done_callback(a._silence)

    def _quarantine(self, token: str) -> None:
        """Evict a repeatedly-violating peer: purge its inbox (its edge
        weight renormalizes to self exactly like a dropped straggler's),
        close its stream, and notify the master with a
        :data:`QUARANTINE_PAYLOAD_KIND` telemetry payload so
        regeneration can exclude it from the next generation."""
        a = self.agent
        if token in self._quarantined:
            return
        self._quarantined.add(token)
        box = self._box(token)
        box.queue.clear()
        box.last = None
        box.dropped = True
        self._scratch.pop(token, None)  # the edge's decode buffer dies too
        a._mux.remove(token)
        stream = a._neighbors.pop(token, None)
        if stream is not None:
            stream.close()
        a._count("async_quarantines")
        task = asyncio.ensure_future(
            a.send_telemetry(
                {
                    "kind": QUARANTINE_PAYLOAD_KIND,
                    "accused": token,
                    "violations": box.violations,
                    "round": self._round,
                    "generation": a._generation,
                }
            )
        )
        task.add_done_callback(a._silence)

    # ------------------------------------------------------------------ #
    # Wire I/O (the dispatch loop; graftlint host-sync-in-hot-path       #
    # covers these — values stay numpy, no device syncs)                 #
    # ------------------------------------------------------------------ #
    async def _push(self, value: np.ndarray, staleness: int = 0) -> None:
        """Ship the current value to every active neighbor (the
        unsolicited push half of the runtime)."""
        a = self.agent
        if a._fused_spans is not None:
            # Fused CHOCO push (run_async_choco(buckets=...)): the whole
            # correction ships as ONE fused frame — the receiver applies
            # it straight onto its replicated estimate, no densify.
            kind = P._ASYNC_FUSED
        elif a.sparse_wire and a._sparse_wins(value):
            kind = P._ASYNC_SPARSE
        else:
            kind = P._ASYNC_DENSE
        msg = P.AsyncValue(
            round_id=self._round, generation=a._generation,
            staleness=staleness, value=value, kind=kind,
            buckets=a._fused_spans,
            bf16_wire=a.bf16_wire, int8_wire=a._int8_active,
        )
        a._count("async_pushes")
        for token in self._active():
            # Trace stamping is per NEIGHBOR (the edge label and seq
            # differ per destination): replace on the shared base frame.
            out = a._stamp_trace(msg, token)
            self._send_detached(token, out)

    def _send_detached(self, token: str, out) -> None:
        """Ship one frame to ``token`` on a detached (tracked) task.

        The round task must never await a neighbor's socket drain: it is
        also the mux pump (``_recv_step``) that re-arms this agent's
        reads.  When every agent pushes a frame larger than the kernel's
        socket buffers at once, synchronous sends form a cycle — each
        round task parked in ``drain()``, nobody pumping reads, every
        reader idle — and the deployment deadlocks (observed at ~2 MB
        frames on loopback; full model width is ~146 MB).  Detached
        sends keep FIFO order per edge (the framer's ``_send_lock``
        wakes waiters in acquisition order) and let the pump resume
        immediately; a failed send marks the edge dropped exactly as the
        inline path did."""
        a = self.agent
        framer = a._neighbors[token]

        async def _send_one():
            try:
                await framer.send(out)
            except (ConnectionError, OSError):
                self._box(token).dropped = True
                return
            if out.trace is not None:
                a._emit_flow("send", out.trace, f"{a.token}->{token}")

        task = asyncio.ensure_future(_send_one())
        self._send_tasks.add(task)
        task.add_done_callback(self._send_tasks.discard)
        task.add_done_callback(a._silence)

    async def _answer_poke(self, token: str) -> None:
        """Re-send the standing published value to a poked-by neighbor
        (best effort; nothing published yet means nothing to send)."""
        a = self.agent
        if self._pub_value is None or token not in a._neighbors:
            return
        a._count("pokes_answered")
        if a._fused_spans is not None:
            # Poke answered inside the fused-push window: same bytes as
            # the push.  Outside it, the standing (already wire-rounded)
            # value re-encodes sparse/dense — narrowing is idempotent,
            # so a CHOCO replay carries identical values and the
            # exactly-once watermark dedups it.
            kind = P._ASYNC_FUSED
        elif a.sparse_wire and a._sparse_wins(self._pub_value):
            kind = P._ASYNC_SPARSE
        else:
            kind = P._ASYNC_DENSE
        msg = a._stamp_trace(
            P.AsyncValue(
                round_id=self._pub_round, generation=a._generation,
                staleness=self._round - self._pub_round,
                value=self._pub_value, kind=kind,
                buckets=a._fused_spans,
                bf16_wire=a.bf16_wire, int8_wire=a._int8_active,
            ),
            token,
        )
        try:
            await a._neighbors[token].send(msg)
        except (ConnectionError, OSError):
            return
        if msg.trace is not None:
            a._emit_flow("send", msg.trace, f"{a.token}->{token}")

    async def _poke(self, token: str) -> None:
        """The re-request half of drop-and-re-request: ask a
        staleness-bound-exceeded neighbor for a fresh push.  One poke
        per staleness excursion (cleared when its next frame lands).

        Shipped detached for the same reason value pushes are: the
        framer's send lock may be held by an in-flight multi-MB frame
        whose receiver has stopped reading (a peer past its last
        round), and an inline ``send`` would park the round task behind
        that drain forever — the deadline loop never expires and the
        round never finishes."""
        a = self.agent
        if token in self._poked or token not in a._neighbors:
            return
        self._poked.add(token)
        a._count("pokes_sent")
        self._send_detached(
            token,
            P.AsyncPoke(round_id=self._round, generation=a._generation),
        )

    async def _recv_step(self, timeout: Optional[float]) -> bool:
        """Receive + handle ONE message from the master or any neighbor;
        False on timeout.  The persistent-task discipline of the agent
        is kept: an in-flight frame read is never cancelled."""
        a = self.agent
        if a._master_task is None and a._master is not None:
            a._master_task = asyncio.ensure_future(a._master.recv())
            a._master_task.add_done_callback(a._silence)
        if a._mux_task is None:
            a._mux_task = asyncio.ensure_future(a._mux.__anext__())
        tasks = {t for t in (a._master_task, a._mux_task) if t is not None}
        done, _ = await asyncio.wait(
            tasks, timeout=timeout, return_when=asyncio.FIRST_COMPLETED
        )
        if not done:
            return False
        if a._master_task is not None and a._master_task in done:
            task, a._master_task = a._master_task, None
            await self._handle_master(task.result())
            return True
        token, msg, src = a._mux_task.result()
        a._mux_task = None
        self._handle_peer_msg(token, msg, src)
        return True

    async def _handle_master(self, msg: Any) -> None:
        a = self.agent
        if isinstance(msg, P.NeighborhoodData):
            # Membership generation broadcast: realign weights/streams;
            # inboxes of removed edges die with their streams, and the
            # WHOLE decode scratch pool is evicted — replacement peers
            # may publish a different model width, and a stale-sized
            # buffer must cost one miss, never a corrupt decode.
            await a._apply_neighborhood(msg)
            # graftlint: disable=task-shared-mutation -- generation-realignment turn discipline: _handle_master runs inside the round task's own _recv_step await, so no pipelined decode is writing into a pooled buffer while the pool empties (the round task is HERE, not in _mix_pipelined) and the next dispatch simply takes misses
            self._scratch.clear()
            for token in list(self._inbox):
                if token not in a._weights:
                    # graftlint: disable=task-shared-mutation -- membership turn discipline: _handle_master runs inside the round task's own _recv_step await (never concurrently with _consume/_mix_plain, which only run after _collect returns), so evicting a removed edge's inbox here cannot race the round's reads
                    del self._inbox[token]
        elif isinstance(msg, P.Shutdown):
            a.status = AgentStatus.SHUTDOWN
            raise ShutdownError(msg.reason)
        # else: round-lifecycle traffic of the lock-step protocol —
        # stale here, dropped.

    def _handle_peer_msg(self, token: str, msg: Any, src: Any) -> None:
        a = self.agent
        if msg is None:
            cur = a._neighbors.get(token)
            if token not in a._weights or (cur is not None and cur is not src):
                return  # removed edge or an already-replaced stream
            # Neighbor died: the async runtime tolerates it — its edge
            # is dropped (sticky) until a replacement pushes; the
            # membership generation machinery heals the stream set.
            a._neighbors.pop(token, None)
            a._count("async_neighbor_deaths")
            self._box(token).dropped = True
            return
        if isinstance(msg, P.AsyncValue):
            if token in self._quarantined:
                a._count("async_quarantined_dropped")
                return
            if msg.generation != a._generation:
                a._count("async_gen_dropped")
                return
            if self.validate_wire and not self._validate_async_fields(
                token, msg
            ):
                self._on_violation(token)
                return
            value = msg.value
            if not self.overlap and not isinstance(value, FusedFrame):
                # Serial mode: densify dense/sparse frames HERE, into
                # the edge's scratch ravel — one pinned buffer per peer
                # stream instead of an allocation per frame.  Fused
                # frames stay lazy in either mode: the CHOCO consume
                # applies their sections straight onto the replicated
                # estimate.  In overlap mode everything stays lazy and
                # _mix_pipelined decodes off the event loop.
                # graftlint: disable=task-shared-mutation -- scratch-pool turn discipline: every pop of an idle decode buffer runs on one of the round task's own turns (dispatch executes inside its _recv_step await; pipelined decode pops on the round task itself), and a buffer only re-enters the pool after that same task supersedes the value decoded into it, so no other task ever holds or writes these buffers
                buf = self._scratch.pop(token, None)
                value = self._densify_dispatch(token, value, buf)
            box = self._box(token)
            box.queue.append(
                (value, msg.round_id, msg.staleness, msg.trace)
            )
            box.dropped = False
            if a.trace and msg.trace is not None:
                # Receiver half of the traced frame: recv+decode hops
                # (the frame body was decoded by the recv that produced
                # msg) plus the edge's wall-clock transit latency.
                edge = f"{token}->{a.token}"
                a._emit_flow("recv", msg.trace, edge)
                a._emit_flow("decode", msg.trace, edge)
                if msg.trace.t_wall:
                    # graftlint: disable=wallclock-duration -- cross-process edge latency: t_wall is the SENDER's wall-clock send stamp; monotonic clocks cannot compare across processes
                    lat = time.time() - msg.trace.t_wall
                    a._observe(f"comm.edge.latency_s/{edge}", lat)
            # graftlint: disable=task-shared-mutation -- arrival-clears-excursion FIFO discipline: the discard runs at the single dispatch service point (inside the round task's _recv_step await), and _poke only re-adds after _collect has re-checked _needs_fresh on the post-arrival state
            self._poked.discard(token)
            a._count("async_values_received")
        elif isinstance(msg, P.AsyncPoke):
            if token in self._quarantined:
                a._count("async_quarantined_dropped")
                return
            a._count("pokes_received")
            # Answer at this service point (we are inside the dispatch
            # loop already): schedule the re-push.
            task = asyncio.ensure_future(self._answer_poke(token))
            task.add_done_callback(a._silence)
        # else: lock-step frames (ValueRequest/...) — not part of an
        # async run; dropped.

    # ------------------------------------------------------------------ #
    # Plain (uncompressed) async rounds                                  #
    # ------------------------------------------------------------------ #
    def _needs_fresh(self, token: str) -> bool:
        """Whether the round must wait for a new frame from ``token``:
        nothing usable is queued AND the standing value would exceed the
        staleness bound (never-arrived counts as infinitely stale), AND
        it has not already been dropped this excursion."""
        box = self._box(token)
        if box.queue or box.dropped:
            return False
        return box.last is None or box.times_mixed > self.tau

    async def _drain_ready(self) -> None:
        """Dispatch every ALREADY-COMPLETED read before computing the
        round's requirements.  Sticky drops only clear at dispatch, so
        a round that requires nothing (every neighbor dropped, or all
        within tau) must still consume what the persistent reader tasks
        finished while the round task was elsewhere — otherwise a
        fully-dropped excursion never polls the mux again and the
        poke/re-push recovery path is a lost wakeup: frames pile up
        parsed-but-undelivered while every round free-runs on self."""
        while await self._recv_step(0):
            pass

    async def _collect(self) -> None:
        """Wait (deadline-bounded) until no active neighbor is required
        to deliver a fresh frame; expiry drops the stragglers for this
        round and pokes them."""
        a = self.agent
        await self._drain_ready()
        deadline = (
            None if self.deadline_s is None
            else asyncio.get_event_loop().time() + self.deadline_s
        )
        while True:
            required = [t for t in self._active() if self._needs_fresh(t)]
            if not required:
                return
            timeout = None
            if deadline is not None:
                timeout = deadline - asyncio.get_event_loop().time()
                if timeout <= 0:
                    for t in required:
                        self._box(t).dropped = True
                        a._count("async_deadline_drops")
                        await self._poke(t)
                    return
            if not await self._recv_step(timeout):
                continue  # deadline re-checked at the loop head

    def _consume(
        self, token: str, stats: AsyncRoundStats, *, densify: bool = True
    ) -> _Inbox:
        """Advance ``token``'s inbox for this round: tau=0 consumes the
        OLDEST unread frame (lock-step order — exactly one frame per
        sender round), tau>0 jumps to the latest (mix the newest
        information, count the skips).  The superseded standing buffer
        re-enters the scratch pool (adopt-on-supersede); a still-lazy
        payload densifies into edge scratch here unless the pipelined
        mixer (``densify=False``) is about to decode it off-loop."""
        box = self._box(token)
        if box.queue:
            if self.tau == 0:
                value, _, sent_stale, trace = box.queue.popleft()
            else:
                stats.skipped += len(box.queue) - 1
                value, _, sent_stale, trace = box.queue[-1]
                box.queue.clear()
            if densify and not isinstance(value, np.ndarray):
                # A FUSED push consumed by a plain round (deployment
                # mismatch — tolerated, the frame is self-describing):
                # densify on the round task.  _consume is round-owned,
                # so the pool hand-off needs no suppression here.
                buf = self._scratch.pop(token, None)
                value = value.densify(
                    out=self._scratch_buf(token, buf, value.size)
                )
            self._recycle(token, box.last, value)
            box.last = value
            box.last_trace = trace
            box.times_mixed = 0
            box.dropped = False
        return box

    def _mix_plain(self, y: np.ndarray) -> np.ndarray:
        """The stale-weighted mixing update, accumulated in sorted-token
        order: fresh neighbors at full weight, stale ones at
        ``w/(1+s)`` with the difference on self, dropped ones fully on
        self — the host-side twin of the fused device program
        (``ops.mixing.stale_weight_matrix``); rows always sum to 1."""
        a = self.agent
        stats = self.last_stats
        total_w = sum(a._weights.values())
        out = (1.0 - total_w) * y
        for token in sorted(a._weights):
            w = a._weights[token]
            box = self._consume(token, stats)
            s = box.times_mixed
            usable = (
                box.last is not None and not box.dropped and s <= self.tau
            )
            if not usable:
                stats.dropped.append(token)
                a._count("async_stale_dropped")
                out = out + w * y  # dropped mass renormalizes to self
            elif s == 0:
                stats.mixed[token] = 0
                out = out + w * box.last
            else:
                stats.mixed[token] = s
                a._count("async_stale_mixed")
                w_eff = w / (1.0 + s)
                out = out + w_eff * box.last + (w - w_eff) * y
            if usable and s == 0 and box.last_trace is not None:
                # First mix of this frame closes its flow chain; stale
                # re-mixes of the standing value don't re-emit.
                a._emit_flow("mix", box.last_trace, f"{token}->{a.token}")
                box.last_trace = None
            box.times_mixed += 1
            stale_pt = float(s if usable else self.tau + 1)
            a._observe("comm.agent.staleness", stale_pt, step=self._round)
            a._observe(
                f"comm.edge.staleness/{token}->{a.token}",
                stale_pt, step=self._round,
            )
        return out

    async def _mix_pipelined(self, y: np.ndarray) -> np.ndarray:
        """Overlap-mode twin of :meth:`_mix_plain`: identical queue
        discipline, accumulation order, and arithmetic, but the inbox
        still holds LAZY frames (dispatch skipped the densify), so each
        frame decodes into edge scratch on the single worker thread
        (``loop.run_in_executor`` — the ctypes engine and numpy release
        the GIL) while the round task numpy-mixes the PREVIOUS
        neighbor's contribution.  At most two decodes are in flight:
        one running, one queued behind it.  The decoded array replaces
        ``box.last`` so stale re-mixes in later rounds never re-decode.
        """
        a = self.agent
        loop = asyncio.get_event_loop()
        stats = self.last_stats
        tokens = sorted(a._weights)
        # Stage 1 (sync, round task): advance every inbox — the frames
        # to decode this round, in mixing order.
        boxes = {t: self._consume(t, stats, densify=False) for t in tokens}
        jobs = [
            t for t in tokens if not isinstance(
                boxes[t].last, (np.ndarray, type(None))
            )
        ]
        inflight: Dict[str, Any] = {}
        nxt = 0

        def _submit(t: str) -> None:
            frame = boxes[t].last
            # Round-task turn: the pool hand-off happens HERE, not on
            # the worker — the thread only ever writes the buffer it
            # was handed (the scratch-pool turn-discipline claim).
            buf = self._scratch.pop(t, None)
            buf = self._scratch_buf(t, buf, frame.size)
            inflight[t] = loop.run_in_executor(
                self._decode_executor(),
                functools.partial(frame.densify, out=buf),
            )

        if jobs:
            _submit(jobs[0])
            nxt = 1
        total_w = sum(a._weights.values())
        out = (1.0 - total_w) * y
        for token in tokens:
            box = boxes[token]
            if token in inflight:
                # Keep the pipe full BEFORE blocking on this decode.
                while nxt < len(jobs) and len(inflight) < 2:
                    _submit(jobs[nxt])
                    nxt += 1
                box.last = await inflight.pop(token)
            w = a._weights[token]
            s = box.times_mixed
            usable = (
                box.last is not None and not box.dropped and s <= self.tau
            )
            if not usable:
                stats.dropped.append(token)
                a._count("async_stale_dropped")
                out = out + w * y
            elif s == 0:
                stats.mixed[token] = 0
                out = out + w * box.last
            else:
                stats.mixed[token] = s
                a._count("async_stale_mixed")
                w_eff = w / (1.0 + s)
                out = out + w_eff * box.last + (w - w_eff) * y
            if usable and s == 0 and box.last_trace is not None:
                a._emit_flow("mix", box.last_trace, f"{token}->{a.token}")
                box.last_trace = None
            box.times_mixed += 1
            stale_pt = float(s if usable else self.tau + 1)
            a._observe("comm.agent.staleness", stale_pt, step=self._round)
            a._observe(
                f"comm.edge.staleness/{token}->{a.token}",
                stale_pt, step=self._round,
            )
        return out

    async def begin_round(self, value: np.ndarray) -> None:
        """Open an async round: advance the round counter and push the
        value.  Run local compute between ``begin_round`` and
        ``finish_round`` — the wire fills the inbox (buffer B) while the
        device works on buffer A."""
        a = self.agent
        if a.status not in (AgentStatus.READY, AgentStatus.IN_ROUND):
            raise RuntimeError(f"agent not ready (status={a.status})")
        self._round += 1
        self.last_stats = AsyncRoundStats(round=self._round)
        y = np.asarray(value, dtype=np.float32).ravel()
        self._pub_value, self._pub_round = y, self._round
        a._count("async_rounds")
        await self._push(y)

    async def finish_round(self) -> np.ndarray:
        """Close the round: deadline-bounded collect, then the
        stale-weighted mix of the published value against the inbox
        (pipelined with the neighbor decodes in ``overlap`` mode)."""
        a = self.agent
        t0 = time.perf_counter()
        await self._collect()
        if self.overlap:
            out = await self._mix_pipelined(self._pub_value)
        else:
            out = self._mix_plain(self._pub_value)
        a._observe(
            "comm.agent.async_round_s",
            time.perf_counter() - t0, step=self._round,
        )
        return out

    async def run_async_round(
        self,
        value: np.ndarray,
        *,
        local: Optional[Callable[[], Any]] = None,
    ) -> np.ndarray:
        """One full async gossip round; with ``local`` given, the
        callable runs between push and collect — overlapping local
        compute with the wire exchange (its result, if awaitable, is
        awaited and stored on ``self.last_local``)."""
        await self.begin_round(value)
        if local is not None:
            result = local()
            if asyncio.iscoroutine(result) or isinstance(
                result, asyncio.Future
            ):
                result = await result
            self.last_local = result
        return await self.finish_round()

    # ------------------------------------------------------------------ #
    # CHOCO (compressed) async rounds                                    #
    # ------------------------------------------------------------------ #
    def _needs_correction(self, token: str) -> bool:
        box = self._box(token)
        if box.queue or box.dropped:
            return False
        return box.choco_lag >= self.tau if self.tau > 0 else True

    async def _collect_choco(self) -> None:
        a = self.agent
        await self._drain_ready()
        deadline = (
            None if self.deadline_s is None
            else asyncio.get_event_loop().time() + self.deadline_s
        )
        while True:
            required = [
                t for t in self._active() if self._needs_correction(t)
            ]
            if not required:
                return
            timeout = None
            if deadline is not None:
                timeout = deadline - asyncio.get_event_loop().time()
                if timeout <= 0:
                    for t in required:
                        self._box(t).dropped = True
                        a._count("async_deadline_drops")
                        await self._poke(t)
                    return
            if not await self._recv_step(timeout):
                continue

    async def run_async_choco(
        self,
        value: np.ndarray,
        compressor: Callable[[np.ndarray], np.ndarray],
        *,
        gamma: float = 0.3,
        buckets: Optional[Tuple] = None,
    ) -> np.ndarray:
        """One asynchronous CHOCO-GOSSIP round: push the compressed
        correction ``q = C(x - x̂_self)``, apply whatever neighbor
        corrections have arrived (exactly once each, in order — the
        replicated-estimate contract), and step the iterate against the
        standing estimates.

        ``tau=0`` blocks for exactly one correction per neighbor per
        round and is bit-identical to the lock-step
        :meth:`~distributed_learning_tpu.comm.agent.ConsensusAgent.
        run_choco_once` sequence; ``tau>0`` lets a straggler's
        correction stream lag up to tau rounds (its backlog is drained
        in one batch when it catches up), and a deadline expiry simply
        proceeds on the standing estimates — a CHOCO round without a
        fresh correction is still exact.

        ``buckets`` (``TreeSpec.dtype_buckets()`` spans) engages the
        fused sparse wire under ``sparse_wire``: the correction ships
        as ONE fused frame per neighbor (``_ASYNC_FUSED``), and an
        arriving fused correction scatter-adds straight onto the
        replicated estimate (``FusedFrame.apply_into``) with no dense
        intermediate — the zero-copy consume path.  All agents of a
        deployment must agree on ``buckets`` (the usual TreeSpec
        deployment invariant).
        """
        a = self.agent
        x = a._choco_begin(value, require_aligned=False)
        self._round += 1
        self.last_stats = AsyncRoundStats(round=self._round)
        a._count("async_choco_rounds")
        q = np.asarray(
            compressor(x - a._choco_hat_self), np.float32
        ).ravel()
        a._int8_active = a.int8_wire
        if buckets is not None and a.sparse_wire:
            a._fused_spans = tuple(buckets)
        try:
            q = a._wire_round(q)
            self._pub_value, self._pub_round = q, self._round
            await self._push(q)
        finally:
            a._int8_active = False
            a._fused_spans = None
        a._choco_hat_self = a._choco_hat_self + q
        for t in a._weights:
            a._choco_hat_nbrs.setdefault(t, np.zeros_like(x))
        await self._collect_choco()
        stats = self.last_stats
        out = x.copy()
        for token in sorted(a._weights):
            box = self._box(token)
            applied = 0
            if box.queue:
                if self.tau == 0:
                    batch = [box.queue.popleft()]
                else:
                    batch = list(box.queue)
                    box.queue.clear()
                if box.choco_applied_gen != a._generation:
                    # New membership generation: the peer's correction
                    # counter legitimately restarts with its round ids.
                    box.choco_applied_gen = a._generation
                    box.choco_applied_round = -1
                for qn, q_round, _, qtrace in batch:
                    if q_round <= box.choco_applied_round:
                        # Replayed correction (a dup, or a poke-answer
                        # re-push of an already-applied round): count
                        # it, never apply — a correction is a delta on
                        # the replicated estimate and must land exactly
                        # once (the choco-replay-apply contract).
                        a._count("async_choco_replay_skipped")
                        stats.skipped += 1
                        self._recycle(token, qn, None)
                        continue
                    box.choco_applied_round = q_round
                    if isinstance(qn, FusedFrame):
                        # Zero-copy consume: the frame's sections
                        # scatter-add straight onto the replicated
                        # estimate (validated at unpack; a CodecError
                        # can no longer happen here).
                        a._apply_fused(qn, a._choco_hat_nbrs[token])
                    else:
                        a._choco_hat_nbrs[token] = a._choco_hat_nbrs[
                            token
                        ] + np.asarray(qn, np.float32).ravel()
                        # The applied correction buffer is dead — back
                        # to the pool for this edge's next frame.
                        self._recycle(token, qn, None)
                    applied += 1
                    if a.trace and qtrace is not None:
                        # Applying the correction is this frame's mix hop.
                        a._emit_flow(
                            "mix", qtrace, f"{token}->{a.token}"
                        )
            if applied:
                box.choco_lag = 0
                box.dropped = False
                stats.applied[token] = applied
                if applied > 1:
                    a._count("async_choco_catchup", applied - 1)
            else:
                # No NEW correction this round (empty queue, or a batch
                # of pure replays): mix against the standing estimates.
                box.choco_lag += 1
                a._count("async_stale_dropped")
                stats.dropped.append(token)
            a._observe(
                "comm.agent.staleness", float(box.choco_lag),
                step=self._round,
            )
            a._observe(
                f"comm.edge.staleness/{token}->{a.token}",
                float(box.choco_lag), step=self._round,
            )
            out += gamma * a._weights[token] * (
                a._choco_hat_nbrs[token] - a._choco_hat_self
            )
        return out
