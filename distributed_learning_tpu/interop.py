"""Torch interop: gossip-average existing ``torch.nn.Module`` replicas.

Migration path for users of the reference, whose models are all torch
(``utils/consensus_simple/mixer.py`` flattens torch parameters to numpy and
mixes with an O(N^2 * P) dense loop on the host, ``mixer.py:43-49,68-76``).
:class:`TorchModelMixer` keeps their models and training loops untouched:
it lifts ``named_parameters()`` into a numpy pytree, runs the mixing rounds
on the JAX device (MXU matmuls / ppermute — the same
:class:`~distributed_learning_tpu.parallel.consensus.Mixer` engine as the
native path), and copies the result back **in place**, so torch optimizer
state (momentum buffers keyed by parameter identity) survives mixing.

Matching the reference's semantics (SURVEY §7: "only params mix"): exactly
the *parameters* are averaged; buffers — BN running stats,
``num_batches_tracked`` — stay per-agent.

Torch is an optional dependency of this module only; nothing else in the
package imports it.
"""

from __future__ import annotations

from typing import Dict, Hashable, Mapping, Optional, Sequence

import numpy as np

from distributed_learning_tpu.parallel.consensus import Mixer

__all__ = ["TorchModelMixer"]


def _require_torch():
    try:
        import torch  # noqa: F401
    except ImportError as exc:  # pragma: no cover - torch is in this image
        raise ImportError(
            "TorchModelMixer needs torch; install it or use the native "
            "Mixer on JAX pytrees"
        ) from exc
    return torch


class TorchModelMixer:
    """Gossip-average the parameters of N torch model replicas.

    Parameters
    ----------
    models:
        ``{token: torch.nn.Module}`` — replicas of one architecture.
    topology:
        The reference's ``{agent: {neighbor: weight}}`` dict
        (``Man_Colab.ipynb`` cell 14) or an (n, n) mixing matrix.
    mesh / tokens / logger / max_rounds:
        Forwarded to the native :class:`Mixer`.

    ``mix(times, eps)`` has the reference ``Mixer.mix`` contract
    (``mixer.py:18-41``): run ``times`` rounds, or with ``eps`` keep going
    until the max across-agent deviation drops below it.  Parameters are
    updated in place under ``torch.no_grad()``.
    """

    def __init__(
        self,
        models: Mapping[Hashable, "object"],
        topology,
        *,
        tokens: Sequence[Hashable] | None = None,
        mesh=None,
        logger=None,
        max_rounds: int = 10_000,
    ):
        self._torch = _require_torch()
        self.models = dict(models)
        if not self.models:
            raise ValueError("models must be a non-empty mapping")
        first = next(iter(self.models.values()))
        sig = [(n, tuple(p.shape)) for n, p in first.named_parameters()]
        for tok, m in self.models.items():
            have = [(n, tuple(p.shape)) for n, p in m.named_parameters()]
            if have != sig:
                diff = [
                    f"{a[0]}{a[1]} vs {b[0]}{b[1]}"
                    for a, b in zip(sig, have) if a != b
                ] or [f"{len(sig)} vs {len(have)} parameters"]
                raise ValueError(
                    f"model {tok!r} parameters differ from the first "
                    f"replica ({'; '.join(diff[:3])}) — are these the same "
                    "architecture?"
                )
        self._names = [n for n, _ in sig]
        self._mixer = Mixer(
            {tok: self._pull(m) for tok, m in self.models.items()},
            topology,
            tokens=tokens,
            mesh=mesh,
            logger=logger,
            max_rounds=max_rounds,
        )

    # ------------------------------------------------------------------ #
    def _pull(self, model) -> Dict[str, np.ndarray]:
        return {
            name: p.detach().cpu().numpy().copy()
            for name, p in model.named_parameters()
        }

    def _push(self, model, tree: Mapping[str, np.ndarray]) -> None:
        torch = self._torch
        with torch.no_grad():
            for name, p in model.named_parameters():
                # .copy() both drops the read-only flag of JAX-backed
                # arrays (from_numpy warns on those) and detaches from the
                # device buffer.
                p.copy_(
                    torch.from_numpy(np.asarray(tree[name]).copy()).to(p.dtype)
                )

    def _resync(self) -> None:
        """Re-pull the torch parameters onto the device; the user trains
        between mixes, so every operation starts from the live models."""
        self._mixer.set_parameters(
            {t: self._pull(self.models[t]) for t in self._mixer.tokens}
        )

    # ------------------------------------------------------------------ #
    def mix(self, times: int = 1, eps: Optional[float] = None) -> int:
        """Pull current torch parameters, gossip on-device, write back."""
        self._resync()
        done = self._mixer.mix(times, eps)
        mixed = self._mixer.parameters()
        for tok in self._mixer.tokens:
            self._push(self.models[tok], mixed[tok])
        return done

    def get_parameters_deviation(self) -> Dict[Hashable, float]:
        """Across-agent deviation of the *current* torch parameters
        (parity: ``mixer.py:78-80``)."""
        self._resync()
        return self._mixer.get_parameters_deviation()

    def get_max_parameters_std(self) -> float:
        """Parity: ``mixer.py:82-84``."""
        self._resync()
        return self._mixer.get_max_parameters_std()
