"""Data pipelines: Titanic (tabular) and CIFAR-10/100 (vision)."""

from distributed_learning_tpu.data.titanic import (
    FEATURES,
    load_titanic,
    prepare_rows,
    split_data,
    synthetic_titanic,
    titanic_source,
)
from distributed_learning_tpu.data.prefetch import (
    epoch_batches,
    prefetch_to_device,
)
from distributed_learning_tpu.data.partition import (
    label_skew_shards,
    size_skew_shards,
)
from distributed_learning_tpu.data.cifar import (
    CIFAR_MEAN,
    CIFAR_STD,
    augment_batch,
    normalized_pad_value,
    load_cifar,
    normalize,
    shard_dataset,
    synthetic_cifar,
)

__all__ = [
    "FEATURES",
    "load_titanic",
    "prepare_rows",
    "split_data",
    "synthetic_titanic",
    "titanic_source",
    "CIFAR_MEAN",
    "CIFAR_STD",
    "augment_batch",
    "normalized_pad_value",
    "load_cifar",
    "normalize",
    "shard_dataset",
    "synthetic_cifar",
    "epoch_batches",
    "prefetch_to_device",
    "label_skew_shards",
    "size_skew_shards",
]
