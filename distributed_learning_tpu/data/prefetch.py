"""Host -> device input pipeline: background prefetch + epoch streaming.

The trainer's default layout keeps every shard device-resident and
gathers batches on device (``training/trainer.py``) — the right call at
the reference's CIFAR scale.  This module is the path for datasets that
do NOT fit in HBM: a host-side batch iterator whose next few batches
are staged onto the device (optionally with a ``NamedSharding``) by a
daemon thread while the current step computes, so the transfer rides
under the compute instead of serializing with it.

``jax.device_put`` is asynchronous: the thread only *initiates*
transfers, the bounded queue provides the lookahead window, and the
consumer blocks (if ever) on data that is usually already resident.
This is the JAX-idiomatic replacement for the torch ``DataLoader``
worker-pool pattern the reference's notebooks rely on
(``CIFAR_10_Baseline.ipynb`` uses torchvision loaders).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from distributed_learning_tpu.obs import get_registry

__all__ = ["prefetch_to_device", "epoch_batches"]

_SENTINEL = object()


def prefetch_to_device(
    iterator: Iterable[Any],
    *,
    size: int = 2,
    sharding: Optional[Any] = None,
) -> Iterator[Any]:
    """Yield items from ``iterator`` with ``size`` batches staged ahead.

    Each item (any pytree of arrays) is placed with ``jax.device_put``
    — onto ``sharding`` (a ``Sharding``/``NamedSharding``; arrays are
    laid out across the mesh while still in flight) or the default
    device.  Exceptions raised by the source iterator propagate to the
    consumer at the matching position; the daemon thread never outlives
    the consumer by more than the queue depth.

    Observability (obs/): ``data.prefetch.batches`` counts staged
    batches, ``data.prefetch.consumer_wait_s`` accumulates the seconds
    the consumer blocked on the queue (the "did the lookahead hide the
    transfer" signal — near zero means the pipeline kept up), and the
    ``data.prefetch.depth`` gauge samples the queue depth at each get.
    All host-side clock reads; the transfers themselves stay async.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    q: "queue.Queue[Any]" = queue.Queue(maxsize=size)
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded-wait put so an abandoned consumer (early `break`)
        # releases the thread instead of pinning size+1 staged device
        # batches until process exit.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in iterator:
                # device_put takes the whole pytree (sharding included).
                staged = jax.device_put(item, sharding) \
                    if sharding is not None else jax.device_put(item)
                if not _put(staged):
                    return
        except BaseException as e:  # propagate into the consumer
            _put((_SENTINEL, e))
            return
        _put((_SENTINEL, None))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    reg = get_registry()
    try:
        while True:
            reg.gauge("data.prefetch.depth", q.qsize())
            t_wait = time.perf_counter()
            item = q.get()
            reg.inc(
                "data.prefetch.consumer_wait_s",
                time.perf_counter() - t_wait,
            )
            if isinstance(item, tuple) and len(item) == 2 and \
                    item[0] is _SENTINEL:
                if item[1] is not None:
                    raise item[1]
                return
            reg.inc("data.prefetch.batches")
            yield item
    finally:
        stop.set()


def epoch_batches(
    X: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    *,
    seed: Optional[int] = None,
    drop_remainder: bool = True,
) -> Iterator[tuple]:
    """Shuffled ``(x_batch, y_batch)`` host batches for one epoch.

    Always shuffles: ``seed`` makes the permutation reproducible (pass
    the epoch number for a distinct deterministic order per epoch);
    ``seed=None`` draws a fresh one.  Host-side counterpart of the
    trainer's device-side permutation gather: a numpy permutation,
    contiguous slices, no copies beyond the batch fancy-index.  Compose with :func:`prefetch_to_device`::

        for xb, yb in prefetch_to_device(
            epoch_batches(X, y, 256, seed=epoch), size=2, sharding=s
        ):
            state = train_step(state, xb, yb)
    """
    n = X.shape[0]
    if y.shape[0] != n:
        raise ValueError(f"X has {n} rows but y has {y.shape[0]}")
    idx = np.arange(n)
    np.random.default_rng(seed).shuffle(idx)
    end = n - (n % batch_size) if drop_remainder else n
    for start in range(0, end, batch_size):
        take = idx[start:start + batch_size]
        yield X[take], y[take]
