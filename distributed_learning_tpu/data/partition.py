"""Non-IID partitioners: seeded label-skew and size-skew shards.

The reference deals IID shards only (``torch.utils.data.random_split``
with near-equal sizes, ``Man_Colab.ipynb`` cell 16 — re-implemented as
:func:`~distributed_learning_tpu.data.cifar.shard_dataset`); every
non-IID claim in the decentralized-learning literature starts from a
*skewed* deal instead.  This module provides the two standard skews as
pure-numpy, seed-deterministic partitioners with the same return
contract as ``shard_dataset`` (token -> ``(X, y)``, disjoint, covering):

* :func:`label_skew_shards` — per-agent class proportions drawn from a
  symmetric Dirichlet(alpha): alpha -> inf recovers IID, alpha -> 0
  gives near single-class agents (the FedAvg/SCAFFOLD benchmark
  convention).
* :func:`size_skew_shards` — geometric shard sizes (each agent ``ratio``
  times the previous), modelling heterogeneous data ownership; ratio=1
  recovers the near-equal deal.

Determinism: all randomness flows through one
``np.random.default_rng(seed)``, so the same ``(inputs, knobs, seed)``
reproduce the identical partition (pinned by ``tests/test_data.py``) —
the property the byzantine breakdown experiments need to be replayable.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Sequence, Tuple

import numpy as np

__all__ = ["label_skew_shards", "size_skew_shards"]


def _tokens(agents) -> List[Hashable]:
    toks = list(range(agents)) if isinstance(agents, int) else list(agents)
    if not toks:
        raise ValueError("need at least one agent")
    return toks


def _truncate(out, batch_size):
    if batch_size is not None:
        for tok, (xs, ys) in out.items():
            ln = (len(xs) // batch_size) * batch_size
            out[tok] = (xs[:ln], ys[:ln])
    return out


def label_skew_shards(
    X: np.ndarray,
    y: np.ndarray,
    agents: int | Sequence[Hashable],
    *,
    alpha: float = 0.5,
    min_per_agent: int = 1,
    seed: int = 0,
    batch_size: int | None = None,
) -> Dict[Hashable, Tuple[np.ndarray, np.ndarray]]:
    """Dirichlet label-skewed disjoint shards.

    For each class, its (shuffled) examples are split across agents by
    proportions drawn from Dirichlet(alpha, ..., alpha) — the standard
    non-IID federated benchmark deal.  Small ``alpha`` concentrates each
    class on few agents; large ``alpha`` approaches the IID deal.

    Raises ValueError when the draw leaves an agent with fewer than
    ``min_per_agent`` examples (retry with another seed or larger
    alpha) — an explicit failure beats a silently-empty shard feeding a
    degenerate gossip experiment.
    """
    if alpha <= 0.0:
        raise ValueError(f"alpha must be > 0, got {alpha}")
    toks = _tokens(agents)
    n = len(toks)
    y_arr = np.asarray(y)
    rng = np.random.default_rng(seed)
    per_agent: List[List[np.ndarray]] = [[] for _ in range(n)]
    for cls in np.unique(y_arr):
        idx = np.flatnonzero(y_arr == cls)
        rng.shuffle(idx)
        p = rng.dirichlet(np.full(n, float(alpha)))
        # Cumulative-proportion cut points; endpoints pinned so the
        # class's examples are dealt exactly once (disjoint, covering).
        cuts = np.round(np.cumsum(p) * len(idx)).astype(int)
        cuts[-1] = len(idx)
        for a, part in enumerate(np.split(idx, cuts[:-1])):
            per_agent[a].append(part)
    out: Dict[Hashable, Tuple[np.ndarray, np.ndarray]] = {}
    for a, tok in enumerate(toks):
        idx = np.concatenate(per_agent[a]) if per_agent[a] else np.array([], int)
        rng.shuffle(idx)  # mix classes within the shard
        if len(idx) < min_per_agent:
            raise ValueError(
                f"label_skew_shards(alpha={alpha}, seed={seed}) left agent "
                f"{tok!r} with {len(idx)} < {min_per_agent} examples; "
                "retry with a different seed or a larger alpha"
            )
        out[tok] = (np.asarray(X)[idx], y_arr[idx])
    return _truncate(out, batch_size)


def size_skew_shards(
    X: np.ndarray,
    y: np.ndarray,
    agents: int | Sequence[Hashable],
    *,
    ratio: float = 2.0,
    seed: int = 0,
    batch_size: int | None = None,
) -> Dict[Hashable, Tuple[np.ndarray, np.ndarray]]:
    """Geometric size-skewed disjoint shards (IID in label distribution).

    Agent ``i`` owns a shard proportional to ``ratio**i`` of the
    (seed-shuffled) data — later tokens are data-rich, earlier ones
    data-poor; ``ratio=1`` recovers the near-equal deal.  Sizes use
    largest-remainder rounding with a floor of one example per agent.
    """
    if ratio <= 0.0:
        raise ValueError(f"ratio must be > 0, got {ratio}")
    toks = _tokens(agents)
    n = len(toks)
    if len(X) < n:
        raise ValueError(f"{len(X)} examples cannot cover {n} agents")
    weights = np.power(float(ratio), np.arange(n))
    target = weights / weights.sum() * len(X)
    sizes = np.maximum(np.floor(target).astype(int), 1)
    # Largest-remainder: hand leftover rows to the largest fractional
    # parts (deterministic: np.argsort is stable on the tie-broken key).
    leftover = len(X) - int(sizes.sum())
    if leftover > 0:
        order = np.argsort(-(target - np.floor(target)), kind="stable")
        for j in order[:leftover]:
            sizes[j] += 1
    elif leftover < 0:
        order = np.argsort(sizes, kind="stable")[::-1]
        for j in order[: -leftover]:
            sizes[j] -= 1
    perm = np.random.default_rng(seed).permutation(len(X))
    out: Dict[Hashable, Tuple[np.ndarray, np.ndarray]] = {}
    start = 0
    for tok, ln in zip(toks, sizes):
        sl = perm[start : start + int(ln)]
        out[tok] = (np.asarray(X)[sl], np.asarray(y)[sl])
        start += int(ln)
    return _truncate(out, batch_size)
