"""CIFAR-10/100 pipeline with per-agent sharding and jitted augmentation.

Parity: the reference loads CIFAR via torchvision with per-dataset
normalization constants and RandomCrop(32, padding=4) + RandomHorizontalFlip
augmentation (``Man_Colab.ipynb`` cell 16, ``CIFAR_10_Baseline.ipynb``), and
splits the train set evenly across agents.

TPU-first differences: no torchvision / no host-side PIL transforms — the
raw uint8 batches go to the device once and augmentation (pad-crop + flip)
is a jitted, vmapped JAX function keyed by PRNG, so it fuses into the
training step.  Data loads from the standard python-pickle batches if a
CIFAR directory exists (``DLT_CIFAR_DIR`` env var or common paths), else a
deterministic synthetic dataset with class-dependent structure stands in so
everything runs hermetically (zero-egress environments included).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Hashable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "CIFAR_MEAN",
    "CIFAR_STD",
    "load_cifar",
    "real_cifar_present",
    "synthetic_cifar",
    "normalize",
    "normalized_pad_value",
    "augment_batch",
    "shard_dataset",
]

# meliketoy config.py constants (used by Man_Colab cell 16 transforms).
CIFAR_MEAN = {
    "cifar10": np.array([0.4914, 0.4822, 0.4465], np.float32),
    "cifar100": np.array([0.5071, 0.4865, 0.4409], np.float32),
}
CIFAR_STD = {
    "cifar10": np.array([0.2470, 0.2435, 0.2616], np.float32),
    "cifar100": np.array([0.2673, 0.2564, 0.2762], np.float32),
}

_DEFAULT_DIRS = (
    os.environ.get("DLT_CIFAR_DIR", ""),
    "data/cifar10",
    "data/cifar-10-batches-py",
    "/root/reference/data/cifar10",
)


def _batch_files(d: str, dataset: str):
    if dataset == "cifar10":
        return (
            [os.path.join(d, f"data_batch_{i}") for i in range(1, 6)],
            [os.path.join(d, "test_batch")],
            b"labels",
        )
    return [os.path.join(d, "train")], [os.path.join(d, "test")], b"fine_labels"


def real_cifar_present(dataset: str = "cifar10", data_dir: str | None = None) -> bool:
    """True when real CIFAR pickle batches exist (file check only — no
    loading), in ``data_dir`` or any default location."""
    dirs = [data_dir] if data_dir else [d for d in _DEFAULT_DIRS if d]
    for d in dirs:
        train_files, test_files, _ = _batch_files(d, dataset)
        if all(os.path.exists(p) for p in train_files + test_files):
            return True
    return False


def _load_pickle_batches(d: str, dataset: str):
    """Read the standard CIFAR python pickle format if present."""
    train_files, test_files, label_key = _batch_files(d, dataset)
    if not all(os.path.exists(p) for p in train_files + test_files):
        return None

    def read(files):
        xs, ys = [], []
        for p in files:
            with open(p, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            xs.append(batch[b"data"])
            ys.extend(batch[label_key])
        X = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return X.astype(np.uint8), np.asarray(ys, np.int32)

    return read(train_files), read(test_files)


def synthetic_cifar(
    dataset: str = "cifar10",
    *,
    n_train: int = 4096,
    n_test: int = 1024,
    seed: int = 0,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Deterministic CIFAR-shaped stand-in: each class is a distinct smooth
    color/texture prototype plus noise, so models can actually learn."""
    num_classes = 10 if dataset == "cifar10" else 100
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    protos = []
    for c in range(num_classes):
        phase = 2 * np.pi * c / num_classes
        base = np.stack(
            [
                0.5 + 0.4 * np.sin(2 * np.pi * (xx * (1 + c % 4)) + phase),
                0.5 + 0.4 * np.cos(2 * np.pi * (yy * (1 + c % 3)) + phase),
                0.5 + 0.4 * np.sin(2 * np.pi * (xx + yy) * (1 + c % 5) + phase),
            ],
            axis=-1,
        )
        protos.append(base)
    protos = np.stack(protos)  # (C, 32, 32, 3)

    def make(n, seed_off):
        r = np.random.default_rng(seed + seed_off)
        y = r.integers(0, num_classes, size=n).astype(np.int32)
        x = protos[y] + r.normal(0, 0.18, size=(n, 32, 32, 3))
        return (np.clip(x, 0, 1) * 255).astype(np.uint8), y

    return make(n_train, 1), make(n_test, 2)


def load_cifar(
    dataset: str = "cifar10", data_dir: str | None = None
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """``((X_train, y_train), (X_test, y_test))`` as uint8 NHWC + int32."""
    dirs = [data_dir] if data_dir else [d for d in _DEFAULT_DIRS if d]
    for d in dirs:
        out = _load_pickle_batches(d, dataset)
        if out is not None:
            return out
    return synthetic_cifar(dataset)


def normalize(x: jax.Array, dataset: str = "cifar10") -> jax.Array:
    """uint8 NHWC -> normalized float32 (meliketoy mean/std)."""
    mean = jnp.asarray(CIFAR_MEAN[dataset])
    std = jnp.asarray(CIFAR_STD[dataset])
    return (x.astype(jnp.float32) / 255.0 - mean) / std


def augment_batch(
    rng: jax.Array, x: jax.Array, pad_value: jax.Array | float = 0.0
) -> jax.Array:
    """RandomCrop(32, padding=4) + RandomHorizontalFlip, jitted/vmapped.

    Operates on (B, 32, 32, 3) images of any float dtype; pure function of
    the PRNG key so it composes into the compiled train step.

    ``pad_value``: what the crop borders contain — scalar or per-channel
    (3,).  The reference pipeline crops BEFORE normalization, so its
    borders are black pixels that normalize to (0 - mean)/std per channel;
    callers working on normalized images should pass
    :func:`normalized_pad_value` to match (zeros would be the dataset
    mean, not black).
    """
    b = x.shape[0]
    k_crop, k_flip = jax.random.split(rng)
    pv = jnp.broadcast_to(jnp.asarray(pad_value, x.dtype), (3,))
    pad = jnp.broadcast_to(pv, (b, 40, 40, 3)).astype(x.dtype)
    pad = pad.at[:, 4:36, 4:36, :].set(x)
    offs = jax.random.randint(k_crop, (b, 2), 0, 9)
    flip = jax.random.bernoulli(k_flip, 0.5, (b,))

    def one(img, off, fl):
        img = jax.lax.dynamic_slice(img, (off[0], off[1], 0), (32, 32, 3))
        return jax.lax.cond(fl, lambda i: i[:, ::-1, :], lambda i: i, img)

    return jax.vmap(one)(pad, offs, flip)


def normalized_pad_value(dataset: str = "cifar10") -> np.ndarray:
    """Per-channel value of a black pixel after :func:`normalize` — the
    crop-border content matching a crop-before-normalize pipeline."""
    return (0.0 - CIFAR_MEAN[dataset]) / CIFAR_STD[dataset]


def shard_dataset(
    X: np.ndarray,
    y: np.ndarray,
    agents: int | Sequence[Hashable],
    *,
    batch_size: int | None = None,
    seed: int = 0,
) -> Dict[Hashable, Tuple[np.ndarray, np.ndarray]]:
    """Random near-equal disjoint shards per agent (parity: the
    ``random_split`` sizes of ``Man_Colab.ipynb`` cell 16).

    If ``batch_size`` is given, each shard is truncated to a multiple of it
    (static shapes for the jitted epoch scan).
    """
    from distributed_learning_tpu.data.titanic import split_data

    perm = np.random.default_rng(seed).permutation(len(X))
    out = split_data(X[perm], y[perm], agents)
    if batch_size is not None:
        for tok, (xs, ys) in out.items():
            ln = (len(xs) // batch_size) * batch_size
            out[tok] = (xs[:ln], ys[:ln])
    return out
