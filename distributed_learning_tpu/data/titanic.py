"""Titanic tabular pipeline (parity: the data-prep cells of
``notebooks/Titanic Consensus GD test.ipynb``).

The reference ships the Kaggle Titanic CSVs (``data/titanic/train.csv``,
891 rows) and prepares features inside the notebook (cell 2:
``prepare_dataset``) — drop Name/Ticket/Cabin/Embarked, Sex -> {-1,+1},
fill Age NaNs with the mean, scale Age and Fare by 1/100, append a bias
column, labels -> {-1,+1}; cell 4 selects
``[Pclass, Sex, Age, SibSp, Parch, Fare, _bias]`` and holds out the first
10% as the common test set; cell 12 (``split_data``) deals contiguous
near-equal shards to agents.

This module reproduces that pipeline over a CSV directory when one is
available (``DLT_TITANIC_DIR`` env var or a configured path), and otherwise
generates a synthetic dataset with the same schema and a comparable
learnable signal so tests and benchmarks run hermetically.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, Hashable, List, Sequence, Tuple

import numpy as np

__all__ = [
    "FEATURES",
    "load_titanic",
    "prepare_rows",
    "split_data",
    "synthetic_titanic",
]

FEATURES = ["Pclass", "Sex", "Age", "SibSp", "Parch", "Fare", "_bias"]

_DEFAULT_DIRS = (
    os.environ.get("DLT_TITANIC_DIR", ""),
    "data/titanic",
    "/root/reference/data/titanic",
)


def prepare_rows(rows: List[Dict[str, str]]) -> Tuple[np.ndarray, np.ndarray]:
    """Feature prep on parsed CSV rows (parity: notebook cell 2).

    Returns ``(X, y)`` with columns in :data:`FEATURES` order and labels in
    {-1, +1}.
    """
    ages = [float(r["Age"]) for r in rows if r.get("Age")]
    age_mean = float(np.mean(ages)) if ages else 0.0
    labeled = any(r.get("Survived", "") != "" for r in rows)
    X, y = [], []
    for r in rows:
        if labeled and r.get("Survived", "") == "":
            # Keep X and y aligned: in a labeled file, a row with a blank
            # label is dropped rather than silently shifting every
            # subsequent (feature, label) pair.
            continue
        sex = 1.0 if r.get("Sex") == "male" else -1.0
        age = float(r["Age"]) if r.get("Age") else age_mean
        X.append(
            [
                float(r.get("Pclass") or 0.0),
                sex,
                age / 100.0,
                float(r.get("SibSp") or 0.0),
                float(r.get("Parch") or 0.0),
                float(r.get("Fare") or 0.0) / 100.0,
                1.0,
            ]
        )
        if labeled:
            y.append(int(r["Survived"]) * 2 - 1)
    return (
        np.asarray(X, dtype=np.float32),
        np.asarray(y, dtype=np.int32) if y else np.zeros(0, np.int32),
    )


def _read_csv(path: str) -> List[Dict[str, str]]:
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def synthetic_titanic(
    n: int = 891, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Hermetic stand-in with the reference schema and a learnable signal.

    Feature marginals roughly match the real dataset; labels come from a
    fixed logistic ground truth (sex/class dominated, like the real data) so
    logreg reaches ~0.8 accuracy — keeping the recorded notebook baselines
    meaningful even without the CSVs.
    """
    rng = np.random.default_rng(seed)
    pclass = rng.choice([1.0, 2.0, 3.0], size=n, p=[0.24, 0.21, 0.55])
    sex = rng.choice([1.0, -1.0], size=n, p=[0.65, 0.35])
    age = np.clip(rng.normal(29.7, 14.5, size=n), 0.4, 80.0) / 100.0
    sibsp = rng.poisson(0.5, size=n).astype(np.float32)
    parch = rng.poisson(0.4, size=n).astype(np.float32)
    fare = np.clip(rng.lognormal(2.9, 1.0, size=n), 0.0, 512.0) / 100.0
    X = np.stack(
        [pclass, sex, age, sibsp, parch, fare, np.ones(n)], axis=1
    ).astype(np.float32)
    logits = -1.3 * sex - 0.9 * (pclass - 2.0) - 1.5 * age + 1.2 * fare - 0.3
    prob = 1.0 / (1.0 + np.exp(-logits))
    y = (rng.random(n) < prob).astype(np.int32) * 2 - 1
    return X, y


def titanic_source(data_dir: str | None = None) -> str:
    """Which dataset :func:`load_titanic` would use: ``"real:<dir>"`` or
    ``"synthetic"``.  Benchmarks record this so synthetic-fallback results
    can never masquerade as real-data evidence."""
    dirs = [data_dir] if data_dir else [d for d in _DEFAULT_DIRS if d]
    for d in dirs:
        if os.path.exists(os.path.join(d, "train.csv")):
            return f"real:{d}"
    return "synthetic"


def load_titanic(
    data_dir: str | None = None, *, test_fraction: float = 0.1
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """``(X_train, y_train, X_test, y_test)`` with the notebook's split:
    the first ``test_fraction`` of rows is the common test set (cell 4).

    Reads ``train.csv`` from ``data_dir`` or the first existing default
    directory; falls back to :func:`synthetic_titanic`.
    """
    source = titanic_source(data_dir)
    if source.startswith("real:"):
        X, y = prepare_rows(_read_csv(os.path.join(source[5:], "train.csv")))
    else:
        X, y = synthetic_titanic()
    n_test = int(len(X) * test_fraction)
    return X[n_test:], y[n_test:], X[:n_test], y[:n_test]


def split_data(
    X: np.ndarray,
    y: np.ndarray,
    agents: int | Sequence[Hashable],
) -> Dict[Hashable, Tuple[np.ndarray, np.ndarray]]:
    """Deal contiguous near-equal shards to agents (parity: notebook cell
    12 ``split_data`` — remainder rows land on the *later* shards, e.g.
    802 rows over 5 agents -> [160, 160, 160, 161, 161])."""
    tokens = list(range(agents)) if isinstance(agents, int) else list(agents)
    num = len(tokens)
    result: Dict[Hashable, Tuple[np.ndarray, np.ndarray]] = {}
    start = 0
    remaining = len(X)
    for i, tok in enumerate(tokens):
        ln = remaining // (num - i)
        result[tok] = (X[start : start + ln], y[start : start + ln])
        start += ln
        remaining -= ln
    return result
