"""Run-report rendering + the ``obs-report`` CLI subcommand.

``python -m distributed_learning_tpu.cli obs-report <run.jsonl>``
replays a JSONL event log (written by
``MetricsRegistry.dump_jsonl`` or streamed by a ``JsonlSink`` /
``JsonlTelemetry``) and prints the aggregated run summary: counter
totals, last gauges, time-series stats, and span timings — "where did
this run's time and bandwidth go" without TensorBoard.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from distributed_learning_tpu.obs.registry import MetricsRegistry

__all__ = ["format_run_report", "obs_report_main"]


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def format_run_report(report: dict) -> str:
    """Human-readable rendering of ``MetricsRegistry.run_report()``."""
    lines: List[str] = []
    wall = report.get("wall_s")
    head = f"run report — {report.get('events', 0)} events"
    if wall is not None:
        head += f" over {wall:.3f}s"
    lines.append(head)
    if report.get("counters"):
        lines.append("\ncounters:")
        for name in sorted(report["counters"]):
            lines.append(f"  {name:44s} {_fmt(report['counters'][name]):>14}")
    if report.get("gauges"):
        lines.append("\ngauges (last value):")
        for name in sorted(report["gauges"]):
            lines.append(f"  {name:44s} {_fmt(report['gauges'][name]):>14}")
    if report.get("series"):
        lines.append(
            f"\nseries:\n  {'name':44s} {'n':>6} {'mean':>12} "
            f"{'min':>12} {'max':>12} {'last':>12}"
        )
        for name in sorted(report["series"]):
            s = report["series"][name]
            lines.append(
                f"  {name:44s} {s['count']:6d} {s['mean']:12.5g} "
                f"{s['min']:12.5g} {s['max']:12.5g} {s['last']:12.5g}"
            )
    if report.get("spans"):
        lines.append(
            f"\nspans (wall clock):\n  {'name':44s} {'n':>6} "
            f"{'total s':>12} {'mean s':>12} {'max s':>12}"
        )
        for name in sorted(
            report["spans"],
            key=lambda n: -report["spans"][n]["total_s"],
        ):
            s = report["spans"][name]
            lines.append(
                f"  {name:44s} {s['count']:6d} {s['total_s']:12.4f} "
                f"{s['mean_s']:12.4f} {s['max_s']:12.4f}"
            )
    return "\n".join(lines)


def obs_report_main(argv: Optional[Sequence[str]] = None) -> int:
    """Entry point for ``cli.py obs-report``."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_learning_tpu.cli obs-report",
        description="summarize a JSONL observability event log",
    )
    ap.add_argument("path", help="JSONL event log (dump_jsonl/JsonlSink)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw run_report dict as JSON")
    args = ap.parse_args(argv)
    try:
        report = MetricsRegistry.from_jsonl(args.path).run_report()
    except FileNotFoundError:
        # graftlint: disable=no-print-in-library -- CLI error reporting to stderr (argparse convention)
        print(f"obs-report: no such file: {args.path}", file=sys.stderr)
        return 2
    except (json.JSONDecodeError, ValueError) as exc:
        # graftlint: disable=no-print-in-library -- CLI error reporting to stderr (argparse convention)
        print(f"obs-report: {args.path} is not a JSONL event log: {exc}",
              file=sys.stderr)
        return 2
    text = (
        json.dumps(report, indent=2, sort_keys=True)
        if args.json else format_run_report(report)
    )
    # graftlint: disable=no-print-in-library -- obs-report's stdout IS its interface (the CLI subcommand's one output)
    print(text)
    return 0
