"""Run-report rendering + the ``obs-report`` / ``obs-monitor`` CLIs.

``python -m distributed_learning_tpu.cli obs-report <run.jsonl>``
replays a JSONL event log (written by ``MetricsRegistry.dump_jsonl`` or
streamed by a ``JsonlSink`` / ``JsonlTelemetry``) and prints the
aggregated run summary: counter totals, last gauges, time-series stats,
and span timings — "where did this run's time and bandwidth go" without
TensorBoard.

The run-wide plane adds three modes (all jax-free):

* ``obs-report --merge a.jsonl b.jsonl ...`` — merge per-agent event
  logs into ONE run report with per-agent labels plus the straggler
  profile (each file's stem names its agent; a DIRECTORY argument
  expands to its sorted ``*.jsonl`` members, so a fleet harness's
  output dir is one argument; ``--trace out.json`` additionally writes
  the merged Perfetto trace);
* ``obs-report --bench BENCH_r*.json`` — the driver's benchmark
  trajectory as one table of headline samples/sec per round with
  regression flagging;
* ``obs-report --ledger PERF_LEDGER.jsonl`` — the persistent perf
  ledger (every ``bench.py`` / ``benchmarks/`` run appends a
  ``{profile, measured, env-health}`` record; ``obs/cost.py``) as a
  trend table with per-metric healthy-best regression flagging, so the
  trajectory survives sessions the tunnel wedged away;
* ``obs-monitor <aggregate.jsonl>`` — live text dashboard over the
  aggregate stream a master-side ``RunAggregator`` + ``JsonlSink``
  writes (round rate, per-agent latency bars, consensus residual, wire
  bytes); ``--once`` renders a single frame.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence

from distributed_learning_tpu.obs.aggregate import (
    RunAggregator,
    straggler_profile_from_registry,
)
from distributed_learning_tpu.obs.registry import MetricsRegistry

__all__ = [
    "format_run_report",
    "format_straggler_profile",
    "format_edge_profile",
    "format_bench_trajectory",
    "obs_report_main",
    "obs_monitor_main",
]


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def format_run_report(report: dict) -> str:
    """Human-readable rendering of ``MetricsRegistry.run_report()``."""
    lines: List[str] = []
    wall = report.get("wall_s")
    head = f"run report — {report.get('events', 0)} events"
    if wall is not None:
        head += f" over {wall:.3f}s"
    lines.append(head)
    if report.get("counters"):
        lines.append("\ncounters:")
        for name in sorted(report["counters"]):
            lines.append(f"  {name:44s} {_fmt(report['counters'][name]):>14}")
    if report.get("gauges"):
        lines.append("\ngauges (last value):")
        for name in sorted(report["gauges"]):
            lines.append(f"  {name:44s} {_fmt(report['gauges'][name]):>14}")
    if report.get("series"):
        lines.append(
            f"\nseries:\n  {'name':44s} {'n':>6} {'mean':>12} "
            f"{'min':>12} {'max':>12} {'last':>12}"
        )
        for name in sorted(report["series"]):
            s = report["series"][name]
            lines.append(
                f"  {name:44s} {s['count']:6d} {s['mean']:12.5g} "
                f"{s['min']:12.5g} {s['max']:12.5g} {s['last']:12.5g}"
            )
    if report.get("spans"):
        lines.append(
            f"\nspans (wall clock):\n  {'name':44s} {'n':>6} "
            f"{'total s':>12} {'mean s':>12} {'max s':>12}"
        )
        for name in sorted(
            report["spans"],
            key=lambda n: -report["spans"][n]["total_s"],
        ):
            s = report["spans"][name]
            lines.append(
                f"  {name:44s} {s['count']:6d} {s['total_s']:12.4f} "
                f"{s['mean_s']:12.4f} {s['max_s']:12.4f}"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# Straggler profile                                                      #
# ---------------------------------------------------------------------- #
def _bar(value: float, top: float, width: int = 24) -> str:
    if top <= 0:
        return ""
    return "#" * max(0, min(width, round(width * value / top)))


def format_straggler_profile(profile: dict) -> str:
    """Render :func:`straggler_profile_from_registry` output."""
    head = (
        f"straggler profile — {profile['rounds']} rounds, "
        f"source: {profile['source']}"
    )
    if profile.get("quantiles") == "sketch":
        # Which statistics path produced the percentiles (the sketch's
        # relative-error guarantee vs the exact small-run oracle).
        alpha = profile.get("alpha", 0.01)
        head += f", quantiles: sketch(α={alpha * 100:g}%)"
    lines = [head]
    skew = profile.get("skew") or {}
    if profile["rounds"]:
        lines.append(
            f"  round skew  p50 {skew.get('p50_s', 0.0):.4f}s  "
            f"p95 {skew.get('p95_s', 0.0):.4f}s  "
            f"max {skew.get('max_s', 0.0):.4f}s"
        )
    per_agent = profile.get("per_agent") or {}
    if per_agent:
        top = max(a["p95_s"] for a in per_agent.values())
        lines.append(
            f"  {'agent':10s} {'n':>5} {'p50 s':>9} {'p95 s':>9} "
            f"{'max s':>9} {'slowest':>8} {'stale':>6} {'defer':>6}  p95"
        )
        for token in sorted(per_agent):
            a = per_agent[token]
            lines.append(
                f"  {token:10s} {a['count']:5d} {a['p50_s']:9.4f} "
                f"{a['p95_s']:9.4f} {a['max_s']:9.4f} "
                f"{a['slowest_rounds']:8d} {_fmt(a['stale_dropped']):>6} "
                f"{_fmt(a['deferred']):>6}  {_bar(a['p95_s'], top)}"
            )
        evicted = sum(
            int(a.get("evicted", 0)) for a in per_agent.values()
        )
        if evicted and profile.get("quantiles") != "sketch":
            # Exact-path percentiles cover the retained ring only; the
            # dropped tail is disclosed, never silently absorbed (the
            # sketch path is eviction-immune and needs no caveat).
            lines.append(
                f"  ! {evicted} series points evicted — exact "
                f"percentiles cover the retained window only"
            )
    # Staleness vs convergence (docs/async_runtime.md): what the async
    # runtime mixed stale/dropped, next to where each agent's consensus
    # residual went — the τ trade-off in one table.
    sv = {
        t: a for t, a in per_agent.items()
        if a.get("staleness") or "residual_last" in a
    }
    if sv:
        lines.append("  staleness vs convergence")
        lines.append(
            f"  {'agent':10s} {'mixes':>6} {'stale mean':>11} "
            f"{'stale max':>10} {'dropped':>8} {'resid first':>12} "
            f"{'resid last':>12}"
        )
        for token in sorted(sv):
            a = sv[token]
            st = a.get("staleness") or {}
            rf, rl = a.get("residual_first"), a.get("residual_last")
            lines.append(
                f"  {token:10s} {st.get('n', 0):6d} "
                f"{st.get('mean', 0.0):11.2f} "
                f"{_fmt(st.get('max', 0)):>10} "
                f"{_fmt(a.get('stale_dropped_mix', 0)):>8} "
                f"{(f'{rf:12.3g}' if rf is not None else ' ' * 12)} "
                f"{(f'{rl:12.3g}' if rl is not None else ' ' * 12)}"
            )
    if profile.get("slowest_agent") is not None:
        lines.append(f"  slowest agent: {profile['slowest_agent']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# Per-edge wire profile                                                  #
# ---------------------------------------------------------------------- #
def _ms(v: Optional[float]) -> str:
    return "—" if v is None else f"{v * 1e3:.1f}"


def format_edge_profile(profile: dict) -> str:
    """Render :func:`~distributed_learning_tpu.obs.aggregate.
    edge_profile_from_registry` output: one row per directed edge —
    volume, throughput, retries, trace-derived latency percentiles,
    mix staleness, and injected-fault attribution.  When any edge
    carries decode scratch-pool attribution (the async runner's
    zero-copy receive path, docs/wire.md), a second subtable breaks
    hits/misses/bytes down per inbound edge; scratch-less profiles
    render byte-identically to the pre-scratch table."""
    edges = profile.get("edges") or {}
    window = profile.get("window_s") or 0.0
    head = f"edge profile — {len(edges)} directed edges"
    if window:
        head += f" over {window:.1f}s"
    if profile.get("quantiles") == "sketch":
        alpha = profile.get("alpha", 0.01)
        head += f", quantiles: sketch(α={alpha * 100:g}%)"
    lines = [head]
    if not edges:
        return "\n".join(lines)
    lines.append(
        f"  {'edge':12s} {'frames':>7} {'KiB out':>9} {'KiB/s':>8} "
        f"{'retry':>6} {'lat p50 ms':>11} {'p95 ms':>8} {'max ms':>8} "
        f"{'stale mean':>11} {'faults':>7}"
    )
    for edge in sorted(edges):
        e = edges[edge]
        lat = e.get("latency") or {}
        st = e.get("staleness") or {}
        faults = int(sum((e.get("faults") or {}).values()))
        stale_mean = f"{st['mean']:.2f}" if st else "—"
        lines.append(
            f"  {edge:12s} {int(e.get('frames_out', 0)):7d} "
            f"{float(e.get('bytes_out', 0.0)) / 1024.0:9.2f} "
            f"{float(e.get('bytes_out_per_s', 0.0)) / 1024.0:8.2f} "
            f"{int(e.get('retries', 0)):6d} "
            f"{_ms(lat.get('p50_s')):>11} {_ms(lat.get('p95_s')):>8} "
            f"{_ms(lat.get('max_s')):>8} "
            f"{stale_mean:>11} {faults:7d}"
        )
    scratch = {
        edge: e["scratch"] for edge, e in edges.items()
        if e.get("scratch")
    }
    if scratch:
        lines.append("  decode scratch pool (zero-copy receive path)")
        lines.append(
            f"  {'edge':12s} {'hits':>7} {'misses':>7} {'hit %':>7} "
            f"{'MiB decoded':>12}"
        )
        for edge in sorted(scratch):
            s = scratch[edge]
            hits = int(s.get("hits", 0))
            misses = int(s.get("misses", 0))
            total = hits + misses
            pct = f"{100.0 * hits / total:.1f}" if total else "—"
            lines.append(
                f"  {edge:12s} {hits:7d} {misses:7d} {pct:>7} "
                f"{float(s.get('bytes', 0.0)) / 2**20:12.2f}"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# Offline merge (obs-report --merge)                                     #
# ---------------------------------------------------------------------- #
def _token_from_path(path: str) -> str:
    stem = path.replace("\\", "/").rsplit("/", 1)[-1]
    if stem.endswith(".jsonl"):
        stem = stem[: -len(".jsonl")]
    return stem


def _expand_log_paths(paths: Sequence[str]) -> List[str]:
    """Expand directory arguments into their sorted ``*.jsonl`` files
    (one fleet-harness output directory is one ``--merge`` argument);
    plain file paths pass through unchanged."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            names = sorted(
                n for n in os.listdir(path) if n.endswith(".jsonl")
            )
            if not names:
                raise FileNotFoundError(
                    f"--merge directory {path!r} holds no .jsonl logs"
                )
            out.extend(os.path.join(path, n) for n in names)
        else:
            out.append(path)
    return out


def merge_agent_logs(paths: Sequence[str]) -> RunAggregator:
    """Merge per-agent JSONL event logs (file stem == agent token) into
    one :class:`RunAggregator`.  Directory arguments expand to their
    sorted ``*.jsonl`` members.  The merged registry re-stamps nothing:
    its clock is pinned to 0 because offline-merge timestamps are the
    agents' own (carried inside the replayed events), and a
    deterministic clock keeps merged reports reproducible."""
    agg = RunAggregator(
        registry=MetricsRegistry(clock=lambda: 0.0)
    )
    for path in _expand_log_paths(paths):
        agg.merge_registry(
            _token_from_path(path), MetricsRegistry.from_jsonl(path)
        )
    return agg


# ---------------------------------------------------------------------- #
# Bench trajectory (obs-report --bench)                                  #
# ---------------------------------------------------------------------- #
#: A round counts as a regression when its headline drops below this
#: fraction of the best healthy value seen in earlier rounds.
BENCH_REGRESSION_FRACTION = 0.9


def read_bench_records(paths: Sequence[str]) -> List[dict]:
    """Parse the driver's ``BENCH_r*.json`` round files, sorted by
    round number.  Each row: round ``n``, ``rc``, and the parsed record
    (or None when the round produced no measurement)."""
    rows = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as fh:
            rec = json.load(fh)
        rows.append({
            "path": path,
            "n": int(rec.get("n", 0)),
            "rc": rec.get("rc"),
            "parsed": rec.get("parsed"),
        })
    rows.sort(key=lambda r: r["n"])
    return rows


def format_bench_trajectory(rows: List[dict]) -> str:
    """One table of headline samples/sec per round, regressions
    flagged.  Provisional and tunnel-wedged CPU-sanity records are
    labeled and excluded from the regression baseline (they measure a
    different configuration)."""
    lines = [
        f"bench trajectory — {len(rows)} rounds",
        f"  {'round':>5} {'rc':>3} {'value':>10} {'unit':>12} "
        f"{'vs_base':>8}  status",
    ]
    best: Optional[float] = None
    best_round: Optional[int] = None
    for row in rows:
        parsed = row["parsed"]
        if not parsed:
            lines.append(
                f"  r{row['n']:04d} {row['rc']!s:>3} {'—':>10} {'—':>12} "
                f"{'—':>8}  no record (driver rc={row['rc']})"
            )
            continue
        value = float(parsed.get("value", 0.0))
        unit = parsed.get("unit", "")
        vs = parsed.get("vs_baseline")
        healthy = not (
            parsed.get("provisional") or parsed.get("tunnel_wedged")
        )
        status = "ok"
        if parsed.get("tunnel_wedged"):
            status = "cpu-sanity (tunnel wedged)"
        elif parsed.get("provisional"):
            status = "provisional"
        elif best is not None and value < BENCH_REGRESSION_FRACTION * best:
            status = (
                f"REGRESSION -{(1 - value / best) * 100:.0f}% "
                f"vs r{best_round:02d}"
            )
        lines.append(
            f"  r{row['n']:04d} {row['rc']!s:>3} {value:10.2f} {unit:>12} "
            f"{('%.3f' % vs) if vs is not None else '—':>8}  {status}"
        )
        if healthy and (best is None or value > best):
            best, best_round = value, row["n"]
    if best is not None:
        lines.append(f"  best healthy headline: {best:.2f} (r{best_round:02d})")
    else:
        lines.append(
            "  no healthy headline yet — every round missed its "
            "measurement window"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# obs-report CLI                                                         #
# ---------------------------------------------------------------------- #
def obs_report_main(argv: Optional[Sequence[str]] = None) -> int:
    """Entry point for ``cli.py obs-report``."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_learning_tpu.cli obs-report",
        description="summarize JSONL observability event logs",
    )
    ap.add_argument("paths", nargs="+",
                    help="JSONL event log(s) (dump_jsonl/JsonlSink), or "
                         "BENCH_r*.json files with --bench")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report dict as JSON")
    ap.add_argument("--merge", action="store_true",
                    help="merge per-agent logs (file stem == agent "
                         "token; a directory expands to its *.jsonl "
                         "files) into one run report + straggler "
                         "profile")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="with --merge: also write the merged "
                         "Chrome/Perfetto trace here")
    ap.add_argument("--bench", action="store_true",
                    help="read BENCH_r*.json driver round files: "
                         "headline samples/sec per round with "
                         "regression flagging")
    ap.add_argument("--ledger", action="store_true",
                    help="read PERF_LEDGER.jsonl perf-ledger file(s): "
                         "the {profile, measured, env-health} trend "
                         "with healthy-best regression flagging")
    args = ap.parse_args(argv)
    try:
        if args.ledger:
            from distributed_learning_tpu.obs.cost import (
                format_ledger_trend,
                read_ledger,
            )

            records: List[dict] = []
            for path in args.paths:
                records.extend(read_ledger(path))
            text = (
                json.dumps(records, indent=2, sort_keys=True)
                if args.json else format_ledger_trend(records)
            )
        elif args.bench:
            rows = read_bench_records(args.paths)
            text = (
                json.dumps(rows, indent=2, sort_keys=True)
                if args.json else format_bench_trajectory(rows)
            )
        elif args.merge:
            agg = merge_agent_logs(args.paths)
            if args.trace:
                agg.export_chrome_trace(args.trace)
            report = agg.registry.run_report()
            profile = agg.straggler_profile()
            edge_profile = agg.edge_profile()
            payload = {"report": report, "straggler": profile}
            text_parts = [
                format_run_report(report),
                format_straggler_profile(profile),
            ]
            if edge_profile["edges"]:
                # Rendered only when edge-labeled streams ran: plain
                # (pre-observatory) logs keep their exact report shape.
                payload["edges"] = edge_profile
                text_parts.append(format_edge_profile(edge_profile))
            text = (
                json.dumps(payload, indent=2, sort_keys=True)
                if args.json else "\n\n".join(text_parts)
            )
        else:
            if len(args.paths) != 1:
                # graftlint: disable=no-print-in-library -- CLI error reporting to stderr (argparse convention)
                print("obs-report: pass one log, or --merge/--bench/"
                      "--ledger for several", file=sys.stderr)
                return 2
            report = MetricsRegistry.from_jsonl(args.paths[0]).run_report()
            text = (
                json.dumps(report, indent=2, sort_keys=True)
                if args.json else format_run_report(report)
            )
    except FileNotFoundError as exc:
        # graftlint: disable=no-print-in-library -- CLI error reporting to stderr (argparse convention)
        print(f"obs-report: no such file: {exc.filename}", file=sys.stderr)
        return 2
    except (json.JSONDecodeError, ValueError) as exc:
        # graftlint: disable=no-print-in-library -- CLI error reporting to stderr (argparse convention)
        print(f"obs-report: input is not a JSONL event log: {exc}",
              file=sys.stderr)
        return 2
    # graftlint: disable=no-print-in-library -- obs-report's stdout IS its interface (the CLI subcommand's one output)
    print(text)
    return 0


# ---------------------------------------------------------------------- #
# obs-monitor: live dashboard over the aggregate stream                  #
# ---------------------------------------------------------------------- #
def _iter_jsonl_tolerant(path: str) -> Iterator[dict]:
    """Yield parseable lines, silently skipping a torn tail — the
    monitor reads a file the master is still appending to."""
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue


def _sum_labeled(counters: Dict[str, float], name: str) -> float:
    """Run-wide total for ``name``: the bare counter when present, else
    the sum over its ``name/label`` dimensions."""
    if name in counters:
        return counters[name]
    return sum(
        v for k, v in counters.items() if k.startswith(name + "/")
    )


def _stream_counters(registry: MetricsRegistry,
                     events: List[dict]) -> Dict[str, float]:
    """Counters over a replayed aggregate STREAM: counter totals don't
    stream as events, but every merged delta leaves an ``obs.delta``
    marker carrying its agent's absolute totals — the last marker per
    agent reconstructs them.  Replayed snapshot lines (a dumped file)
    land in ``registry.counters`` and win."""
    latest: Dict[str, Dict[str, float]] = {}
    for ev in events:
        if (ev.get("kind") == "event" and ev.get("name") == "obs.delta"
                and isinstance(ev.get("counters"), dict)):
            latest[str(ev.get("token"))] = ev["counters"]
    counters: Dict[str, float] = {}
    sums: Dict[str, float] = {}
    for token, per_agent in latest.items():
        for name, total in per_agent.items():
            counters[f"{name}/{token}"] = float(total)
            sums[name] = sums.get(name, 0.0) + float(total)
    counters.update(sums)
    counters.update(registry.counters)
    return counters


def render_dashboard(registry: MetricsRegistry, *,
                     window_s: float = 30.0,
                     now: Optional[float] = None,
                     title: str = "") -> str:
    """One text-dashboard frame over a (replayed) aggregate registry."""
    events = registry.recent_events()
    counters = _stream_counters(registry, events)
    ts = [e["ts"] for e in events if "ts" in e]
    # Cross-process ages compare wall-clock timestamps, the one clock
    # every process shares; this is reporting, not a measured duration.
    # graftlint: disable=wallclock-duration -- cross-process staleness: event ts are wall-clock stamps from other processes, monotonic clocks cannot compare across them
    age = (time.time() if now is None else now) - max(ts) if ts else None
    lines = [
        "obs-monitor"
        + (f" — {title}" if title else "")
        + f" · {len(events)} events"
        + (f" · last update {age:.1f}s ago" if age is not None else "")
    ]
    # Round rate over the trailing window.  The done count falls back
    # to the master's per-round series (one point per completed round)
    # when no counter reached the stream.
    done = int(
        _sum_labeled(counters, "comm.master.rounds_done")
        or len(registry.series.get("comm.master.round_s", ()))
    )
    cutoff = (max(ts) if ts else 0.0) - window_s
    recent = [
        e for e in events
        if e.get("kind") == "series"
        and e.get("name") == "comm.master.round_s"
        and e.get("ts", 0.0) >= cutoff
    ]
    rate = len(recent) / window_s if recent else 0.0
    lines.append(
        f"rounds: {done} done · rate {rate:.2f}/s "
        f"(last {window_s:.0f}s)"
    )
    profile = straggler_profile_from_registry(registry, counters=counters)
    if profile["per_agent"]:
        lines.append(format_straggler_profile(profile))
    residuals = {
        name: pts for name, pts in registry.series.items()
        if "consensus.residual" in name
    }
    if residuals:
        last = {
            name: list(pts)[-1][1] for name, pts in residuals.items()
        }
        worst = max(last.values())
        lines.append(f"consensus residual (worst last): {worst:.3g}")
    # Async-runtime staleness line (docs/async_runtime.md): how stale
    # the values being mixed are, and how much was dropped outright.
    stale_pts = [
        v for name, pts in registry.series.items()
        if "comm.agent.staleness" in name
        for _, v in pts
    ]
    if stale_pts:
        dropped = int(
            _sum_labeled(counters, "comm.agent.async_stale_dropped")
        )
        lines.append(
            f"staleness: mean {sum(stale_pts) / len(stale_pts):.2f} · "
            f"max {max(stale_pts):.0f} over {len(stale_pts)} mixes · "
            f"{dropped} dropped"
        )
    # Device-cost gauges (obs/cost.py): the sampled dispatch timer's
    # MFU / bytes-per-sec, per program name.
    mfus = {
        name.split("/", 1)[1] if "/" in name else "step": value
        for name, value in sorted(registry.gauges.items())
        if name.startswith("cost.mfu")
    }
    if mfus:
        bps = {
            name.split("/", 1)[1] if "/" in name else "step": value
            for name, value in registry.gauges.items()
            if name.startswith("cost.bytes_per_sec")
        }
        parts = []
        for prog, value in mfus.items():
            part = f"{prog} {value * 100:.1f}%"
            if prog in bps:
                part += f" ({bps[prog] / 2**30:.2f} GiB/s)"
            parts.append(part)
        lines.append("mfu: " + " · ".join(parts))
    out_b = _sum_labeled(counters, "comm.bytes_framed_out")
    in_b = _sum_labeled(counters, "comm.bytes_framed_in")
    if out_b or in_b:
        lines.append(
            f"wire: {out_b / 1024.0:.1f} KiB out · "
            f"{in_b / 1024.0:.1f} KiB in · "
            f"{int(_sum_labeled(counters, 'comm.frames_out'))} frames out"
        )
    lost = counters.get("obs.deltas_lost", 0)
    if lost:
        lines.append(f"obs: {int(lost)} telemetry deltas lost")
    lines.extend(_health_lines(registry, counters, events))
    return "\n".join(lines)


def _health_lines(registry: MetricsRegistry,
                  counters: Dict[str, float],
                  events: List[dict]) -> List[str]:
    """The dashboard's live health section: rules breached by the run's
    own sentinel (``health.breach`` events riding the stream) unioned
    with a fresh evaluation over the replayed registry (catches
    breaches a sentinel-less master never evaluated).  Empty when the
    stream carries no health signal at all, so pre-sentinel streams
    render unchanged."""
    from distributed_learning_tpu.obs.health import HealthSentinel

    # Signal detection BEFORE the fresh evaluation: evaluate() writes
    # health.* gauges of its own, which must not count as "this stream
    # already carried health data".
    had_signal = any(k.startswith("health.") for k in counters) or any(
        k.startswith("health.") for k in registry.gauges
    )
    live = sorted({
        str(ev.get("rule")) for ev in events
        if ev.get("kind") == "event" and ev.get("name") == "health.breach"
        and ev.get("rule")
    })
    sentinel = HealthSentinel(registry)
    try:
        fresh = {
            b.rule: b for b in sentinel.evaluate(counters=counters)
        }
    except Exception:  # pragma: no cover - render must never die
        fresh = {}
    names = sorted(set(live) | set(fresh))
    if not (names or had_signal):
        return []
    if not names:
        return [f"health: OK ({len(sentinel.rules)} rules)"]
    lines = [f"health: BREACH — {', '.join(names)}"]
    for name in names:
        br = fresh.get(name)
        if br is not None:
            lines.append(f"  {name}: {br.detail}")
    return lines


def obs_monitor_main(argv: Optional[Sequence[str]] = None) -> int:
    """Entry point for ``cli.py obs-monitor``: tail an aggregate JSONL
    stream (a master-side ``RunAggregator`` registry with a
    ``JsonlSink``) and re-render the dashboard every ``--interval``
    seconds; ``--once`` prints a single frame (scripts, tests)."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_learning_tpu.cli obs-monitor",
        description="live text dashboard over an aggregate obs stream",
    )
    ap.add_argument("path", help="aggregate JSONL stream (JsonlSink on "
                                 "the RunAggregator registry)")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--window", type=float, default=30.0,
                    help="trailing seconds for the round-rate estimate")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    args = ap.parse_args(argv)
    while True:
        try:
            reg = MetricsRegistry.from_events(
                _iter_jsonl_tolerant(args.path)
            )
        except FileNotFoundError:
            # graftlint: disable=no-print-in-library -- CLI error reporting to stderr (argparse convention)
            print(f"obs-monitor: no such file: {args.path}",
                  file=sys.stderr)
            return 2
        frame = render_dashboard(
            reg, window_s=args.window, title=args.path
        )
        # graftlint: disable=no-print-in-library -- obs-monitor's stdout IS its interface (the live dashboard)
        print(frame, flush=True)
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        # graftlint: disable=no-print-in-library -- obs-monitor's stdout IS its interface (frame separator)
        print("", flush=True)
