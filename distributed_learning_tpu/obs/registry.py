"""Metrics registry: counters, gauges, time-series, and an event log.

The reference has no observability beyond ad-hoc ``_debug`` prints
(``consensus_tcp/master.py:63-68``, ``consensus_tcp/agent.py:46-51``)
and notebook ``%time`` cells; decentralized-training work lives on
exactly the signals those prints threw away — consensus residuals,
communication volume, gossip-round counts (the headline traces of
arXiv 2105.09080 and the local/communication step accounting of
arXiv 1805.09767).  This registry is the one sink for all of them:

* **counters** — monotonically increasing totals (`inc`): gossip rounds
  run/aborted, bytes framed, batches prefetched;
* **gauges** — last-value-wins scalars (`gauge`): queue depth, current
  learning rate;
* **time-series** — `(step, value)` observations (`observe`): per-chunk
  loss, grad norm, consensus residual;
* **events** — series points, spans, and free-form events append to an
  ordered log (counters/gauges stay aggregate-only so per-frame byte
  counts cannot flood it; exports snapshot their totals), each line of
  which is one JSON object (the JSONL event-log exporter) replayable by
  ``MetricsRegistry.from_jsonl`` — a run report builds offline from the
  file alone (``python -m distributed_learning_tpu.cli obs-report
  run.jsonl``).

Everything here is host-side and jax-free: device-side metrics ride the
jitted chunk's existing outputs (see :mod:`~distributed_learning_tpu.obs.carry`)
and reach the registry once per chunk, never per step.

Thread-safe: the trainer's host loop, the prefetch daemon thread, and
the asyncio comm backend all write to one registry.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from typing import Any, Callable, Dict, Hashable, IO, Iterator, List, Mapping, Optional

from distributed_learning_tpu.utils.telemetry import TelemetryProcessor

__all__ = [
    "MetricsRegistry",
    "JsonlSink",
    "JsonlTelemetry",
    "get_registry",
    "set_registry",
    "use_registry",
    "read_jsonl",
    "run_report",
]


class MetricsRegistry:
    """One run's metrics: counters / gauges / series plus the event log.

    ``max_events`` bounds the in-memory log as a ring (the *last* N
    events are retained — the flight-recorder semantics a post-mortem
    needs); ``max_points`` does the same per series.  Aggregates —
    counters, gauges, series summaries, span stats — are exact
    regardless of either cap, evictions are counted (visible in
    :meth:`snapshot` / :meth:`run_report`), and a :class:`JsonlSink`
    streams the *full* log to disk when nothing may be lost.
    ``max_points=None`` keeps the pre-ring unbounded-list behaviour
    (explicit opt-in for short-lived test registries); the process-wide
    default registry — the one the comm layer counts into — is
    constructed bounded.
    """

    def __init__(self, *, clock: Callable[[], float] = time.time,
                 max_events: int = 1 << 20,
                 max_points: Optional[int] = None):
        self._lock = threading.Lock()
        self._clock = clock
        self._max_events = int(max_events)
        self._max_points = None if max_points is None else int(max_points)
        self._dropped_events = 0
        self.points_dropped: Dict[str, int] = {}
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        # name -> sequence of (step, value); step may be None (arrival
        # order).  A deque ring when max_points is set, a plain list
        # otherwise (so unbounded registries keep list semantics).
        self.series: Dict[str, Any] = {}
        # name -> [count, total_s, max_s] span aggregates.
        self.span_stats: Dict[str, List[float]] = {}
        self.events: Any = (
            collections.deque(maxlen=self._max_events)
            if self._max_events else []
        )
        self._sinks: List[Callable[[dict], None]] = []

    # ------------------------------------------------------------------ #
    def add_sink(self, sink: Callable[[dict], None]) -> None:
        """Stream every event dict to ``sink`` as it is recorded (e.g. a
        :class:`JsonlSink`); long runs stream metrics instead of holding
        them until exit."""
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[dict], None]) -> None:
        """Detach a sink added with :meth:`add_sink` (no-op if absent)."""
        with self._lock:
            try:
                self._sinks.remove(sink)
            except ValueError:
                pass

    def recent_events(self) -> List[dict]:
        """A consistent copy of the retained event log (oldest first) —
        what a late-attached consumer (delta source, flight ring) backfills
        from."""
        with self._lock:
            return list(self.events)

    def _new_series(self):
        if self._max_points is None:
            return []
        return collections.deque(maxlen=self._max_points)

    def _record(self, event: dict) -> None:
        # Caller holds the lock.  The event log is a ring: at capacity
        # the OLDEST event is evicted (and counted), so a post-mortem
        # reads the run's tail, not its first hour.
        if (self._max_events and len(self.events) >= self._max_events):
            self._dropped_events += 1
        self.events.append(event)
        for sink in self._sinks:
            sink(event)

    # ------------------------------------------------------------------ #
    def inc(self, name: str, value: float = 1.0) -> float:
        """Add ``value`` to counter ``name``; returns the new total.

        Counters are hot-path-friendly: an inc is a lock + dict update,
        no per-inc event (per-frame byte counts would otherwise flood
        the log); the export paths snapshot the totals instead."""
        with self._lock:
            total = self.counters.get(name, 0.0) + float(value)
            self.counters[name] = total
            return total

    def gauge(self, name: str, value: float) -> None:
        """Last-value-wins scalar (same aggregate-only discipline as
        counters; use :meth:`observe` when the history matters)."""
        with self._lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value: float,
                step: Optional[int] = None) -> None:
        """Append one time-series observation (ring-evicting the oldest
        point, counted in ``points_dropped``, when ``max_points`` is
        set)."""
        with self._lock:
            pts = self.series.get(name)
            if pts is None:
                pts = self.series[name] = self._new_series()
            if (self._max_points is not None
                    and len(pts) >= self._max_points):
                self.points_dropped[name] = (
                    self.points_dropped.get(name, 0) + 1
                )
            pts.append(
                (None if step is None else int(step), float(value))
            )
            ev = {
                "ts": self._clock(), "kind": "series", "name": name,
                "value": float(value),
            }
            if step is not None:
                ev["step"] = int(step)
            self._record(ev)

    def record_span(self, name: str, dur_s: float, *, depth: int = 0,
                    t0: Optional[float] = None) -> None:
        """Aggregate + log one completed wall-clock span (the
        :class:`~distributed_learning_tpu.obs.spans.SpanTracer` calls
        this; spans are events too, so the JSONL log replays them).
        ``t0``, when known, is the span's wall-clock (unix-epoch) start
        — the anchor that lets per-agent logs merge onto one
        timeline."""
        with self._lock:
            agg = self.span_stats.setdefault(name, [0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += float(dur_s)
            agg[2] = max(agg[2], float(dur_s))
            ev = {
                "ts": self._clock(), "kind": "span", "name": name,
                "value": float(dur_s), "depth": int(depth),
            }
            if t0 is not None:
                ev["t0"] = float(t0)
            self._record(ev)

    def event(self, name: str, **fields: Any) -> None:
        """Free-form event (e.g. a telemetry payload, a round abort)."""
        with self._lock:
            ev = {"ts": self._clock(), "kind": "event", "name": name}
            ev.update(fields)
            self._record(ev)

    # ------------------------------------------------------------------ #
    def snapshot(self) -> dict:
        """Current aggregate state (counters, gauges, series lengths);
        ``dropped`` makes ring truncation visible."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "series": {k: len(v) for k, v in self.series.items()},
                "spans": {k: int(v[0]) for k, v in self.span_stats.items()},
                "dropped": {
                    "events": self._dropped_events,
                    "series_points": sum(self.points_dropped.values()),
                },
            }

    def run_report(self) -> dict:
        """Aggregated run summary: counter totals, last gauges, per-series
        count/mean/min/max/last, per-span count/total/mean/max."""
        with self._lock:
            series = {}
            for name, pts in self.series.items():
                vals = [v for _, v in pts]
                last_step = next(
                    (s for s, _ in reversed(pts) if s is not None), None
                )
                series[name] = {
                    "count": len(vals),
                    "mean": sum(vals) / len(vals),
                    "min": min(vals),
                    "max": max(vals),
                    "last": vals[-1],
                    "last_step": last_step,
                }
                # Ring eviction is visible: stats cover the retained
                # window, "dropped" says how much history it lost.
                if self.points_dropped.get(name):
                    series[name]["dropped"] = self.points_dropped[name]
            spans = {
                name: {
                    "count": int(c),
                    "total_s": total,
                    "mean_s": total / c if c else 0.0,
                    "max_s": mx,
                }
                for name, (c, total, mx) in self.span_stats.items()
            }
            report = {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "series": series,
                "spans": spans,
                "events": len(self.events) + self._dropped_events,
            }
            if self._dropped_events:
                report["events_dropped"] = self._dropped_events
            if self.events:
                report["wall_s"] = (
                    self.events[-1]["ts"] - self.events[0]["ts"]
                )
            return report

    # -- JSONL event-log exporter -------------------------------------- #
    def dump_jsonl(self, path: str) -> int:
        """Write the event log, one JSON object per line, followed by a
        counter/gauge totals snapshot (counters record no per-inc
        events, so the snapshot is how they reach the file); returns
        the number of lines written."""
        ts = self._clock()
        with self._lock:
            events = list(self.events)
            events += [
                {"ts": ts, "kind": "counter", "name": k, "total": v}
                for k, v in sorted(self.counters.items())
            ]
            events += [
                {"ts": ts, "kind": "gauge", "name": k, "value": v}
                for k, v in sorted(self.gauges.items())
            ]
        with open(path, "w", encoding="utf-8") as fh:
            for ev in events:
                fh.write(json.dumps(ev, sort_keys=True) + "\n")
        return len(events)

    @classmethod
    def from_jsonl(cls, path: str) -> "MetricsRegistry":
        """Rebuild a registry by replaying a JSONL event log (the
        round-trip inverse of :meth:`dump_jsonl`; timestamps are
        preserved from the file, not re-stamped)."""
        return cls.from_events(read_jsonl(path))

    @classmethod
    def from_events(cls, events) -> "MetricsRegistry":
        """Rebuild a registry by replaying an iterable of event dicts
        (what :meth:`from_jsonl` and the tolerant mid-write reader of
        ``obs-monitor`` share)."""
        reg = cls()
        for ev in events:
            kind = ev.get("kind")
            name = ev.get("name", "")
            if kind == "counter":
                # Snapshot lines carry the running total (authoritative);
                # plain increment lines add up.
                if "total" in ev:
                    reg.counters[name] = ev["total"]
                else:
                    reg.counters[name] = (
                        reg.counters.get(name, 0.0) + ev.get("value", 0.0)
                    )
            elif kind == "gauge":
                reg.gauges[name] = ev.get("value", 0.0)
            elif kind == "series":
                reg.series.setdefault(name, []).append(
                    (ev.get("step"), ev.get("value", 0.0))
                )
            elif kind == "span":
                agg = reg.span_stats.setdefault(name, [0, 0.0, 0.0])
                agg[0] += 1
                agg[1] += ev.get("value", 0.0)
                agg[2] = max(agg[2], ev.get("value", 0.0))
            reg.events.append(ev)
        return reg


def read_jsonl(path: str) -> Iterator[dict]:
    """Yield each non-blank line of a JSONL file as a dict."""
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield json.loads(line)


def run_report(path: str) -> dict:
    """Run-report exporter over an on-disk JSONL event log."""
    return MetricsRegistry.from_jsonl(path).run_report()


class JsonlSink:
    """Streaming JSONL writer: attach with ``registry.add_sink(sink)``
    and every event lands on disk (flushed) the moment it is recorded —
    a crash loses nothing, a long run never buffers unboundedly."""

    def __init__(self, path_or_file: Any):
        self._own = isinstance(path_or_file, (str, bytes))
        self._fh: IO = (
            open(path_or_file, "a", encoding="utf-8")
            if self._own else path_or_file
        )
        self._lock = threading.Lock()

    def __call__(self, event: Mapping[str, Any]) -> None:
        with self._lock:
            self._fh.write(json.dumps(dict(event), sort_keys=True) + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._own:
            self._fh.close()


class JsonlTelemetry(TelemetryProcessor):
    """:class:`TelemetryProcessor` that streams each per-node payload to
    a JSONL file as it arrives — the trainer flushes telemetry once per
    jitted chunk, so a long run's metrics are on disk while it trains
    instead of only at exit.  The abstract ``process(token, payload)``
    interface is unchanged; existing subclasses are unaffected."""

    def __init__(self, path: str, *,
                 registry: Optional[MetricsRegistry] = None):
        self._sink = JsonlSink(path)
        self._registry = registry
        self._clock = time.time

    def process(self, token: Hashable, payload: Any) -> None:
        self._sink({
            "ts": self._clock(), "kind": "event", "name": "telemetry",
            "token": str(token), "payload": payload,
        })
        if self._registry is not None:
            self._registry.event("telemetry", token=str(token),
                                 payload=payload)

    def close(self) -> None:
        self._sink.close()


# ---------------------------------------------------------------------- #
# Default (process-wide) registry                                        #
# ---------------------------------------------------------------------- #
# Bounded by default: the comm/prefetch layers count into this registry
# for the life of the process, and an unbounded series (one residual
# observation per gossip round, forever) is a slow memory leak on a
# long-lived agent.  The rings keep the last 16Ki points per series /
# 64Ki events; evictions stay visible via ``points_dropped`` /
# ``events_dropped``.
_DEFAULT = MetricsRegistry(max_points=1 << 14, max_events=1 << 16)
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what the comm/prefetch layers
    count into when no explicit registry is wired through)."""
    return _DEFAULT


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Replace the default registry; returns the previous one."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, registry
        return prev


@contextlib.contextmanager
def use_registry(registry: MetricsRegistry) -> Iterator[MetricsRegistry]:
    """Scope the default registry (tests isolate their counters with
    this)."""
    prev = set_registry(registry)
    try:
        yield registry
    finally:
        set_registry(prev)
