"""Nested wall-clock span tracing with Chrome trace-event export.

``jax.profiler`` answers "where did the *device* time go" (see
``utils/profiling.py``); these spans answer the host-side half — "where
did this *step's wall clock* go": jitted-chunk dispatch vs gossip vs
eval vs host bookkeeping.  A span is a context manager; spans nest, the
per-thread stack tracks depth/parentage, and the result exports as
Chrome ``traceEvents`` JSON (load in ``chrome://tracing`` / Perfetto)
or aggregates into the run report through the
:class:`~distributed_learning_tpu.obs.registry.MetricsRegistry`.

``profiler=True`` additionally wraps every span in
``jax.profiler.TraceAnnotation`` (via
:func:`distributed_learning_tpu.utils.profiling.annotate`), so the same
span names appear inside a TensorBoard device profile when one is being
captured — one naming scheme across both tools.

Everything is host-side: entering/leaving a span is two monotonic clock
reads and a list append.  No device syncs, no jax import unless
``profiler=True``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from distributed_learning_tpu.obs.registry import MetricsRegistry, get_registry

__all__ = [
    "Span",
    "SpanTracer",
    "get_tracer",
    "set_tracer",
    "span",
    "FLOW_EVENT",
    "FLOW_PHASES",
    "emit_flow",
    "flow_key",
    "trace_keep",
]

# ---------------------------------------------------------------------- #
# Frame flow events (the wire trace plane)                               #
# ---------------------------------------------------------------------- #
#: Registry event name every frame-lifecycle hop emits under.
FLOW_EVENT = "trace.flow"

#: The frame lifecycle, in causal order: the sender encodes and sends,
#: the receiver recvs, decodes, and mixes.  A frame is identified
#: across processes by its wire-carried
#: :class:`~distributed_learning_tpu.comm.protocol.TraceContext`
#: ``(run_id, origin, seq)`` triple, so the N processes' phase events
#: chain into one arrow-linked flow in the merged Perfetto trace
#: (``RunAggregator.to_chrome_trace``).
FLOW_PHASES = ("encode", "send", "recv", "decode", "mix")


def flow_key(run_id: int, origin: str, seq: int) -> str:
    """The fleet-unique flow id shared by one frame's phase events."""
    return f"{int(run_id)}:{origin}:{int(seq)}"


def emit_flow(registry: MetricsRegistry, phase: str, *,
              origin: str, seq: int, run_id: int = 0,
              edge: str = "", **fields) -> None:
    """Record one frame-lifecycle hop as a ``trace.flow`` registry
    event.  ``phase`` is one of :data:`FLOW_PHASES`; ``origin``/``seq``/
    ``run_id`` come from the frame's wire-carried ``TraceContext`` (the
    sender stamps them, the receiver replays the received ones — both
    sides of an edge MUST agree or the chain breaks); ``edge`` labels
    the directed link ``src->dst`` when known.  Extra ``fields`` ride
    along into the event (round, staleness, ...).  Cost when tracing is
    on: one dict append into the registry's event ring — no clock
    beyond the registry's own stamp, no device sync."""
    registry.event(
        FLOW_EVENT, phase=phase, origin=origin, seq=int(seq),
        run=int(run_id), edge=edge, **fields,
    )


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a fixed, platform-independent 64-bit
    mix.  NOT Python's ``hash()`` — that is salted per process
    (PYTHONHASHSEED), and the whole point is that every process
    computes the same bits for the same flow identity."""
    x &= 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def trace_keep(run_id: int, origin: str, seq: int,
               rate: float) -> bool:
    """Consistent flow-sampling decision: keep this frame's trace?

    Derived deterministically from the wire-carried ``TraceContext``
    identity ``(run_id, origin, seq)`` — the SAME triple every hop of
    the frame sees — so the sender and every receiver agree on
    keep/drop without coordination, and a sampled flow chain is always
    complete (encode→send→recv→decode→mix all present or all absent;
    a partially-sampled chain would render as broken arrows).
    ``rate >= 1.0`` short-circuits to True before any hashing: the
    neutral knob is bit-identical to no sampling at all.
    """
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = _mix64(int(run_id) * 0x9E3779B97F4A7C15 + int(seq))
    for ch in origin:
        h = _mix64(h ^ ord(ch))
    # Top 53 bits -> uniform float in [0, 1).
    return (h >> 11) * (1.0 / (1 << 53)) < rate


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed span (times are seconds on the tracer's clock)."""

    name: str
    t0: float
    dur: float
    depth: int
    parent: Optional[str]
    tid: int


class SpanTracer:
    """Collects nested wall-clock spans.

    Parameters
    ----------
    registry:
        Optional :class:`MetricsRegistry` to aggregate completed spans
        into (``record_span``), so span stats join the run report and
        the JSONL event log.  A zero-arg callable is resolved per span
        (the default tracer passes ``get_registry`` so
        ``use_registry`` scoping applies to spans too).
    profiler:
        Also emit each span as a ``jax.profiler.TraceAnnotation`` so the
        names land inside an active device profile.
    max_spans:
        Bound on the retained per-span detail (aggregates in the
        registry stay exact past the cap; the Chrome export covers the
        first ``max_spans`` spans).

    Span times are read on the monotonic ``clock`` (durations must
    never come from ``time.time()`` deltas — graftlint
    ``wallclock-duration``), but ``perf_counter`` origins are
    process-local, so every tracer also records ``wall0``: the
    wall-clock epoch of its monotonic zero.  Exports anchor span starts
    to ``wall0``, which is what lets N processes' traces merge onto ONE
    timeline (``RunAggregator.to_chrome_trace``).
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 profiler: bool = False, max_spans: int = 1 << 16,
                 clock=time.perf_counter):
        self.registry = registry
        self.profiler = bool(profiler)
        self._clock = clock
        self._max_spans = int(max_spans)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._epoch = self._clock()
        # Wall-clock anchor: the absolute time of monotonic zero
        # (_epoch).  The two reads are adjacent, so the anchor is good
        # to well under a millisecond — plenty for cross-process trace
        # alignment (gossip rounds are >= milliseconds).
        self.wall0 = time.time()
        self.spans: List[Span] = []
        self.dropped = 0

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        """Time the enclosed block as span ``name`` (nested spans record
        their depth and parent)."""
        stack = self._stack()
        depth = len(stack)
        parent = stack[-1] if stack else None
        stack.append(name)
        if self.profiler:
            from distributed_learning_tpu.utils.profiling import annotate

            cm: Any = annotate(name)
        else:
            cm = contextlib.nullcontext()
        t0 = self._clock()
        try:
            with cm:
                yield
        finally:
            dur = self._clock() - t0
            stack.pop()
            with self._lock:
                if len(self.spans) < self._max_spans:
                    self.spans.append(Span(
                        name=name, t0=t0 - self._epoch, dur=dur,
                        depth=depth, parent=parent,
                        tid=threading.get_ident(),
                    ))
                else:
                    self.dropped += 1
            reg = (
                self.registry() if callable(self.registry)
                else self.registry
            )
            if reg is not None:
                # Wall-anchored start: registry/JSONL span events carry
                # an absolute t0, so per-agent logs merge onto one
                # timeline without knowing each tracer's epoch.
                reg.record_span(
                    name, dur, depth=depth,
                    t0=self.wall0 + (t0 - self._epoch),
                )

    # ------------------------------------------------------------------ #
    def aggregate(self) -> Dict[str, dict]:
        """Per-name count/total/mean/max over the retained spans."""
        with self._lock:
            spans = list(self.spans)
        out: Dict[str, dict] = {}
        for s in spans:
            agg = out.setdefault(
                s.name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            agg["count"] += 1
            agg["total_s"] += s.dur
            agg["max_s"] = max(agg["max_s"], s.dur)
        for agg in out.values():
            agg["mean_s"] = agg["total_s"] / agg["count"]
        return out

    def to_chrome_trace(self, *, wall_clock: bool = True) -> dict:
        """Chrome trace-event JSON (complete 'X' events, microseconds);
        load the exported file in ``chrome://tracing`` or Perfetto.

        ``wall_clock=True`` (default) anchors ``ts`` to the tracer's
        ``wall0`` — absolute unix-epoch microseconds — so traces
        exported by N processes land on ONE shared timeline when merged
        (the run-wide plane's per-agent tracks); ``wall_clock=False``
        keeps the tracer-relative origin."""
        with self._lock:
            spans = list(self.spans)
        base = self.wall0 if wall_clock else 0.0
        events = [
            {
                "name": s.name,
                "ph": "X",
                "ts": round((base + s.t0) * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "pid": 0,
                "tid": s.tid,
                "args": {"depth": s.depth, "parent": s.parent or ""},
            }
            for s in spans
        ]
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> int:
        """Write :meth:`to_chrome_trace` to ``path``; returns the event
        count."""
        trace = self.to_chrome_trace()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        return len(trace["traceEvents"])

    def reset(self) -> None:
        with self._lock:
            self.spans.clear()
            self.dropped = 0
            self._epoch = self._clock()
            self.wall0 = time.time()  # re-anchor with the new epoch


# ---------------------------------------------------------------------- #
# Default (process-wide) tracer                                          #
# ---------------------------------------------------------------------- #
_DEFAULT: Optional[SpanTracer] = None
_DEFAULT_LOCK = threading.Lock()


def get_tracer() -> SpanTracer:
    """The process-wide default tracer, lazily bound to the default
    registry (so library spans aggregate into the same run report as the
    library counters)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanTracer(registry=get_registry)
        return _DEFAULT


def set_tracer(tracer: SpanTracer) -> Optional[SpanTracer]:
    """Replace the default tracer; returns the previous one."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, tracer
        return prev


def span(name: str):
    """Convenience: a span on the default tracer."""
    return get_tracer().span(name)
