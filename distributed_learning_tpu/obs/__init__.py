"""Unified observability layer: metrics registry, device-side metrics
carry, span tracing, and the comm-layer gossip counters.

One import surface for the four pieces:

* :class:`MetricsRegistry` (+ JSONL event-log / run-report exporters)
  — `registry.py`;
* :func:`flush_chunk` / :func:`global_norm` — the device-side metrics
  carry that keeps instrumentation out of the hot path — `carry.py`;
* :class:`SpanTracer` (nested wall-clock spans, Chrome trace export,
  ``jax.profiler`` integration) — `spans.py`;
* :func:`instrument_step` — transparent call wrapping for compiled step
  functions — `instrument.py`;
* run-report rendering + the ``obs-report`` / ``obs-monitor`` CLIs —
  `report.py`;
* the **run-wide plane** — `aggregate.py` (:class:`ObsDeltaSource`
  agent-side registry deltas, :class:`RunAggregator` master-side merge
  with per-agent labels, straggler profiles, merged Perfetto traces)
  and `flight.py` (:class:`FlightRecorder` — per-agent event rings
  dumped to a JSONL black box on abort/death/deadline/shutdown);
* the **trace plane + health sentinel** — wire-propagated frame flow
  events (`spans.py` :func:`emit_flow` over the
  ``protocol.TraceContext`` carried on the gossip wire, arrow-linked in
  the merged trace), per-edge wire profiles
  (:func:`edge_profile_from_registry`), and `health.py`
  (:class:`HealthSentinel` — declarative live-run rules over the
  merged registry, reason-tagged flight dumps on breach);
* the **device-cost observatory** — `cost.py` (:class:`CostProfile`
  extracted from any compiled entry point: FLOPs, bytes, peak HBM,
  donation, collective inventory; :class:`SampledDispatchTimer`
  1-in-N chunk-boundary step timing with MFU/bytes-per-sec gauges;
  the persistent `PERF_LEDGER.jsonl` perf ledger behind
  ``obs-report --ledger``).

Library code counts into the process-wide default registry/tracer
(`get_registry()` / `get_tracer()`); tests and multi-run drivers scope
them with `use_registry` / `set_tracer`.
"""

from distributed_learning_tpu.obs.carry import flush_chunk, global_norm
from distributed_learning_tpu.obs.cost import (
    CostProfile,
    SampledDispatchTimer,
    all_profiles,
    clear_profiles,
    device_peak_flops,
    get_profile,
    ledger_append,
    profile_fn,
    read_ledger,
    register_profile,
)
from distributed_learning_tpu.obs.instrument import InstrumentedStep, instrument_step
from distributed_learning_tpu.obs.registry import (
    JsonlSink,
    JsonlTelemetry,
    MetricsRegistry,
    get_registry,
    read_jsonl,
    run_report,
    set_registry,
    use_registry,
)
from distributed_learning_tpu.obs.aggregate import (
    OBS_PAYLOAD_KIND,
    OBS_PAYLOAD_SECTIONS,
    OBS_PAYLOAD_VERSION,
    SKETCH_SERIES,
    ObsDeltaSource,
    RunAggregator,
    SubAggregator,
    edge_profile_from_registry,
    is_obs_payload,
    straggler_profile_from_registry,
)
from distributed_learning_tpu.obs.sketch import (
    DEFAULT_ALPHA,
    LabelRollup,
    QuantileSketch,
)
from distributed_learning_tpu.obs.flight import FlightRecorder
from distributed_learning_tpu.obs.health import (
    HealthBreach,
    HealthRule,
    HealthSentinel,
    default_rules,
)
from distributed_learning_tpu.obs.report import format_run_report, obs_report_main
from distributed_learning_tpu.obs.spans import (
    FLOW_EVENT,
    FLOW_PHASES,
    Span,
    SpanTracer,
    emit_flow,
    flow_key,
    get_tracer,
    set_tracer,
    span,
    trace_keep,
)

__all__ = [
    "MetricsRegistry",
    "JsonlSink",
    "JsonlTelemetry",
    "get_registry",
    "set_registry",
    "use_registry",
    "read_jsonl",
    "run_report",
    "flush_chunk",
    "global_norm",
    "Span",
    "SpanTracer",
    "get_tracer",
    "set_tracer",
    "span",
    "InstrumentedStep",
    "instrument_step",
    "CostProfile",
    "SampledDispatchTimer",
    "profile_fn",
    "register_profile",
    "get_profile",
    "all_profiles",
    "clear_profiles",
    "device_peak_flops",
    "ledger_append",
    "read_ledger",
    "format_run_report",
    "obs_report_main",
    "OBS_PAYLOAD_KIND",
    "OBS_PAYLOAD_SECTIONS",
    "OBS_PAYLOAD_VERSION",
    "SKETCH_SERIES",
    "DEFAULT_ALPHA",
    "QuantileSketch",
    "LabelRollup",
    "ObsDeltaSource",
    "RunAggregator",
    "SubAggregator",
    "FlightRecorder",
    "is_obs_payload",
    "straggler_profile_from_registry",
    "edge_profile_from_registry",
    "FLOW_EVENT",
    "FLOW_PHASES",
    "emit_flow",
    "flow_key",
    "trace_keep",
    "HealthBreach",
    "HealthRule",
    "HealthSentinel",
    "default_rules",
]
