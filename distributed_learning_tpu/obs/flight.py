"""Fault flight recorder: a bounded per-agent ring of recent events,
dumped to a JSONL artifact the moment something goes wrong.

The failure modes this repo has actually hit — an agent dying mid-round
(``comm.master.rounds_aborted``), the TPU tunnel wedging for hours
(BENCH_r02-r05), a master tearing the deployment down with a reason —
all used to leave behind a counter increment and nothing else.  The
recorder keeps the last ``capacity`` events *per agent* (telemetry
deltas, gossip round spans, series points, free-form notes) in memory,
and :meth:`trigger` writes them all to one ``flight-NNN-<reason>.jsonl``
file: every abort ships its own black box.

Everything is host-side and jax-free.  The rings are deques, recording
is a lock + append, and the only IO is the dump itself — which runs on
the failure path, where a few milliseconds of file writing is free.

Wired by the run-wide plane (``obs/aggregate.py`` feeds every merged
per-agent event in; ``comm/master.py`` notes control-plane transitions
and fires the triggers: round abort, agent death, round-deadline
expiry, shutdown-with-reason).  Usable standalone too: ``record`` /
``note`` / ``trigger`` have no comm dependencies.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

__all__ = ["FlightRecorder"]

_SLUG_RE = re.compile(r"[^A-Za-z0-9_.-]+")


class FlightRecorder:
    """Bounded ring of the last ``capacity`` events per agent, dumped to
    JSONL on demand.

    Parameters
    ----------
    directory:
        Where dump artifacts land (created if missing).
    capacity:
        Events retained per agent (ring: oldest evicted first).
    global_capacity:
        Optional cap on TOTAL retained events across all agents — the
        fleet-scale memory bound.  Past it, every agent's effective
        ring length shrinks proportionally
        (``max(8, global_capacity // n_agents)``, never above
        ``capacity``), so 500 churning agents cannot multiply the
        recorder's footprint 500x; the shed tail counts into the same
        per-agent eviction ledger the dumps disclose.  ``None`` (the
        default) keeps the pre-fleet behavior: per-agent rings only.
    clock:
        Wall-clock source for dump/note timestamps — wall clock on
        purpose: artifacts from different processes must line up on one
        timeline, which process-local monotonic clocks cannot give.
    """

    def __init__(self, directory: str, *, capacity: int = 256,
                 global_capacity: Optional[int] = None,
                 clock=time.time):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.capacity = int(capacity)
        self.global_capacity = (
            None if global_capacity is None else int(global_capacity)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._rings: Dict[str, collections.deque] = {}
        self._dropped: Dict[str, int] = {}
        self._dumps = 0
        #: Paths of every artifact written so far (newest last).
        self.dumped: List[str] = []

    def _per_agent_capacity(self, n_agents: int) -> int:
        """Effective ring length at ``n_agents`` under the global cap."""
        if self.global_capacity is None or n_agents <= 0:
            return self.capacity
        share = max(8, self.global_capacity // n_agents)
        return min(self.capacity, share)

    def _resize_rings_locked(self, cap: int) -> None:
        """Shrink/regrow every ring to ``cap`` (deques are recreated —
        maxlen is immutable); the tail shed by a shrink counts as
        evictions, same ledger as ring overwrites."""
        for agent, ring in list(self._rings.items()):
            if ring.maxlen == cap:
                continue
            shed = max(0, len(ring) - cap)
            if shed:
                self._dropped[agent] = (
                    self._dropped.get(agent, 0) + shed
                )
            self._rings[agent] = collections.deque(ring, maxlen=cap)

    # ------------------------------------------------------------------ #
    def record(self, agent: str, event: Mapping[str, Any]) -> None:
        """Append one event dict to ``agent``'s ring."""
        agent = str(agent)
        with self._lock:
            ring = self._rings.get(agent)
            if ring is None:
                cap = self._per_agent_capacity(len(self._rings) + 1)
                # A new agent may tighten everyone's share (no-op
                # whenever the cap did not actually change).
                self._resize_rings_locked(cap)
                ring = self._rings[agent] = collections.deque(
                    maxlen=cap
                )
            cap = ring.maxlen if ring.maxlen is not None else self.capacity
            if len(ring) >= cap:
                self._dropped[agent] = self._dropped.get(agent, 0) + 1
            ring.append(dict(event))

    def note(self, agent: str, name: str, **fields: Any) -> None:
        """Free-form timestamped event (the master's control-plane
        transitions use this under the ``<master>`` pseudo-agent)."""
        ev = {"ts": self._clock(), "kind": "event", "name": name}
        ev.update(fields)
        self.record(agent, ev)

    # ------------------------------------------------------------------ #
    def trigger(self, reason: str, **context: Any) -> str:
        """Dump every agent's ring to one JSONL artifact; returns its
        path.

        Line 1 is a header ``{"kind": "flight", "reason": ..., ...}``
        with the trigger context; each following line is one retained
        event tagged with its ``"agent"``.  The rings are snapshotted
        under the lock and KEPT (not cleared): a second fault shortly
        after the first still has its full window, and overlapping
        dumps are cheap."""
        with self._lock:
            self._dumps += 1
            seq = self._dumps
            snapshot = {
                agent: list(ring) for agent, ring in self._rings.items()
            }
            dropped = dict(self._dropped)
        slug = _SLUG_RE.sub("-", reason).strip("-") or "fault"
        path = os.path.join(
            self.directory, f"flight-{seq:03d}-{slug}.jsonl"
        )
        header = {
            "kind": "flight",
            "reason": reason,
            "ts": self._clock(),
            "agents": sorted(snapshot),
            "events": sum(len(v) for v in snapshot.values()),
            "capacity": self.capacity,
        }
        if self.global_capacity is not None:
            header["global_capacity"] = self.global_capacity
        if dropped:
            header["ring_evictions"] = dropped
        header.update(context)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(header, sort_keys=True, default=str) + "\n")
            for agent in sorted(snapshot):
                for ev in snapshot[agent]:
                    line = {"agent": agent}
                    line.update(ev)
                    fh.write(json.dumps(line, sort_keys=True, default=str)
                             + "\n")
        with self._lock:
            self.dumped.append(path)
        return path

    # ------------------------------------------------------------------ #
    def snapshot(self) -> dict:
        """The recorder's current footprint: agents tracked, configured
        caps, the effective per-agent ring length, total retained
        events (``occupancy``), and the per-agent eviction ledger —
        the visibility half of the global-cap contract."""
        with self._lock:
            n = len(self._rings)
            return {
                "agents": n,
                "capacity": self.capacity,
                "global_capacity": self.global_capacity,
                "per_agent_capacity": self._per_agent_capacity(n),
                "occupancy": sum(
                    len(r) for r in self._rings.values()
                ),
                "evictions": dict(self._dropped),
            }

    def agents(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def ring(self, agent: str) -> List[dict]:
        """A copy of ``agent``'s current ring (oldest first)."""
        with self._lock:
            return list(self._rings.get(str(agent), ()))

    @staticmethod
    def read_dump(path: str) -> tuple:
        """(header, events) from a dump artifact written by
        :meth:`trigger`."""
        with open(path, "r", encoding="utf-8") as fh:
            lines = [json.loads(l) for l in fh if l.strip()]
        if not lines or lines[0].get("kind") != "flight":
            raise ValueError(f"{path} is not a flight-recorder dump")
        return lines[0], lines[1:]
