"""Online health sentinel: declarative live-run rules over the merged
run registry.

Everything before this watched a run *post-mortem*: flight dumps are
written when something already died, and ``obs-report`` replays logs
after the fact.  The :class:`HealthSentinel` is the live half — the
master (or any aggregator owner) drives it once per merged telemetry
batch, it evaluates a small set of declarative :class:`HealthRule`\\ s
over the :class:`~distributed_learning_tpu.obs.aggregate.RunAggregator`
registry, and on a breach it

* emits a ``health.breach`` event + ``health.breaches/<rule>`` counter
  + per-rule ``health.breached/<rule>`` gauge into the same registry
  (so breaches ride the aggregate JSONL stream into ``obs-monitor``'s
  live health section), and
* proactively triggers a reason-tagged
  :class:`~distributed_learning_tpu.obs.flight.FlightRecorder` dump
  (``health-<rule>``) — the black box is written while the run is
  still alive, not after it died.

The default rule set covers the failure modes the comm stack already
counts but nothing watched (docs/observability.md §Health sentinel):

===========================  ==========================================
rule                         breaches when
===========================  ==========================================
``consensus-stall``          a ``consensus.residual/<token>`` series
                             stopped improving over its trailing window
``staleness-pressure``       the mean mixed staleness
                             (``comm.agent.staleness/*``) exceeds the
                             configured tau pressure bound
``round-latency-regression`` the recent mean round wall time regressed
                             past ``factor`` x the rolling baseline of
                             earlier rounds
``wire-error-storm``         wire-error counters (frame retries, codec
                             drops, robust-gossip violations/
                             quarantines, injected faults) grew by more
                             than ``threshold`` since the last
                             evaluation
``eviction-pressure``        the obs plane itself is losing data
                             (``obs.deltas_lost`` +
                             ``obs.delta_events_dropped/*`` growth)
===========================  ==========================================

Growth-based rules prime on their first evaluation (no breach on the
first batch — a restarted master must not re-fire on totals it never
saw grow).  Evaluation is host-side, jax-free, and never raises: a rule
that throws is counted (``health.rule_errors``) and skipped, because a
monitoring plane must not be able to kill the run it watches.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from distributed_learning_tpu.obs.flight import FlightRecorder
from distributed_learning_tpu.obs.registry import MetricsRegistry

__all__ = [
    "HealthBreach",
    "HealthRule",
    "HealthSentinel",
    "default_rules",
]


@dataclasses.dataclass(frozen=True)
class HealthBreach:
    """One rule violation at one evaluation."""

    rule: str
    detail: str
    value: float
    threshold: float


class HealthRule:
    """Base: subclasses set ``name``/``description`` and implement
    :meth:`check` against the sentinel's evaluation context."""

    name = "rule"
    description = ""

    def check(self, ctx: "HealthSentinel") -> Optional[HealthBreach]:
        raise NotImplementedError


def _series_tails(registry: MetricsRegistry, prefix: str,
                  n: int) -> Dict[str, List[float]]:
    """label -> last ``n`` values, for every series under ``prefix``."""
    out: Dict[str, List[float]] = {}
    for name, pts in registry.series.items():
        if name == prefix.rstrip("/") or name.startswith(prefix):
            vals = [v for _s, v in pts]
            if vals:
                out[name] = vals[-n:]
    return out


class ConsensusStallRule(HealthRule):
    """A consensus residual that stopped shrinking: the run burns
    rounds without converging (dead link, diverged weights, a
    Byzantine neighbor past the defense's budget)."""

    name = "consensus-stall"
    description = ("consensus.residual stopped improving over its "
                   "trailing window")

    def __init__(self, *, window: int = 6, min_drop: float = 0.02,
                 floor: float = 1e-6):
        self.window = int(window)
        self.min_drop = float(min_drop)
        self.floor = float(floor)

    def check(self, ctx: "HealthSentinel") -> Optional[HealthBreach]:
        worst: Optional[HealthBreach] = None
        for name, pts in ctx.registry.series.items():
            if not name.startswith("consensus.residual"):
                continue
            vals = [v for _s, v in pts][-self.window:]
            if len(vals) < self.window:
                continue
            first, last = vals[0], vals[-1]
            if first <= self.floor:
                continue  # converged; nothing left to improve
            improvement = (first - last) / abs(first)
            if improvement < self.min_drop:
                br = HealthBreach(
                    rule=self.name,
                    detail=(
                        f"{name}: {first:.3g} -> {last:.3g} over last "
                        f"{self.window} points "
                        f"({improvement * 100:.1f}% < "
                        f"{self.min_drop * 100:.0f}% drop)"
                    ),
                    value=improvement,
                    threshold=self.min_drop,
                )
                if worst is None or br.value < worst.value:
                    worst = br
        return worst


class StalenessPressureRule(HealthRule):
    """Mixed staleness blowing past the tau the schedule was tuned for:
    the async runtime is mixing mostly-old values, convergence quality
    degrades silently (docs/async_runtime.md tau trade-off)."""

    name = "staleness-pressure"
    description = "mean mixed staleness exceeds the tau pressure bound"

    def __init__(self, *, max_mean: float = 4.0, window: int = 16):
        self.max_mean = float(max_mean)
        self.window = int(window)

    def check(self, ctx: "HealthSentinel") -> Optional[HealthBreach]:
        tails = _series_tails(
            ctx.registry, "comm.agent.staleness/", self.window
        )
        vals = [v for tail in tails.values() for v in tail]
        if not vals:
            return None
        mean = sum(vals) / len(vals)
        if mean <= self.max_mean:
            return None
        return HealthBreach(
            rule=self.name,
            detail=(
                f"mean mixed staleness {mean:.2f} > {self.max_mean:g} "
                f"over {len(vals)} recent mixes "
                f"(max {max(vals):.0f})"
            ),
            value=mean,
            threshold=self.max_mean,
        )


class RoundLatencyRegressionRule(HealthRule):
    """Recent rounds run ``factor``x slower than the rolling healthy
    baseline: a link went bad, a host started swapping, a straggler
    appeared — catch it from the trend, before the deadline logic has
    to amputate anyone."""

    name = "round-latency-regression"
    description = ("recent mean round wall time regressed vs the "
                   "rolling healthy baseline")

    def __init__(self, *, factor: float = 2.0, recent: int = 5,
                 min_history: int = 10):
        self.factor = float(factor)
        self.recent = int(recent)
        self.min_history = int(min_history)

    def _candidates(
        self, registry: MetricsRegistry
    ) -> Sequence[Tuple[str, List[float]]]:
        for prefix in ("comm.master.round_s", "comm.agent.round_s/",
                       "comm.agent.async_round_s/"):
            tails = _series_tails(registry, prefix, 1 << 30)
            if tails:
                return sorted(tails.items())
        return ()

    def check(self, ctx: "HealthSentinel") -> Optional[HealthBreach]:
        worst: Optional[HealthBreach] = None
        for label, vals in self._candidates(ctx.registry):
            if len(vals) < max(self.min_history, self.recent + 1):
                continue
            baseline_vals = vals[:-self.recent]
            baseline = sum(baseline_vals) / len(baseline_vals)
            recent = sum(vals[-self.recent:]) / self.recent
            if baseline <= 0 or recent <= self.factor * baseline:
                continue
            br = HealthBreach(
                rule=self.name,
                detail=(
                    f"{label}: recent mean {recent:.4f}s > "
                    f"{self.factor:g}x baseline {baseline:.4f}s "
                    f"(last {self.recent} of {len(vals)} rounds)"
                ),
                value=recent / baseline,
                threshold=self.factor,
            )
            if worst is None or br.value > worst.value:
                worst = br
        return worst


class WireErrorStormRule(HealthRule):
    """Wire-error counters growing in a burst: frame retries, codec
    drops, robust-gossip violations/quarantines, injected faults.  Any
    one of them trickling is survivable; a storm means an edge (or a
    peer) is actively failing."""

    name = "wire-error-storm"
    description = ("wire error/quarantine counters grew past the "
                   "storm threshold since the last evaluation")

    #: substrings of BARE (unlabeled) counter names that count as wire
    #: errors.  comm.faults.* is matched by prefix: its bare per-kind
    #: counters (comm.faults.drop, ...) have no label dimension.
    MARKERS = ("frame_retries", "crc_drop", "decode_failed",
               "validation", "violation", "quarantin")

    def __init__(self, *, threshold: float = 10.0):
        self.threshold = float(threshold)

    def check(self, ctx: "HealthSentinel") -> Optional[HealthBreach]:
        total = 0.0
        for name, v in ctx.counters.items():
            if "/" in name:
                continue
            if name.startswith("comm.faults.") or any(
                m in name for m in self.MARKERS
            ):
                total += float(v)
        growth = ctx.growth(self.name, total)
        if growth is None or growth < self.threshold:
            return None
        return HealthBreach(
            rule=self.name,
            detail=(
                f"wire errors grew by {growth:g} since the last "
                f"evaluation (total {total:g})"
            ),
            value=growth,
            threshold=self.threshold,
        )


class EvictionPressureRule(HealthRule):
    """The obs plane itself is shedding data: lost telemetry deltas or
    agent-side event-buffer evictions growing means every OTHER signal
    here is becoming partial — surface it before trusting them."""

    name = "eviction-pressure"
    description = ("obs.deltas_lost / delta_events_dropped grew past "
                   "the eviction threshold since the last evaluation")

    def __init__(self, *, threshold: float = 64.0):
        self.threshold = float(threshold)

    def check(self, ctx: "HealthSentinel") -> Optional[HealthBreach]:
        total = float(ctx.counters.get("obs.deltas_lost", 0))
        for name, v in ctx.counters.items():
            if (name.startswith("obs.delta_events_dropped/")
                    and name.count("/") == 1):
                total += float(v)
        growth = ctx.growth(self.name, total)
        if growth is None or growth < self.threshold:
            return None
        return HealthBreach(
            rule=self.name,
            detail=(
                f"obs-plane data loss grew by {growth:g} since the "
                f"last evaluation (total {total:g})"
            ),
            value=growth,
            threshold=self.threshold,
        )


def default_rules() -> Tuple[HealthRule, ...]:
    """The five stock rules with their default thresholds."""
    return (
        ConsensusStallRule(),
        StalenessPressureRule(),
        RoundLatencyRegressionRule(),
        WireErrorStormRule(),
        EvictionPressureRule(),
    )


class HealthSentinel:
    """Evaluates :class:`HealthRule`\\ s over a merged run registry.

    Drive it from whoever owns the :class:`RunAggregator` — the master
    calls :meth:`evaluate` after each merged telemetry batch
    (``ConsensusMaster(sentinel=...)``).  Breaches are emitted into the
    SAME registry the rules read (``health.*`` names are never
    themselves rule inputs), and each breached rule triggers one
    reason-tagged flight dump per ``cooldown_s`` window so a persistent
    breach cannot write an unbounded dump stream.
    """

    def __init__(self, registry: MetricsRegistry, *,
                 flight: Optional[FlightRecorder] = None,
                 rules: Optional[Sequence[HealthRule]] = None,
                 cooldown_s: float = 30.0,
                 counters_source: Optional[
                     Callable[[], Mapping[str, float]]
                 ] = None):
        self.registry = registry
        self.flight = flight
        self.rules: Tuple[HealthRule, ...] = tuple(
            rules if rules is not None else default_rules()
        )
        self.cooldown_s = float(cooldown_s)
        self._counters_source = counters_source
        self._growth_baseline: Dict[str, float] = {}
        self._last_dump: Dict[str, float] = {}
        self.breaches: List[HealthBreach] = []
        #: rule evaluation context, refreshed per evaluate() call.
        self.counters: Mapping[str, float] = {}

    # ------------------------------------------------------------------ #
    def growth(self, key: str, value: float) -> Optional[float]:
        """Delta of ``value`` since the last evaluation that reported
        ``key``; None on the priming observation (a fresh sentinel must
        not breach on totals it never watched grow)."""
        prev = self._growth_baseline.get(key)
        self._growth_baseline[key] = float(value)
        if prev is None:
            return None
        return float(value) - prev

    # ------------------------------------------------------------------ #
    def evaluate(self, *, counters: Optional[Mapping[str, float]] = None
                 ) -> List[HealthBreach]:
        """Run every rule once; record + return this batch's breaches.

        ``counters`` overrides the registry totals for replayed streams
        (the ``obs-monitor`` path), like the profile functions.  Never
        raises: rule exceptions are counted and skipped.
        """
        if counters is not None:
            self.counters = counters
        elif self._counters_source is not None:
            self.counters = self._counters_source()
        else:
            self.counters = self.registry.counters
        breaches: List[HealthBreach] = []
        for rule in self.rules:
            try:
                br = rule.check(self)
            except Exception:
                self.registry.inc("health.rule_errors")
                self.registry.inc(f"health.rule_errors/{rule.name}")
                continue
            self.registry.gauge(
                f"health.breached/{rule.name}",
                1.0 if br is not None else 0.0,
            )
            if br is not None:
                breaches.append(br)
        for br in breaches:
            self.breaches.append(br)
            self.registry.inc("health.breaches")
            self.registry.inc(f"health.breaches/{br.rule}")
            self.registry.event(
                "health.breach", rule=br.rule, detail=br.detail,
                value=br.value, threshold=br.threshold,
            )
            self._maybe_dump(br)
        return breaches

    def _maybe_dump(self, br: HealthBreach) -> None:
        if self.flight is None:
            return
        now = time.monotonic()
        last = self._last_dump.get(br.rule)
        if last is not None and now - last < self.cooldown_s:
            return
        self._last_dump[br.rule] = now
        try:
            self.flight.trigger(
                f"health-{br.rule}", rule=br.rule, detail=br.detail,
                value=br.value, threshold=br.threshold,
            )
            self.registry.inc("health.flight_dumps")
        except Exception:
            # The black box failing to write must not take down the
            # run the sentinel is protecting.
            self.registry.inc("health.flight_dump_failed")

    # ------------------------------------------------------------------ #
    def breached_rules(self) -> List[str]:
        """Distinct rule names breached so far, in first-breach order."""
        seen: List[str] = []
        for br in self.breaches:
            if br.rule not in seen:
                seen.append(br.rule)
        return seen
