"""Transparent call instrumentation for compiled step functions.

The training factories (``make_tp_train_step``, ``make_1f1b_train_step``,
``make_pipeline_apply``, ...) return jitted callables whose ``.lower()``
/ ``.trace()`` surface callers (and the graftlint jaxpr/HLO audit) rely
on.  :func:`instrument_step` wraps such a callable with a span + call
counter while delegating every other attribute to the wrapped function,
so ``step.lower(...)`` still reaches the jit object and the compiled
program — and therefore the pinned collective inventory — is untouched.

The overhead per call is two clock reads and two dict updates on the
host, nothing on the device.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from distributed_learning_tpu.obs.registry import get_registry
from distributed_learning_tpu.obs.spans import get_tracer

__all__ = ["instrument_step", "InstrumentedStep"]


class InstrumentedStep:
    """Callable proxy: ``__call__`` is spanned + counted, everything
    else (``lower``, ``trace``, ``clear_cache``, ...) delegates to the
    wrapped function."""

    def __init__(self, fn: Callable, name: str):
        self.__wrapped__ = fn
        self._name = name
        functools.update_wrapper(self, fn, updated=())

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        get_registry().inc(f"{self._name}.calls")
        with get_tracer().span(self._name):
            return self.__wrapped__(*args, **kwargs)

    def __getattr__(self, attr: str) -> Any:
        return getattr(self.__wrapped__, attr)

    # The AOT stages surface is delegated EXPLICITLY (not only via
    # __getattr__) so the profilable contract is part of this class's
    # API: the audit and cost paths (tools/graftlint, obs/cost.py)
    # call ``step.lower(...)`` / ``step.compile(...)`` on instrumented
    # steps and must never need to unwrap.
    def lower(self, *args: Any, **kwargs: Any) -> Any:
        """Delegate to the wrapped jit object's ``lower`` (the lowered
        program is the wrapped function's — instrumentation is
        host-side only, so audit pins and cost profiles are of the real
        program)."""
        return self.__wrapped__.lower(*args, **kwargs)

    def compile(self, *args: Any, **kwargs: Any) -> Any:
        """AOT-compile the wrapped program at these argument shapes.

        Delegates ``compile`` when the wrapped object has one; jitted
        callables (which expose only ``lower``) get the standard
        two-step ``lower(*args).compile()`` — either way the caller
        holds a ``jax.stages.Compiled`` whose ``cost_analysis()`` /
        ``memory_analysis()`` feed :mod:`distributed_learning_tpu.obs.cost`."""
        inner = getattr(self.__wrapped__, "compile", None)
        if inner is not None:
            return inner(*args, **kwargs)
        return self.__wrapped__.lower(*args, **kwargs).compile()

    def __repr__(self) -> str:
        return f"InstrumentedStep({self._name}, {self.__wrapped__!r})"


def instrument_step(fn: Callable, name: str) -> InstrumentedStep:
    """Wrap ``fn`` so each call records span ``name`` and bumps the
    ``{name}.calls`` counter on the default registry."""
    return InstrumentedStep(fn, name)
