"""Run-wide observability plane: merge per-agent metric streams into one
run registry, straggler profiles, and merged cross-agent traces.

PR 2 gave every *process* a :class:`MetricsRegistry`; this module gives
the *run* one.  Each agent periodically packs a delta of its registry —
counter totals, gauges, and the events recorded since the last pack
(series points, wall-anchored spans, free-form events) — into the
existing ``Telemetry`` wire message as a structured payload
(:data:`OBS_PAYLOAD_KIND`, versioned; re-exported by
``comm/protocol.py`` as part of the wire surface).  The master hands
every payload to a :class:`RunAggregator`, which

* merges the streams into ONE registry with per-agent label dimensions
  (``comm.agent.rounds_run/a`` per agent + the run-wide
  ``comm.agent.rounds_run`` sum — the same ``name/label`` convention the
  trainer uses for ``train.loss/node``);
* computes **straggler profiles** (:func:`straggler_profile_from_registry`):
  per-agent round-latency percentiles + histograms, per-round
  slowest-agent attribution from the master's arrival lags, round skew,
  and the staleness picture from the existing
  ``stale_requests_dropped`` / ``requests_deferred`` counters — exactly
  the signals stale-weighted mixing and deadline rounds
  (arxiv.org/pdf/2002.01119) and adaptive synchronization
  (arxiv.org/pdf/1910.13598) need as input;
* feeds every merged event into the
  :class:`~distributed_learning_tpu.obs.flight.FlightRecorder` ring, so
  a fault dump carries each agent's recent history;
* exports a **merged Chrome/Perfetto trace**: one track (pid) per
  agent, span starts wall-clock-anchored (``SpanTracer.wall0``), so N
  processes' spans land on one shared timeline.

Everything is host-side and jax-free (the ``obs-report`` /
``obs-monitor`` CLIs replay these structures offline); nothing here may
touch a jitted program — the plane observes training, it never joins
it.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

from distributed_learning_tpu.obs.flight import FlightRecorder
from distributed_learning_tpu.obs.registry import MetricsRegistry
from distributed_learning_tpu.obs.sketch import (
    DEFAULT_ALPHA,
    LabelRollup,
    QuantileSketch,
)
from distributed_learning_tpu.obs.spans import FLOW_EVENT, FLOW_PHASES
from distributed_learning_tpu.utils.telemetry import TelemetryProcessor

__all__ = [
    "OBS_PAYLOAD_KIND",
    "OBS_PAYLOAD_VERSION",
    "OBS_PAYLOAD_SECTIONS",
    "SKETCH_SERIES",
    "is_obs_payload",
    "ObsDeltaSource",
    "RunAggregator",
    "SubAggregator",
    "straggler_profile_from_registry",
    "edge_profile_from_registry",
]

#: ``payload["kind"]`` marking a Telemetry payload as a registry delta
#: (any other payload is opaque user telemetry, recorded as-is).
OBS_PAYLOAD_KIND = "obs.delta"
#: Schema version inside the payload (``payload["v"]``).  Bump on
#: incompatible layout changes; the aggregator records-but-skips
#: payloads from the future instead of crashing a running master.
#: v2 (fleet-scale plane): adds the ``sketches``/``rollups`` sections
#: and the ``agg`` sub-aggregator flag; v1 payloads still merge (the
#: new sections are simply absent, and the aggregator derives sketches
#: from the raw series they carry).
OBS_PAYLOAD_VERSION = 2

#: The payload's section keys, in wire order — part of the declared
#: wire surface (re-exported by ``comm/protocol.py``, cross-checked and
#: pinned by graftlint's wire-contract stage): adding/renaming a
#: section is a schema change and must ride a version bump through
#: ``--audit-write``.
OBS_PAYLOAD_SECTIONS = ("counters", "gauges", "events", "sketches", "rollups")

#: Series (by name, or ``name/<label>``) summarized as mergeable
#: quantile sketches in v2 deltas — the straggler/edge/latency paths
#: whose percentiles the profiles render.  Everything else (loss
#: curves, residual trends) keeps raw points: order matters there.
SKETCH_SERIES = (
    "straggler.lag_s",
    "straggler.skew_s",
    "comm.agent.round_s",
    "comm.agent.async_round_s",
    "comm.agent.staleness",
    "comm.edge.latency_s",
    "comm.edge.staleness",
    "comm.master.round_s",
)


def _sketched(name: str) -> bool:
    """Whether series ``name`` belongs to a sketched metric family."""
    for base in SKETCH_SERIES:
        if name == base or name.startswith(base + "/"):
            return True
    return False

#: Round-latency histogram bucket upper bounds (seconds; last is +inf).
LATENCY_BUCKETS_S = (
    0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, math.inf,
)


def is_obs_payload(payload: Any) -> bool:
    """Whether a Telemetry payload is a structured registry delta."""
    return (
        isinstance(payload, Mapping)
        and payload.get("kind") == OBS_PAYLOAD_KIND
    )


# ---------------------------------------------------------------------- #
# Agent side: incremental registry deltas                                #
# ---------------------------------------------------------------------- #
class ObsDeltaSource:
    """Packs a registry's growth since the last pack into an
    ``obs.delta`` payload.

    Counters/gauges travel as *absolute totals* (idempotent: a lost or
    repeated delta cannot double-count — the aggregator diffs against
    the last totals it saw); series points, spans, and events travel as
    the buffered event stream (a sink registered on the registry, so
    packing is O(new events), never a rescan).  ``seq`` increments per
    pack; gaps tell the aggregator how many deltas a flaky wire lost.

    v2 (fleet-scale plane): points of the :data:`SKETCH_SERIES`
    families additionally fold into per-pack
    :class:`~distributed_learning_tpu.obs.sketch.QuantileSketch` deltas
    (``payload["sketches"]``, drained each pack — the aggregator merges
    them by pure addition, so seq dedup/gap accounting carries over
    unchanged).  ``raw_series=False`` is the fleet mode: sketched
    series stop travelling as raw points entirely, making the delta's
    byte size O(metrics) instead of O(samples); the substitution is
    disclosed per pack (``series_sketched``), never silent.
    """

    def __init__(self, registry: MetricsRegistry, *,
                 max_buffer: int = 4096, backfill: bool = True,
                 sketch: bool = True, sketch_alpha: float = DEFAULT_ALPHA,
                 raw_series: bool = True):
        self._registry = registry
        self._lock = threading.Lock()
        self._buffer: collections.deque = collections.deque(
            maxlen=int(max_buffer)
        )
        self._dropped = 0
        self._seq = 0
        self._closed = False
        self._sketch = bool(sketch)
        self._sketch_alpha = float(sketch_alpha)
        self._raw_series = bool(raw_series)
        self._pending_sketches: Dict[str, QuantileSketch] = {}
        self._suppressed = 0
        if backfill:
            # A late-attached source still ships the registry's retained
            # history in its first delta (events recorded before the
            # sink existed would otherwise be invisible to the run).
            for ev in registry.recent_events():
                self._ingest(dict(ev))
        registry.add_sink(self._sink)

    def _sink(self, event: Mapping[str, Any]) -> None:
        self._ingest(dict(event))

    def _ingest(self, event: dict) -> None:
        with self._lock:
            if (event.get("kind") == "series"
                    and _sketched(event.get("name", ""))):
                if self._sketch:
                    name = event["name"]
                    sk = self._pending_sketches.get(name)
                    if sk is None:
                        sk = self._pending_sketches[name] = QuantileSketch(
                            self._sketch_alpha
                        )
                    sk.add(float(event.get("value", 0.0)))
                if not self._raw_series:
                    # Fleet mode: the sketch IS the wire form of this
                    # point; count the substitution so it is visible.
                    self._suppressed += 1
                    return
            if (self._buffer.maxlen is not None
                    and len(self._buffer) >= self._buffer.maxlen):
                self._dropped += 1
            self._buffer.append(event)

    def pack(self) -> dict:
        """One delta payload; drains the event buffer and the pending
        sketch deltas."""
        with self._lock:
            events = list(self._buffer)
            self._buffer.clear()
            dropped, self._dropped = self._dropped, 0
            suppressed, self._suppressed = self._suppressed, 0
            sketches = {
                name: sk.to_dict()
                for name, sk in sorted(self._pending_sketches.items())
            }
            self._pending_sketches.clear()
            self._seq += 1
            seq = self._seq
        snap = self._registry.snapshot()
        payload = {
            "kind": OBS_PAYLOAD_KIND,
            "v": OBS_PAYLOAD_VERSION,
            "seq": seq,
            "wall": time.time(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "events": events,
        }
        if sketches:
            payload["sketches"] = sketches
        if dropped:
            payload["events_dropped"] = dropped
        if suppressed:
            payload["series_sketched"] = suppressed
        return payload

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._registry.remove_sink(self._sink)


# ---------------------------------------------------------------------- #
# Master side: the run aggregator                                        #
# ---------------------------------------------------------------------- #
class _AgentView:
    """Per-agent merge state inside the aggregator."""

    __slots__ = ("last_seq", "counters", "spans", "flows", "last_wall")

    def __init__(self, max_spans: int):
        self.last_seq = 0
        self.counters: Dict[str, float] = {}
        # (name, wall_t0, dur_s, depth) for the merged trace.
        self.spans: collections.deque = collections.deque(maxlen=max_spans)
        # trace.flow frame-lifecycle events ({phase, origin, seq, run,
        # edge, ts, ...}) — the arrow-linked causal chains of the
        # merged trace.
        self.flows: collections.deque = collections.deque(maxlen=max_spans)
        self.last_wall: Optional[float] = None


class RunAggregator(TelemetryProcessor):
    """Merge per-agent ``obs.delta`` payloads into one run registry.

    Implements the ``TelemetryProcessor`` interface, so it plugs
    straight into the master's existing telemetry dispatch
    (``ConsensusMaster(aggregator=...)`` wires it; a user telemetry
    processor still runs beside it).  Non-delta payloads are recorded
    as plain ``telemetry`` events with their token — the plane subsumes
    the old path, it does not break it.
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None,
                 max_spans_per_agent: int = 4096):
        #: The merged run registry (per-agent labels + run-wide sums).
        self.registry = (
            registry if registry is not None
            else MetricsRegistry(max_points=1 << 14, max_events=1 << 16)
        )
        self.flight = flight
        self._lock = threading.Lock()
        self._max_spans = int(max_spans_per_agent)
        self._views: Dict[str, _AgentView] = {}
        #: Merged quantile sketches, keyed like the merged series
        #: (``name/<token>`` per agent + the bare run-wide ``name``).
        #: Constant-size per metric and eviction-immune — the profile
        #: paths read quantiles from here, the raw rings stay as the
        #: small-run exact oracle.
        self.sketches: Dict[str, QuantileSketch] = {}
        #: Merged bounded label rollups (sub-aggregator exports).
        self.rollups: Dict[str, LabelRollup] = {}

    # ------------------------------------------------------------------ #
    def agents(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    def _view(self, token: str) -> _AgentView:
        with self._lock:
            view = self._views.get(token)
            if view is None:
                view = self._views[token] = _AgentView(self._max_spans)
            return view

    # ------------------------------------------------------------------ #
    def process(self, token: Any, payload: Any) -> None:
        """TelemetryProcessor entry point: merge one payload."""
        token = str(token)
        if not is_obs_payload(payload):
            self.registry.event("telemetry", token=token, payload=payload)
            if self.flight is not None:
                self.flight.note(token, "telemetry", payload=payload)
            return
        if int(payload.get("v", 0)) > OBS_PAYLOAD_VERSION:
            # A newer agent talking to an older master: visible, not
            # fatal — the rest of the plane keeps running.
            self.registry.inc("obs.unknown_version")
            return
        # Sub-aggregator export (payload["agg"], SubAggregator): names
        # already carry their per-agent labels from the sub's merge, so
        # everything lands as-is — no relabel, no run-wide duplication.
        # That pass-through is exactly what makes aggregate-of-
        # aggregates equal the flat merge.
        agg = bool(payload.get("agg"))
        view = self._view(token)
        seq = int(payload.get("seq", view.last_seq + 1))
        if seq <= view.last_seq:
            self.registry.inc("obs.stale_deltas")
            return
        if seq > view.last_seq + 1:
            self.registry.inc("obs.deltas_lost", seq - view.last_seq - 1)
        view.last_seq = seq
        view.last_wall = payload.get("wall")

        self._merge_counters(
            token, view, payload.get("counters") or {}, relabel=not agg
        )
        for name, value in (payload.get("gauges") or {}).items():
            if not agg:
                self.registry.gauge(f"{name}/{token}", float(value))
            self.registry.gauge(name, float(value))
        for name, d in sorted((payload.get("sketches") or {}).items()):
            self._merge_sketch_dict(token, name, d, relabel=not agg)
        for name, d in sorted((payload.get("rollups") or {}).items()):
            self._merge_rollup_dict(name, d)
        # A payload that carries sketch sections is the authority on its
        # sketched series; one that does not (v1 producers, offline
        # merge_registry replays, sketch-less sources) gets them derived
        # from its raw points here — either way the sketch state covers
        # every point exactly once.
        sketch_series = (not agg) and ("sketches" not in payload)
        for ev in payload.get("events") or ():
            self._merge_event(
                token, view, ev,
                relabel=not agg, sketch_series=sketch_series,
            )
        if payload.get("events_dropped"):
            self.registry.inc(
                f"obs.delta_events_dropped/{token}",
                payload["events_dropped"],
            )
        if payload.get("series_sketched"):
            self.registry.inc(
                "obs.series_sketched", payload["series_sketched"]
            )
        # Self-contained stream marker: carries this agent's absolute
        # counter totals, so a JsonlSink'd aggregate file replays into
        # a live dashboard (obs-monitor) with counters intact.
        self.registry.event(
            "obs.delta", token=token, seq=seq,
            wall=view.last_wall, counters=dict(view.counters),
        )
        self.registry.inc("obs.deltas_merged")

    def _merge_counters(self, token: str, view: _AgentView,
                        counters: Mapping[str, Any], *,
                        relabel: bool = True) -> None:
        for name, total in counters.items():
            total = float(total)
            prev = view.counters.get(name, 0.0)
            diff = total - prev
            if diff < 0:
                # The token restarted with fresh counters (elastic
                # rejoin): its new life counts from zero.
                self.registry.inc("obs.counter_resets")
                diff = total
            if diff:
                if relabel:
                    self.registry.inc(f"{name}/{token}", diff)
                self.registry.inc(name, diff)
            view.counters[name] = total

    # ------------------------------------------------------------------ #
    # Sketch / rollup state.  These two hooks are the ONE write path    #
    # into the merged sketch maps — SubAggregator overrides them to    #
    # also accumulate its pending upstream delta.                       #
    # ------------------------------------------------------------------ #
    def _sketch_point(self, key: str, value: float) -> None:
        with self._lock:
            sk = self.sketches.get(key)
            if sk is None:
                sk = self.sketches[key] = QuantileSketch()
            sk.add(value)

    def sketch(self, key: str) -> Optional[QuantileSketch]:
        """A copy of the merged sketch under ``key`` (``name/<token>``
        or the bare run-wide ``name``), or None."""
        with self._lock:
            sk = self.sketches.get(key)
            return None if sk is None else sk.copy()

    def _sketch_merge(self, key: str, sk: QuantileSketch) -> None:
        mismatch = False
        with self._lock:
            cur = self.sketches.get(key)
            if cur is None:
                self.sketches[key] = sk.copy()
            else:
                try:
                    cur.merge(sk)
                except ValueError:
                    # Geometry mismatch (foreign α): visible, not fatal.
                    mismatch = True
        if mismatch:
            self.registry.inc("obs.sketch_errors")

    def _rollup_merge(self, name: str, ru: LabelRollup) -> None:
        with self._lock:
            cur = self.rollups.get(name)
            if cur is None:
                self.rollups[name] = ru.copy()
            else:
                cur.merge(ru)

    def rollup(self, name: str) -> Optional[LabelRollup]:
        """A copy of the merged label rollup for counter family
        ``name``, or None."""
        with self._lock:
            ru = self.rollups.get(name)
            return None if ru is None else ru.copy()

    def _merge_sketch_dict(self, token: str, name: str, d: Any, *,
                           relabel: bool) -> None:
        try:
            sk = QuantileSketch.from_dict(d)
        except (TypeError, ValueError, AttributeError):
            self.registry.inc("obs.sketch_errors")
            return
        if relabel:
            self._sketch_merge(f"{name}/{token}", sk)
        self._sketch_merge(name, sk)

    def _merge_rollup_dict(self, name: str, d: Any) -> None:
        try:
            ru = LabelRollup.from_dict(d)
        except (TypeError, ValueError, AttributeError):
            self.registry.inc("obs.sketch_errors")
            return
        self._rollup_merge(name, ru)

    def _merge_event(self, token: str, view: _AgentView,
                     ev: Mapping[str, Any], *, relabel: bool = True,
                     sketch_series: bool = False) -> None:
        kind = ev.get("kind")
        name = ev.get("name", "")
        flight_token = token
        if kind == "series":
            value = float(ev.get("value", 0.0))
            self.registry.observe(
                f"{name}/{token}" if relabel else name, value,
                step=ev.get("step"),
            )
            if sketch_series and _sketched(name):
                self._sketch_point(f"{name}/{token}", value)
                self._sketch_point(name, value)
        elif kind == "span":
            dur = float(ev.get("value", 0.0))
            t0 = ev.get("t0")
            self.registry.record_span(
                f"{name}/{token}" if relabel else name, dur,
                depth=int(ev.get("depth", 0)), t0=t0,
            )
            if t0 is not None:
                view.spans.append(
                    (name, float(t0), dur, int(ev.get("depth", 0)))
                )
        elif kind == "event":
            fields = {
                k: v for k, v in ev.items()
                if k not in ("kind", "name", "ts")
            }
            if relabel:
                # A replayed *aggregated* dump (``obs-report --merge``
                # over pod registries) already carries the original
                # agent attribution in the fields — keep it rather
                # than relabeling every event with the pod's token.
                inner = fields.pop("token", None)
                inner_ts = fields.pop("agent_ts", None)
                if inner is not None:
                    flight_token = str(inner)
                self.registry.event(
                    name, token=flight_token,
                    agent_ts=(inner_ts if inner_ts is not None
                              else ev.get("ts")),
                    **fields)
            else:
                # Sub-aggregator pass-through: token/agent_ts already
                # ride inside the fields from the sub's own merge.
                self.registry.event(name, **fields)
                flight_token = str(fields.get("token", token))
            if name == FLOW_EVENT:
                # Frame-lifecycle hop: keep it (with the emitting
                # agent's wall stamp) for the merged trace's arrows.
                flow = dict(fields)
                if relabel:
                    flow["agent"] = flight_token
                    flow["ts"] = (inner_ts if inner_ts is not None
                                  else ev.get("ts"))
                else:
                    flow.setdefault("agent", flight_token)
                    flow["ts"] = fields.get("agent_ts", ev.get("ts"))
                view.flows.append(flow)
        elif kind in ("counter", "gauge"):
            # Snapshot lines from a replayed dump file: totals already
            # merged through the counters/gauges maps — skip, or the
            # offline merge would double-count.
            return
        if self.flight is not None:
            self.flight.record(flight_token, ev)

    # ------------------------------------------------------------------ #
    def merge_registry(self, token: str,
                       registry: MetricsRegistry) -> None:
        """Offline merge of a whole per-agent registry (the
        ``obs-report --merge`` path over per-agent JSONL files): one
        synthetic delta carrying the registry's totals and full event
        log."""
        self.process(str(token), {
            "kind": OBS_PAYLOAD_KIND,
            "v": OBS_PAYLOAD_VERSION,
            "seq": self._view(str(token)).last_seq + 1,
            "counters": dict(registry.counters),
            "gauges": dict(registry.gauges),
            "events": list(registry.events),
        })

    # ------------------------------------------------------------------ #
    # Master-side round accounting (control-plane signals the agents    #
    # cannot see about themselves).                                      #
    # ------------------------------------------------------------------ #
    def note_round_arrivals(self, round_id: int,
                            arrivals: Mapping[str, float]) -> None:
        """Per-round straggler attribution from the master's view: the
        wall-clock arrival time of each agent's round request.  The
        LAST arrival is the straggler — it set the round's start time
        for everyone (lock-step rounds run at the pace of the slowest
        agent, which is exactly what the async runtime will relax)."""
        if not arrivals:
            return
        t_first = min(arrivals.values())
        t_last = max(arrivals.values())
        for token, t in arrivals.items():
            self.registry.observe(
                f"straggler.lag_s/{token}", t - t_first, step=round_id
            )
            self._sketch_point(f"straggler.lag_s/{token}", t - t_first)
        self.registry.observe(
            "straggler.skew_s", t_last - t_first, step=round_id
        )
        self._sketch_point("straggler.skew_s", t_last - t_first)
        slowest = max(arrivals, key=lambda t: arrivals[t])
        self.registry.inc(f"straggler.slowest/{slowest}")
        if self.flight is not None:
            self.flight.note(
                "<master>", "round_arrivals", round_id=int(round_id),
                skew_s=t_last - t_first, slowest=slowest,
            )

    def note_round_done(self, round_id: int, dur_s: float,
                        wall_t0: Optional[float] = None) -> None:
        """Master-side whole-round wall time (request-complete to
        all-converged)."""
        self.registry.inc("comm.master.rounds_done")
        self.registry.observe(
            "comm.master.round_s", float(dur_s), step=round_id
        )
        self._sketch_point("comm.master.round_s", float(dur_s))
        self.registry.record_span(
            "comm.master.round", float(dur_s), t0=wall_t0
        )
        if wall_t0 is not None:
            self._view("<master>").spans.append(
                ("comm.master.round", float(wall_t0), float(dur_s), 0)
            )

    # ------------------------------------------------------------------ #
    def _sketch_snapshot(self) -> Dict[str, QuantileSketch]:
        with self._lock:
            return {k: sk.copy() for k, sk in self.sketches.items()}

    def straggler_profile(self) -> dict:
        """See :func:`straggler_profile_from_registry` (the aggregator
        hands over its merged sketches, so quantiles stay
        constant-memory and eviction-immune at fleet scale)."""
        return straggler_profile_from_registry(
            self.registry, sketches=self._sketch_snapshot()
        )

    def edge_profile(self) -> dict:
        """See :func:`edge_profile_from_registry`."""
        return edge_profile_from_registry(
            self.registry, sketches=self._sketch_snapshot()
        )

    # ------------------------------------------------------------------ #
    def to_chrome_trace(self) -> dict:
        """Merged Chrome/Perfetto trace: one track (pid) per agent,
        wall-clock-anchored span starts normalized to the earliest span
        (the shared timeline), ``process_name`` metadata naming each
        track after its agent.

        ``trace.flow`` frame-lifecycle events additionally render as
        per-frame causal chains: each hop becomes a small anchor slice
        (``frame.<phase>``, tid 2 — the "wire" lane of its agent's
        track) and the hops sharing one wire-carried
        ``(run, origin, seq)`` identity are linked with Chrome flow
        arrows (``ph`` s/t/f, one id per frame), so
        encode→send→recv→decode→mix reads as ONE arrow-linked path
        across process tracks in Perfetto."""
        with self._lock:
            per_agent = {
                token: (list(view.spans), list(view.flows))
                for token, view in sorted(self._views.items())
                if view.spans or view.flows
            }
        events: List[dict] = []
        all_t0 = [t0 for spans, _flows in per_agent.values()
                  for (_n, t0, _d, _dep) in spans]
        all_t0 += [
            float(f["ts"]) for _spans, flows in per_agent.values()
            for f in flows if f.get("ts") is not None
        ]
        base = min(all_t0) if all_t0 else 0.0
        pids: Dict[str, int] = {}
        for pid, (token, (spans, _flows)) in enumerate(
            per_agent.items(), start=1
        ):
            pids[token] = pid
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"agent {token}"},
            })
            for name, t0, dur, depth in spans:
                events.append({
                    "name": name,
                    "ph": "X",
                    "ts": round((t0 - base) * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                    "pid": pid,
                    "tid": 1,
                    "args": {"agent": token, "depth": depth},
                })
        # Frame chains: group hops by wire identity, order each chain
        # by lifecycle phase (the wall stamps of two processes are only
        # ~ms-aligned; the phase order is the causal truth).
        chains: Dict[str, List[Tuple[int, float, int, dict]]] = {}
        for token, (_spans, flows) in per_agent.items():
            for f in flows:
                ts, phase = f.get("ts"), f.get("phase")
                if ts is None or phase not in FLOW_PHASES:
                    continue
                key = (
                    f"{f.get('run', 0)}:{f.get('origin', '')}:"
                    f"{f.get('seq', 0)}"
                )
                chains.setdefault(key, []).append(
                    (FLOW_PHASES.index(phase), float(ts), pids[token], f)
                )
        flow_id = 0
        for key in sorted(chains):
            hops = sorted(chains[key], key=lambda h: (h[0], h[1]))
            flow_id += 1
            for _order, ts, pid, f in hops:
                events.append({
                    "name": f"frame.{f['phase']}",
                    "ph": "X",
                    "ts": round((ts - base) * 1e6, 3),
                    "dur": 20.0,
                    "pid": pid,
                    "tid": 2,
                    "args": {
                        k: f[k]
                        for k in ("origin", "seq", "run", "edge", "agent")
                        if k in f
                    },
                })
            if len(hops) < 2:
                continue
            for i, (_order, ts, pid, _f) in enumerate(hops):
                ph = "s" if i == 0 else (
                    "f" if i == len(hops) - 1 else "t"
                )
                arrow = {
                    "name": "frame",
                    "cat": FLOW_EVENT,
                    "ph": ph,
                    "id": flow_id,
                    # +1us: strictly inside the anchor slice, so the
                    # arrow binds to it on every Perfetto version.
                    "ts": round((ts - base) * 1e6 + 1.0, 3),
                    "pid": pid,
                    "tid": 2,
                }
                if ph == "f":
                    arrow["bp"] = "e"
                events.append(arrow)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"wall0": base},
        }

    def export_chrome_trace(self, path: str) -> int:
        """Write :meth:`to_chrome_trace` to ``path``; returns the span
        event count (metadata rows excluded)."""
        import json

        trace = self.to_chrome_trace()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        return sum(1 for e in trace["traceEvents"] if e["ph"] == "X")


# ---------------------------------------------------------------------- #
# Hierarchical tier: the per-pod sub-aggregator                          #
# ---------------------------------------------------------------------- #
class SubAggregator(RunAggregator):
    """A mid-tier aggregator that re-exports its merged state upstream.

    A per-pod sub-master merges its own agents' ``obs.delta`` payloads
    exactly like :class:`RunAggregator` (same dedup, same labels, same
    local profiles), and periodically :meth:`export_delta`\\ s ONE
    bounded payload for a root aggregator — the aggregate-of-aggregates
    shape the sharded-master control plane needs.  The export is itself
    a v2 ``obs.delta``:

    * ``agg: True`` tells the root to merge it as-is (names already
      carry their ``/token`` labels from this tier's merge — no
      relabel, no run-wide duplication), which is what makes the
      two-tier merge equal the flat one;
    * counters/gauges travel as absolute totals (idempotent at the
      root, same as an agent delta); ``obs.*`` plane bookkeeping is
      filtered — each tier keeps its own merge-health counters;
    * sketch state travels as per-export DELTAS mirrored at merge time
      (:meth:`_sketch_point` / :meth:`_sketch_merge` overrides), so the
      root's merge is pure addition and seq gap/dedup accounting
      carries over unchanged;
    * ``forward_raw_series=False`` is the fleet mode: sketched-series
      points stop riding the event stream upstream (the sketch IS
      their wire form), making export bytes O(metrics);
    * ``rollup_labels=N`` additionally folds per-label counter deltas
      (``name/<label>``, label cardinality unbounded under churn) into
      bounded :class:`LabelRollup` sections, keeping only the bare
      run-wide counters exact.  Edge-shaped labels (``src->dst``) stay
      exact — the per-edge observatory depends on them.
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None,
                 max_spans_per_agent: int = 4096,
                 forward_raw_series: bool = True,
                 rollup_labels: int = 0):
        super().__init__(
            registry=registry, flight=flight,
            max_spans_per_agent=max_spans_per_agent,
        )
        #: Sketch/rollup growth since the last export (drained by
        #: :meth:`export_delta`; the merged totals stay in
        #: ``self.sketches`` for this tier's own profiles).
        self._pending_sketches: Dict[str, QuantileSketch] = {}
        self._pending_rollups: Dict[str, LabelRollup] = {}
        self._rollup_labels = int(rollup_labels)
        #: Last-export absolute totals of the labeled counters folded
        #: into rollups (delta accounting with reset handling, same
        #: contract as the root's per-view counter diff).
        self._rollup_base: Dict[str, float] = {}
        # sketch=False: this tier's merge hooks own the sketch state
        # (below); the source still buffers events and, in fleet mode,
        # suppresses raw sketched-series points.
        self._source = ObsDeltaSource(
            self.registry, sketch=False, backfill=True,
            raw_series=bool(forward_raw_series),
        )

    # The ONE write path into the sketch maps, mirrored into the
    # pending upstream delta.
    def _sketch_point(self, key: str, value: float) -> None:
        super()._sketch_point(key, value)
        with self._lock:
            sk = self._pending_sketches.get(key)
            if sk is None:
                sk = self._pending_sketches[key] = QuantileSketch()
            sk.add(value)

    def _sketch_merge(self, key: str, sk: QuantileSketch) -> None:
        super()._sketch_merge(key, sk)
        with self._lock:
            cur = self._pending_sketches.get(key)
            if cur is None:
                self._pending_sketches[key] = sk.copy()
            else:
                try:
                    cur.merge(sk)
                except ValueError:
                    pass  # geometry mismatch already counted by super

    def _rollup_merge(self, name: str, ru: LabelRollup) -> None:
        super()._rollup_merge(name, ru)
        with self._lock:
            cur = self._pending_rollups.get(name)
            if cur is None:
                self._pending_rollups[name] = ru.copy()
            else:
                cur.merge(ru)

    # ------------------------------------------------------------------ #
    def export_delta(self) -> dict:
        """One upstream ``obs.delta`` for the root aggregator: the
        registry's growth since the last export plus the pending
        sketch/rollup deltas, marked ``agg: True``."""
        payload = self._source.pack()
        payload["agg"] = True
        with self._lock:
            sketches = {
                name: sk.to_dict()
                for name, sk in sorted(self._pending_sketches.items())
            }
            self._pending_sketches.clear()
            rollups = dict(self._pending_rollups)
            self._pending_rollups.clear()
        counters = {
            name: total for name, total in payload["counters"].items()
            if not name.startswith("obs.")
        }
        if self._rollup_labels > 0:
            counters = self._fold_label_counters(counters, rollups)
        payload["counters"] = counters
        payload["gauges"] = {
            name: v for name, v in payload["gauges"].items()
            if not name.startswith("obs.")
        }
        # This tier's own stream markers are per-tier bookkeeping; the
        # root stamps its own when it merges this export.
        payload["events"] = [
            ev for ev in payload["events"]
            if not (ev.get("kind") == "event"
                    and ev.get("name") == "obs.delta")
        ]
        if sketches:
            payload["sketches"] = sketches
        if rollups:
            payload["rollups"] = {
                name: ru.to_dict() for name, ru in sorted(rollups.items())
            }
        return payload

    def _fold_label_counters(
            self, counters: Dict[str, Any],
            rollups: Dict[str, LabelRollup]) -> Dict[str, Any]:
        kept: Dict[str, Any] = {}
        with self._lock:
            for name, total in counters.items():
                base, slash, label = name.partition("/")
                if not slash or "->" in label:
                    kept[name] = total
                    continue
                total = float(total)
                prev = self._rollup_base.get(name, 0.0)
                diff = total - prev
                if diff < 0:
                    # Restarted source (elastic rejoin): new life
                    # counts from zero, same as the root's view diff.
                    diff = total
                self._rollup_base[name] = total
                if diff:
                    ru = rollups.get(base)
                    if ru is None:
                        ru = rollups[base] = LabelRollup(
                            self._rollup_labels
                        )
                    ru.add(label, diff)
        return kept

    def close(self) -> None:
        self._source.close()


# ---------------------------------------------------------------------- #
# Straggler profile                                                      #
# ---------------------------------------------------------------------- #
def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[rank - 1]


def _hist(vals: List[float]) -> List[List[float]]:
    """``[upper_bound_s, count]`` rows over LATENCY_BUCKETS_S."""
    counts = [0] * len(LATENCY_BUCKETS_S)
    for v in vals:
        for i, ub in enumerate(LATENCY_BUCKETS_S):
            if v <= ub:
                counts[i] += 1
                break
    return [
        [ub, c] for ub, c in zip(LATENCY_BUCKETS_S, counts) if c
    ]


def _series_by_token(registry: MetricsRegistry,
                     prefix: str) -> Dict[str, list]:
    out = {}
    for name, pts in registry.series.items():
        if name.startswith(prefix):
            out[name[len(prefix):]] = list(pts)
    return out


def _sketches_by_token(
        sketches: Mapping[str, QuantileSketch],
        prefix: str) -> Dict[str, QuantileSketch]:
    """Non-empty sketches keyed ``<prefix><token>`` (no further label
    dimension), by token."""
    out = {}
    for name, sk in sketches.items():
        if name.startswith(prefix) and sk.n:
            token = name[len(prefix):]
            if "/" not in token:
                out[token] = sk
    return out


def straggler_profile_from_registry(
        registry: MetricsRegistry, *,
        counters: Optional[Mapping[str, float]] = None,
        sketches: Optional[Mapping[str, QuantileSketch]] = None) -> dict:
    """Who is slow, how slow, and how often — from a merged run
    registry.

    Latency source, in preference order: the master's per-round arrival
    lags (``straggler.lag_s/<token>`` — how long each agent kept the
    round waiting; authoritative attribution) or, when no master-side
    data exists, the agents' own round wall times
    (``comm.agent.round_s/<token>`` — in lock-step rounds these include
    waiting on peers, so attribution from them is weak; the profile
    names its ``source`` so a reader knows which it got).  Staleness
    comes from the per-agent ``stale_requests_dropped`` /
    ``requests_deferred`` counters; ``counters`` overrides the
    registry's own totals for callers that reconstructed them from a
    replayed stream (``obs-monitor``, where counter totals travel as
    delta markers, not events).

    ``sketches`` (the aggregator's merged quantile sketches) switch the
    per-agent latency/staleness statistics to the sketch path: counts
    and percentiles come from the eviction-immune
    :class:`~distributed_learning_tpu.obs.sketch.QuantileSketch` state
    (``max`` stays exact — the sketch tracks it), and every entry says
    which path produced it (``"quantiles": "sketch" | "exact"``).
    Without sketches the exact nearest-rank path over the raw rings is
    used — the small-run oracle — and each entry carries the ring's
    ``evicted`` point count so a truncated percentile is never
    presented as a complete one.
    """
    if counters is None:
        counters = registry.counters
    sketches = sketches or {}
    dropped = registry.points_dropped
    lag_prefix = "straggler.lag_s/"
    lag = _series_by_token(registry, lag_prefix)
    lag_sk = _sketches_by_token(sketches, lag_prefix)
    source = "master-arrival-lag"
    if not lag and not lag_sk:
        lag_prefix = "comm.agent.round_s/"
        lag = _series_by_token(registry, lag_prefix)
        lag_sk = _sketches_by_token(sketches, lag_prefix)
        source = "agent-round-wall"
    if not lag and not lag_sk:
        # Pure async runs have no master-gated rounds at all: fall back
        # to the async runtime's per-round wall times.
        lag_prefix = "comm.agent.async_round_s/"
        lag = _series_by_token(registry, lag_prefix)
        lag_sk = _sketches_by_token(sketches, lag_prefix)
        source = "agent-async-round-wall"
    # Per-round grouping for attribution (step == round id).
    rounds: Dict[Any, List[Tuple[str, float]]] = {}
    for token, pts in lag.items():
        for step, val in pts:
            if step is not None:
                rounds.setdefault(step, []).append((token, val))
    slowest_counts: Dict[str, int] = {}
    for entries in rounds.values():
        if len(entries) >= 2:
            tok = max(entries, key=lambda tv: tv[1])[0]
            slowest_counts[tok] = slowest_counts.get(tok, 0) + 1
    # Master-side attribution counters win when present (they cover
    # rounds whose lag series may have been ring-evicted).
    master_counts = {
        name[len("straggler.slowest/"):]: int(total)
        for name, total in counters.items()
        if name.startswith("straggler.slowest/")
    }
    if master_counts:
        slowest_counts = master_counts

    # Staleness-vs-convergence picture (docs/async_runtime.md): the
    # async runtime's per-mix staleness series and per-agent consensus
    # residual trends, so the trade-off τ buys is readable from one
    # merged JSONL.
    staleness = _series_by_token(registry, "comm.agent.staleness/")
    stale_sk = _sketches_by_token(sketches, "comm.agent.staleness/")
    residual = _series_by_token(registry, "consensus.residual/")

    per_agent = {}
    tokens = (set(lag) | set(lag_sk) | set(staleness) | set(stale_sk)
              | set(residual))
    for token in sorted(tokens):
        sk = lag_sk.get(token)
        if sk is not None:
            entry = {
                "count": sk.n,
                "p50_s": sk.quantile(0.50),
                "p95_s": sk.quantile(0.95),
                "max_s": sk.max,
                "hist": sk.histogram(LATENCY_BUCKETS_S),
                "quantiles": "sketch",
            }
        else:
            vals = sorted(v for _, v in lag.get(token, ()))
            entry = {
                "count": len(vals),
                "p50_s": _pct(vals, 0.50),
                "p95_s": _pct(vals, 0.95),
                "max_s": vals[-1] if vals else 0.0,
                "hist": _hist(vals),
                "quantiles": "exact",
            }
        entry["evicted"] = int(dropped.get(lag_prefix + token, 0))
        entry.update({
            "slowest_rounds": slowest_counts.get(token, 0),
            "stale_dropped": counters.get(
                f"comm.agent.stale_requests_dropped/{token}", 0
            ),
            "deferred": counters.get(
                f"comm.agent.requests_deferred/{token}", 0
            ),
        })
        ssk = stale_sk.get(token)
        spts = [v for _, v in staleness.get(token, ())]
        if ssk is not None:
            entry["staleness"] = {
                "n": ssk.n,
                "mean": ssk.mean,
                "max": ssk.max,
            }
        elif spts:
            buckets: Dict[int, int] = {}
            for v in spts:
                buckets[int(v)] = buckets.get(int(v), 0) + 1
            entry["staleness"] = {
                "n": len(spts),
                "mean": sum(spts) / len(spts),
                "max": max(spts),
                "hist": sorted(buckets.items()),
            }
        if ssk is not None or spts:
            entry["stale_mixed"] = counters.get(
                f"comm.agent.async_stale_mixed/{token}", 0
            )
            entry["stale_dropped_mix"] = counters.get(
                f"comm.agent.async_stale_dropped/{token}", 0
            )
        rpts = [v for _, v in residual.get(token, ())]
        if rpts:
            entry["residual_first"] = rpts[0]
            entry["residual_last"] = rpts[-1]
        per_agent[token] = entry
    skew_sk = sketches.get("straggler.skew_s")
    if skew_sk is not None and skew_sk.n:
        skew = {
            "p50_s": skew_sk.quantile(0.50),
            "p95_s": skew_sk.quantile(0.95),
            "max_s": skew_sk.max,
            "quantiles": "sketch",
        }
    else:
        skew_pts = sorted(
            v for _, v in registry.series.get("straggler.skew_s", ())
        )
        skew = {
            "p50_s": _pct(skew_pts, 0.50),
            "p95_s": _pct(skew_pts, 0.95),
            "max_s": skew_pts[-1] if skew_pts else 0.0,
            "quantiles": "exact",
        }
    slowest_agent = (
        max(slowest_counts, key=lambda t: slowest_counts[t])
        if slowest_counts else None
    )
    profile = {
        "source": source,
        "rounds": len(rounds),
        "quantiles": "sketch" if lag_sk else "exact",
        "per_agent": per_agent,
        "skew": skew,
        "slowest_agent": slowest_agent,
    }
    if lag_sk:
        profile["alpha"] = next(iter(lag_sk.values())).alpha
    return profile


# ---------------------------------------------------------------------- #
# Per-edge wire profile                                                  #
# ---------------------------------------------------------------------- #
#: profile field -> bare counter prefix (``FramedStream._edge_inc``).
_EDGE_COUNTER_FIELDS = (
    ("bytes_out", "comm.edge.bytes_out/"),
    ("bytes_in", "comm.edge.bytes_in/"),
    ("frames_out", "comm.edge.frames_out/"),
    ("frames_in", "comm.edge.frames_in/"),
    ("retries", "comm.edge.retries/"),
)

#: Decode scratch-pool attribution (docs/wire.md §Zero-copy receive
#: path): the async runner labels every pool hit/miss with the frame's
#: inbound edge.  Attached to an edge entry as a ``"scratch"`` sub-dict
#: only when the counters exist, so pre-scratch streams keep their
#: exact profile shape.
_SCRATCH_COUNTER_FIELDS = (
    ("hits", "comm.wire.scratch_hits/"),
    ("misses", "comm.wire.scratch_misses/"),
    ("bytes", "comm.wire.scratch_bytes/"),
)


def _bare_edge(name: str, prefix: str) -> Optional[str]:
    """The ``src->dst`` edge label of a BARE per-edge counter name
    (``comm.edge.bytes_out/a->b``); labeled variants with a trailing
    ``/token`` dimension (the aggregator's per-agent copies) return
    None so totals are not double-counted."""
    if not name.startswith(prefix):
        return None
    rest = name[len(prefix):]
    if "->" in rest and "/" not in rest:
        return rest
    return None


def edge_profile_from_registry(
        registry: MetricsRegistry, *,
        counters: Optional[Mapping[str, float]] = None,
        sketches: Optional[Mapping[str, QuantileSketch]] = None) -> dict:
    """The per-edge wire observatory: which directed link moved how
    many bytes/frames, how slowly, and how unreliably — from a merged
    run registry.

    Volume/retry totals come from the bare ``comm.edge.*/<src>-><dst>``
    counters every edge-labeled :class:`FramedStream` maintains;
    latency from the ``comm.edge.latency_s/<edge>`` series (receiver
    wall-clock minus the frame's wire-carried ``TraceContext.t_wall``
    send stamp, so it needs tracing on); per-edge mix staleness from
    ``comm.edge.staleness/<edge>``; injected-fault attribution from the
    ``comm.faults.<kind>/<edge>`` counters; decode scratch-pool
    attribution (a ``"scratch"`` sub-dict, present only when the async
    runner's zero-copy receive path ran) from the
    ``comm.wire.scratch_{hits,misses,bytes}/<edge>`` labeled copies.  ``counters`` overrides the
    registry totals for replayed streams, and ``sketches`` switches the
    latency/staleness statistics to the merged-sketch path (marked per
    edge as ``"quantiles": "sketch" | "exact"``, with ring ``evicted``
    counts disclosed on the exact path), exactly like
    :func:`straggler_profile_from_registry`.  This is the measured
    per-link cost picture topology/schedule choices key off
    (arxiv.org/pdf/2002.01119 §3; the two-tier link split of
    arxiv.org/pdf/2105.09080 needs per-edge latency as input).
    """
    if counters is None:
        counters = registry.counters
    sketches = sketches or {}
    dropped = registry.points_dropped
    edges: Dict[str, dict] = {}

    def entry(edge: str) -> dict:
        return edges.setdefault(edge, {
            "bytes_out": 0.0, "bytes_in": 0.0,
            "frames_out": 0, "frames_in": 0, "retries": 0,
            "faults": {},
        })

    for name, total in counters.items():
        for field, prefix in _EDGE_COUNTER_FIELDS:
            edge = _bare_edge(name, prefix)
            if edge is not None:
                if field.startswith("bytes"):
                    entry(edge)[field] = float(total)
                else:
                    entry(edge)[field] = int(total)
        if name.startswith("comm.faults."):
            rest = name[len("comm.faults."):]
            kind, _slash, label = rest.partition("/")
            if label and "->" in label and "/" not in label:
                entry(label)["faults"][kind] = int(total)
        for field, prefix in _SCRATCH_COUNTER_FIELDS:
            edge = _bare_edge(name, prefix)
            if edge is not None:
                entry(edge).setdefault("scratch", {})[field] = (
                    float(total) if field == "bytes" else int(total)
                )

    lat: Dict[str, List[float]] = {}
    stale: Dict[str, List[float]] = {}
    for name, pts in registry.series.items():
        for prefix, dest in (("comm.edge.latency_s/", lat),
                             ("comm.edge.staleness/", stale)):
            if name.startswith(prefix):
                edge = name[len(prefix):].split("/", 1)[0]
                if "->" in edge:
                    dest.setdefault(edge, []).extend(v for _, v in pts)
    # Merged per-edge sketches: the BARE ``<family>/<src>-><dst>`` keys
    # (labeled ``.../<token>`` copies exist too; the bare key is the
    # edge total, mirroring the raw path's bare-counter convention).
    lat_sk: Dict[str, QuantileSketch] = {}
    stale_sk: Dict[str, QuantileSketch] = {}
    for name, sk in sketches.items():
        for prefix, dest in (("comm.edge.latency_s/", lat_sk),
                             ("comm.edge.staleness/", stale_sk)):
            if name.startswith(prefix) and sk.n:
                edge = name[len(prefix):]
                if "->" in edge and "/" not in edge:
                    dest[edge] = sk
    for edge in sorted(set(lat) | set(lat_sk)):
        sk = lat_sk.get(edge)
        if sk is not None:
            entry(edge)["latency"] = {
                "n": sk.n,
                "p50_s": sk.quantile(0.50),
                "p95_s": sk.quantile(0.95),
                "max_s": sk.max,
                "quantiles": "sketch",
            }
        else:
            vals = sorted(lat[edge])
            entry(edge)["latency"] = {
                "n": len(vals),
                "p50_s": _pct(vals, 0.50),
                "p95_s": _pct(vals, 0.95),
                "max_s": vals[-1] if vals else 0.0,
                "quantiles": "exact",
            }
        entry(edge)["latency"]["evicted"] = sum(
            n for name, n in dropped.items()
            if name.startswith("comm.edge.latency_s/" + edge)
        )
    for edge in sorted(set(stale) | set(stale_sk)):
        sk = stale_sk.get(edge)
        if sk is not None:
            entry(edge)["staleness"] = {
                "n": sk.n,
                "mean": sk.mean,
                "max": sk.max,
            }
        else:
            vals = stale[edge]
            entry(edge)["staleness"] = {
                "n": len(vals),
                "mean": sum(vals) / len(vals) if vals else 0.0,
                "max": max(vals) if vals else 0,
            }

    # Throughput window: the wall spread of the merged event stream
    # (agents' own stamps when the events travelled a delta; the
    # registry clock's otherwise).  Zero/one-stamp registries render
    # totals only.
    stamps: List[float] = []
    for ev in registry.recent_events():
        t = ev.get("agent_ts")
        if t is None:
            t = ev.get("ts")
        if t:
            stamps.append(float(t))
    window = (max(stamps) - min(stamps)) if len(stamps) >= 2 else 0.0
    for e in edges.values():
        e["bytes_out_per_s"] = (
            e["bytes_out"] / window if window > 0 else 0.0
        )
    profile = {
        "edges": {k: edges[k] for k in sorted(edges)},
        "window_s": window,
        "quantiles": "sketch" if lat_sk else "exact",
    }
    if lat_sk:
        profile["alpha"] = next(iter(lat_sk.values())).alpha
    return profile
