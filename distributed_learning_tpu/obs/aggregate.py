"""Run-wide observability plane: merge per-agent metric streams into one
run registry, straggler profiles, and merged cross-agent traces.

PR 2 gave every *process* a :class:`MetricsRegistry`; this module gives
the *run* one.  Each agent periodically packs a delta of its registry —
counter totals, gauges, and the events recorded since the last pack
(series points, wall-anchored spans, free-form events) — into the
existing ``Telemetry`` wire message as a structured payload
(:data:`OBS_PAYLOAD_KIND`, versioned; re-exported by
``comm/protocol.py`` as part of the wire surface).  The master hands
every payload to a :class:`RunAggregator`, which

* merges the streams into ONE registry with per-agent label dimensions
  (``comm.agent.rounds_run/a`` per agent + the run-wide
  ``comm.agent.rounds_run`` sum — the same ``name/label`` convention the
  trainer uses for ``train.loss/node``);
* computes **straggler profiles** (:func:`straggler_profile_from_registry`):
  per-agent round-latency percentiles + histograms, per-round
  slowest-agent attribution from the master's arrival lags, round skew,
  and the staleness picture from the existing
  ``stale_requests_dropped`` / ``requests_deferred`` counters — exactly
  the signals stale-weighted mixing and deadline rounds
  (arxiv.org/pdf/2002.01119) and adaptive synchronization
  (arxiv.org/pdf/1910.13598) need as input;
* feeds every merged event into the
  :class:`~distributed_learning_tpu.obs.flight.FlightRecorder` ring, so
  a fault dump carries each agent's recent history;
* exports a **merged Chrome/Perfetto trace**: one track (pid) per
  agent, span starts wall-clock-anchored (``SpanTracer.wall0``), so N
  processes' spans land on one shared timeline.

Everything is host-side and jax-free (the ``obs-report`` /
``obs-monitor`` CLIs replay these structures offline); nothing here may
touch a jitted program — the plane observes training, it never joins
it.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

from distributed_learning_tpu.obs.flight import FlightRecorder
from distributed_learning_tpu.obs.registry import MetricsRegistry
from distributed_learning_tpu.obs.spans import FLOW_EVENT, FLOW_PHASES
from distributed_learning_tpu.utils.telemetry import TelemetryProcessor

__all__ = [
    "OBS_PAYLOAD_KIND",
    "OBS_PAYLOAD_VERSION",
    "is_obs_payload",
    "ObsDeltaSource",
    "RunAggregator",
    "straggler_profile_from_registry",
    "edge_profile_from_registry",
]

#: ``payload["kind"]`` marking a Telemetry payload as a registry delta
#: (any other payload is opaque user telemetry, recorded as-is).
OBS_PAYLOAD_KIND = "obs.delta"
#: Schema version inside the payload (``payload["v"]``).  Bump on
#: incompatible layout changes; the aggregator records-but-skips
#: payloads from the future instead of crashing a running master.
OBS_PAYLOAD_VERSION = 1

#: Round-latency histogram bucket upper bounds (seconds; last is +inf).
LATENCY_BUCKETS_S = (
    0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, math.inf,
)


def is_obs_payload(payload: Any) -> bool:
    """Whether a Telemetry payload is a structured registry delta."""
    return (
        isinstance(payload, Mapping)
        and payload.get("kind") == OBS_PAYLOAD_KIND
    )


# ---------------------------------------------------------------------- #
# Agent side: incremental registry deltas                                #
# ---------------------------------------------------------------------- #
class ObsDeltaSource:
    """Packs a registry's growth since the last pack into an
    ``obs.delta`` payload.

    Counters/gauges travel as *absolute totals* (idempotent: a lost or
    repeated delta cannot double-count — the aggregator diffs against
    the last totals it saw); series points, spans, and events travel as
    the buffered event stream (a sink registered on the registry, so
    packing is O(new events), never a rescan).  ``seq`` increments per
    pack; gaps tell the aggregator how many deltas a flaky wire lost.
    """

    def __init__(self, registry: MetricsRegistry, *,
                 max_buffer: int = 4096, backfill: bool = True):
        self._registry = registry
        self._lock = threading.Lock()
        self._buffer: collections.deque = collections.deque(
            maxlen=int(max_buffer)
        )
        self._dropped = 0
        self._seq = 0
        self._closed = False
        if backfill:
            # A late-attached source still ships the registry's retained
            # history in its first delta (events recorded before the
            # sink existed would otherwise be invisible to the run).
            self._buffer.extend(
                dict(ev) for ev in registry.recent_events()
            )
        registry.add_sink(self._sink)

    def _sink(self, event: Mapping[str, Any]) -> None:
        with self._lock:
            if (self._buffer.maxlen is not None
                    and len(self._buffer) >= self._buffer.maxlen):
                self._dropped += 1
            self._buffer.append(dict(event))

    def pack(self) -> dict:
        """One delta payload; drains the event buffer."""
        with self._lock:
            events = list(self._buffer)
            self._buffer.clear()
            dropped, self._dropped = self._dropped, 0
            self._seq += 1
            seq = self._seq
        snap = self._registry.snapshot()
        payload = {
            "kind": OBS_PAYLOAD_KIND,
            "v": OBS_PAYLOAD_VERSION,
            "seq": seq,
            "wall": time.time(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "events": events,
        }
        if dropped:
            payload["events_dropped"] = dropped
        return payload

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._registry.remove_sink(self._sink)


# ---------------------------------------------------------------------- #
# Master side: the run aggregator                                        #
# ---------------------------------------------------------------------- #
class _AgentView:
    """Per-agent merge state inside the aggregator."""

    __slots__ = ("last_seq", "counters", "spans", "flows", "last_wall")

    def __init__(self, max_spans: int):
        self.last_seq = 0
        self.counters: Dict[str, float] = {}
        # (name, wall_t0, dur_s, depth) for the merged trace.
        self.spans: collections.deque = collections.deque(maxlen=max_spans)
        # trace.flow frame-lifecycle events ({phase, origin, seq, run,
        # edge, ts, ...}) — the arrow-linked causal chains of the
        # merged trace.
        self.flows: collections.deque = collections.deque(maxlen=max_spans)
        self.last_wall: Optional[float] = None


class RunAggregator(TelemetryProcessor):
    """Merge per-agent ``obs.delta`` payloads into one run registry.

    Implements the ``TelemetryProcessor`` interface, so it plugs
    straight into the master's existing telemetry dispatch
    (``ConsensusMaster(aggregator=...)`` wires it; a user telemetry
    processor still runs beside it).  Non-delta payloads are recorded
    as plain ``telemetry`` events with their token — the plane subsumes
    the old path, it does not break it.
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None,
                 max_spans_per_agent: int = 4096):
        #: The merged run registry (per-agent labels + run-wide sums).
        self.registry = (
            registry if registry is not None
            else MetricsRegistry(max_points=1 << 14, max_events=1 << 16)
        )
        self.flight = flight
        self._lock = threading.Lock()
        self._max_spans = int(max_spans_per_agent)
        self._views: Dict[str, _AgentView] = {}

    # ------------------------------------------------------------------ #
    def agents(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    def _view(self, token: str) -> _AgentView:
        with self._lock:
            view = self._views.get(token)
            if view is None:
                view = self._views[token] = _AgentView(self._max_spans)
            return view

    # ------------------------------------------------------------------ #
    def process(self, token: Any, payload: Any) -> None:
        """TelemetryProcessor entry point: merge one payload."""
        token = str(token)
        if not is_obs_payload(payload):
            self.registry.event("telemetry", token=token, payload=payload)
            if self.flight is not None:
                self.flight.note(token, "telemetry", payload=payload)
            return
        if int(payload.get("v", 0)) > OBS_PAYLOAD_VERSION:
            # A newer agent talking to an older master: visible, not
            # fatal — the rest of the plane keeps running.
            self.registry.inc("obs.unknown_version")
            return
        view = self._view(token)
        seq = int(payload.get("seq", view.last_seq + 1))
        if seq <= view.last_seq:
            self.registry.inc("obs.stale_deltas")
            return
        if seq > view.last_seq + 1:
            self.registry.inc("obs.deltas_lost", seq - view.last_seq - 1)
        view.last_seq = seq
        view.last_wall = payload.get("wall")

        self._merge_counters(token, view, payload.get("counters") or {})
        for name, value in (payload.get("gauges") or {}).items():
            self.registry.gauge(f"{name}/{token}", float(value))
            self.registry.gauge(name, float(value))
        for ev in payload.get("events") or ():
            self._merge_event(token, view, ev)
        if payload.get("events_dropped"):
            self.registry.inc(
                f"obs.delta_events_dropped/{token}",
                payload["events_dropped"],
            )
        # Self-contained stream marker: carries this agent's absolute
        # counter totals, so a JsonlSink'd aggregate file replays into
        # a live dashboard (obs-monitor) with counters intact.
        self.registry.event(
            "obs.delta", token=token, seq=seq,
            wall=view.last_wall, counters=dict(view.counters),
        )
        self.registry.inc("obs.deltas_merged")

    def _merge_counters(self, token: str, view: _AgentView,
                        counters: Mapping[str, Any]) -> None:
        for name, total in counters.items():
            total = float(total)
            prev = view.counters.get(name, 0.0)
            diff = total - prev
            if diff < 0:
                # The token restarted with fresh counters (elastic
                # rejoin): its new life counts from zero.
                self.registry.inc("obs.counter_resets")
                diff = total
            if diff:
                self.registry.inc(f"{name}/{token}", diff)
                self.registry.inc(name, diff)
            view.counters[name] = total

    def _merge_event(self, token: str, view: _AgentView,
                     ev: Mapping[str, Any]) -> None:
        kind = ev.get("kind")
        name = ev.get("name", "")
        if kind == "series":
            self.registry.observe(
                f"{name}/{token}", float(ev.get("value", 0.0)),
                step=ev.get("step"),
            )
        elif kind == "span":
            dur = float(ev.get("value", 0.0))
            t0 = ev.get("t0")
            self.registry.record_span(
                f"{name}/{token}", dur,
                depth=int(ev.get("depth", 0)), t0=t0,
            )
            if t0 is not None:
                view.spans.append(
                    (name, float(t0), dur, int(ev.get("depth", 0)))
                )
        elif kind == "event":
            fields = {
                k: v for k, v in ev.items()
                if k not in ("kind", "name", "ts")
            }
            self.registry.event(name, token=token,
                                agent_ts=ev.get("ts"), **fields)
            if name == FLOW_EVENT:
                # Frame-lifecycle hop: keep it (with the emitting
                # agent's wall stamp) for the merged trace's arrows.
                flow = dict(fields)
                flow["agent"] = token
                flow["ts"] = ev.get("ts")
                view.flows.append(flow)
        elif kind in ("counter", "gauge"):
            # Snapshot lines from a replayed dump file: totals already
            # merged through the counters/gauges maps — skip, or the
            # offline merge would double-count.
            return
        if self.flight is not None:
            self.flight.record(token, ev)

    # ------------------------------------------------------------------ #
    def merge_registry(self, token: str,
                       registry: MetricsRegistry) -> None:
        """Offline merge of a whole per-agent registry (the
        ``obs-report --merge`` path over per-agent JSONL files): one
        synthetic delta carrying the registry's totals and full event
        log."""
        self.process(str(token), {
            "kind": OBS_PAYLOAD_KIND,
            "v": OBS_PAYLOAD_VERSION,
            "seq": self._view(str(token)).last_seq + 1,
            "counters": dict(registry.counters),
            "gauges": dict(registry.gauges),
            "events": list(registry.events),
        })

    # ------------------------------------------------------------------ #
    # Master-side round accounting (control-plane signals the agents    #
    # cannot see about themselves).                                      #
    # ------------------------------------------------------------------ #
    def note_round_arrivals(self, round_id: int,
                            arrivals: Mapping[str, float]) -> None:
        """Per-round straggler attribution from the master's view: the
        wall-clock arrival time of each agent's round request.  The
        LAST arrival is the straggler — it set the round's start time
        for everyone (lock-step rounds run at the pace of the slowest
        agent, which is exactly what the async runtime will relax)."""
        if not arrivals:
            return
        t_first = min(arrivals.values())
        t_last = max(arrivals.values())
        for token, t in arrivals.items():
            self.registry.observe(
                f"straggler.lag_s/{token}", t - t_first, step=round_id
            )
        self.registry.observe(
            "straggler.skew_s", t_last - t_first, step=round_id
        )
        slowest = max(arrivals, key=lambda t: arrivals[t])
        self.registry.inc(f"straggler.slowest/{slowest}")
        if self.flight is not None:
            self.flight.note(
                "<master>", "round_arrivals", round_id=int(round_id),
                skew_s=t_last - t_first, slowest=slowest,
            )

    def note_round_done(self, round_id: int, dur_s: float,
                        wall_t0: Optional[float] = None) -> None:
        """Master-side whole-round wall time (request-complete to
        all-converged)."""
        self.registry.inc("comm.master.rounds_done")
        self.registry.observe(
            "comm.master.round_s", float(dur_s), step=round_id
        )
        self.registry.record_span(
            "comm.master.round", float(dur_s), t0=wall_t0
        )
        if wall_t0 is not None:
            self._view("<master>").spans.append(
                ("comm.master.round", float(wall_t0), float(dur_s), 0)
            )

    # ------------------------------------------------------------------ #
    def straggler_profile(self) -> dict:
        """See :func:`straggler_profile_from_registry`."""
        return straggler_profile_from_registry(self.registry)

    def edge_profile(self) -> dict:
        """See :func:`edge_profile_from_registry`."""
        return edge_profile_from_registry(self.registry)

    # ------------------------------------------------------------------ #
    def to_chrome_trace(self) -> dict:
        """Merged Chrome/Perfetto trace: one track (pid) per agent,
        wall-clock-anchored span starts normalized to the earliest span
        (the shared timeline), ``process_name`` metadata naming each
        track after its agent.

        ``trace.flow`` frame-lifecycle events additionally render as
        per-frame causal chains: each hop becomes a small anchor slice
        (``frame.<phase>``, tid 2 — the "wire" lane of its agent's
        track) and the hops sharing one wire-carried
        ``(run, origin, seq)`` identity are linked with Chrome flow
        arrows (``ph`` s/t/f, one id per frame), so
        encode→send→recv→decode→mix reads as ONE arrow-linked path
        across process tracks in Perfetto."""
        with self._lock:
            per_agent = {
                token: (list(view.spans), list(view.flows))
                for token, view in sorted(self._views.items())
                if view.spans or view.flows
            }
        events: List[dict] = []
        all_t0 = [t0 for spans, _flows in per_agent.values()
                  for (_n, t0, _d, _dep) in spans]
        all_t0 += [
            float(f["ts"]) for _spans, flows in per_agent.values()
            for f in flows if f.get("ts") is not None
        ]
        base = min(all_t0) if all_t0 else 0.0
        pids: Dict[str, int] = {}
        for pid, (token, (spans, _flows)) in enumerate(
            per_agent.items(), start=1
        ):
            pids[token] = pid
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"agent {token}"},
            })
            for name, t0, dur, depth in spans:
                events.append({
                    "name": name,
                    "ph": "X",
                    "ts": round((t0 - base) * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                    "pid": pid,
                    "tid": 1,
                    "args": {"agent": token, "depth": depth},
                })
        # Frame chains: group hops by wire identity, order each chain
        # by lifecycle phase (the wall stamps of two processes are only
        # ~ms-aligned; the phase order is the causal truth).
        chains: Dict[str, List[Tuple[int, float, int, dict]]] = {}
        for token, (_spans, flows) in per_agent.items():
            for f in flows:
                ts, phase = f.get("ts"), f.get("phase")
                if ts is None or phase not in FLOW_PHASES:
                    continue
                key = (
                    f"{f.get('run', 0)}:{f.get('origin', '')}:"
                    f"{f.get('seq', 0)}"
                )
                chains.setdefault(key, []).append(
                    (FLOW_PHASES.index(phase), float(ts), pids[token], f)
                )
        flow_id = 0
        for key in sorted(chains):
            hops = sorted(chains[key], key=lambda h: (h[0], h[1]))
            flow_id += 1
            for _order, ts, pid, f in hops:
                events.append({
                    "name": f"frame.{f['phase']}",
                    "ph": "X",
                    "ts": round((ts - base) * 1e6, 3),
                    "dur": 20.0,
                    "pid": pid,
                    "tid": 2,
                    "args": {
                        k: f[k]
                        for k in ("origin", "seq", "run", "edge", "agent")
                        if k in f
                    },
                })
            if len(hops) < 2:
                continue
            for i, (_order, ts, pid, _f) in enumerate(hops):
                ph = "s" if i == 0 else (
                    "f" if i == len(hops) - 1 else "t"
                )
                arrow = {
                    "name": "frame",
                    "cat": FLOW_EVENT,
                    "ph": ph,
                    "id": flow_id,
                    # +1us: strictly inside the anchor slice, so the
                    # arrow binds to it on every Perfetto version.
                    "ts": round((ts - base) * 1e6 + 1.0, 3),
                    "pid": pid,
                    "tid": 2,
                }
                if ph == "f":
                    arrow["bp"] = "e"
                events.append(arrow)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"wall0": base},
        }

    def export_chrome_trace(self, path: str) -> int:
        """Write :meth:`to_chrome_trace` to ``path``; returns the span
        event count (metadata rows excluded)."""
        import json

        trace = self.to_chrome_trace()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        return sum(1 for e in trace["traceEvents"] if e["ph"] == "X")


# ---------------------------------------------------------------------- #
# Straggler profile                                                      #
# ---------------------------------------------------------------------- #
def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[rank - 1]


def _hist(vals: List[float]) -> List[List[float]]:
    """``[upper_bound_s, count]`` rows over LATENCY_BUCKETS_S."""
    counts = [0] * len(LATENCY_BUCKETS_S)
    for v in vals:
        for i, ub in enumerate(LATENCY_BUCKETS_S):
            if v <= ub:
                counts[i] += 1
                break
    return [
        [ub, c] for ub, c in zip(LATENCY_BUCKETS_S, counts) if c
    ]


def _series_by_token(registry: MetricsRegistry,
                     prefix: str) -> Dict[str, list]:
    out = {}
    for name, pts in registry.series.items():
        if name.startswith(prefix):
            out[name[len(prefix):]] = list(pts)
    return out


def straggler_profile_from_registry(
        registry: MetricsRegistry, *,
        counters: Optional[Mapping[str, float]] = None) -> dict:
    """Who is slow, how slow, and how often — from a merged run
    registry.

    Latency source, in preference order: the master's per-round arrival
    lags (``straggler.lag_s/<token>`` — how long each agent kept the
    round waiting; authoritative attribution) or, when no master-side
    data exists, the agents' own round wall times
    (``comm.agent.round_s/<token>`` — in lock-step rounds these include
    waiting on peers, so attribution from them is weak; the profile
    names its ``source`` so a reader knows which it got).  Staleness
    comes from the per-agent ``stale_requests_dropped`` /
    ``requests_deferred`` counters; ``counters`` overrides the
    registry's own totals for callers that reconstructed them from a
    replayed stream (``obs-monitor``, where counter totals travel as
    delta markers, not events).
    """
    if counters is None:
        counters = registry.counters
    lag = _series_by_token(registry, "straggler.lag_s/")
    source = "master-arrival-lag"
    if not lag:
        lag = _series_by_token(registry, "comm.agent.round_s/")
        source = "agent-round-wall"
    if not lag:
        # Pure async runs have no master-gated rounds at all: fall back
        # to the async runtime's per-round wall times.
        lag = _series_by_token(registry, "comm.agent.async_round_s/")
        source = "agent-async-round-wall"
    # Per-round grouping for attribution (step == round id).
    rounds: Dict[Any, List[Tuple[str, float]]] = {}
    for token, pts in lag.items():
        for step, val in pts:
            if step is not None:
                rounds.setdefault(step, []).append((token, val))
    slowest_counts: Dict[str, int] = {}
    for entries in rounds.values():
        if len(entries) >= 2:
            tok = max(entries, key=lambda tv: tv[1])[0]
            slowest_counts[tok] = slowest_counts.get(tok, 0) + 1
    # Master-side attribution counters win when present (they cover
    # rounds whose lag series may have been ring-evicted).
    master_counts = {
        name[len("straggler.slowest/"):]: int(total)
        for name, total in counters.items()
        if name.startswith("straggler.slowest/")
    }
    if master_counts:
        slowest_counts = master_counts

    # Staleness-vs-convergence picture (docs/async_runtime.md): the
    # async runtime's per-mix staleness series and per-agent consensus
    # residual trends, so the trade-off τ buys is readable from one
    # merged JSONL.
    staleness = _series_by_token(registry, "comm.agent.staleness/")
    residual = _series_by_token(registry, "consensus.residual/")

    per_agent = {}
    for token in sorted(set(lag) | set(staleness) | set(residual)):
        vals = sorted(v for _, v in lag.get(token, ()))
        entry = {
            "count": len(vals),
            "p50_s": _pct(vals, 0.50),
            "p95_s": _pct(vals, 0.95),
            "max_s": vals[-1] if vals else 0.0,
            "hist": _hist(vals),
            "slowest_rounds": slowest_counts.get(token, 0),
            "stale_dropped": counters.get(
                f"comm.agent.stale_requests_dropped/{token}", 0
            ),
            "deferred": counters.get(
                f"comm.agent.requests_deferred/{token}", 0
            ),
        }
        spts = [v for _, v in staleness.get(token, ())]
        if spts:
            buckets: Dict[int, int] = {}
            for v in spts:
                buckets[int(v)] = buckets.get(int(v), 0) + 1
            entry["staleness"] = {
                "n": len(spts),
                "mean": sum(spts) / len(spts),
                "max": max(spts),
                "hist": sorted(buckets.items()),
            }
            entry["stale_mixed"] = counters.get(
                f"comm.agent.async_stale_mixed/{token}", 0
            )
            entry["stale_dropped_mix"] = counters.get(
                f"comm.agent.async_stale_dropped/{token}", 0
            )
        rpts = [v for _, v in residual.get(token, ())]
        if rpts:
            entry["residual_first"] = rpts[0]
            entry["residual_last"] = rpts[-1]
        per_agent[token] = entry
    skew_pts = sorted(
        v for _, v in registry.series.get("straggler.skew_s", ())
    )
    skew = {
        "p50_s": _pct(skew_pts, 0.50),
        "p95_s": _pct(skew_pts, 0.95),
        "max_s": skew_pts[-1] if skew_pts else 0.0,
    }
    slowest_agent = (
        max(slowest_counts, key=lambda t: slowest_counts[t])
        if slowest_counts else None
    )
    return {
        "source": source,
        "rounds": len(rounds),
        "per_agent": per_agent,
        "skew": skew,
        "slowest_agent": slowest_agent,
    }


# ---------------------------------------------------------------------- #
# Per-edge wire profile                                                  #
# ---------------------------------------------------------------------- #
#: profile field -> bare counter prefix (``FramedStream._edge_inc``).
_EDGE_COUNTER_FIELDS = (
    ("bytes_out", "comm.edge.bytes_out/"),
    ("bytes_in", "comm.edge.bytes_in/"),
    ("frames_out", "comm.edge.frames_out/"),
    ("frames_in", "comm.edge.frames_in/"),
    ("retries", "comm.edge.retries/"),
)


def _bare_edge(name: str, prefix: str) -> Optional[str]:
    """The ``src->dst`` edge label of a BARE per-edge counter name
    (``comm.edge.bytes_out/a->b``); labeled variants with a trailing
    ``/token`` dimension (the aggregator's per-agent copies) return
    None so totals are not double-counted."""
    if not name.startswith(prefix):
        return None
    rest = name[len(prefix):]
    if "->" in rest and "/" not in rest:
        return rest
    return None


def edge_profile_from_registry(
        registry: MetricsRegistry, *,
        counters: Optional[Mapping[str, float]] = None) -> dict:
    """The per-edge wire observatory: which directed link moved how
    many bytes/frames, how slowly, and how unreliably — from a merged
    run registry.

    Volume/retry totals come from the bare ``comm.edge.*/<src>-><dst>``
    counters every edge-labeled :class:`FramedStream` maintains;
    latency from the ``comm.edge.latency_s/<edge>`` series (receiver
    wall-clock minus the frame's wire-carried ``TraceContext.t_wall``
    send stamp, so it needs tracing on); per-edge mix staleness from
    ``comm.edge.staleness/<edge>``; injected-fault attribution from the
    ``comm.faults.<kind>/<edge>`` counters.  ``counters`` overrides the
    registry totals for replayed streams, exactly like
    :func:`straggler_profile_from_registry`.  This is the measured
    per-link cost picture topology/schedule choices key off
    (arxiv.org/pdf/2002.01119 §3; the two-tier link split of
    arxiv.org/pdf/2105.09080 needs per-edge latency as input).
    """
    if counters is None:
        counters = registry.counters
    edges: Dict[str, dict] = {}

    def entry(edge: str) -> dict:
        return edges.setdefault(edge, {
            "bytes_out": 0.0, "bytes_in": 0.0,
            "frames_out": 0, "frames_in": 0, "retries": 0,
            "faults": {},
        })

    for name, total in counters.items():
        for field, prefix in _EDGE_COUNTER_FIELDS:
            edge = _bare_edge(name, prefix)
            if edge is not None:
                if field.startswith("bytes"):
                    entry(edge)[field] = float(total)
                else:
                    entry(edge)[field] = int(total)
        if name.startswith("comm.faults."):
            rest = name[len("comm.faults."):]
            kind, _slash, label = rest.partition("/")
            if label and "->" in label and "/" not in label:
                entry(label)["faults"][kind] = int(total)

    lat: Dict[str, List[float]] = {}
    stale: Dict[str, List[float]] = {}
    for name, pts in registry.series.items():
        for prefix, dest in (("comm.edge.latency_s/", lat),
                             ("comm.edge.staleness/", stale)):
            if name.startswith(prefix):
                edge = name[len(prefix):].split("/", 1)[0]
                if "->" in edge:
                    dest.setdefault(edge, []).extend(v for _, v in pts)
    for edge, vals in lat.items():
        vals.sort()
        entry(edge)["latency"] = {
            "n": len(vals),
            "p50_s": _pct(vals, 0.50),
            "p95_s": _pct(vals, 0.95),
            "max_s": vals[-1] if vals else 0.0,
        }
    for edge, vals in stale.items():
        entry(edge)["staleness"] = {
            "n": len(vals),
            "mean": sum(vals) / len(vals) if vals else 0.0,
            "max": max(vals) if vals else 0,
        }

    # Throughput window: the wall spread of the merged event stream
    # (agents' own stamps when the events travelled a delta; the
    # registry clock's otherwise).  Zero/one-stamp registries render
    # totals only.
    stamps: List[float] = []
    for ev in registry.recent_events():
        t = ev.get("agent_ts")
        if t is None:
            t = ev.get("ts")
        if t:
            stamps.append(float(t))
    window = (max(stamps) - min(stamps)) if len(stamps) >= 2 else 0.0
    for e in edges.values():
        e["bytes_out_per_s"] = (
            e["bytes_out"] / window if window > 0 else 0.0
        )
    return {
        "edges": {k: edges[k] for k in sorted(edges)},
        "window_s": window,
    }
