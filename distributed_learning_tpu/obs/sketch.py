"""Mergeable metric sketches: log-bucketed quantile sketches and
bounded-cardinality label rollups — the fleet-scale obs primitives.

The run-wide plane (PR 6/14) kept raw per-agent point lists and took
nearest-rank percentiles over them, so aggregator memory, delta bytes,
and report cost all grew with agents × samples, and ring eviction
silently biased long-run percentiles.  At the scale the sharded-master
ROADMAP item targets (1000+ agents; the efficiency constraints of
arxiv.org/pdf/2002.01119), per-sample anything is a non-starter.  This
module provides the two constant-size, exactly-mergeable summaries the
hierarchical plane (``obs/aggregate.py`` payload v2) ships instead:

* :class:`QuantileSketch` — a DDSketch-style log-bucketed quantile
  sketch: values land in geometric buckets ``(γ^(k-1), γ^k]`` with
  ``γ = (1+α)/(1-α)``, so any quantile reconstructs within **relative
  error α** (default 1%).  Merging is bucket-wise count addition —
  *exact*, associative, and commutative: merging 500 agents' sketches
  in any order or grouping yields byte-identical state, which is what
  makes aggregate-of-aggregates (agent → sub-aggregator → root) safe.
  Size is O(buckets touched) — bounded by the data's dynamic range and
  the hard ``key_bound`` clamp, never by the sample count.
* :class:`LabelRollup` — a bounded-cardinality ``label -> total``
  counter map: past ``max_labels`` distinct labels the smallest entries
  fold deterministically into an explicit ``other`` bucket (fold order:
  ascending ``(total, label)``).  Total mass is preserved *exactly*;
  only the per-label attribution of the folded tail is coarsened, and
  the fold is disclosed (``other_labels``).  This is how a
  sub-aggregator forwards per-agent counter dimensions without the
  upstream delta growing with its pod size.

Both encode to compact JSON-able dicts (sorted, delta-encoded integer
bucket keys — varint-friendly and byte-identical for equal state) and
round-trip through :meth:`to_dict` / :meth:`from_dict`.

Everything here is host-side, jax-free, and deterministic: no wall
clocks, no RNG, no platform-hashed iteration order.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = ["DEFAULT_ALPHA", "QuantileSketch", "LabelRollup"]

#: Default relative-error bound α: reconstructed quantiles are within
#: ±1% of the exact nearest-rank value (for values inside the clamp
#: range).  1% is far below the ≥2x effects the straggler/edge
#: profiles exist to surface.
DEFAULT_ALPHA = 0.01

#: Default hard bucket-key clamp: keys are confined to
#: ``[-key_bound, key_bound]``, so a hostile or degenerate stream
#: (denormals, 1e300 outliers) cannot grow the bucket map without
#: bound.  With α=1% this still spans ~±e^82 ≈ 1e35 in magnitude;
#: values beyond the clamp land in the edge bucket (α no longer holds
#: for them, but ``min``/``max`` stay exact and merge stays exact).
DEFAULT_KEY_BOUND = 4096


class QuantileSketch:
    """Log-bucketed quantile sketch with exact merge.

    Positive values bucket by ``k = ceil(log_γ(v))``; negative values
    bucket their magnitude into a separate map; exact zeros count in a
    dedicated bucket.  ``n``/``sum``/``min``/``max`` ride along exactly,
    so ``mean`` is exact and ``quantile(0)``/``quantile(1)`` return the
    true extremes.
    """

    __slots__ = ("alpha", "gamma", "key_bound", "_lg",
                 "n", "sum", "min", "max", "zeros", "buckets", "neg")

    def __init__(self, alpha: float = DEFAULT_ALPHA, *,
                 key_bound: int = DEFAULT_KEY_BOUND):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self.key_bound = int(key_bound)
        self._lg = math.log(self.gamma)
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.zeros = 0
        self.buckets: Dict[int, int] = {}
        self.neg: Dict[int, int] = {}

    # ------------------------------------------------------------------ #
    def _key(self, mag: float) -> int:
        """Bucket key of a positive magnitude: the k with
        ``γ^(k-1) < mag <= γ^k``, clamped to ``±key_bound``.  The libm
        ``log`` is followed by a boundary correction so the assignment
        is exactly consistent with the ``γ**k`` bounds used by
        :meth:`quantile` — a value can never straddle its bucket edge
        because of rounding."""
        k = math.ceil(math.log(mag) / self._lg)
        if abs(k) <= self.key_bound:
            while k > -self.key_bound and self.gamma ** (k - 1) >= mag:
                k -= 1
            while k < self.key_bound and self.gamma ** k < mag:
                k += 1
        return max(-self.key_bound, min(self.key_bound, k))

    def add(self, value: float, count: int = 1) -> None:
        """Fold ``count`` occurrences of ``value`` into the sketch."""
        value = float(value)
        count = int(count)
        if count <= 0 or math.isnan(value):
            return
        self.n += count
        self.sum += value * count
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if value == 0.0:
            self.zeros += count
        elif value > 0.0:
            k = self._key(value)
            self.buckets[k] = self.buckets.get(k, 0) + count
        else:
            k = self._key(-value)
            self.neg[k] = self.neg.get(k, 0) + count

    def extend(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    # ------------------------------------------------------------------ #
    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """In-place exact merge (bucket-wise addition); returns self.

        Raises ``ValueError`` on an α/clamp mismatch — two sketches
        with different bucket geometry have no exact merge, and an
        approximate one would silently void the error bound."""
        if (other.alpha != self.alpha
                or other.key_bound != self.key_bound):
            raise ValueError(
                "sketch geometry mismatch: "
                f"alpha {self.alpha} vs {other.alpha}, "
                f"key_bound {self.key_bound} vs {other.key_bound}"
            )
        self.n += other.n
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self.zeros += other.zeros
        for k, c in other.buckets.items():
            self.buckets[k] = self.buckets.get(k, 0) + c
        for k, c in other.neg.items():
            self.neg[k] = self.neg.get(k, 0) + c
        return self

    def copy(self) -> "QuantileSketch":
        out = QuantileSketch(self.alpha, key_bound=self.key_bound)
        out.merge(self)
        return out

    # ------------------------------------------------------------------ #
    def _estimate(self, key: int, negative: bool) -> float:
        """Representative value of a bucket: ``2γ^k / (1+γ)``, the
        point whose relative distance to every value in the bucket is
        <= α; clamped into ``[min, max]`` (exact extremes can only
        tighten the bound)."""
        est = 2.0 * (self.gamma ** key) / (1.0 + self.gamma)
        if negative:
            est = -est
        return max(self.min, min(self.max, est))

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile estimate, within relative error α of
        the exact nearest-rank value (for in-clamp values)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.n == 0:
            return 0.0
        if q <= 0.0:
            return self.min
        if q >= 1.0:
            return self.max
        rank = max(1, math.ceil(q * self.n))
        seen = 0
        # Ascending value order: most-negative first (descending
        # magnitude keys), then zeros, then positives (ascending keys).
        for k in sorted(self.neg, reverse=True):
            seen += self.neg[k]
            if seen >= rank:
                return self._estimate(k, negative=True)
        if self.zeros:
            seen += self.zeros
            if seen >= rank:
                return 0.0
        for k in sorted(self.buckets):
            seen += self.buckets[k]
            if seen >= rank:
                return self._estimate(k, negative=False)
        return self.max

    @property
    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    def count_le(self, x: float) -> int:
        """Approximate count of values <= ``x`` (a bucket straddling
        ``x`` counts fully iff its representative is <= ``x``) — the
        histogram reconstruction the profile renderers use."""
        x = float(x)
        total = 0
        for k, c in self.neg.items():
            if self._estimate(k, negative=True) <= x:
                total += c
        if x >= 0.0:
            total += self.zeros
        for k, c in self.buckets.items():
            if self._estimate(k, negative=False) <= x:
                total += c
        return total

    def histogram(self, bounds: Iterable[float]) -> List[List[float]]:
        """``[upper_bound, count]`` rows over ascending ``bounds``
        (last may be +inf); empty rows are omitted — the same shape as
        the exact-path ``_hist`` in ``obs/aggregate.py``."""
        rows: List[List[float]] = []
        prev = 0
        for ub in bounds:
            cum = self.n if math.isinf(ub) else self.count_le(ub)
            if cum - prev:
                rows.append([ub, cum - prev])
            prev = cum
        return rows

    # ------------------------------------------------------------------ #
    @staticmethod
    def _pack_buckets(buckets: Mapping[int, int]) -> Tuple[list, list]:
        """Sorted keys delta-encoded (first absolute, then gaps — small
        non-negative ints, varint-friendly) + parallel counts."""
        keys = sorted(buckets)
        dk = [
            k if i == 0 else k - keys[i - 1]
            for i, k in enumerate(keys)
        ]
        return dk, [buckets[k] for k in keys]

    @staticmethod
    def _unpack_buckets(dk: list, counts: list) -> Dict[int, int]:
        out: Dict[int, int] = {}
        key = 0
        for i, (d, c) in enumerate(zip(dk, counts)):
            key = d if i == 0 else key + d
            out[key] = int(c)
        return out

    def to_dict(self) -> dict:
        """Compact deterministic encoding: equal state encodes to an
        equal dict (``json.dumps(..., sort_keys=True)`` is then
        byte-identical)."""
        d: Dict[str, Any] = {
            "a": self.alpha, "kb": self.key_bound, "n": self.n,
        }
        if self.n:
            d["sum"] = self.sum
            d["min"] = self.min
            d["max"] = self.max
        if self.zeros:
            d["z"] = self.zeros
        if self.buckets:
            d["k"], d["c"] = self._pack_buckets(self.buckets)
        if self.neg:
            d["nk"], d["nc"] = self._pack_buckets(self.neg)
        return d

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "QuantileSketch":
        out = cls(
            float(d.get("a", DEFAULT_ALPHA)),
            key_bound=int(d.get("kb", DEFAULT_KEY_BOUND)),
        )
        out.n = int(d.get("n", 0))
        if out.n:
            out.sum = float(d.get("sum", 0.0))
            out.min = float(d.get("min", math.inf))
            out.max = float(d.get("max", -math.inf))
        out.zeros = int(d.get("z", 0))
        out.buckets = cls._unpack_buckets(
            d.get("k") or [], d.get("c") or []
        )
        out.neg = cls._unpack_buckets(
            d.get("nk") or [], d.get("nc") or []
        )
        return out

    # ------------------------------------------------------------------ #
    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, QuantileSketch):
            return NotImplemented
        return self.to_dict() == other.to_dict()

    def __len__(self) -> int:
        return len(self.buckets) + len(self.neg) + (1 if self.zeros else 0)

    def __repr__(self) -> str:
        return (
            f"QuantileSketch(alpha={self.alpha}, n={self.n}, "
            f"buckets={len(self)})"
        )


# ---------------------------------------------------------------------- #
class LabelRollup:
    """Bounded-cardinality ``label -> total`` counter map.

    ``add``/``merge`` accumulate exactly; past ``max_labels`` distinct
    labels the smallest entries (ascending ``(total, label)`` — fully
    deterministic) fold into the ``other`` bucket.  ``total()`` is
    exact regardless; the fold only coarsens per-label attribution of
    the tail, and ``other_labels`` says how many labels it absorbed —
    the bound is disclosed, never silent.
    """

    __slots__ = ("max_labels", "counts", "other", "other_labels")

    def __init__(self, max_labels: int = 64):
        if max_labels < 1:
            raise ValueError("max_labels must be >= 1")
        self.max_labels = int(max_labels)
        self.counts: Dict[str, float] = {}
        self.other = 0.0
        self.other_labels = 0

    def add(self, label: str, value: float = 1.0) -> None:
        self.counts[str(label)] = (
            self.counts.get(str(label), 0.0) + float(value)
        )
        self._bound()

    def merge(self, other: "LabelRollup") -> "LabelRollup":
        """In-place merge; total mass adds exactly.  ``max_labels``
        tightens to the smaller of the two bounds."""
        self.max_labels = min(self.max_labels, other.max_labels)
        for label, value in other.counts.items():
            self.counts[label] = self.counts.get(label, 0.0) + value
        self.other += other.other
        self.other_labels += other.other_labels
        self._bound()
        return self

    def _bound(self) -> None:
        while len(self.counts) > self.max_labels:
            label = min(self.counts, key=lambda l: (self.counts[l], l))
            self.other += self.counts.pop(label)
            self.other_labels += 1

    # ------------------------------------------------------------------ #
    def total(self) -> float:
        return sum(self.counts.values()) + self.other

    def top(self, k: Optional[int] = None) -> List[Tuple[str, float]]:
        """Labels by descending total (ties broken by label)."""
        rows = sorted(
            self.counts.items(), key=lambda kv: (-kv[1], kv[0])
        )
        return rows if k is None else rows[:k]

    def copy(self) -> "LabelRollup":
        out = LabelRollup(self.max_labels)
        out.counts = dict(self.counts)
        out.other = self.other
        out.other_labels = self.other_labels
        return out

    # ------------------------------------------------------------------ #
    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "m": self.max_labels,
            "l": {k: self.counts[k] for k in sorted(self.counts)},
        }
        if self.other:
            d["o"] = self.other
        if self.other_labels:
            d["on"] = self.other_labels
        return d

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "LabelRollup":
        out = cls(int(d.get("m", 64)))
        for label, value in (d.get("l") or {}).items():
            out.counts[str(label)] = float(value)
        out.other = float(d.get("o", 0.0))
        out.other_labels = int(d.get("on", 0))
        out._bound()
        return out

    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, LabelRollup):
            return NotImplemented
        return self.to_dict() == other.to_dict()

    def __repr__(self) -> str:
        return (
            f"LabelRollup(labels={len(self.counts)}, "
            f"other={self.other}, max={self.max_labels})"
        )
