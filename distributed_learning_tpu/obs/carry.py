"""Device-side metrics carry: per-step scalars accumulated *inside* the
jitted chunk, flushed to the registry once per chunk host-side.

The repo's hot-path contract (graftlint ``host-sync-in-hot-path``, the
pinned jaxpr/HLO audits) forbids instrumentation that syncs or
communicates per step.  The carry pattern satisfies it by construction:

* inside the jitted chunk, each tracked metric is an ordinary traced
  scalar (loss, grad norm, consensus residual, mixing-round count) that
  the ``lax.scan`` stacks into a ``(steps, ...)`` trace — pure device
  compute, no collectives, no callbacks;
* the chunk returns those traces alongside its existing outputs, and
  the host flushes them with ONE ``np.asarray`` materialization per
  array per chunk (:func:`flush_chunk`) — the same sync the trainer
  already pays to read its loss curve.

The carry is part of the compiled program whether or not a registry is
attached, so toggling observability cannot change the computation: an
obs-enabled run is bit-identical to an obs-disabled one (the oracle
test in ``tests/test_obs.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Sequence

from distributed_learning_tpu.obs.registry import MetricsRegistry

__all__ = ["global_norm", "flush_chunk"]


def global_norm(tree: Any):
    """L2 norm of a pytree, accumulated in f32 — the device-side grad
    norm metric (jax-traced; call inside the jitted step).  Equivalent
    to ``optax.global_norm`` but f32-accumulated regardless of the
    state dtype, so bf16 training still reports a usable norm."""
    import jax
    import jax.numpy as jnp

    total = jnp.float32(0.0)
    for leaf in jax.tree.leaves(tree):
        lf = leaf.astype(jnp.float32)
        total = total + jnp.sum(lf * lf)
    return jnp.sqrt(total)


def flush_chunk(
    registry: Optional[MetricsRegistry],
    carry: Mapping[str, Any],
    *,
    step0: int = 0,
    node_names: Optional[Sequence] = None,
    prefix: str = "train",
) -> Dict[str, Any]:
    """Flush one jitted chunk's carried metric traces to ``registry``.

    ``carry`` maps metric name to a per-chunk array: scalars, ``(steps,)``
    traces, ``(steps, n_nodes)`` stacked traces, or — when the chunk is
    an epoch *superstep* — ``(k_epochs, steps, n_nodes)`` doubly-stacked
    traces (the outer epoch scan stacks the per-epoch traces; the two
    leading axes collapse to one ``k*steps`` step trace here, so the
    one-flush-per-chunk contract holds whether the chunk is one epoch or
    K).  Each array is materialized host-side exactly once
    (``np.asarray``) — the single per-chunk sync the carry pattern
    allows.  Per-node chunk means are recorded as
    ``{prefix}.{name}/{node}`` series points at the chunk's final step,
    plus the cross-node mean as ``{prefix}.{name}``; scalars record one
    point.  Returns the materialized numpy arrays (original shapes) so
    the caller reuses them (the trainer feeds the same arrays to its
    stats/telemetry paths — no second sync).
    """
    import numpy as np

    arrays = {k: np.asarray(v) for k, v in carry.items()}
    if registry is None:
        return arrays
    for name, arr in arrays.items():
        key = f"{prefix}.{name}" if prefix else str(name)
        if arr.ndim == 0:
            registry.observe(key, float(arr), step=step0)
            continue
        flat = arr
        if arr.ndim >= 3 and node_names is not None and \
                arr.shape[-1] == len(node_names):
            # (k_epochs, steps, n) superstep trace -> (k*steps, n).
            flat = arr.reshape(-1, arr.shape[-1])
        steps = flat.shape[0]
        end = step0 + steps
        if flat.ndim >= 2 and node_names is not None and \
                flat.shape[1] == len(node_names):
            for a, node in enumerate(node_names):
                registry.observe(
                    f"{key}/{node}", float(flat[:, a].mean()), step=end
                )
        registry.observe(key, float(flat.mean()), step=end)
    return arrays
