"""Device-cost observatory: what a compiled dispatch actually costs.

PR 6's run-wide plane says *who* is slow; this module says *why*: it
reads the costs XLA already knows about every compiled program —
FLOPs and bytes accessed (``compiled.cost_analysis()``), peak HBM and
argument/output/temp/donated bytes (``compiled.memory_analysis()``) —
plus the collective inventory (the same HLO scan the graftlint audit
pins), and pairs that *static* profile with *measured* step time so
throughput claims decompose into compute vs. communication vs. idle
(the decomposition adaptive-synchronization schedules are built on:
arxiv.org/pdf/2002.01119, arxiv.org/pdf/1910.13598).

Three pieces, all host-side, none touching a compiled program:

* :class:`CostProfile` — extracted from any jitted entry point via the
  AOT ``.lower(...).compile()`` surface (``InstrumentedStep`` delegates
  both, so instrumented tp/pp steps profile without unwrapping) and
  registered process-wide by program name
  (:func:`profile_fn` / :func:`get_profile` / :func:`all_profiles`).
  Registration also lands ``cost.*`` gauges in the metrics registry, so
  profiles ride ``run_report()`` / obs deltas / ``obs-report`` with no
  new plumbing.
* :class:`SampledDispatchTimer` — the measurement side: an explicit
  ``jax.block_until_ready`` on 1-in-N dispatches at chunk boundaries
  only, **off by default** (``every_n=0``).  A sampled chunk records
  ``cost.step_time_s`` and, when the program's profile and the chip's
  peak FLOP/s are known, the ``cost.mfu`` / ``cost.bytes_per_sec``
  gauges.  Unsampled dispatches pay two integer ops on the host —
  nothing on the device, no program change (the obs on/off bit-identity
  oracle covers the timer).
* the **perf ledger** — ``PERF_LEDGER.jsonl``: every ``bench.py`` /
  ``benchmarks/`` run appends one ``{profile, measured, env-health}``
  record (:func:`ledger_append`), and ``obs-report --ledger`` renders
  the trend with healthy-best regression flagging
  (:func:`format_ledger_trend`) — the machine-readable baseline the
  BENCH_r02–r05 tunnel wedges showed the repo was missing.

MFU definition: ``achieved FLOP/s / peak FLOP/s`` where achieved is the
compiled program's XLA-counted FLOPs per dispatch times dispatches over
wall seconds, and peak comes from :func:`device_peak_flops` — a dense
bf16/fp16 per-chip table keyed on ``jax.Device.device_kind``,
overridable with ``DLT_PEAK_FLOPS`` (unknown chips and CPU return None:
no peak, no MFU, never a made-up number).

Everything importable here without jax (``obs-report --ledger`` is
jax-free); jax is imported lazily inside the extraction paths only.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "CostProfile",
    "SampledDispatchTimer",
    "profile_fn",
    "register_profile",
    "get_profile",
    "all_profiles",
    "clear_profiles",
    "device_peak_flops",
    "mfu",
    "ledger_path",
    "ledger_append",
    "read_ledger",
    "format_ledger_trend",
    "LEDGER_ENV",
    "DEFAULT_LEDGER",
    "PEAK_FLOPS_ENV",
]

#: env override for the perf-ledger path; default resolves in the cwd
#: (the driver and benchmarks both run from the repo root).
LEDGER_ENV = "DLT_PERF_LEDGER"
DEFAULT_LEDGER = "PERF_LEDGER.jsonl"

#: env override for the chip's peak dense FLOP/s (a float, e.g. 197e12).
PEAK_FLOPS_ENV = "DLT_PEAK_FLOPS"

#: Peak dense bf16 FLOP/s per chip, keyed on a lowercase substring of
#: ``jax.Device.device_kind``.  Longest match wins (``"v5 lite"`` before
#: ``"v5"``).  Sources: published TPU per-chip peaks (v2 45T, v3 123T,
#: v4 275T, v5e 197T, v5p 459T, v6e/Trillium 918T).  CPU has no entry
#: on purpose: MFU against an unknown peak is noise.
PEAK_FLOPS_TABLE: Dict[str, float] = {
    "v6e": 918e12,
    "trillium": 918e12,
    "v5p": 459e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device: Any = None) -> Optional[float]:
    """Peak dense FLOP/s of ``device`` (default: ``jax.devices()[0]``),
    or None when the chip is unknown (CPU, new hardware) — callers must
    treat None as "no MFU", never substitute a guess.  ``DLT_PEAK_FLOPS``
    overrides the table (it wins even over known chips, so a sliced or
    down-clocked part can be pinned to its real ceiling)."""
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device is None:
        import jax

        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    kind = str(getattr(device, "device_kind", "")).lower()
    best: Optional[float] = None
    best_len = -1
    for key, peak in PEAK_FLOPS_TABLE.items():
        if key in kind and len(key) > best_len:
            best, best_len = peak, len(key)
    return best


def mfu(flops: Optional[float], seconds: Optional[float],
        peak_flops: Optional[float]) -> Optional[float]:
    """Model-FLOPs-utilization: ``(flops / seconds) / peak_flops``.
    Any missing/non-positive input yields None — an MFU is either
    grounded in all three measurements or absent."""
    if not flops or not seconds or not peak_flops:
        return None
    if flops <= 0 or seconds <= 0 or peak_flops <= 0:
        return None
    return (flops / seconds) / peak_flops


# ---------------------------------------------------------------------- #
# CostProfile                                                            #
# ---------------------------------------------------------------------- #
def _first_cost_dict(cost_analysis: Any) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` returns a per-program list on some
    backends and a flat dict on others; normalize to one dict."""
    if cost_analysis is None:
        return {}
    if isinstance(cost_analysis, dict):
        return dict(cost_analysis)
    if isinstance(cost_analysis, (list, tuple)) and cost_analysis:
        first = cost_analysis[0]
        return dict(first) if isinstance(first, dict) else {}
    return {}


def _collectives_of(hlo_text: str) -> Dict[str, int]:
    """Collective-instruction inventory of compiled HLO text, reusing
    the graftlint audit's scanner so the two surfaces cannot drift.
    ``tools`` is a repo-root package; when this library runs installed
    elsewhere the inventory is simply absent (empty dict)."""
    try:
        from tools.graftlint.jaxpr_audit import collect_hlo_collectives
    except Exception:
        return {}
    return {
        op: int(n) for (op, _axes), n in
        sorted(collect_hlo_collectives(hlo_text).items())
    }


@dataclasses.dataclass
class CostProfile:
    """Static cost of ONE compiled program (one XLA dispatch).

    ``peak_bytes`` is the backend's reported peak when available, else
    the standard estimate ``argument + output + temp - alias`` (donated
    buffers alias their outputs, so donation headroom is visible as
    ``alias_bytes``).  Fields the backend does not report are None —
    absent, not zero.

    Loop caveat (load-bearing for MFU): XLA's cost analysis counts a
    ``while``/``scan`` BODY once — trip counts are not folded in — so
    ``flops`` for a scanned program is per loop body, not per dispatch.
    Callers that know the trip count (the trainer knows ``epoch_len``,
    bench knows ``steps x superstep``) pass it as ``loop_steps`` to
    :meth:`mfu` / :meth:`bytes_per_sec`; without it the derived rates
    are lower bounds.  (Pinned by
    ``tests/test_obs_cost.py::test_cost_profile_counts_loop_body_once``.)
    """

    name: str
    platform: str = ""
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)

    # -- construction --------------------------------------------------- #
    @classmethod
    def from_compiled(cls, name: str, compiled: Any,
                      *, platform: str = "") -> "CostProfile":
        """Extract a profile from a ``jax.stages.Compiled`` (the object
        ``fn.lower(*args).compile()`` returns).  Every field degrades to
        None independently: a backend that reports cost but not memory
        still yields a useful profile."""
        prof = cls(name=name, platform=platform)
        try:
            cost = _first_cost_dict(compiled.cost_analysis())
        except Exception:
            cost = {}
        if "flops" in cost:
            prof.flops = float(cost["flops"])
        if "bytes accessed" in cost:
            prof.bytes_accessed = float(cost["bytes accessed"])
        try:
            ma = compiled.memory_analysis()
        except Exception:
            ma = None
        if ma is not None:
            prof.argument_bytes = int(ma.argument_size_in_bytes)
            prof.output_bytes = int(ma.output_size_in_bytes)
            prof.temp_bytes = int(ma.temp_size_in_bytes)
            prof.alias_bytes = int(ma.alias_size_in_bytes)
            prof.generated_code_bytes = int(
                ma.generated_code_size_in_bytes
            )
            peak = getattr(ma, "peak_memory_in_bytes", None)
            prof.peak_bytes = (
                int(peak) if peak else
                prof.argument_bytes + prof.output_bytes
                + prof.temp_bytes - prof.alias_bytes
            )
        try:
            prof.collectives = _collectives_of(compiled.as_text())
        except Exception:
            prof.collectives = {}
        return prof

    # -- derived measurements ------------------------------------------- #
    def mfu(self, seconds: Optional[float],
            peak_flops: Optional[float] = None,
            *, dispatches: int = 1,
            loop_steps: int = 1) -> Optional[float]:
        """MFU of ``dispatches`` runs of this program over ``seconds``
        wall time; ``peak_flops`` defaults to :func:`device_peak_flops`
        (None on unknown chips — then MFU is None too).  ``loop_steps``
        is the caller-known scan/while trip product (see the class
        docstring: XLA counts loop bodies once); leaving it 1 makes the
        result a lower bound for looped programs."""
        if peak_flops is None:
            peak_flops = device_peak_flops()
        f = (
            None if self.flops is None
            else self.flops * dispatches * max(int(loop_steps), 1)
        )
        return mfu(f, seconds, peak_flops)

    def bytes_per_sec(self, seconds: Optional[float],
                      *, dispatches: int = 1,
                      loop_steps: int = 1) -> Optional[float]:
        """Achieved HBM traffic (XLA bytes-accessed per counted body,
        times dispatches and the caller-known loop trip product, over
        wall seconds)."""
        if not seconds or seconds <= 0 or self.bytes_accessed is None:
            return None
        return (
            self.bytes_accessed * dispatches * max(int(loop_steps), 1)
            / seconds
        )

    # -- (de)serialization ---------------------------------------------- #
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "CostProfile":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


# ---------------------------------------------------------------------- #
# Process-wide profile registry                                          #
# ---------------------------------------------------------------------- #
_PROFILES: Dict[str, CostProfile] = {}
_PROFILES_LOCK = threading.Lock()


def register_profile(profile: CostProfile, *, registry: Any = None) -> CostProfile:
    """Register ``profile`` process-wide under its program name and
    mirror its headline numbers as ``cost.*`` gauges so they ride
    ``run_report()``, obs deltas, and ``obs-report`` (``registry``
    defaults to the process-wide metrics registry; pass False to skip
    the gauges)."""
    with _PROFILES_LOCK:
        _PROFILES[profile.name] = profile
    if registry is False:
        return profile
    if registry is None:
        from distributed_learning_tpu.obs.registry import get_registry

        registry = get_registry()
    for key, value in (
        ("flops", profile.flops),
        ("bytes_accessed", profile.bytes_accessed),
        ("peak_bytes", profile.peak_bytes),
        ("alias_bytes", profile.alias_bytes),
    ):
        if value is not None:
            registry.gauge(f"cost.{key}/{profile.name}", float(value))
    if profile.collectives:
        registry.gauge(
            f"cost.collectives/{profile.name}",
            float(sum(profile.collectives.values())),
        )
    return profile


def get_profile(name: str) -> Optional[CostProfile]:
    """The registered profile for program ``name`` (None when absent)."""
    with _PROFILES_LOCK:
        return _PROFILES.get(name)


def all_profiles() -> Dict[str, CostProfile]:
    """Snapshot of every registered profile, by program name."""
    with _PROFILES_LOCK:
        return dict(_PROFILES)


def clear_profiles() -> None:
    """Drop all registered profiles (test isolation)."""
    with _PROFILES_LOCK:
        _PROFILES.clear()


def profile_fn(fn: Callable, *args: Any, name: Optional[str] = None,
               register: bool = True, registry: Any = None,
               **kwargs: Any) -> CostProfile:
    """Extract (and by default register) the :class:`CostProfile` of
    ``fn`` at these argument shapes.

    ``fn`` may be a jitted callable, an :class:`InstrumentedStep`
    (which delegates the AOT surface), a ``jax.stages.Lowered``, or a
    plain traceable callable (jitted here).  Profiling uses the AOT
    ``lower → compile`` path only — it never executes the program and
    never changes what a later call compiles (the obs on/off
    bit-identity oracle covers this).  ``name`` defaults to the
    instrumented step's span name or the function's ``__name__``."""
    import jax

    if name is None:
        name = getattr(fn, "_name", None) or getattr(
            fn, "__name__", fn.__class__.__name__
        )
    if hasattr(fn, "compile") and not hasattr(fn, "lower"):
        lowered = fn  # already a Lowered
    else:
        if not hasattr(fn, "lower"):
            fn = jax.jit(fn)
        lowered = fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    platform = jax.devices()[0].platform if jax.devices() else ""
    profile = CostProfile.from_compiled(name, compiled, platform=platform)
    if register:
        register_profile(profile, registry=registry)
    return profile


# ---------------------------------------------------------------------- #
# Sampled dispatch timer                                                 #
# ---------------------------------------------------------------------- #
class SampledDispatchTimer:
    """Measured step time on 1-in-N chunk-boundary dispatches.

    OFF by default (``every_n=0``): the constructor is free, ``tick()``
    always answers False, nothing syncs.  With ``every_n=N >= 1`` the
    caller asks ``tick()`` before each chunk dispatch; on every N-th it
    answers True and the caller closes the chunk with
    ``measure(outputs, t0)`` — ONE explicit ``jax.block_until_ready``
    at the chunk boundary (the same host boundary the metrics-carry
    flush already syncs at; never inside a compiled program, never per
    step).  Each sample records the ``cost.step_time_s[/name]`` series
    and — when ``profile`` (or a registered profile under ``name``) and
    the chip peak are known — the ``cost.mfu[/name]`` and
    ``cost.bytes_per_sec[/name]`` gauges.

    Sync accounting is explicit: ``samples`` / ``skipped`` count every
    decision, mirrored as ``cost.timer.samples`` / ``cost.timer.skipped``
    counters so a report shows exactly how many extra syncs the timer
    added (the declared 1-in-N, and nothing else)."""

    def __init__(self, every_n: int = 0, *, name: str = "",
                 registry: Any = None,
                 peak_flops: Optional[float] = None):
        self.every_n = max(int(every_n), 0)
        self.name = name
        self._registry = registry
        self._peak_flops = peak_flops
        self._count = 0
        self.samples = 0
        self.skipped = 0
        self.last_step_time_s: Optional[float] = None
        self.last_mfu: Optional[float] = None
        self.last_bytes_per_sec: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.every_n > 0

    def _suffix(self, name: Optional[str]) -> str:
        n = name or self.name
        return f"/{n}" if n else ""

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from distributed_learning_tpu.obs.registry import get_registry

        return get_registry()

    def tick(self) -> bool:
        """Should THIS dispatch be sampled?  Two host integer ops when
        disabled or off-sample; increments the sync accounting either
        way."""
        if not self.enabled:
            return False
        sample = self._count % self.every_n == 0
        self._count += 1
        if sample:
            self.samples += 1
            self._reg().inc("cost.timer.samples")
        else:
            self.skipped += 1
            self._reg().inc("cost.timer.skipped")
        return sample

    def measure(self, outputs: Any, t0: float, *,
                name: Optional[str] = None,
                profile: Optional[CostProfile] = None,
                loop_steps: int = 1,
                step: Optional[int] = None) -> float:
        """Close a sampled chunk: drain ``outputs`` with ONE
        ``jax.block_until_ready``, record the elapsed wall time since
        ``t0`` (a ``time.perf_counter()`` stamp taken just before the
        dispatch), derive MFU / bytes-per-sec when the program's profile
        is known (``loop_steps`` = the caller-known scan trip product;
        see :class:`CostProfile`'s loop caveat), and return the chunk
        wall time in seconds."""
        import jax

        # The declared 1-in-N chunk-boundary sync — the ONLY sync this
        # timer ever adds, at a boundary the carry flush already pays.
        jax.block_until_ready(outputs)
        dt = time.perf_counter() - t0
        reg = self._reg()
        suffix = self._suffix(name)
        reg.observe(f"cost.step_time_s{suffix}", dt, step=step)
        self.last_step_time_s = dt
        prof = profile or get_profile(name or self.name)
        peak = self._peak_flops
        if peak is None:
            peak = device_peak_flops()
        self.last_mfu = (
            None if prof is None
            else prof.mfu(dt, peak, loop_steps=loop_steps)
        )
        self.last_bytes_per_sec = (
            None if prof is None
            else prof.bytes_per_sec(dt, loop_steps=loop_steps)
        )
        if self.last_mfu is not None:
            reg.gauge(f"cost.mfu{suffix}", self.last_mfu)
        if self.last_bytes_per_sec is not None:
            reg.gauge(
                f"cost.bytes_per_sec{suffix}", self.last_bytes_per_sec
            )
        return dt


# ---------------------------------------------------------------------- #
# Perf ledger                                                            #
# ---------------------------------------------------------------------- #
def ledger_path(path: Optional[str] = None) -> str:
    """Resolve the ledger path: explicit arg > $DLT_PERF_LEDGER >
    ``PERF_LEDGER.jsonl`` in the cwd (driver and benchmarks run from
    the repo root)."""
    return path or os.environ.get(LEDGER_ENV) or DEFAULT_LEDGER


def ledger_append(record: dict, path: Optional[str] = None) -> bool:
    """Append one perf record as a JSONL line; best-effort (a full disk
    or read-only checkout must never fail the measurement that produced
    the record).  Returns whether the line landed."""
    record = dict(record)
    record.setdefault("ts", time.time())
    record.setdefault("kind", "perf")
    try:
        with open(ledger_path(path), "a", encoding="utf-8") as fh:
            fh.write(json.dumps(record, sort_keys=True) + "\n")
        return True
    except OSError:
        return False


def read_ledger(path: Optional[str] = None) -> List[dict]:
    """Parse the ledger, skipping blank/torn lines (a run may be
    appending while a report reads), ordered as appended."""
    out: List[dict] = []
    with open(ledger_path(path), "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


#: A record regresses when its value drops below this fraction of the
#: best healthy value previously recorded for the same metric (the
#: ``obs-report --bench`` convention, shared on purpose).
LEDGER_REGRESSION_FRACTION = 0.9


def _rec_healthy(rec: dict) -> bool:
    env = rec.get("env") or {}
    return not (
        rec.get("provisional")
        or rec.get("tunnel_wedged")
        or env.get("tunnel_wedged")
    )


def _fmt_opt(value: Any, fmt: str, width: int) -> str:
    if value is None:
        return f"{'—':>{width}}"
    return f"{value:{fmt}}"


def format_ledger_trend(
    records: Sequence[dict],
    *, regression_fraction: float = LEDGER_REGRESSION_FRACTION,
) -> str:
    """The perf-ledger trend: one row per record in append order —
    wall date, metric, value, MFU, per-dispatch GFLOPs and peak-HBM GiB
    from the attached profile — with healthy-best regression flagging
    per metric.  Provisional and tunnel-wedged records are labeled and
    excluded from the baseline (they measure a different
    configuration), exactly like the ``--bench`` trajectory."""
    lines = [
        f"perf ledger — {len(records)} records",
        f"  {'when':16} {'metric':44} {'value':>10} {'unit':>12} "
        f"{'mfu%':>6} {'gflops':>9} {'peak GiB':>9}  status",
    ]
    best: Dict[str, float] = {}
    best_when: Dict[str, str] = {}
    for rec in records:
        ts = rec.get("ts")
        when = (
            time.strftime("%Y-%m-%d %H:%M", time.gmtime(ts))
            if isinstance(ts, (int, float)) else "—"
        )
        metric = str(rec.get("metric", "?"))
        value = rec.get("value")
        cost = rec.get("cost") or {}
        m = cost.get("mfu")
        flops = cost.get("flops")
        peak = cost.get("peak_bytes") or cost.get("peak_hbm_bytes")
        healthy = _rec_healthy(rec)
        status = "ok"
        if rec.get("tunnel_wedged") or (rec.get("env") or {}).get(
            "tunnel_wedged"
        ):
            status = "cpu-sanity (tunnel wedged)"
        elif rec.get("provisional"):
            status = "provisional"
        elif (
            isinstance(value, (int, float))
            and metric in best
            and value < regression_fraction * best[metric]
        ):
            status = (
                f"REGRESSION -{(1 - value / best[metric]) * 100:.0f}% "
                f"vs {best_when[metric]}"
            )
        lines.append(
            f"  {when:16} {metric[:44]:44} "
            f"{_fmt_opt(value, '10.2f', 10)} "
            f"{str(rec.get('unit', '—'))[:12]:>12} "
            f"{_fmt_opt(None if m is None else m * 100, '6.2f', 6)} "
            f"{_fmt_opt(None if flops is None else flops / 1e9, '9.2f', 9)} "
            f"{_fmt_opt(None if peak is None else peak / 2**30, '9.3f', 9)}"
            f"  {status}"
        )
        if healthy and isinstance(value, (int, float)):
            if metric not in best or value > best[metric]:
                best[metric] = float(value)
                best_when[metric] = when
    for metric in sorted(best):
        lines.append(
            f"  best healthy {metric}: {best[metric]:.2f} "
            f"({best_when[metric]})"
        )
    if not best:
        lines.append("  no healthy record yet")
    return "\n".join(lines)
