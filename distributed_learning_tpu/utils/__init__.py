"""Utilities: telemetry hooks, logging."""

from distributed_learning_tpu.utils.profiling import (
    DebugLogger,
    annotate,
    enable_debug_logging,
    trace,
)
from distributed_learning_tpu.utils.telemetry import (
    CallbackTelemetry,
    RecordingTelemetry,
    TelemetryProcessor,
)

__all__ = [
    "CallbackTelemetry",
    "RecordingTelemetry",
    "TelemetryProcessor",
    "DebugLogger",
    "annotate",
    "enable_debug_logging",
    "trace",
]
