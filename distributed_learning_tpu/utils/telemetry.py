"""Telemetry hook (parity: ``utils/consensus_tcp/telemetry_processor.py``).

The reference's TCP backend lets agents push opaque payloads to the master,
which forwards them to a user-supplied ``TelemetryProcessor.process(token,
payload)`` (``master.py:192-199``, ``agent.py:214-218``).  In the SPMD design
there is no master process; the trainer invokes the processor host-side after
each jitted chunk with per-agent metric payloads.  The abstract interface is
kept identical so user subclasses port over unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Hashable, List, Tuple

__all__ = ["TelemetryProcessor", "RecordingTelemetry", "CallbackTelemetry"]


class TelemetryProcessor:
    """Abstract telemetry sink: override :meth:`process`."""

    def process(self, token: Hashable, payload: Any) -> None:
        raise NotImplementedError


class RecordingTelemetry(TelemetryProcessor):
    """Appends every (token, payload) pair — handy default and test double."""

    def __init__(self) -> None:
        self.records: List[Tuple[Hashable, Any]] = []

    def process(self, token: Hashable, payload: Any) -> None:
        self.records.append((token, payload))

    def by_token(self) -> Dict[Hashable, List[Any]]:
        out: Dict[Hashable, List[Any]] = {}
        for tok, payload in self.records:
            out.setdefault(tok, []).append(payload)
        return out


class CallbackTelemetry(TelemetryProcessor):
    """Adapts a plain function ``f(token, payload)``."""

    def __init__(self, fn: Callable[[Hashable, Any], None]) -> None:
        self._fn = fn

    def process(self, token: Hashable, payload: Any) -> None:
        self._fn(token, payload)
