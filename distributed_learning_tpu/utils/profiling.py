"""Tracing & debug instrumentation.

The reference's tracing is ad-hoc ``_debug(...)`` printers gated by a
``debug`` flag (``consensus_asyncio.py:52-57``, ``master.py:63-68``,
``agent.py:46-51``) plus notebook ``%time`` cells.  TPU-native
equivalents:

* :func:`trace` — a context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of device execution;
* :func:`annotate` — named ``TraceAnnotation`` spans that show up inside
  the profile;
* :class:`DebugLogger` — the reference's debug-flag pattern as a small
  structured logger with per-round residual reporting
  (``log_residual(round, residual)``), usable anywhere the reference
  passed its ``logger``/``debug`` args.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Iterator, Optional

__all__ = [
    "trace",
    "annotate",
    "maybe_trace",
    "DebugLogger",
    "enable_debug_logging",
    "summarize_trace",
    "format_trace_summary",
]


def enable_debug_logging(name: str = "dlt") -> logging.Logger:
    """Make the framework's named loggers (``dlt.comm.agent.<token>``,
    ``dlt.comm.master``, ...) visible: set the ``dlt`` root to DEBUG and
    attach a stderr handler if none is configured.

    The comm layer's legacy ``debug=True`` flags call this, so the old
    print-style debugging experience survives the move to ``logging``;
    applications that configure logging themselves never need it.
    """
    logger = logging.getLogger(name)
    logger.setLevel(logging.DEBUG)
    if not logger.handlers and not logging.getLogger().handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(name)s %(levelname).1s %(message)s")
        )
        logger.addHandler(handler)
    return logger


@contextlib.contextmanager
def trace(log_dir: str, *, host_profile: bool = True) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block.

    View with TensorBoard (``tensorboard --logdir <log_dir>``) or
    ``xprof``.  Host-side Python activity is included unless
    ``host_profile=False``.
    """
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def maybe_trace(log_dir: Optional[str]):
    """:func:`trace` when ``log_dir`` is set, a no-op otherwise — the
    programmatic capture hook measurement loops wrap their measure
    phase in unconditionally (``bench.py`` honors ``BENCH_TRACE_DIR``
    through this, ``benchmarks/profile_wrn.py`` passes ``--trace``'s
    dir), so "profile this run" is an environment decision, not a code
    path."""
    if not log_dir:
        return contextlib.nullcontext()
    return trace(log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span inside an active profiler trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class DebugLogger:
    """Structured replacement for the reference's injected logger +
    ``debug`` flag; quacks like ``logging.Logger`` for ``Mixer(logger=)``.
    """

    def __init__(self, name: str = "dlt", *, enabled: bool = True,
                 logger: Optional[logging.Logger] = None):
        self.enabled = enabled
        self._log = logger or logging.getLogger(name)
        self._t0 = time.perf_counter()
        self.residuals: list = []

    def debug(self, msg, *args):
        if self.enabled:
            self._log.debug("[%7.3fs] %s", time.perf_counter() - self._t0,
                            msg % args if args else msg)

    info = debug

    def log_residual(self, round_idx: int, residual: float) -> None:
        """Record + report a per-round consensus residual (the metric the
        reference's Mixer debug lines printed, ``mixer.py:37,54``)."""
        self.residuals.append((round_idx, float(residual)))
        self.debug(f"round {round_idx}: residual {residual:.3e}")


def _as_percent(row: dict):
    """Self-time share in percent regardless of source tool:
    ``framework_op_stats`` reports 0-100 percents, ``hlo_stats`` reports
    0-1 fractions.  Explicit None checks — a legitimate 0.0 must not
    fall through to the other column."""
    pct = row.get("device_total_self_time_percent")
    if pct is not None:
        return pct
    frac = row.get("total_self_time_as_fraction")
    if frac is not None:
        return frac * 100.0
    return None


def summarize_trace(
    log_dir: str, *, top: int = 15, tool: str = "framework_op_stats"
) -> list:
    """Digest a ``jax.profiler`` trace into the top-``top`` ops by
    self-time — the "where did the step go" table, without TensorBoard.

    Parses the ``.xplane.pb`` files under ``log_dir`` with xprof's
    converter (the TensorBoard profile plugin's own backend).  Returns a
    list of dicts sorted by total self-time, each with ``operation``,
    ``type``, ``occurrences``, ``total_self_us``, ``avg_self_us``, and
    (on device rows) ``device_self_pct``.  Raises ``FileNotFoundError``
    when the dir holds no xplanes and ``ImportError`` when xprof isn't
    installed — callers decide whether that is fatal.
    """
    import glob
    import json as _json

    paths = sorted(
        glob.glob(f"{log_dir}/**/*.xplane.pb", recursive=True)
    )
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {log_dir}")
    from xprof.convert import raw_to_tool_data as _rtd  # tensorboard plugin

    # No output-format option: the converter returns gviz-DataTable JSON,
    # which is exactly what the parser below consumes.
    data, _ = _rtd.xspace_to_tool_data(paths, tool, {})
    if isinstance(data, bytes):
        data = data.decode()
    table = _json.loads(data)
    # DataTable-style payload: a list of {"cols": [...], "rows": [...]}
    # blocks (framework_op_stats emits device and host tables separately).
    blocks = table if isinstance(table, list) else [table]
    out = []
    for block in blocks:
        if not isinstance(block, dict) or "cols" not in block:
            continue
        cols = [c["id"] for c in block["cols"]]
        for r in block.get("rows") or block.get("data") or []:
            cells = r.get("c") if isinstance(r, dict) else r
            row = dict(zip(cols, [
                c.get("v") if isinstance(c, dict) else c for c in cells
            ]))
            # Column ids differ per tool (framework_op_stats vs
            # hlo_stats); coalesce the common concepts.  Numeric fields
            # use first-non-None (not `or`): a legitimate 0.0 must not
            # fall through to the other tool's absent column.
            first = lambda *keys: next(
                (row[k] for k in keys if row.get(k) is not None), None
            )
            out.append({
                "operation": first(
                    "operation", "hlo_op_name", "hlo_op_expression"
                ),
                "type": first("type", "category"),
                "host_or_device": row.get("host_or_device"),
                "occurrences": row.get("occurrences"),
                "total_self_us": first(
                    "total_self_time", "total_self_time_us"
                ),
                "avg_self_us": first("avg_self_time", "avg_self_time_us"),
                "device_self_pct": _as_percent(row),
            })
    out.sort(key=lambda d: -(d["total_self_us"] or 0.0))
    return out[:top]


def format_trace_summary(rows: list) -> str:
    """Readable table for :func:`summarize_trace` output."""
    lines = [
        f"{'self us':>12} {'avg us':>10} {'n':>6} {'where':>6}  operation"
    ]
    for r in rows:
        lines.append(
            f"{(r['total_self_us'] or 0):12.1f} {(r['avg_self_us'] or 0):10.2f} "
            f"{int(r['occurrences'] or 0):6d} {(r['host_or_device'] or '?'):>6}  "
            f"{(r['type'] or '')}: {str(r['operation'] or '')[:70]}"
        )
    return "\n".join(lines)
