"""Tracing & debug instrumentation.

The reference's tracing is ad-hoc ``_debug(...)`` printers gated by a
``debug`` flag (``consensus_asyncio.py:52-57``, ``master.py:63-68``,
``agent.py:46-51``) plus notebook ``%time`` cells.  TPU-native
equivalents:

* :func:`trace` — a context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of device execution;
* :func:`annotate` — named ``TraceAnnotation`` spans that show up inside
  the profile;
* :class:`DebugLogger` — the reference's debug-flag pattern as a small
  structured logger with per-round residual reporting
  (``log_residual(round, residual)``), usable anywhere the reference
  passed its ``logger``/``debug`` args.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Iterator, Optional

__all__ = ["trace", "annotate", "DebugLogger"]


@contextlib.contextmanager
def trace(log_dir: str, *, host_profile: bool = True) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block.

    View with TensorBoard (``tensorboard --logdir <log_dir>``) or
    ``xprof``.  Host-side Python activity is included unless
    ``host_profile=False``.
    """
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span inside an active profiler trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class DebugLogger:
    """Structured replacement for the reference's injected logger +
    ``debug`` flag; quacks like ``logging.Logger`` for ``Mixer(logger=)``.
    """

    def __init__(self, name: str = "dlt", *, enabled: bool = True,
                 logger: Optional[logging.Logger] = None):
        self.enabled = enabled
        self._log = logger or logging.getLogger(name)
        self._t0 = time.perf_counter()
        self.residuals: list = []

    def debug(self, msg, *args):
        if self.enabled:
            self._log.debug("[%7.3fs] %s", time.perf_counter() - self._t0,
                            msg % args if args else msg)

    info = debug

    def log_residual(self, round_idx: int, residual: float) -> None:
        """Record + report a per-round consensus residual (the metric the
        reference's Mixer debug lines printed, ``mixer.py:37,54``)."""
        self.residuals.append((round_idx, float(residual)))
        self.debug(f"round {round_idx}: residual {residual:.3e}")
