// Native tensor wire codec for the TCP comm backend.
//
// The reference's transport pickles raw float64 numpy arrays over TCP
// (utils/consensus_tcp/pickled_socket.py:11-23) — unsafe (pickle) and 4-8x
// larger on the wire than needed for gossip values.  This codec provides
// the two hot operations of the replacement binary protocol:
//
//   * float32 <-> bfloat16 conversion (round-to-nearest-even, the TPU
//     wire/storage format) — halves gossip bandwidth with the same
//     exponent range as f32;
//   * crc32 (reflected polynomial 0xEDB88320) integrity checksums for
//     frames, so a torn TCP stream is detected instead of deserialized;
//   * symmetric int8 quantization (scale = max|x|/127, round-to-nearest
//     ties-to-even, matching np.rint) — quarter-size gossip payloads
//     whose quantization error CHOCO's error feedback absorbs.
//
// Exposed with C linkage for ctypes; built by native/__init__.py with g++
// -O3 at first use and cached next to this file.

#include <cstddef>
#include <cstdint>
#include <cstring>

#include "dlt_abi.h"

extern "C" {

// Checked by native/__init__.py right after dlopen: a cached .so built
// from an older source (missing symbols or changed signatures) must
// trigger a rebuild, never an AttributeError at first use.
uint32_t dlt_abi_version() { return DLT_ABI_VERSION; }

// f32 -> bf16 with round-to-nearest-even (ties to even), matching the
// hardware semantics XLA uses when it narrows f32 to bf16.
void dlt_f32_to_bf16(const float* src, uint16_t* dst, size_t n) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(src);
  for (size_t i = 0; i < n; ++i) {
    uint32_t x = bits[i];
    // NaN must stay NaN: round-up could flow a signalling NaN mantissa to
    // zero (infinity); force a quiet-NaN payload instead.
    if ((x & 0x7fffffffu) > 0x7f800000u) {
      dst[i] = static_cast<uint16_t>((x >> 16) | 0x0040u);
      continue;
    }
    uint32_t lsb = (x >> 16) & 1u;
    uint32_t rounded = x + 0x7fffu + lsb;
    dst[i] = static_cast<uint16_t>(rounded >> 16);
  }
}

void dlt_bf16_to_f32(const uint16_t* src, float* dst, size_t n) {
  uint32_t* out = reinterpret_cast<uint32_t*>(dst);
  for (size_t i = 0; i < n; ++i) {
    out[i] = static_cast<uint32_t>(src[i]) << 16;
  }
}

// f32 -> int8 with a caller-supplied inverse scale:
// q = clamp(rint(x/scale), -127, 127).  nearbyintf under the default
// FE_TONEAREST mode rounds ties to even — bit-identical to the Python
// fallback's np.rint.
void dlt_f32_to_i8(const float* src, int8_t* dst, size_t n, float inv_scale) {
  for (size_t i = 0; i < n; ++i) {
    float v = src[i] * inv_scale;
    // Match np.rint (ties to even): use __builtin_nearbyint under the
    // default FE_TONEAREST mode.
    float r = __builtin_nearbyintf(v);
    if (r > 127.0f) r = 127.0f;
    if (r < -127.0f) r = -127.0f;
    dst[i] = static_cast<int8_t>(r);
  }
}

void dlt_i8_to_f32(const int8_t* src, float* dst, size_t n, float scale) {
  for (size_t i = 0; i < n; ++i) {
    dst[i] = static_cast<float>(src[i]) * scale;
  }
}

static uint32_t kCrcTable[8][256];
static bool kCrcInit = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1u) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    }
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    for (int t = 1; t < 8; ++t) {
      kCrcTable[t][i] =
          (kCrcTable[t - 1][i] >> 8) ^ kCrcTable[0][kCrcTable[t - 1][i] & 0xFFu];
    }
  }
  kCrcInit = true;
}

// Same polynomial/reflection as zlib.crc32, so the Python fallback and the
// native path produce identical checksums.  Slicing-by-8 (ISSUE 9): the
// old byte-at-a-time loop bottlenecked framing.py's per-frame checksum
// behind one serial table lookup per byte; eight parallel tables process
// 8 bytes per iteration at ~4-5x the throughput.
uint32_t dlt_crc32(const uint8_t* data, size_t n, uint32_t seed) {
  if (!kCrcInit) crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, data, 4);
    std::memcpy(&hi, data + 4, 4);
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ != __ORDER_LITTLE_ENDIAN__
    lo = __builtin_bswap32(lo);
    hi = __builtin_bswap32(hi);
#endif
    lo ^= c;
    c = kCrcTable[7][lo & 0xFFu] ^ kCrcTable[6][(lo >> 8) & 0xFFu] ^
        kCrcTable[5][(lo >> 16) & 0xFFu] ^ kCrcTable[4][lo >> 24] ^
        kCrcTable[3][hi & 0xFFu] ^ kCrcTable[2][(hi >> 8) & 0xFFu] ^
        kCrcTable[1][(hi >> 16) & 0xFFu] ^ kCrcTable[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  for (size_t i = 0; i < n; ++i) {
    c = kCrcTable[0][(c ^ data[i]) & 0xFFu] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

}  // extern "C"
