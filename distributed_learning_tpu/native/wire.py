"""ctypes wrapper for the native wire engine (``wire.cpp``).

The frame FORMAT is owned by :mod:`distributed_learning_tpu.comm.tensor_codec`
— its pure-Python implementation stays the byte-for-byte authoritative
oracle and the ``DLT_NO_NATIVE=1`` fallback.  This module only makes the
native whole-frame paths callable:

* :func:`encode_fused` / :func:`decode_fused` — fused sparse frames in
  one native call each (u32 gather/scatter fused with the bf16/int8 wire
  conversion, slicing-by-8 crc32 over the assembled frame);
* :func:`encode_dense` / :func:`decode_dense` — dense tensor frames for
  the f32-sourced wire modes.

Status discipline: corrupt frames surface as
:class:`~distributed_learning_tpu.comm.tensor_codec.CodecError` (raised
by the caller from :data:`ERR_*`), and :data:`ERR_UNSUPPORTED` means "a
valid frame this engine does not speak — decode it with the Python
oracle instead" (never an error to the peer).

Availability is decided per call: ``available()`` is False whenever the
library cannot build/load *or* ``DLT_NO_NATIVE=1`` is set in the
environment at call time, so tests (and operators) can force the
fallback without restarting the process.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from distributed_learning_tpu.native import _HERE, _cache_override, _load_lib

__all__ = [
    "available",
    "encode_fused",
    "decode_fused",
    "decode_apply",
    "validate_fused",
    "encode_dense",
    "decode_dense",
    "crc32",
    "MODE_F32",
    "MODE_BF16",
    "MODE_I8",
    "ERR_UNSUPPORTED",
    "ERR_NONFINITE",
]

_SRC = os.path.join(_HERE, "wire.cpp")
_LIB = os.path.join(_HERE, "_wire.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

#: Per-bucket / dense wire modes (wire.cpp kMode*).
MODE_F32, MODE_BF16, MODE_I8 = 0, 1, 2

#: Status codes (wire.cpp kErr*).
ERR_TRUNC = -1
ERR_MAGIC = -2
ERR_VERSION = -3
ERR_CRC = -4
ERR_BOUNDS = -5
ERR_RANGE = -6
ERR_TOTAL = -7
ERR_UNSUPPORTED = -8
ERR_NONFINITE = -9
ERR_INTERNAL = -10

#: Corrupt-frame statuses -> the message the caller raises (parity with
#: the Python oracle's wording so tests can match either path).
CORRUPT_MESSAGES = {
    ERR_TRUNC: "fused sparse frame truncated",
    ERR_MAGIC: "not a fused sparse frame",
    ERR_VERSION: "unsupported fused sparse frame version",
    ERR_CRC: "fused sparse frame checksum mismatch",
    ERR_BOUNDS: "fused sparse frame section out of bounds",
    ERR_RANGE: "fused sparse index out of range",
    ERR_TOTAL: "fused sparse frame total mismatch",
    ERR_INTERNAL: "native wire engine internal error",
}


def _configure(lib: ctypes.CDLL) -> None:
    u64p = ctypes.c_void_p
    lib.dlt_wire_crc32.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
    ]
    lib.dlt_wire_crc32.restype = ctypes.c_uint32
    lib.dlt_wire_fused_size.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u64p, u64p, u64p,
        ctypes.c_void_p, ctypes.c_uint32, u64p, ctypes.c_void_p,
    ]
    lib.dlt_wire_fused_size.restype = ctypes.c_longlong
    lib.dlt_wire_fused_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u64p, u64p, u64p,
        ctypes.c_void_p, ctypes.c_uint32, u64p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.dlt_wire_fused_encode.restype = ctypes.c_longlong
    lib.dlt_wire_fused_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.dlt_wire_fused_decode.restype = ctypes.c_longlong
    lib.dlt_wire_fused_apply.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_float,
    ]
    lib.dlt_wire_fused_apply.restype = ctypes.c_longlong
    lib.dlt_wire_fused_validate.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.dlt_wire_fused_validate.restype = ctypes.c_longlong
    lib.dlt_wire_dense_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.dlt_wire_dense_encode.restype = ctypes.c_longlong
    lib.dlt_wire_dense_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.dlt_wire_dense_decode.restype = ctypes.c_longlong


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DLT_NO_NATIVE") == "1":
            return None
        # DLT_NATIVE_CACHE_DIR reroutes the built .so (the sanitized-
        # build hook for graftlint --native): instrumented builds live
        # in their own cache, never clobbering the production _wire.so.
        _lib = _load_lib(_SRC, _cache_override(_LIB), _configure)
        return _lib


def available() -> bool:
    """True iff the native engine is loadable AND not disabled by
    ``DLT_NO_NATIVE=1`` right now (checked per call, not cached, so the
    fallback can be forced mid-process)."""
    if os.environ.get("DLT_NO_NATIVE") == "1":
        return False
    return _load() is not None


def crc32(data: bytes, seed: int = 0) -> int:
    """Slicing-by-8 crc32 (zlib-compatible); requires :func:`available`."""
    lib = _load()
    return int(lib.dlt_wire_crc32(data, len(data), ctypes.c_uint32(seed)))


def _span_arrays(
    buckets: Sequence[Tuple[int, Sequence[Tuple[int, int]]]],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(span_off, span_size, bucket_ptr, modes) CSR arrays for the C ABI.

    ``buckets`` is ``((mode, ((off, size), ...)), ...)`` — dtype names
    already resolved to wire modes by the caller.
    """
    modes = np.asarray([m for m, _ in buckets], dtype=np.uint8)
    ptr = np.zeros(len(buckets) + 1, dtype=np.uint64)
    offs, sizes = [], []
    for b, (_mode, spans) in enumerate(buckets):
        for off, size in spans:
            offs.append(off)
            sizes.append(size)
        ptr[b + 1] = len(offs)
    span_off = np.asarray(offs, dtype=np.uint64)
    span_size = np.asarray(sizes, dtype=np.uint64)
    return span_off, span_size, ptr, modes


def encode_fused(
    flat: np.ndarray,
    buckets: Sequence[Tuple[int, Sequence[Tuple[int, int]]]],
) -> Optional[bytes]:
    """Encode one fused sparse frame from the f32 ravel in two native
    passes (measure, then gather+convert+crc into an exact-size buffer).

    Returns the frame bytes, ``None`` when the engine is unavailable, or
    raises ``ValueError`` for the int8-over-nonfinite-values contract
    (the caller re-raises as its own error type).
    """
    lib = _load()
    if lib is None:
        return None
    flat = np.ascontiguousarray(flat, dtype=np.float32)
    span_off, span_size, ptr, modes = _span_arrays(buckets)
    ks = np.zeros(len(buckets), dtype=np.uint64)
    maxabs = np.zeros(len(buckets), dtype=np.float32)
    size = int(lib.dlt_wire_fused_size(
        flat.ctypes.data, ctypes.c_uint64(flat.size),
        span_off.ctypes.data, span_size.ctypes.data, ptr.ctypes.data,
        modes.ctypes.data, ctypes.c_uint32(len(buckets)),
        ks.ctypes.data, maxabs.ctypes.data,
    ))
    if size == ERR_NONFINITE:
        raise ValueError(
            "int8 wire requires finite values; refusing to quantize a "
            "poisoned tensor"
        )
    if size < 0:  # pragma: no cover - defensive
        raise ValueError(CORRUPT_MESSAGES.get(size, f"wire status {size}"))
    out = np.empty(size, dtype=np.uint8)
    n = int(lib.dlt_wire_fused_encode(
        flat.ctypes.data, ctypes.c_uint64(flat.size),
        span_off.ctypes.data, span_size.ctypes.data, ptr.ctypes.data,
        modes.ctypes.data, ctypes.c_uint32(len(buckets)),
        ks.ctypes.data, maxabs.ctypes.data,
        out.ctypes.data, ctypes.c_uint64(size),
    ))
    if n != size:  # pragma: no cover - defensive
        raise ValueError(CORRUPT_MESSAGES[ERR_INTERNAL])
    return out.tobytes()


def decode_fused(buf: bytes, out: np.ndarray) -> int:
    """Decode one fused sparse frame into the caller's f32 ravel.

    The ravel's prior contents are ignored — the native side zero-fills
    it between validation and scatter, so reused (dirty) scratch
    buffers are safe.  Returns 0 on success or :data:`ERR_UNSUPPORTED`
    (caller falls back to the Python oracle); corrupt frames return
    their negative status (caller raises ``CodecError`` with
    :data:`CORRUPT_MESSAGES`).  The native side verifies the crc and
    bounds-checks every section header BEFORE the first write.
    """
    lib = _load()
    assert lib is not None, "decode_fused requires available()"
    return int(lib.dlt_wire_fused_decode(
        buf, ctypes.c_uint64(len(buf)),
        out.ctypes.data, ctypes.c_uint64(out.size),
    ))


def decode_apply(buf: bytes, target: np.ndarray, scale: float = 1.0) -> int:
    """Scatter-ADD one fused sparse frame into a live f32 ravel
    (``target[idx] += scale * vals``), no dense intermediate.

    Same status discipline and validate-before-first-write guarantee as
    :func:`decode_fused`; untouched positions of ``target`` keep their
    exact bytes.  For the duplicate-free frames the encoder produces,
    the result is ulp-identical to decode-then-``target += scale *
    dense``.
    """
    lib = _load()
    assert lib is not None, "decode_apply requires available()"
    return int(lib.dlt_wire_fused_apply(
        buf, ctypes.c_uint64(len(buf)),
        target.ctypes.data, ctypes.c_uint64(target.size),
        ctypes.c_float(scale),
    ))


def validate_fused(buf: bytes, total: int) -> int:
    """Run the full decode-side validation walk (crc + section geometry
    + dtype support + index range) with no output buffer — the
    lazy-payload path's unpack-time corruption check.  Same status
    discipline as :func:`decode_fused`."""
    lib = _load()
    assert lib is not None, "validate_fused requires available()"
    return int(lib.dlt_wire_fused_validate(
        buf, ctypes.c_uint64(len(buf)), ctypes.c_uint64(total),
    ))


def encode_dense(x: np.ndarray, mode: int) -> Optional[bytes]:
    """Whole-frame dense encode of a C-contiguous f32 array under a wire
    mode; ``None`` when unavailable, ``ValueError`` on int8-nonfinite."""
    lib = _load()
    if lib is None:
        return None
    dims = np.asarray(x.shape, dtype=np.uint32)
    hdr = 4 + 4 * x.ndim
    payload = {MODE_F32: 4 * x.size, MODE_BF16: 2 * x.size,
               MODE_I8: 4 + x.size}[mode]
    out = np.empty(hdr + payload, dtype=np.uint8)
    n = int(lib.dlt_wire_dense_encode(
        x.ctypes.data, ctypes.c_uint64(x.size),
        dims.ctypes.data, ctypes.c_uint32(x.ndim), ctypes.c_uint32(mode),
        out.ctypes.data, ctypes.c_uint64(out.size),
    ))
    if n == ERR_NONFINITE:
        raise ValueError(
            "int8 wire requires finite values; refusing to quantize a "
            "poisoned tensor"
        )
    if n != out.size:  # pragma: no cover - defensive
        raise ValueError(CORRUPT_MESSAGES[ERR_INTERNAL])
    return out.tobytes()


def decode_dense(buf: bytes, out: np.ndarray) -> int:
    """Whole-frame dense decode into the caller's f32 buffer (sized from
    the pre-parsed header).  0, ERR_UNSUPPORTED, or a corrupt status."""
    lib = _load()
    assert lib is not None, "decode_dense requires available()"
    return int(lib.dlt_wire_dense_decode(
        buf, ctypes.c_uint64(len(buf)),
        out.ctypes.data, ctypes.c_uint64(out.size),
    ))
