"""Native (C++) components, loaded via ctypes with pure-Python fallbacks.

``codec.cpp`` holds the wire-codec hot path (f32<->bf16 conversion, crc32).
The shared library is compiled with g++ on first use and cached beside the
source; environments without a toolchain fall back to numpy/ml_dtypes/zlib
implementations with identical semantics (the tests assert bit-equality).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import Optional

import numpy as np

__all__ = [
    "native_available",
    "f32_to_bf16",
    "bf16_to_f32",
    "f32_to_i8",
    "i8_to_f32",
    "crc32",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "codec.cpp")
_LIB = os.path.join(_HERE, "_codec.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    # Per-process temp name: concurrent first-use builds (multi-process
    # deployments) must not interleave g++ output on a shared path; the
    # final os.replace is atomic either way.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB)
        return _LIB
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DLT_NO_NATIVE") == "1":
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.dlt_f32_to_bf16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dlt_f32_to_bf16.restype = None
        lib.dlt_bf16_to_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dlt_bf16_to_f32.restype = None
        lib.dlt_f32_to_i8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_float,
        ]
        lib.dlt_f32_to_i8.restype = None
        lib.dlt_i8_to_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_float,
        ]
        lib.dlt_i8_to_f32.restype = None
        lib.dlt_crc32.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32,
        ]
        lib.dlt_crc32.restype = ctypes.c_uint32
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def f32_to_bf16(x: np.ndarray) -> np.ndarray:
    """float32 array -> uint16 array of bfloat16 bit patterns (RNE)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    out = np.empty(x.shape, dtype=np.uint16)
    lib = _load()
    if lib is not None and x.size:
        lib.dlt_f32_to_bf16(
            x.ctypes.data, out.ctypes.data, ctypes.c_size_t(x.size)
        )
        return out
    import ml_dtypes  # bundled with jax

    return x.astype(ml_dtypes.bfloat16).view(np.uint16)


def bf16_to_f32(bits: np.ndarray) -> np.ndarray:
    """uint16 bfloat16 bit patterns -> float32 array."""
    bits = np.ascontiguousarray(bits, dtype=np.uint16)
    out = np.empty(bits.shape, dtype=np.float32)
    lib = _load()
    if lib is not None and bits.size:
        lib.dlt_bf16_to_f32(
            bits.ctypes.data, out.ctypes.data, ctypes.c_size_t(bits.size)
        )
        return out
    import ml_dtypes

    return bits.view(ml_dtypes.bfloat16).astype(np.float32)


def f32_to_i8(x: np.ndarray, scale: float) -> np.ndarray:
    """Symmetric int8 quantization: round(x/scale) clamped to [-127, 127]
    (ties to even, matching np.rint).  ``scale`` is the caller's
    per-tensor max|x|/127."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    out = np.empty(x.shape, dtype=np.int8)
    inv = 0.0 if scale == 0.0 else 1.0 / float(scale)
    lib = _load()
    if lib is not None and x.size:
        lib.dlt_f32_to_i8(
            x.ctypes.data, out.ctypes.data, ctypes.c_size_t(x.size),
            ctypes.c_float(inv),
        )
        return out
    return np.clip(np.rint(x * inv), -127, 127).astype(np.int8)


def i8_to_f32(q: np.ndarray, scale: float) -> np.ndarray:
    """Dequantize int8 back to f32: q * scale."""
    q = np.ascontiguousarray(q, dtype=np.int8)
    out = np.empty(q.shape, dtype=np.float32)
    lib = _load()
    if lib is not None and q.size:
        lib.dlt_i8_to_f32(
            q.ctypes.data, out.ctypes.data, ctypes.c_size_t(q.size),
            ctypes.c_float(scale),
        )
        return out
    return q.astype(np.float32) * np.float32(scale)


def crc32(data, seed: int = 0) -> int:
    """crc32 (zlib-compatible) of a bytes-like or contiguous array."""
    lib = _load()
    if lib is not None:
        buf = np.frombuffer(memoryview(data).cast("B"), dtype=np.uint8)
        if buf.size == 0:
            return zlib.crc32(b"", seed) & 0xFFFFFFFF
        return int(
            lib.dlt_crc32(
                buf.ctypes.data, ctypes.c_size_t(buf.size), ctypes.c_uint32(seed)
            )
        )
    return zlib.crc32(memoryview(data).cast("B"), seed) & 0xFFFFFFFF
