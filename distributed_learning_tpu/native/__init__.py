"""Native (C++) components, loaded via ctypes with pure-Python fallbacks.

``codec.cpp`` holds the element-wise wire-codec hot path (f32<->bf16
conversion, int8 quantization, crc32); ``wire.cpp`` (wrapped by
:mod:`.wire`) is the whole-frame wire engine layered on the same
primitives.  Each shared library is compiled with g++ on first use and
cached beside its source; environments without a toolchain fall back to
numpy/ml_dtypes/zlib implementations with identical semantics (the tests
assert bit-equality).

Build hardening (ISSUE 9): every library exports ``dlt_abi_version()``
(``dlt_abi.h``), checked right after ``dlopen`` — a stale cached ``.so``
missing new symbols triggers a rebuild, never an ``AttributeError`` at
first use.  A failed g++ build logs ONE warning on the ``dlt.native``
logger and bumps the ``native.build_failed`` obs counter (it used to
return ``None`` silently), then the pure-Python fallback serves.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
import zlib
from typing import Callable, Optional

import numpy as np

__all__ = [
    "native_available",
    "f32_to_bf16",
    "bf16_to_f32",
    "f32_to_i8",
    "i8_to_f32",
    "crc32",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "codec.cpp")
_LIB = os.path.join(_HERE, "_codec.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

#: Expected ``dlt_abi_version()`` of every native library; must match
#: DLT_ABI_VERSION in ``dlt_abi.h`` (bumped when the symbol set changes).
_ABI_VERSION = 3

_logger = logging.getLogger("dlt.native")


def _report_build_failure(src: str, detail: str) -> None:
    """One warning + one counter per failed build — a box quietly running
    the slow path is an observability bug, not a convenience."""
    _logger.warning(
        "native build of %s failed (%s); falling back to the pure-Python "
        "codec — wire throughput will be the fallback's",
        os.path.basename(src), detail,
    )
    try:  # lazy: obs must stay importable without the comm/native stack
        from distributed_learning_tpu.obs import get_registry

        get_registry().inc("native.build_failed")
    except Exception:
        pass


def _cache_override(lib_path: str) -> str:
    """Instrumented-build hook (graftlint --native, ISSUE 10): when
    ``DLT_NATIVE_CACHE_DIR`` is set, the built ``.so`` lives under that
    directory instead of beside its source — a sanitizer run rebuilds
    with its own flags WITHOUT ever clobbering the production cache."""
    cache_dir = os.environ.get("DLT_NATIVE_CACHE_DIR")
    if not cache_dir:
        return lib_path
    os.makedirs(cache_dir, exist_ok=True)
    return os.path.join(cache_dir, os.path.basename(lib_path))


def _build_lib(src: str, lib_path: str, *, force: bool = False) -> Optional[str]:
    """Compile ``src`` to ``lib_path`` unless a fresh cache exists.

    ``force`` ignores the cache (the ABI-mismatch rebuild path).
    ``DLT_NATIVE_EXTRA_CFLAGS`` (space-separated) appends build flags —
    the sanitizer stage's ``-fsanitize=...`` hook; combined with
    ``DLT_NATIVE_CACHE_DIR`` the instrumented build is fully separate.
    """
    if (
        not force
        and os.path.exists(lib_path)
        and os.path.getmtime(lib_path) >= os.path.getmtime(src)
    ):
        return lib_path
    # Per-process temp name: concurrent first-use builds (multi-process
    # deployments) must not interleave g++ output on a shared path; the
    # final os.replace is atomic either way.  -march=native is safe for
    # a compiled-per-box-at-first-use cache (it IS this box) and lets
    # the wire engine's bulk loops vectorize; boxes whose toolchain
    # rejects it retry with the portable baseline.
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    extra_cflags = os.environ.get("DLT_NATIVE_EXTRA_CFLAGS", "").split()
    base = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        *extra_cflags, src, "-o", tmp,
    ]
    last_exc: Optional[BaseException] = None
    for extra in (["-march=native"], []):
        try:
            subprocess.run(
                base[:2] + extra + base[2:],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, lib_path)
            return lib_path
        except (OSError, subprocess.SubprocessError) as exc:
            last_exc = exc
            try:
                os.unlink(tmp)
            except OSError:
                pass
    detail = type(last_exc).__name__
    stderr = getattr(last_exc, "stderr", None)
    if stderr:
        detail += ": " + stderr.decode("utf-8", "replace").strip()[:200]
    _report_build_failure(src, detail)
    return None


def _abi_ok(lib: ctypes.CDLL) -> bool:
    try:
        fn = lib.dlt_abi_version
    except AttributeError:
        return False
    fn.argtypes = []
    fn.restype = ctypes.c_uint32
    return int(fn()) == _ABI_VERSION


def _load_lib(
    src: str,
    lib_path: str,
    configure: Callable[[ctypes.CDLL], None],
) -> Optional[ctypes.CDLL]:
    """Build (if needed), dlopen, ABI-check, and configure one library.

    An ABI mismatch — a cached ``.so`` from an older source whose mtime
    beat the checkout's — forces ONE rebuild from the current source; a
    second mismatch means the toolchain itself is stale and the Python
    fallback serves.
    """
    path = _build_lib(src, lib_path)
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    if not _abi_ok(lib):
        _logger.warning(
            "cached %s has a stale ABI (wanted v%d); rebuilding from source",
            os.path.basename(lib_path), _ABI_VERSION,
        )
        try:
            # dlopen caches by pathname while a handle stays open: the
            # rebuilt library would silently resolve to the stale image
            # unless the old handle is closed first.
            import _ctypes

            _ctypes.dlclose(lib._handle)
        except Exception:
            pass
        path = _build_lib(src, lib_path, force=True)
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        if not _abi_ok(lib):
            _report_build_failure(src, "rebuilt library still ABI-stale")
            return None
    configure(lib)
    return lib


def _configure_codec(lib: ctypes.CDLL) -> None:
    lib.dlt_f32_to_bf16.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
    ]
    lib.dlt_f32_to_bf16.restype = None
    lib.dlt_bf16_to_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
    ]
    lib.dlt_bf16_to_f32.restype = None
    lib.dlt_f32_to_i8.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_float,
    ]
    lib.dlt_f32_to_i8.restype = None
    lib.dlt_i8_to_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_float,
    ]
    lib.dlt_i8_to_f32.restype = None
    lib.dlt_crc32.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32,
    ]
    lib.dlt_crc32.restype = ctypes.c_uint32


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DLT_NO_NATIVE") == "1":
            return None
        _lib = _load_lib(_SRC, _cache_override(_LIB), _configure_codec)
        return _lib


def native_available() -> bool:
    return _load() is not None


def f32_to_bf16(x: np.ndarray) -> np.ndarray:
    """float32 array -> uint16 array of bfloat16 bit patterns (RNE)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    out = np.empty(x.shape, dtype=np.uint16)
    lib = _load()
    if lib is not None and x.size:
        lib.dlt_f32_to_bf16(
            x.ctypes.data, out.ctypes.data, ctypes.c_size_t(x.size)
        )
        return out
    import ml_dtypes  # bundled with jax

    return x.astype(ml_dtypes.bfloat16).view(np.uint16)


def bf16_to_f32(bits: np.ndarray) -> np.ndarray:
    """uint16 bfloat16 bit patterns -> float32 array."""
    bits = np.ascontiguousarray(bits, dtype=np.uint16)
    out = np.empty(bits.shape, dtype=np.float32)
    lib = _load()
    if lib is not None and bits.size:
        lib.dlt_bf16_to_f32(
            bits.ctypes.data, out.ctypes.data, ctypes.c_size_t(bits.size)
        )
        return out
    import ml_dtypes

    return bits.view(ml_dtypes.bfloat16).astype(np.float32)


def f32_to_i8(x: np.ndarray, scale: float) -> np.ndarray:
    """Symmetric int8 quantization: round(x/scale) clamped to [-127, 127]
    (ties to even, matching np.rint).  ``scale`` is the caller's
    per-tensor max|x|/127."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    out = np.empty(x.shape, dtype=np.int8)
    inv = 0.0 if scale == 0.0 else 1.0 / float(scale)
    lib = _load()
    if lib is not None and x.size:
        lib.dlt_f32_to_i8(
            x.ctypes.data, out.ctypes.data, ctypes.c_size_t(x.size),
            ctypes.c_float(inv),
        )
        return out
    return np.clip(np.rint(x * inv), -127, 127).astype(np.int8)


def i8_to_f32(q: np.ndarray, scale: float) -> np.ndarray:
    """Dequantize int8 back to f32: q * scale."""
    q = np.ascontiguousarray(q, dtype=np.int8)
    out = np.empty(q.shape, dtype=np.float32)
    lib = _load()
    if lib is not None and q.size:
        lib.dlt_i8_to_f32(
            q.ctypes.data, out.ctypes.data, ctypes.c_size_t(q.size),
            ctypes.c_float(scale),
        )
        return out
    return q.astype(np.float32) * np.float32(scale)


def crc32(data, seed: int = 0) -> int:
    """crc32 (zlib-compatible) of a bytes-like or contiguous array."""
    lib = _load()
    if lib is not None:
        buf = np.frombuffer(memoryview(data).cast("B"), dtype=np.uint8)
        if buf.size == 0:
            return zlib.crc32(b"", seed) & 0xFFFFFFFF
        return int(
            lib.dlt_crc32(
                buf.ctypes.data, ctypes.c_size_t(buf.size), ctypes.c_uint32(seed)
            )
        )
    return zlib.crc32(memoryview(data).cast("B"), seed) & 0xFFFFFFFF
