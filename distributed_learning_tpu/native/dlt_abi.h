// Shared ABI version for the native codec/wire shared objects.
//
// Bump DLT_ABI_VERSION whenever the exported C symbol set or any
// function signature changes.  native/__init__.py calls the exported
// dlt_abi_version() right after dlopen and force-rebuilds a cached .so
// whose version does not match — a stale cache must become a rebuild,
// never an AttributeError at first use (ISSUE 9 build hardening).
#ifndef DLT_ABI_H_
#define DLT_ABI_H_

// v3: dlt_wire_fused_apply joins the export set, and
// dlt_wire_fused_decode's out-buffer contract changed (the decode now
// zero-fills the ravel itself, so callers may pass dirty scratch).
#define DLT_ABI_VERSION 3u

// Transport-frame and trace-context versions, restated here so the
// native side carries the full wire identity in one header.  The
// Python authorities are comm/framing.py (WIRE_VERSION) and
// comm/protocol.py (TRACE_CTX_VERSION); graftlint's wire-contract
// stage fails lint whenever the three statements of either version
// (Python authority, wire.cpp constexpr, this define) disagree.
#define DLT_WIRE_VERSION 2u
#define DLT_TRACE_CTX_VERSION 1u

#endif  // DLT_ABI_H_
