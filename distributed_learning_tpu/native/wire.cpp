// Native wire engine: whole-frame encode/decode for the TCP comm backend.
//
// codec.cpp provides the element-wise primitives (f32<->bf16, int8
// quantization, byte-at-a-time crc32); this module is the frame layer on
// top of them — it encodes and decodes WHOLE frames in one call, operating
// directly on the TreeSpec ravel buffer:
//
//   * fused sparse frames (one `indices|values` section per dtype bucket,
//     u32 flat positions into the ravel): the u32 gather/scatter is FUSED
//     with the bf16/int8 wire conversion, so a frame is two linear passes
//     (measure, then write) instead of the per-bucket numpy pipeline of
//     comm/tensor_codec.py — and the frame's trailing crc32 is computed
//     over the assembled bytes with a slicing-by-8 table in the same call;
//   * dense tensor frames (header + converted payload written into one
//     preallocated output buffer).
//
// Decode is validate-then-scatter: every section header is bounds-checked
// against the frame length and the ravel size, and the trailing crc is
// verified, BEFORE the first scatter write — a corrupt length/offset or a
// flipped bit becomes a negative status (comm/tensor_codec.py raises
// CodecError), never an out-of-bounds write.  Wire layout parity is with
// the pure-Python codec in comm/tensor_codec.py, which stays the
// byte-for-byte authoritative oracle (and the DLT_NO_NATIVE=1 fallback).
//
// Exposed with C linkage for ctypes; built by native/__init__.py with g++
// -O3 at first use and cached next to this file (ABI-checked, see
// dlt_abi.h).

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__SSE2__) || defined(__AVX__)
#include <immintrin.h>
#endif

#if defined(__linux__)
#include <sys/mman.h>
#include <unistd.h>
#endif

#include "dlt_abi.h"

namespace {

// ---- status codes (negative returns; mirrored in native/wire.py) ---- //
constexpr long long kErrTrunc = -1;        // frame shorter than its headers
constexpr long long kErrMagic = -2;        // not a fused sparse frame
constexpr long long kErrVersion = -3;      // unknown fused frame version
constexpr long long kErrCrc = -4;          // checksum mismatch
constexpr long long kErrBounds = -5;       // section length/offset corrupt
constexpr long long kErrRange = -6;        // scatter index outside the ravel
constexpr long long kErrTotal = -7;        // header total != caller's buffer
constexpr long long kErrUnsupported = -8;  // valid frame, dtype the native
                                           // path does not handle (caller
                                           // falls back to Python)
constexpr long long kErrNonFinite = -9;    // int8 wire over NaN/Inf values
constexpr long long kErrInternal = -10;    // output capacity / pass-1 vs
                                           // pass-2 disagreement (a bug)

// Transport constants shared with comm/framing.py / comm/protocol.py.
// Mirror-only (the native engine codes payload sections, not transport
// frames) but kept in lockstep by graftlint's wire-contract stage: v2
// adds the TraceContext trailer (u8 present | u32 run_id | i64 seq |
// f64 t_wall | u16-len origin) to the value-bearing message bodies.
constexpr uint8_t kWireVersion = 2;
constexpr uint8_t kTraceCtxVersion = 1;

// Wire constants shared with comm/tensor_codec.py.
constexpr uint8_t kFusedMagic = 0xFE;
constexpr uint8_t kFusedVersion = 1;
constexpr uint8_t kDtypeF32 = 0;   // _DTYPE_CODES[np.float32]
constexpr uint8_t kDtypeBf16 = 5;  // _DTYPE_CODES[np.uint16] (bf16 bits)
constexpr uint8_t kDtypeI8 = 7;    // _DTYPE_CODES[np.int8]
constexpr uint8_t kFlagBf16 = 0x01;
constexpr uint8_t kFlagI8 = 0x02;
// Per-bucket / dense encode modes (native/wire.py _MODE_*).
constexpr uint8_t kModeF32 = 0;
constexpr uint8_t kModeBf16 = 1;
constexpr uint8_t kModeI8 = 2;

// ---- little-endian scalar IO --------------------------------------- //
// On little-endian hosts (every deployment target) a 4-byte memcpy is a
// single unaligned mov the compiler can vectorize across loop
// iterations; the byte-wise form is kept for exotic hosts.
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
inline void put_u32(uint8_t* p, uint32_t v) { std::memcpy(p, &v, 4); }
inline uint32_t get_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline void put_u16(uint8_t* p, uint16_t v) { std::memcpy(p, &v, 2); }
inline uint16_t get_u16(const uint8_t* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
#else
inline void put_u32(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
  p[2] = static_cast<uint8_t>(v >> 16);
  p[3] = static_cast<uint8_t>(v >> 24);
}

inline uint32_t get_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

inline void put_u16(uint8_t* p, uint16_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
}

inline uint16_t get_u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) | (static_cast<uint16_t>(p[1]) << 8);
}
#endif

inline void put_f32(uint8_t* p, float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  put_u32(p, bits);
}

inline float get_f32(const uint8_t* p) {
  uint32_t bits = get_u32(p);
  float v;
  std::memcpy(&v, &bits, 4);
  return v;
}

// ---- element conversions (bit-identical to codec.cpp's kernels) ----- //
inline uint16_t f32_to_bf16_one(float v) {
  uint32_t x;
  std::memcpy(&x, &v, 4);
  // NaN stays NaN: round-up could flow a signalling mantissa to zero
  // (infinity); force a quiet-NaN payload instead.  Branchless (select,
  // not branch) so the bulk encode loops vectorize.
  const bool is_nan = (x & 0x7fffffffu) > 0x7f800000u;
  const uint16_t nan_bits = static_cast<uint16_t>((x >> 16) | 0x0040u);
  const uint32_t lsb = (x >> 16) & 1u;
  const uint16_t rne_bits = static_cast<uint16_t>((x + 0x7fffu + lsb) >> 16);
  return is_nan ? nan_bits : rne_bits;
}

inline float bf16_to_f32_one(uint16_t bits) {
  uint32_t x = static_cast<uint32_t>(bits) << 16;
  float v;
  std::memcpy(&v, &x, 4);
  return v;
}

inline int8_t f32_to_i8_one(float v, float inv) {
  // Match np.rint (ties to even): nearbyint under FE_TONEAREST — the
  // same contract as codec.cpp's dlt_f32_to_i8.
  float r = __builtin_nearbyintf(v * inv);
  if (r > 127.0f) r = 127.0f;
  if (r < -127.0f) r = -127.0f;
  return static_cast<int8_t>(r);
}

// Rounding barrier: -O3 contracts ``a += s * v`` into an FMA (one
// rounding), but the Python oracle (np.add.at of ``s * vals``) rounds
// the multiply and the add separately.  Forcing the product through an
// opaque register keeps the apply path bit-identical to the oracle.
inline float fp_barrier(float x) {
#if defined(__SSE2__)
  __asm__("" : "+x"(x));
#else
  volatile float y = x;
  x = y;
#endif
  return x;
}

// Python-parity int8 scale plumbing (tensor_codec.encode_tensor):
//   scale = float(np.max(np.abs(x)) / 127.0)   # f32 max, f64 divide
//   wire stores struct.pack('<f', scale); the kernel receives
//   c_float(1.0 / scale).
struct I8Scale {
  float wire;  // f32 scale written ahead of the int8 payload
  float inv;   // f32 inverse handed to the quantizer
};

inline I8Scale i8_scale_of(float maxabs, uint64_t k) {
  if (k == 0 || maxabs == 0.0f) return {0.0f, 0.0f};
  double scale_d = static_cast<double>(maxabs) / 127.0;
  return {static_cast<float>(scale_d), static_cast<float>(1.0 / scale_d)};
}

// Value-section byte length for k elements under a mode (encode_tensor of
// a 1-D f32 vector: 4-byte header + u32 dim, int8 adds the f32 scale).
inline uint64_t vlen_of(uint8_t mode, uint64_t k) {
  switch (mode) {
    case kModeBf16:
      return 8 + 2 * k;
    case kModeI8:
      return 12 + k;
    default:
      return 8 + 4 * k;
  }
}

// Pre-fault a freshly-allocated buffer in one batched kernel call
// instead of ~one page fault per 4 KiB during the scatter/write loops —
// on a full-width ravel (146 MB) the per-fault overhead, not the
// zeroing, is the decode bottleneck.  Best-effort: any failure (old
// kernel, non-anon mapping) just leaves the lazy-fault behavior.
inline void prefault_writable(void* ptr, uint64_t nbytes) {
#if defined(__linux__) && defined(MADV_POPULATE_WRITE)
  if (nbytes < (1u << 22)) return;  // not worth a syscall below 4 MB
  const uint64_t page = static_cast<uint64_t>(sysconf(_SC_PAGESIZE));
  uint64_t lo = reinterpret_cast<uint64_t>(ptr);
  uint64_t hi = lo + nbytes;
  lo = (lo + page - 1) & ~(page - 1);
  hi &= ~(page - 1);
  if (hi > lo) {
    (void)madvise(reinterpret_cast<void*>(lo), hi - lo,
                  MADV_POPULATE_WRITE);
  }
#else
  (void)ptr;
  (void)nbytes;
#endif
}

// ---- slicing-by-8 crc32 (zlib polynomial, zlib-identical results) --- //
uint32_t kCrcTab[8][256];
bool kCrcTabInit = false;

void crc_tab_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1u) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    }
    kCrcTab[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    for (int t = 1; t < 8; ++t) {
      kCrcTab[t][i] =
          (kCrcTab[t - 1][i] >> 8) ^ kCrcTab[0][kCrcTab[t - 1][i] & 0xFFu];
    }
  }
  kCrcTabInit = true;
}

uint32_t crc32_sliced(const uint8_t* p, size_t n, uint32_t seed) {
  if (!kCrcTabInit) crc_tab_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo = get_u32(p) ^ c;
    uint32_t hi = get_u32(p + 4);
    c = kCrcTab[7][lo & 0xFFu] ^ kCrcTab[6][(lo >> 8) & 0xFFu] ^
        kCrcTab[5][(lo >> 16) & 0xFFu] ^ kCrcTab[4][lo >> 24] ^
        kCrcTab[3][hi & 0xFFu] ^ kCrcTab[2][(hi >> 8) & 0xFFu] ^
        kCrcTab[1][(hi >> 16) & 0xFFu] ^ kCrcTab[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) {
    c = kCrcTab[0][(c ^ *p++) & 0xFFu] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// ---- crc32 combine (zlib's GF(2) matrix method) --------------------- //
// crc(A||B) from crc(A), crc(B), len(B): lets two halves of a frame run
// as INDEPENDENT slicing chains in one interleaved loop — the chain's
// load-use latency, not bandwidth, bounds a single stream.
uint32_t gf2_matrix_times(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  while (vec) {
    if (vec & 1u) sum ^= *mat;
    vec >>= 1;
    ++mat;
  }
  return sum;
}

void gf2_matrix_square(uint32_t* square, const uint32_t* mat) {
  for (int i = 0; i < 32; ++i) square[i] = gf2_matrix_times(mat, mat[i]);
}

uint32_t crc32_combine(uint32_t crc1, uint32_t crc2, uint64_t len2) {
  if (len2 == 0) return crc1;
  uint32_t even[32], odd[32];
  odd[0] = 0xEDB88320u;  // the reflected polynomial: "times x" operator
  uint32_t row = 1;
  for (int i = 1; i < 32; ++i) {
    odd[i] = row;
    row <<= 1;
  }
  gf2_matrix_square(even, odd);  // times x^2
  gf2_matrix_square(odd, even);  // times x^4
  do {
    gf2_matrix_square(even, odd);
    if (len2 & 1u) crc1 = gf2_matrix_times(even, crc1);
    len2 >>= 1;
    if (len2 == 0) break;
    gf2_matrix_square(odd, even);
    if (len2 & 1u) crc1 = gf2_matrix_times(odd, crc1);
    len2 >>= 1;
  } while (len2);
  return crc1 ^ crc2;
}

// Dual-stream crc: two interleaved slicing-by-8 chains over the two
// halves (ILP hides the per-chain latency), merged with crc32_combine.
uint32_t crc32_fast(const uint8_t* p, size_t n, uint32_t seed) {
  if (n < (1u << 14)) return crc32_sliced(p, n, seed);
  if (!kCrcTabInit) crc_tab_init();
  const size_t half = (n / 2) & ~size_t(7);
  const uint8_t* p1 = p;
  const uint8_t* p2 = p + half;
  uint32_t c1 = seed ^ 0xFFFFFFFFu;
  uint32_t c2 = 0xFFFFFFFFu;  // seed 0 for the second stream
  for (size_t i = 0; i + 8 <= half; i += 8) {
    const uint32_t lo1 = get_u32(p1 + i) ^ c1;
    const uint32_t hi1 = get_u32(p1 + i + 4);
    const uint32_t lo2 = get_u32(p2 + i) ^ c2;
    const uint32_t hi2 = get_u32(p2 + i + 4);
    c1 = kCrcTab[7][lo1 & 0xFFu] ^ kCrcTab[6][(lo1 >> 8) & 0xFFu] ^
         kCrcTab[5][(lo1 >> 16) & 0xFFu] ^ kCrcTab[4][lo1 >> 24] ^
         kCrcTab[3][hi1 & 0xFFu] ^ kCrcTab[2][(hi1 >> 8) & 0xFFu] ^
         kCrcTab[1][(hi1 >> 16) & 0xFFu] ^ kCrcTab[0][hi1 >> 24];
    c2 = kCrcTab[7][lo2 & 0xFFu] ^ kCrcTab[6][(lo2 >> 8) & 0xFFu] ^
         kCrcTab[5][(lo2 >> 16) & 0xFFu] ^ kCrcTab[4][lo2 >> 24] ^
         kCrcTab[3][hi2 & 0xFFu] ^ kCrcTab[2][(hi2 >> 8) & 0xFFu] ^
         kCrcTab[1][(hi2 >> 16) & 0xFFu] ^ kCrcTab[0][hi2 >> 24];
  }
  c1 ^= 0xFFFFFFFFu;  // finalize stream 1 = crc of [0, half)
  // Stream 2 continues byte-wise through the tail [2*half, n).
  size_t rest = n - 2 * half;
  const uint8_t* pt = p + 2 * half;
  while (rest--) {
    c2 = kCrcTab[0][(c2 ^ *pt++) & 0xFFu] ^ (c2 >> 8);
  }
  c2 ^= 0xFFFFFFFFu;  // crc of [half, n)
  return crc32_combine(c1, c2, n - half);
}

// Sparse compaction driver for the encode write pass.  A gossip
// correction ravel is ~90% zeros, so per-element branches are all
// mispredictions and per-element branchless stores waste bandwidth;
// instead a SIMD nonzero mask (CMPNEQ, unordered — NaN counts nonzero,
// like np.flatnonzero) is reduced to a bitmask per block, all-zero
// blocks are skipped in a few ops, and only actual nonzeros reach the
// scalar emit (iterated via count-trailing-zeros).  Output order is
// strictly ascending positions — identical bytes to the Python oracle.
template <typename Emit>
inline uint64_t compact_span(const float* p, uint64_t n, uint64_t base,
                             uint64_t w, Emit emit) {
  uint64_t i = 0;
#if defined(__AVX__)
  const __m256 zero8 = _mm256_setzero_ps();
  for (; i + 8 <= n; i += 8) {
    const __m256 v = _mm256_loadu_ps(p + i);
    int m = _mm256_movemask_ps(_mm256_cmp_ps(v, zero8, _CMP_NEQ_UQ));
    while (m) {
      const int j = __builtin_ctz(m);
      m &= m - 1;
      emit(w, base + i + j, p[i + j]);
      ++w;
    }
  }
#elif defined(__SSE2__)
  const __m128 zero4 = _mm_setzero_ps();
  for (; i + 4 <= n; i += 4) {
    const __m128 v = _mm_loadu_ps(p + i);
    int m = _mm_movemask_ps(_mm_cmpneq_ps(v, zero4));
    while (m) {
      const int j = __builtin_ctz(m);
      m &= m - 1;
      emit(w, base + i + j, p[i + j]);
      ++w;
    }
  }
#endif
  for (; i < n; ++i) {
    const float v = p[i];
    if (v != 0.0f) {
      emit(w, base + i, v);
      ++w;
    }
  }
  return w;
}

#if defined(__AVX512F__)
// AVX-512 compaction: vcompressps / vpcompressd ARE the sparse-wire
// primitive — one masked compress-store packs a block's nonzero lanes
// (and their flat positions) straight into the frame's sections, no
// per-nonzero branches at all.  Blocks that could overrun the k-sized
// sections (only possible if the ravel changed between the size and
// write passes) fall to the guarded scalar tail, so the compress-stores
// can never write past their sections.
inline uint64_t compact_span_f32_avx512(const float* p, uint64_t n,
                                        uint64_t base, uint64_t w,
                                        uint64_t k, uint8_t* idx_p,
                                        uint8_t* val_p) {
  const __m512 zero16 = _mm512_setzero_ps();
  const __m512i lane_iota = _mm512_set_epi32(
      15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0);
  uint64_t i = 0;
  for (; i + 16 <= n && w + 16 <= k; i += 16) {
    const __m512 v = _mm512_loadu_ps(p + i);
    const __mmask16 m = _mm512_cmp_ps_mask(v, zero16, _CMP_NEQ_UQ);
    if (!m) continue;
    const __m512i pos = _mm512_add_epi32(
        _mm512_set1_epi32(static_cast<int>(base + i)), lane_iota);
    _mm512_mask_compressstoreu_epi32(idx_p + 4 * w, m, pos);
    _mm512_mask_compressstoreu_ps(val_p + 4 * w, m, v);
    w += __builtin_popcount(static_cast<unsigned>(m));
  }
  for (; i < n; ++i) {
    const float v = p[i];
    if (v != 0.0f) {
      if (w < k) {
        put_u32(idx_p + 4 * w, static_cast<uint32_t>(base + i));
        put_f32(val_p + 4 * w, v);
      }
      ++w;
    }
  }
  return w;
}

// bf16: positions compress-store into the final idx section; the RNE
// conversion runs 16-wide in integer vectors (bit-identical to
// f32_to_bf16_one, NaN quieting and denormals included — the hardware
// vcvtneps2bf16 flushes denormals and so cannot serve), and the 2-byte
// values compress via vpmovdw of the compressed 32-bit lanes.
inline uint64_t compact_span_bf16_avx512(const float* p, uint64_t n,
                                         uint64_t base, uint64_t w,
                                         uint64_t k, uint8_t* idx_p,
                                         uint8_t* val_p) {
  const __m512 zero16 = _mm512_setzero_ps();
  const __m512i lane_iota = _mm512_set_epi32(
      15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0);
  const __m512i abs_mask = _mm512_set1_epi32(0x7fffffff);
  const __m512i inf_bits = _mm512_set1_epi32(0x7f800000);
  const __m512i round_c = _mm512_set1_epi32(0x7fff);
  const __m512i one = _mm512_set1_epi32(1);
  const __m512i quiet = _mm512_set1_epi32(0x0040);
  uint64_t i = 0;
  for (; i + 16 <= n && w + 16 <= k; i += 16) {
    const __m512 v = _mm512_loadu_ps(p + i);
    const __mmask16 m = _mm512_cmp_ps_mask(v, zero16, _CMP_NEQ_UQ);
    if (!m) continue;
    const __m512i pos = _mm512_add_epi32(
        _mm512_set1_epi32(static_cast<int>(base + i)), lane_iota);
    _mm512_mask_compressstoreu_epi32(idx_p + 4 * w, m, pos);
    const __m512i x = _mm512_castps_si512(v);
    const __m512i hi16 = _mm512_srli_epi32(x, 16);
    const __mmask16 is_nan = _mm512_cmpgt_epi32_mask(
        _mm512_and_si512(x, abs_mask), inf_bits);
    const __m512i rne = _mm512_srli_epi32(
        _mm512_add_epi32(
            _mm512_add_epi32(x, round_c), _mm512_and_si512(hi16, one)),
        16);
    const __m512i bits = _mm512_mask_or_epi32(rne, is_nan, hi16, quiet);
    const __m512i packed = _mm512_maskz_compress_epi32(m, bits);
    const int c = __builtin_popcount(static_cast<unsigned>(m));
    // Narrow the c compressed 32-bit lanes to u16 and store them; the
    // store may cover up to 32 bytes, all inside the val section
    // thanks to the w + 16 <= k loop guard.
    _mm256_storeu_si256(
        reinterpret_cast<__m256i*>(val_p + 2 * w),
        _mm512_cvtepi32_epi16(packed));
    w += c;
  }
  for (; i < n; ++i) {
    const float v = p[i];
    if (v != 0.0f) {
      if (w < k) {
        put_u32(idx_p + 4 * w, static_cast<uint32_t>(base + i));
        put_u16(val_p + 2 * w, f32_to_bf16_one(v));
      }
      ++w;
    }
  }
  return w;
}
#endif  // __AVX512F__

// Nonzero count of one span via the same mask reduction (popcount per
// block instead of per-element adds).
inline uint64_t count_nonzero(const float* p, uint64_t n) {
  uint64_t k = 0;
  uint64_t i = 0;
#if defined(__AVX512F__)
  const __m512 zero16 = _mm512_setzero_ps();
  for (; i + 16 <= n; i += 16) {
    const __m512 v = _mm512_loadu_ps(p + i);
    k += __builtin_popcount(static_cast<unsigned>(
        _mm512_cmp_ps_mask(v, zero16, _CMP_NEQ_UQ)));
  }
#elif defined(__AVX__)
  const __m256 zero8 = _mm256_setzero_ps();
  for (; i + 8 <= n; i += 8) {
    const __m256 v = _mm256_loadu_ps(p + i);
    k += __builtin_popcount(
        _mm256_movemask_ps(_mm256_cmp_ps(v, zero8, _CMP_NEQ_UQ)));
  }
#elif defined(__SSE2__)
  const __m128 zero4 = _mm_setzero_ps();
  for (; i + 4 <= n; i += 4) {
    const __m128 v = _mm_loadu_ps(p + i);
    k += __builtin_popcount(
        _mm_movemask_ps(_mm_cmpneq_ps(v, zero4)));
  }
#endif
  for (; i < n; ++i) k += (p[i] != 0.0f);
  return k;
}

// Shared validation walk for the fused-frame read paths (decode and
// apply): crc, then every section header bounds-checked against the
// frame length and the ravel size — BEFORE the first write to the
// caller's memory.  Returns 0 or a negative status.
long long fused_validate(const uint8_t* buf, uint64_t len, uint64_t total) {
  if (len < 12) return kErrTrunc;
  if (buf[0] != kFusedMagic) return kErrMagic;
  if (buf[1] != kFusedVersion) return kErrVersion;
  const uint32_t nbuckets = buf[2];
  if (get_u32(buf + 4) != total) return kErrTotal;
  const uint64_t body_end = len - 4;
  if (crc32_fast(buf, body_end, 0) != get_u32(buf + body_end)) {
    return kErrCrc;
  }
  uint64_t off = 8;
  for (uint32_t b = 0; b < nbuckets; ++b) {
    if (off + 4 > body_end) return kErrTrunc;
    const uint64_t k = get_u32(buf + off);
    if (k > total) return kErrBounds;
    off += 4;
    if (off + 4 * k + 4 > body_end) return kErrTrunc;
    const uint8_t* idx_p = buf + off;
    off += 4 * k;
    const uint64_t vlen = get_u32(buf + off);
    off += 4;
    if (off + vlen > body_end || vlen < 8) return kErrTrunc;
    const uint8_t* vhdr = buf + off;
    const uint8_t code = vhdr[0], flags = vhdr[1], ndim = vhdr[2];
    if (ndim != 1 || get_u32(vhdr + 4) != k) return kErrBounds;
    uint8_t mode;
    if (code == kDtypeF32 && flags == 0) {
      mode = kModeF32;
    } else if (code == kDtypeBf16 && flags == kFlagBf16) {
      mode = kModeBf16;
    } else if (code == kDtypeI8 && flags == kFlagI8) {
      mode = kModeI8;
    } else {
      return kErrUnsupported;  // caller re-decodes via the Python oracle
    }
    if (vlen != vlen_of(mode, k)) return kErrBounds;
    // Branchless max over the index section (vectorizes), one compare.
    uint32_t mx = 0;
    for (uint64_t i = 0; i < k; ++i) {
      const uint32_t u = get_u32(idx_p + 4 * i);
      mx = u > mx ? u : mx;
    }
    if (k && mx >= total) return kErrRange;
    off += vlen;
  }
  if (off != body_end) return kErrBounds;  // trailing slack between
                                           // sections and crc
  return 0;
}

}  // namespace

extern "C" {

uint32_t dlt_abi_version() { return DLT_ABI_VERSION; }

// Exposed so the Python side can cross-check the sliced table against
// zlib (and reuse it for large buffers).
uint32_t dlt_wire_crc32(const uint8_t* data, size_t n, uint32_t seed) {
  return crc32_fast(data, n, seed);
}

// --------------------------------------------------------------------- //
// Fused sparse frames                                                   //
//                                                                       //
//   u8 0xFE | u8 version=1 | u8 nbuckets | u8 0 | u32 total |           //
//   per bucket: u32 k | u32 idx[k] | u32 vlen | value section |         //
//   u32 crc32(all preceding bytes)                                      //
//                                                                       //
// Buckets arrive as a CSR over (offset, size) spans of the ravel:       //
// bucket b owns spans [bucket_ptr[b], bucket_ptr[b+1]).  The caller     //
// (comm/tensor_codec.py) has already validated that spans tile the      //
// ravel exactly.                                                        //
// --------------------------------------------------------------------- //

// Pass 1: per-bucket nonzero counts (and, for int8 buckets, max|v| with a
// NaN/Inf check) + the exact frame size.  Writes out_k[nbuckets] and
// out_maxabs[nbuckets]; returns the frame byte size or a negative status.
long long dlt_wire_fused_size(
    const float* flat, uint64_t total, const uint64_t* span_off,
    const uint64_t* span_size, const uint64_t* bucket_ptr,
    const uint8_t* bucket_mode, uint32_t nbuckets, uint64_t* out_k,
    float* out_maxabs) {
  (void)total;
  uint64_t size = 8;  // frame header
  for (uint32_t b = 0; b < nbuckets; ++b) {
    uint64_t k = 0;
    float maxabs = 0.0f;
    bool any_nan = false;
    const bool want_scale = bucket_mode[b] == kModeI8;
    for (uint64_t s = bucket_ptr[b]; s < bucket_ptr[b + 1]; ++s) {
      const float* p = flat + span_off[s];
      const uint64_t n = span_size[s];
      if (!want_scale) {
        k += count_nonzero(p, n);
      } else {
        for (uint64_t i = 0; i < n; ++i) {
          const float v = p[i];
          k += (v != 0.0f);
          any_nan |= (v != v);
          const float a = std::fabs(v);
          maxabs = a > maxabs ? a : maxabs;
        }
      }
    }
    if (want_scale && (any_nan || std::isinf(maxabs))) return kErrNonFinite;
    out_k[b] = k;
    out_maxabs[b] = maxabs;
    size += 4 + 4 * k + 4 + vlen_of(bucket_mode[b], k);
  }
  return static_cast<long long>(size + 4);  // + trailing crc
}


// Pass 2: assemble the frame into out (capacity cap, which must be the
// pass-1 size) — gather + convert + section headers + trailing crc, one
// linear scan of the ravel.  Returns bytes written or a negative status.
long long dlt_wire_fused_encode(
    const float* flat, uint64_t total, const uint64_t* span_off,
    const uint64_t* span_size, const uint64_t* bucket_ptr,
    const uint8_t* bucket_mode, uint32_t nbuckets, const uint64_t* ks,
    const float* maxabs, uint8_t* out, uint64_t cap) {
  if (cap < 12 || total > 0xFFFFFFFFull) return kErrInternal;
  prefault_writable(out, cap);
  out[0] = kFusedMagic;
  out[1] = kFusedVersion;
  out[2] = static_cast<uint8_t>(nbuckets);
  out[3] = 0;
  put_u32(out + 4, static_cast<uint32_t>(total));
  uint64_t cur = 8;
  for (uint32_t b = 0; b < nbuckets; ++b) {
    const uint64_t k = ks[b];
    const uint8_t mode = bucket_mode[b];
    const uint64_t vlen = vlen_of(mode, k);
    if (cur + 4 + 4 * k + 4 + vlen + 4 > cap) return kErrInternal;
    uint8_t* idx_p = out + cur + 4;
    uint8_t* vhdr = idx_p + 4 * k + 4;
    uint8_t* val_p = vhdr + (mode == kModeI8 ? 12 : 8);
    I8Scale sc{0.0f, 0.0f};
    if (mode == kModeI8) sc = i8_scale_of(maxabs[b], k);
    const float inv = sc.inv;
    uint64_t w = 0;
    for (uint64_t s = bucket_ptr[b]; s < bucket_ptr[b + 1]; ++s) {
      const float* p = flat + span_off[s];
      const uint64_t base = span_off[s];
      uint64_t n = span_size[s];
      // Defense against the ravel changing between the size and write
      // passes (a caller bug): never emit past this bucket's k section.
      if (n > 0 && w >= k + 1) return kErrInternal;
      if (mode == kModeBf16) {
#if defined(__AVX512F__)
        w = compact_span_bf16_avx512(p, n, base, w, k, idx_p, val_p);
#else
        w = compact_span(p, n, base, w,
                         [&](uint64_t c, uint64_t pos, float v) {
                           if (c < k) {
                             put_u32(idx_p + 4 * c,
                                     static_cast<uint32_t>(pos));
                             put_u16(val_p + 2 * c, f32_to_bf16_one(v));
                           }
                         });
#endif
      } else if (mode == kModeI8) {
        w = compact_span(p, n, base, w,
                         [&](uint64_t c, uint64_t pos, float v) {
                           if (c < k) {
                             put_u32(idx_p + 4 * c,
                                     static_cast<uint32_t>(pos));
                             val_p[c] = static_cast<uint8_t>(
                                 f32_to_i8_one(v, inv));
                           }
                         });
      } else {
#if defined(__AVX512F__)
        w = compact_span_f32_avx512(p, n, base, w, k, idx_p, val_p);
#else
        w = compact_span(p, n, base, w,
                         [&](uint64_t c, uint64_t pos, float v) {
                           if (c < k) {
                             put_u32(idx_p + 4 * c,
                                     static_cast<uint32_t>(pos));
                             put_f32(val_p + 4 * c, v);
                           }
                         });
#endif
      }
    }
    if (w != k) return kErrInternal;  // ravel changed between passes
    put_u32(out + cur, static_cast<uint32_t>(k));
    put_u32(vhdr - 4, static_cast<uint32_t>(vlen));
    // encode_tensor header of the 1-D f32 value vector.
    vhdr[0] = mode == kModeBf16 ? kDtypeBf16
              : mode == kModeI8 ? kDtypeI8
                                : kDtypeF32;
    vhdr[1] = mode == kModeBf16 ? kFlagBf16 : mode == kModeI8 ? kFlagI8 : 0;
    vhdr[2] = 1;  // ndim
    vhdr[3] = 0;
    put_u32(vhdr + 4, static_cast<uint32_t>(k));
    if (mode == kModeI8) put_f32(vhdr + 8, sc.wire);
    cur += 4 + 4 * k + 4 + vlen;
  }
  if (cur + 4 > cap) return kErrInternal;
  put_u32(out + cur, crc32_fast(out, cur, 0));
  return static_cast<long long>(cur + 4);
}

// Decode: crc first, then a full bounds-checking validation walk over
// every section header, and only then the scatter pass into the ravel —
// a corrupt frame can never write out, let alone out of bounds.
// ``out`` is the caller's f32 ravel of ``total`` elements; its prior
// contents are IGNORED (the decode zero-fills between validation and
// scatter), so per-edge scratch buffers can be handed back dirty.
long long dlt_wire_fused_decode(const uint8_t* buf, uint64_t len, float* out,
                                uint64_t total) {
  const long long st = fused_validate(buf, len, total);
  if (st != 0) return st;
  const uint32_t nbuckets = buf[2];
  prefault_writable(out, total * 4);
  std::memset(out, 0, total * 4);
  // Scatter walk: fused gather-position + wire->f32 conversion.
  uint64_t off = 8;
  for (uint32_t b = 0; b < nbuckets; ++b) {
    const uint64_t k = get_u32(buf + off);
    const uint8_t* idx_p = buf + off + 4;
    const uint8_t* vhdr = buf + off + 4 + 4 * k + 4;
    const uint8_t code = vhdr[0], flags = vhdr[1];
    const uint8_t* val_p = vhdr + 8;
    if (code == kDtypeF32 && flags == 0) {
      uint64_t i = 0;
#if defined(__AVX512F__)
      // vscatterdps: same last-lane-wins overlap semantics as the
      // sequential loop (and numpy's out[idx] = vals).
      for (; i + 16 <= k; i += 16) {
        _mm512_i32scatter_ps(
            out,
            _mm512_loadu_si512(
                reinterpret_cast<const void*>(idx_p + 4 * i)),
            _mm512_loadu_ps(
                reinterpret_cast<const void*>(val_p + 4 * i)),
            4);
      }
#endif
      for (; i < k; ++i) {
        out[get_u32(idx_p + 4 * i)] = get_f32(val_p + 4 * i);
      }
      off += 4 + 4 * k + 4 + 8 + 4 * k;
    } else if (code == kDtypeBf16 && flags == kFlagBf16) {
      uint64_t i = 0;
#if defined(__AVX512F__)
      for (; i + 16 <= k; i += 16) {
        const __m256i raw = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(val_p + 2 * i));
        const __m512 vals = _mm512_castsi512_ps(
            _mm512_slli_epi32(_mm512_cvtepu16_epi32(raw), 16));
        _mm512_i32scatter_ps(
            out,
            _mm512_loadu_si512(
                reinterpret_cast<const void*>(idx_p + 4 * i)),
            vals, 4);
      }
#endif
      for (; i < k; ++i) {
        const uint16_t bits = static_cast<uint16_t>(val_p[2 * i]) |
                              (static_cast<uint16_t>(val_p[2 * i + 1]) << 8);
        out[get_u32(idx_p + 4 * i)] = bf16_to_f32_one(bits);
      }
      off += 4 + 4 * k + 4 + 8 + 2 * k;
    } else {  // int8
      const float scale = get_f32(val_p);
      const int8_t* q = reinterpret_cast<const int8_t*>(val_p + 4);
      for (uint64_t i = 0; i < k; ++i) {
        out[get_u32(idx_p + 4 * i)] = static_cast<float>(q[i]) * scale;
      }
      off += 4 + 4 * k + 4 + 12 + k;
    }
  }
  return 0;
}

// Validate-only entry: the full decode-side walk (crc + section
// geometry + dtype support + index range) with no output buffer at all
// — the lazy-payload path (comm/tensor_codec.py FusedFrame) rejects
// corrupt frames at unpack time while deferring the densify/apply to
// the consumer that owns the scratch.
long long dlt_wire_fused_validate(const uint8_t* buf, uint64_t len,
                                  uint64_t total) {
  return fused_validate(buf, len, total);
}

// Apply: scatter-ADD the frame's sections straight into a live f32
// ravel (``target[idx] += scale * val``) with no dense intermediate —
// the CHOCO hat update consumes a correction frame without ever
// materializing it.  Same validate-then-write discipline as decode: a
// corrupt frame returns a negative status before the first add.
// Accumulation is np.add.at semantics (duplicate indices add once per
// occurrence, sequentially); honestly-encoded frames carry strictly
// ascending positions, for which this is ulp-identical to
// decode-then-``target += scale * dense``.  Deliberately scalar: a
// SIMD gather-add-scatter would lose one addition per duplicate lane.
long long dlt_wire_fused_apply(const uint8_t* buf, uint64_t len,
                               float* target, uint64_t total, float scale) {
  const long long st = fused_validate(buf, len, total);
  if (st != 0) return st;
  const uint32_t nbuckets = buf[2];
  uint64_t off = 8;
  for (uint32_t b = 0; b < nbuckets; ++b) {
    const uint64_t k = get_u32(buf + off);
    const uint8_t* idx_p = buf + off + 4;
    const uint8_t* vhdr = buf + off + 4 + 4 * k + 4;
    const uint8_t code = vhdr[0], flags = vhdr[1];
    const uint8_t* val_p = vhdr + 8;
    if (code == kDtypeF32 && flags == 0) {
      for (uint64_t i = 0; i < k; ++i) {
        target[get_u32(idx_p + 4 * i)] +=
            fp_barrier(scale * get_f32(val_p + 4 * i));
      }
      off += 4 + 4 * k + 4 + 8 + 4 * k;
    } else if (code == kDtypeBf16 && flags == kFlagBf16) {
      for (uint64_t i = 0; i < k; ++i) {
        const uint16_t bits = static_cast<uint16_t>(val_p[2 * i]) |
                              (static_cast<uint16_t>(val_p[2 * i + 1]) << 8);
        target[get_u32(idx_p + 4 * i)] +=
            fp_barrier(scale * bf16_to_f32_one(bits));
      }
      off += 4 + 4 * k + 4 + 8 + 2 * k;
    } else {  // int8
      const float q_scale = get_f32(val_p);
      const int8_t* q = reinterpret_cast<const int8_t*>(val_p + 4);
      for (uint64_t i = 0; i < k; ++i) {
        target[get_u32(idx_p + 4 * i)] +=
            fp_barrier(scale * (static_cast<float>(q[i]) * q_scale));
      }
      off += 4 + 4 * k + 4 + 12 + k;
    }
  }
  return 0;
}

// --------------------------------------------------------------------- //
// Dense tensor frames (encode_tensor/decode_tensor parity):             //
//   u8 dtype_code | u8 flags | u8 ndim | u8 0 | u32 dim[ndim] |         //
//   [f32 scale if int8] | payload                                       //
// --------------------------------------------------------------------- //

// Whole-frame dense encode of an f32 source under a wire mode.  ``n``
// must be prod(dims); returns bytes written or a negative status.
long long dlt_wire_dense_encode(const float* src, uint64_t n,
                                const uint32_t* dims, uint32_t ndim,
                                uint32_t mode, uint8_t* out, uint64_t cap) {
  const uint64_t hdr = 4 + 4ull * ndim;
  const uint64_t need =
      hdr + (mode == kModeI8 ? 4 + n : mode == kModeBf16 ? 2 * n : 4 * n);
  if (cap < need) return kErrInternal;
  out[0] = mode == kModeBf16 ? kDtypeBf16 : mode == kModeI8 ? kDtypeI8
                                                            : kDtypeF32;
  out[1] = mode == kModeBf16 ? kFlagBf16 : mode == kModeI8 ? kFlagI8 : 0;
  out[2] = static_cast<uint8_t>(ndim);
  out[3] = 0;
  for (uint32_t d = 0; d < ndim; ++d) put_u32(out + 4 + 4 * d, dims[d]);
  uint8_t* p = out + hdr;
  if (mode == kModeBf16) {
    for (uint64_t i = 0; i < n; ++i) {
      const uint16_t bits = f32_to_bf16_one(src[i]);
      p[2 * i] = static_cast<uint8_t>(bits);
      p[2 * i + 1] = static_cast<uint8_t>(bits >> 8);
    }
  } else if (mode == kModeI8) {
    float maxabs = 0.0f;
    bool any_nan = false;
    for (uint64_t i = 0; i < n; ++i) {
      const float v = src[i];
      if (v != v) any_nan = true;
      const float a = std::fabs(v);
      if (a > maxabs) maxabs = a;
    }
    if (any_nan || std::isinf(maxabs)) return kErrNonFinite;
    const I8Scale sc = i8_scale_of(maxabs, n);
    put_f32(p, sc.wire);
    p += 4;
    for (uint64_t i = 0; i < n; ++i) {
      p[i] = static_cast<uint8_t>(f32_to_i8_one(src[i], sc.inv));
    }
  } else {
    for (uint64_t i = 0; i < n; ++i) put_f32(p + 4 * i, src[i]);
  }
  return static_cast<long long>(need);
}

// Whole-frame dense decode into an f32 buffer of ``n`` elements.  The
// caller (native/wire.py) sized ``out`` from the already-parsed header;
// this call re-validates the frame end to end.  Returns 0, or a negative
// status (kErrUnsupported: a dtype/flags combo the caller must route to
// the Python decoder).
long long dlt_wire_dense_decode(const uint8_t* buf, uint64_t len, float* out,
                                uint64_t n) {
  if (len < 4) return kErrTrunc;
  const uint8_t code = buf[0], flags = buf[1], ndim = buf[2];
  if (ndim > 16) return kErrBounds;
  const uint64_t hdr = 4 + 4ull * ndim;
  if (len < hdr) return kErrTrunc;
  uint64_t count = 1;
  for (uint32_t d = 0; d < ndim; ++d) {
    const uint64_t dim = get_u32(buf + 4 + 4 * d);
    if (dim != 0 && count > (1ull << 40) / (dim ? dim : 1)) return kErrBounds;
    count *= dim;
  }
  if (count != n) return kErrTotal;
  const uint8_t* p = buf + hdr;
  if (code == kDtypeBf16 && flags == kFlagBf16) {
    if (len != hdr + 2 * n) return kErrTrunc;
    for (uint64_t i = 0; i < n; ++i) {
      const uint16_t bits = static_cast<uint16_t>(p[2 * i]) |
                            (static_cast<uint16_t>(p[2 * i + 1]) << 8);
      out[i] = bf16_to_f32_one(bits);
    }
  } else if (code == kDtypeI8 && flags == kFlagI8) {
    if (len != hdr + 4 + n) return kErrTrunc;
    const float scale = get_f32(p);
    const int8_t* q = reinterpret_cast<const int8_t*>(p + 4);
    for (uint64_t i = 0; i < n; ++i) {
      out[i] = static_cast<float>(q[i]) * scale;
    }
  } else if (code == kDtypeF32 && flags == 0) {
    if (len != hdr + 4 * n) return kErrTrunc;
    for (uint64_t i = 0; i < n; ++i) out[i] = get_f32(p + 4 * i);
  } else {
    return kErrUnsupported;
  }
  return 0;
}

}  // extern "C"
