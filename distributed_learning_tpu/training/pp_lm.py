"""Pipeline-parallel training for the flagship TransformerLM.

``training/pp.py`` pipelines any uniform stage function; this module
binds it to the real model: the LM's block stack (homogeneous by
construction — ``models/transformer.py:377-384`` instantiates the same
``_Block`` config ``num_layers`` times) is split into ``n_stages``
groups whose stacked parameters shard over a ``stage`` mesh axis, while
the thin non-uniform ends — token/position embeddings in front, final
LayerNorm + vocab head behind — run replicated outside the pipeline.

Two schedules, same gradients (pinned per param group by
``tests/test_pp_lm.py``):

* :func:`make_lm_pipeline_train_step` — GPipe: one ``jax.grad`` wraps
  embed -> pipeline -> head, so the ends get ordinary reverse-mode and
  the interior backward is the reverse pipeline (activation memory
  grows with the microbatch count);
* :func:`make_lm_1f1b_train_step` — 1F1B (O(stages) activation stash):
  the head rides the generic schedule's ``head_fn`` (its grads
  accumulate at the last stage, one microbatch per tick) and the
  embeddings chain through ``collect_input_grads`` — stage 0's input
  cotangents feed an explicit embedding vjp.

Layout: per-stage params are the (S, L/S, ...) restacking of the
``_Block_i`` subtrees; ``split_lm_params``/``merge_lm_params`` convert
between this and the flax tree so a pipelined training run can be
checkpointed or evaluated with the ordinary ``model.apply``/
``generate`` paths at any point.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_learning_tpu.models.moe import collect_load_balance_loss
from distributed_learning_tpu.models.transformer import _Block
from distributed_learning_tpu.training.fsdp import reject_dropout_model
from distributed_learning_tpu.training.pp import (
    make_1f1b_train_step,
    make_pipeline_apply,
)

__all__ = [
    "split_lm_params",
    "merge_lm_params",
    "stage_layout",
    "interleaved_stage_layout",
    "make_lm_pipeline_train_step",
    "make_lm_1f1b_train_step",
    "make_lm_interleaved_train_step",
]


def stage_layout(stacked, n_stages: int):
    """(L, ...) block stack -> (S, L/S, ...) per-stage groups — the
    layout the train step and ``tx.init`` both consume."""
    def fold(leaf):
        L = leaf.shape[0]
        if L % n_stages:
            raise ValueError(
                f"{L} blocks do not divide into {n_stages} stages"
            )
        return leaf.reshape((n_stages, L // n_stages) + leaf.shape[1:])

    return jax.tree.map(fold, stacked)


def interleaved_stage_layout(stacked, n_stages: int, n_chunks: int):
    """(L, ...) block stack -> (S, V, L/(S*V), ...) chunk groups for the
    interleaved schedule: chunk ``c`` of device ``d`` holds the blocks
    of virtual stage ``v = c*S + d`` (``training/pp_interleaved.py``'s
    placement), i.e. leaf[d, c, l] = block ``(c*S + d)*Lc + l``."""
    S, V = n_stages, n_chunks

    def fold(leaf):
        L = leaf.shape[0]
        if L % (S * V):
            raise ValueError(
                f"{L} blocks do not divide into {S} stages x {V} chunks"
            )
        Lc = L // (S * V)
        return leaf.reshape((V, S, Lc) + leaf.shape[1:]).swapaxes(0, 1)

    return jax.tree.map(fold, stacked)


def _outer_keys(params) -> list:
    return [k for k in params if not k.startswith("_Block_")]


def split_lm_params(model, params) -> Tuple[Any, Any]:
    """Flax param tree -> (outer, stacked).

    ``outer`` holds the embeddings and the final LayerNorm + head;
    ``stacked`` is the block subtrees restacked with a leading
    ``num_layers`` axis (reshaped to (S, L/S, ...) by the step builder).
    """
    blocks = [params[f"_Block_{i}"] for i in range(model.num_layers)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *blocks)
    outer = {k: params[k] for k in _outer_keys(params)}
    return outer, stacked


def merge_lm_params(model, outer, stacked, *, n_stages: int | None = None,
                    n_chunks: int | None = None) -> Any:
    """Inverse of :func:`split_lm_params`: rebuild the flax tree (e.g.
    to checkpoint, evaluate, or ``generate`` mid-training).

    Pass ``n_stages`` when ``stacked`` is in the step's (S, L/S, ...)
    :func:`stage_layout`, and additionally ``n_chunks`` for the
    interleaved (S, V, L/(S*V), ...) :func:`interleaved_stage_layout`;
    omit both for ``split_lm_params``' (L, ...) form.  Explicit because
    the layouts are indistinguishable from shapes alone whenever S == L.
    """
    L = model.num_layers

    def unstack(leaf):
        if n_chunks is not None:
            # (S, V, Lc, ...) -> (V, S, Lc, ...) -> (L, ...): C-order
            # flattening of [c, d, l] is block (c*S + d)*Lc + l.
            return leaf.swapaxes(0, 1).reshape((L,) + leaf.shape[3:])
        if n_stages is not None:
            return leaf.reshape((L,) + leaf.shape[2:])
        return leaf

    flat = jax.tree.map(unstack, stacked)
    params = dict(outer)
    for i in range(model.num_layers):
        params[f"_Block_{i}"] = jax.tree.map(lambda a: a[i], flat)
    return params


class _LMParts:
    """Everything both step builders share: validation, the per-stage
    block scan, and the embed/head closures over the model config.

    Two round-5 capabilities (VERDICT r4 weak #3):

    * a SEQUENCE-PARALLEL ``attn_impl`` ("ring" | "ring_flash" |
      "ulysses") makes ``self.sp`` true — the step builders then name
      ``model.seq_axis`` manual and shard the microbatches' token dim
      over it, so each stage's attention rotates K/V around the seq
      ring while activations hop the stage ring (the generic mechanism
      proven by tests/test_pp_sp.py, now carrying the real model);
    * ``mlp="moe"`` flips the stage scan to the aux-returning contract:
      each block applies with ``mutable=["moe_stats"]`` so the sown
      load-balance loss is COLLECTED (not silently dropped), the stage
      reports the mean over its blocks, and the schedule executors fold
      ``moe_aux_coef x mean`` into the objective (``stage_aux`` /
      ``stage_aux_coef`` in pp.py / pp_interleaved.py).
    """

    def __init__(self, mesh: Mesh, model, stage_axis: str,
                 expert_axis: str | None = None,
                 tp_axis: str | None = None):
        reject_dropout_model(model)
        if model.attn_impl not in (
            "full", "flash", "ring", "ring_flash", "ulysses"
        ):
            raise ValueError(
                f"unknown attn_impl {model.attn_impl!r} (want full|flash|"
                "ring|ring_flash|ulysses)"
            )
        self.sp = model.attn_impl in ("ring", "ring_flash", "ulysses")
        self.seq_axis = model.seq_axis if self.sp else None
        if self.sp and model.seq_axis not in mesh.axis_names:
            raise ValueError(
                f"attn_impl {model.attn_impl!r} needs mesh axis "
                f"{model.seq_axis!r}; the mesh has {mesh.axis_names}"
            )
        self.moe = model.mlp == "moe"
        if expert_axis is not None:
            if not self.moe:
                raise ValueError(
                    "expert_axis needs mlp='moe' — a dense LM has no "
                    "expert kernels to shard"
                )
            if expert_axis not in mesh.axis_names:
                raise ValueError(
                    f"expert_axis {expert_axis!r} is not on the mesh "
                    f"{mesh.axis_names}"
                )
            if model.num_experts % mesh.shape[expert_axis]:
                raise ValueError(
                    f"num_experts {model.num_experts} must be divisible by "
                    f"the {expert_axis!r} axis size "
                    f"{mesh.shape[expert_axis]}"
                )
        if tp_axis is not None:
            if self.moe:
                raise ValueError(
                    "tp_axis with mlp='moe' is not supported; shard the "
                    "experts instead (expert_axis)"
                )
            if tp_axis not in mesh.axis_names:
                raise ValueError(
                    f"tp_axis {tp_axis!r} is not on the mesh "
                    f"{mesh.axis_names}"
                )
            n_tp = mesh.shape[tp_axis]
            Hkv = (model.num_kv_heads if model.num_kv_heads is not None
                   else model.num_heads)
            for what, val in (("num_heads", model.num_heads),
                              ("num_kv_heads", Hkv),
                              ("mlp width",
                               model.mlp_ratio * model.num_heads
                               * model.head_dim)):
                if val % n_tp:
                    raise ValueError(
                        f"{what} {val} must be divisible by the "
                        f"{tp_axis!r} axis size {n_tp}"
                    )
        self.tp_axis = tp_axis
        self.expert_axis = expert_axis
        self.stage_axis = stage_axis
        self.S = mesh.shape[stage_axis]
        L = model.num_layers
        if L % self.S:
            raise ValueError(
                f"num_layers {L} must divide into {self.S} stages"
            )
        self.model = model
        self.use_rope = model.pos_emb == "rope"
        d_model = model.num_heads * model.head_dim

        block = _Block(
            model.num_heads, model.head_dim, model.mlp_ratio,
            model.attn_impl, model.seq_axis, model.dtype,
            model.mlp, model.num_experts, model.moe_top_k,
            model.attn_window, False, model.max_len,
            self.use_rope, model.num_kv_heads, 0.0,
            moe_expert_axis=expert_axis, tp_axis=tp_axis,
            moe_capacity_factor=model.moe_capacity_factor,
        )
        use_rope = self.use_rope
        sp, seq_axis, moe = self.sp, self.seq_axis, self.moe

        def stage_fn(p, act):
            if not use_rope:
                positions = None
            elif sp:
                # Global positions: each seq shard offsets by its index
                # (the models/transformer.py:360-366 convention).
                T_loc = act.shape[-2]
                positions = (
                    lax.axis_index(seq_axis) * T_loc + jnp.arange(T_loc)
                )
            else:
                positions = jnp.arange(act.shape[-2])

            if moe:
                def one(a, bp):
                    out, state = block.apply(
                        {"params": bp}, a, positions,
                        mutable=["moe_stats"],
                    )
                    return out, collect_load_balance_loss(state)

                act, auxs = lax.scan(one, act, p)
                return act, jnp.mean(auxs)

            def one(a, bp):
                return block.apply({"params": bp}, a, positions), None

            act, _ = lax.scan(one, act, p)
            return act

        self.stage_fn = stage_fn
        self.tok_embed = nn.Embed(model.vocab_size, d_model,
                                  dtype=model.dtype)
        self.pos_embed = nn.Embed(model.max_len, d_model,
                                  dtype=model.dtype)
        self.final_ln = nn.LayerNorm(dtype=model.dtype)
        self.head = nn.Dense(model.vocab_size, dtype=model.dtype)

    @property
    def extra_axes(self) -> tuple:
        return (self.seq_axis,) if self.sp else ()

    @property
    def mb_spec(self) -> P:
        # (M, mb, T[, d]): dim 2 is the token dim for both the embedded
        # activations and the (M, mb, T) integer labels.
        return P(None, None, self.seq_axis) if self.sp else P()

    def build_param_specs(self, *, n_chunks: int | None = None):
        """Per-leaf PartitionSpecs for the stacked stage params, or
        ``None`` for the uniform-P(stage) default.

        With ``expert_axis`` the MoE kernels (``w_up``/``b_up``/
        ``w_dn``/``b_dn``) shard their stacked-expert dim; with
        ``tp_axis`` the attention kernels shard their HEAD dim and the
        MLP pair its column/row dims (the megatron split of
        ``training/tp.py::transformer_tp_rules``, restated against the
        stacked layout).  ``off`` is where a block-param's own dims
        start: 2 after the (S, L/S, ...) stage layout, 3 after the
        (S, V, Lc, ...) interleaved layout.  Everything else stays
        P(stage) — pp x ep / pp x tp from specs alone.  The tree's
        STRUCTURE comes from ``jax.eval_shape`` over the model's init
        (no FLOPs, no devices), so the step builders get their specs at
        build time without real parameters."""
        if self.expert_axis is None and self.tp_axis is None:
            return None
        off = 2 if n_chunks is None else 3
        eax, tax = self.expert_axis, self.tp_axis
        stage_ax = self.stage_axis
        model = self.model

        def shape_fn():
            p = model.clone(attn_impl="full").init(
                jax.random.key(0), jnp.zeros((1, 2), jnp.int32)
            )["params"]
            _, stacked = split_lm_params(model, p)
            if n_chunks is not None:
                return interleaved_stage_layout(stacked, self.S, n_chunks)
            return stage_layout(stacked, self.S)

        def at(ndim, dim):
            ent = [None] * ndim
            ent[0] = stage_ax
            ent[off + dim] = tax
            return P(*ent)

        def spec(path, leaf):
            names = [getattr(k, "key", str(k)) for k in path]
            leafname = names[-1] if names else ""
            parent = names[-2] if len(names) > 1 else ""
            if eax is not None and leafname in (
                "w_up", "b_up", "w_dn", "b_dn"
            ):
                ent = [None] * leaf.ndim
                ent[0] = stage_ax
                ent[off] = eax
                return P(*ent)
            if tax is not None:
                if parent == "DenseGeneral_0" and leafname == "kernel":
                    return at(leaf.ndim, 2)   # (d, 3, H, Dh): heads
                if parent == "q_proj" and leafname == "kernel":
                    return at(leaf.ndim, 1)   # (d, H, Dh)
                if parent == "kv_proj" and leafname == "kernel":
                    return at(leaf.ndim, 2)   # (d, 2, Hkv, Dh)
                if parent == "DenseGeneral_1" and leafname == "kernel":
                    return at(leaf.ndim, 0)   # (H, Dh, d): head rows
                if parent == "Dense_0":       # columns: kernel (d, h),
                    return at(leaf.ndim, leaf.ndim - off - 1)  # bias (h)
                if parent == "Dense_1" and leafname == "kernel":
                    return at(leaf.ndim, 0)   # rows: (h, d)
                # Dense_1 bias, LayerNorms: replicated over tp.
            return P(stage_ax)

        return jax.tree_util.tree_map_with_path(
            spec, jax.eval_shape(shape_fn)
        )

    def embed(self, embed_params, tok_mb):
        T = tok_mb.shape[-1]
        if not self.use_rope and T > self.model.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len {self.model.max_len}"
            )
        x = self.tok_embed.apply(
            {"params": embed_params["Embed_0"]}, tok_mb
        )
        if not self.use_rope:
            pos = self.pos_embed.apply(
                {"params": embed_params["Embed_1"]}, jnp.arange(T)
            )
            x = x + pos[None, None]
        return x

    def head_loss(self, head_params, out, y_mb):
        out = self.final_ln.apply(
            {"params": head_params["LayerNorm_0"]}, out
        )
        logits = self.head.apply(
            {"params": head_params["Dense_0"]}, out
        ).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y_mb
        ).mean()

    def head_loss_sharded(self, head_params, out, y_mb):
        """The schedule-internal (shard_map) head: under pp x sp the
        per-shard token mean must end in a pmean over the seq axis so
        the scalar (and the 1F1B backward seed) is the GLOBAL mean —
        the head_fn contract of ``pp.head_seed``.  Identical to
        :meth:`head_loss` on a 1D stage mesh."""
        loss = self.head_loss(head_params, out, y_mb)
        if self.sp:
            # graftlint: disable=raw-collective-in-shard-map -- head-loss exit (pp x sp contract): the loss must END reduced over seq so the scalar is sequence-invariant (pp.head_seed docstring)
            loss = lax.pmean(loss, self.seq_axis)
        return loss

    @staticmethod
    def split_outer(outer):
        ep = {k: v for k, v in outer.items() if k.startswith("Embed")}
        hp = {k: v for k, v in outer.items() if not k.startswith("Embed")}
        return ep, hp


def make_lm_pipeline_train_step(
    mesh: Mesh,
    model,
    tx: Any,
    *,
    stage_axis: str = "stage",
    remat_stage: bool = False,
    moe_aux_coef: float = 0.01,
    expert_axis: str | None = None,
    tp_axis: str | None = None,
) -> Callable[..., Tuple[Any, Any, Any, jax.Array]]:
    """Build ``step(outer, stages, opt_state, tok_mb, y_mb) ->
    (outer, stages, opt_state, loss)`` — GPipe schedule, backward by
    autodiff (activation memory O(microbatches); the 1F1B variant below
    holds O(stages)).

    ``tok_mb``/``y_mb`` are (M, mb, T) int32 microbatched tokens /
    pre-shifted targets (replicated; each microbatch is small by
    construction).  ``stages`` is ``stage_layout(split_lm_params(...)[1],
    S)`` — the (S, L/S, ...) form; ``opt_state = tx.init((outer,
    stages))`` on that same layout.

    A sequence-parallel ``attn_impl`` ("ring"|"ring_flash"|"ulysses")
    needs ``model.seq_axis`` on the mesh; token/label dim 2 then shards
    over it (pp x sp).  ``mlp="moe"`` folds ``moe_aux_coef`` times the
    per-layer-mean load-balance aux into the objective (the Switch
    convention every non-pipelined builder uses — e.g.
    ``training/fsdp.py``).  ``dropout_rate`` must be 0 (rng-less
    builder).
    """

    parts = _LMParts(mesh, model, stage_axis, expert_axis, tp_axis)
    pipe = make_pipeline_apply(mesh, parts.stage_fn, stage_axis=stage_axis,
                               param_specs=parts.build_param_specs(),
                               remat_stage=remat_stage,
                               extra_manual_axes=parts.extra_axes,
                               microbatch_spec=parts.mb_spec,
                               stage_aux=parts.moe)

    def loss_fn(outer, stages, tok_mb, y_mb):
        ep, hp = parts.split_outer(outer)
        out = pipe(stages, parts.embed(ep, tok_mb))
        if parts.moe:
            out, aux = out
            return parts.head_loss(hp, out, y_mb) + moe_aux_coef * aux
        return parts.head_loss(hp, out, y_mb)

    @jax.jit
    def step(outer, stages, opt_state, tok_mb, y_mb):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            outer, stages, tok_mb, y_mb
        )
        updates, opt_state = tx.update(grads, opt_state, (outer, stages))
        outer, stages = optax.apply_updates((outer, stages), updates)
        return outer, stages, opt_state, loss

    return step


def _lm_chained_step(parts, inner, tx):
    """The embed-vjp -> inner-schedule -> grad-merge -> optimizer
    sequence shared by every head_fn-based LM step builder."""

    @jax.jit
    def step(outer, stages, opt_state, tok_mb, y_mb):
        ep, hp = parts.split_outer(outer)
        x, emb_vjp = jax.vjp(lambda e: parts.embed(e, tok_mb), ep)
        g_stages, g_head, d_x, loss = inner(stages, hp, x, y_mb)
        (g_embed,) = emb_vjp(d_x)
        grads = ({**g_embed, **g_head}, g_stages)
        updates, opt_state = tx.update(grads, opt_state, (outer, stages))
        outer, stages = optax.apply_updates((outer, stages), updates)
        return outer, stages, opt_state, loss

    return step


def make_lm_1f1b_train_step(
    mesh: Mesh,
    model,
    tx: Any,
    *,
    stage_axis: str = "stage",
    moe_aux_coef: float = 0.01,
    expert_axis: str | None = None,
    tp_axis: str | None = None,
) -> Callable[..., Tuple[Any, Any, Any, jax.Array]]:
    """The same contract as :func:`make_lm_pipeline_train_step`, under
    the hand-scheduled 1F1B pipeline (O(stages) activation stash).

    Composition of the generic schedule's two extensions: the final
    LayerNorm + vocab head ride as the 1F1B ``head_fn`` (their grads
    accumulate at the last stage, one microbatch per tick), and the
    embeddings chain through ``collect_input_grads`` — stage 0's input
    cotangents feed the embedding's vjp, so every parameter group
    trains, with the same per-group gradients as the GPipe/autodiff
    builder (pinned by tests/test_pp_lm.py).  Sequence-parallel
    attention and MoE compose exactly as there (the head ends in a
    seq-pmean; the aux seeds ride ``stage_aux_coef`` — see
    ``pp.make_1f1b_train_step``).
    """

    parts = _LMParts(mesh, model, stage_axis, expert_axis, tp_axis)
    inner = make_1f1b_train_step(
        mesh, parts.stage_fn,
        head_fn=parts.head_loss_sharded,
        collect_input_grads=True,
        stage_axis=stage_axis,
        param_specs=parts.build_param_specs(),
        extra_manual_axes=parts.extra_axes,
        microbatch_spec=parts.mb_spec,
        stage_aux_coef=moe_aux_coef if parts.moe else None,
    )
    return _lm_chained_step(parts, inner, tx)


def make_lm_interleaved_train_step(
    mesh: Mesh,
    model,
    tx: Any,
    n_chunks: int,
    n_microbatches: int,
    *,
    stage_axis: str = "stage",
    moe_aux_coef: float = 0.01,
    expert_axis: str | None = None,
    tp_axis: str | None = None,
) -> Callable[..., Tuple[Any, Any, Any, jax.Array]]:
    """The LM under the INTERLEAVED 1F1B schedule
    (``training/pp_interleaved.py``): same contract as
    :func:`make_lm_1f1b_train_step`, but ``stages`` is
    ``interleaved_stage_layout(..., S, n_chunks)`` — each device hosts
    ``n_chunks`` virtual-stage chunks, shrinking the pipeline bubble.
    ``n_microbatches`` is static (the schedule is precomputed for it);
    ``tok_mb``/``y_mb`` must carry exactly that many microbatches.
    """
    from distributed_learning_tpu.training.pp_interleaved import (
        make_interleaved_1f1b_train_step,
    )

    parts = _LMParts(mesh, model, stage_axis, expert_axis, tp_axis)
    if model.num_layers % (parts.S * n_chunks):
        raise ValueError(
            f"num_layers {model.num_layers} must divide into "
            f"{parts.S} stages x {n_chunks} chunks"
        )
    inner = make_interleaved_1f1b_train_step(
        mesh, parts.stage_fn,
        n_chunks=n_chunks,
        n_microbatches=n_microbatches,
        head_fn=parts.head_loss_sharded,
        collect_input_grads=True,
        stage_axis=stage_axis,
        param_specs=parts.build_param_specs(n_chunks=n_chunks),
        extra_manual_axes=parts.extra_axes,
        microbatch_spec=parts.mb_spec,
        stage_aux_coef=moe_aux_coef if parts.moe else None,
    )
    return _lm_chained_step(parts, inner, tx)
